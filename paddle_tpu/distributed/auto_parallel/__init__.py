"""Auto-parallel API (paddle.distributed auto_parallel parity).

Reference capability (SURVEY.md §2.3 "Auto-parallel"): `DistAttr`
(process_mesh + dims_mapping), `shard_tensor`, sharding completion/
partitioner/reshard passes over a static program
(`python/paddle/distributed/auto_parallel/`).

TPU-native design: this IS the native execution model — `shard_tensor` is a
device_put with a NamedSharding; "completion" (propagating shardings through
the graph) and "partitioner/reshard" (inserting collectives) are what GSPMD
does inside XLA for every jit'ed program. The API is therefore thin and
total: every op in the framework is auto-parallel by construction.
"""
from __future__ import annotations

import sys
from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ...framework.core import Tensor
from ...framework.op import raw
from .. import mesh as _mesh
from . import planner  # noqa: F401  (cost-model layout planner, AUTOPLAN.md)


class Placement:
    pass


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim


class Partial(Placement):
    """Pending-reduction placement. GSPMD tracks partial values internally;
    at the API boundary we reduce eagerly (a psum via resharding)."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """paddle.distributed.ProcessMesh parity — wraps jax.sharding.Mesh."""

    def __init__(
        self,
        mesh: Union[Sequence, np.ndarray, None] = None,
        dim_names: Optional[Sequence[str]] = None,
        shape: Optional[Sequence[int]] = None,
        process_ids: Optional[Sequence[int]] = None,
    ):
        if mesh is not None:
            arr = np.asarray(mesh)
        else:
            arr = np.asarray(process_ids or range(len(jax.devices()))).reshape(
                shape or (-1,)
            )
        self._ids = arr
        self.shape = list(arr.shape)
        self.ndim = arr.ndim
        self.dim_names = list(dim_names) if dim_names else [f"d{i}" for i in range(arr.ndim)]
        self.process_ids = [int(i) for i in arr.ravel()]
        devs = np.asarray(jax.devices(), dtype=object)[arr.ravel()].reshape(arr.shape)
        self.jax_mesh = Mesh(devs, tuple(self.dim_names))

    def __eq__(self, other):
        return (
            isinstance(other, ProcessMesh)
            and self.process_ids == other.process_ids
            and self.shape == other.shape
        )

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def _placements_to_spec(mesh: ProcessMesh, placements: Sequence[Placement], ndim: int) -> P:
    entries: List = [None] * ndim
    for axis_idx, pl in enumerate(placements):
        if isinstance(pl, Shard):
            name = mesh.dim_names[axis_idx]
            if entries[pl.dim] is None:
                entries[pl.dim] = name
            elif isinstance(entries[pl.dim], tuple):
                entries[pl.dim] = entries[pl.dim] + (name,)
            else:
                entries[pl.dim] = (entries[pl.dim], name)
    return P(*entries)


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement], dtype=None, **kwargs):
    """Place a tensor on a process mesh (paddle.distributed.shard_tensor)."""
    v = raw(data) if isinstance(data, Tensor) else jax.numpy.asarray(data)
    spec = _placements_to_spec(mesh, placements, v.ndim)
    out = jax.device_put(v, NamedSharding(mesh.jax_mesh, spec))
    t = Tensor(out, stop_gradient=getattr(data, "stop_gradient", True))
    t.process_mesh = mesh
    t.placements = list(placements)
    return t


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


class _ShardDataLoader:
    """Iterable that places every batch on the mesh as it is yielded."""

    def __init__(self, dataloader, mesh, shard_dim, input_keys):
        self._dl = dataloader
        self._mesh = mesh
        self._dim = shard_dim  # mesh axis NAME or None
        self._keys = set(input_keys) if input_keys else None

    def __len__(self):
        return len(self._dl)

    def _place(self, item, shard):
        if isinstance(item, (list, tuple)):
            return type(item)(self._place(x, shard) for x in item)
        if isinstance(item, dict):
            return {
                k: self._place(
                    v, shard and (self._keys is None or k in self._keys))
                for k, v in item.items()
            }
        if not (isinstance(item, Tensor) or hasattr(item, "shape")):
            return item
        # one placement per MESH axis; Shard(0) = shard the batch (tensor
        # dim 0) along the axis named by shard_dims
        placements = [Replicate()] * self._mesh.ndim
        if shard and self._dim is not None and len(item.shape):
            placements[self._mesh.dim_names.index(self._dim)] = Shard(0)
        return shard_tensor(item, self._mesh, placements)

    def __iter__(self):
        for batch in self._dl:
            yield self._place(batch, True)


def shard_dataloader(dataloader, meshes, shard_dims=None, input_keys=None):
    """paddle.distributed.shard_dataloader parity: wrap a DataLoader so each
    yielded batch is placed on ``meshes`` with its leading (batch) axis
    sharded along ``shard_dims``, or fully replicated when ``shard_dims``
    is None. ``shard_dims`` accepts a mesh axis name (``"dp"``), a mesh
    axis index, or a list of either (one per mesh, as the reference allows);
    ``input_keys`` restricts sharding to those keys of a dict batch.

    TPU-native note: placement is a ``jax.device_put`` with a NamedSharding —
    the SPMD program consumes the batch without further resharding. Multiple
    meshes (the reference's per-pipeline-stage input feed) collapse to the
    first mesh here: under one-program SPMD pipeline stages read slices of
    the same placed batch.
    """
    mesh = meshes[0] if isinstance(meshes, (list, tuple)) else meshes
    if isinstance(shard_dims, (list, tuple)):
        # one entry per mesh in the reference; SPMD collapses to one mesh
        shard_dims = shard_dims[0] if len(shard_dims) else None
    if isinstance(shard_dims, (int, np.integer)):
        try:
            shard_dims = mesh.dim_names[int(shard_dims)]
        except IndexError:
            raise ValueError(
                f"shard_dims index {shard_dims} out of range for mesh axes "
                f"{mesh.dim_names}") from None
    if shard_dims is not None:
        names = tuple(getattr(mesh, "dim_names", ()) or ())
        if names and shard_dims not in names:
            raise ValueError(
                f"shard_dims {shard_dims!r} is not a mesh axis of {names}")
    return _ShardDataLoader(dataloader, mesh, shard_dims, input_keys)


def reshard(tensor, mesh: ProcessMesh, placements: Sequence[Placement]):
    """Move a tensor to a new placement (reference: auto_parallel reshard —
    the comm-inserting pass; here a single resharding device_put / constraint)."""
    v = raw(tensor)
    spec = _placements_to_spec(mesh, placements, v.ndim)
    from ...framework.op import defop

    if any(isinstance(p, Partial) for p in placements):
        raise NotImplementedError("reshard to Partial is not supported")
    from ..mesh import sharding_constraint
    from ...framework.core import is_tracer_value

    if is_tracer_value(v):
        out = sharding_constraint(v, spec, mesh.jax_mesh)
    else:
        out = jax.device_put(v, NamedSharding(mesh.jax_mesh, spec))
    t = Tensor(out, stop_gradient=tensor.stop_gradient if isinstance(tensor, Tensor) else True)
    t.process_mesh = mesh
    t.placements = list(placements)
    return t


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None, input_fn=None, output_fn=None):
    """Apply a user shard_fn(name, layer, mesh) over sublayers (paddle parity)."""
    if shard_fn is not None:
        for name, sub in layer.named_sublayers(include_self=True):
            shard_fn(name, sub, process_mesh)
    return layer


def get_mesh() -> Optional[ProcessMesh]:
    m = _mesh.get_global_mesh()
    if m is None:
        return None
    pm = ProcessMesh.__new__(ProcessMesh)
    pm.jax_mesh = m
    pm.shape = list(m.devices.shape)
    pm.ndim = m.devices.ndim
    pm.dim_names = list(m.axis_names)
    pm.process_ids = [d.id for d in m.devices.ravel()]
    pm._ids = np.asarray(pm.process_ids).reshape(pm.shape)
    return pm


def set_mesh(mesh: ProcessMesh):
    _mesh.set_global_mesh(mesh.jax_mesh)


class Strategy:
    """auto_parallel Strategy parity. `amp` is applied by Engine (auto_cast
    around the compiled loss); `recompute`/`gradient_merge` are accepted but
    emit a warning when enabled (use fleet's recompute_helper / manual grad
    accumulation); `sharding`/`pipeline` degrees are owned by the fleet
    hybrid mesh config."""

    class _Section(dict):
        __getattr__ = dict.get

        def __setattr__(self, k, v):
            self[k] = v

    def __init__(self):
        self.auto_mode = "semi"
        self.amp = self._Section(enable=False, dtype="bfloat16")
        self.recompute = self._Section(enable=False)
        self.sharding = self._Section(enable=False, degree=1, stage=1)
        self.gradient_merge = self._Section(enable=False, k_steps=1)
        self.pipeline = self._Section(enable=False, schedule_mode="1F1B")


class Engine:
    """auto_parallel.Engine parity (reference:
    python/paddle/distributed/auto_parallel/static/engine.py): fit/evaluate/
    predict driving a model + loss + optimizer over a dataset. TPU-native:
    the 'planner/partitioner/reshard' passes are GSPMD; the Engine is a thin
    training driver over the fleet DistTrainStep (one compiled SPMD program
    per shape signature)."""

    def __init__(self, model, loss=None, optimizer=None, metrics=None, strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics or []
        self._strategy = strategy or Strategy()
        self._step = None

    def _get_step(self):
        if self._step is None:
            from ..fleet import DistTrainStep

            loss_fn = self._loss
            strat = self._strategy
            for knob in ("recompute", "gradient_merge"):
                if getattr(strat, knob, None) and getattr(strat, knob).get("enable"):
                    import warnings

                    warnings.warn(
                        f"auto_parallel Strategy.{knob} is not applied by this "
                        "Engine (use fleet recompute_helper / manual grad "
                        "accumulation); continuing without it"
                    )
            amp_on = bool(strat.amp.get("enable"))
            amp_dtype = strat.amp.get("dtype") or "bfloat16"

            def compute_loss(model, *batch):
                *xs, y = batch
                if amp_on:
                    from ... import amp as _amp

                    with _amp.auto_cast(enable=True, dtype=amp_dtype):
                        out = model(*xs)
                        return loss_fn(out, y)
                out = model(*xs)
                return loss_fn(out, y)

            self._step = DistTrainStep(self._model, compute_loss, self._optimizer)
        return self._step

    def fit(self, train_data, epochs=1, batch_size=None, steps_per_epoch=None, log_freq=10, verbose=1, **kwargs):
        history = {"loss": []}
        step_fn = self._get_step()
        if epochs > 1 and iter(train_data) is iter(train_data):
            # one-shot iterator: materialize so epochs 2..N see data
            train_data = list(train_data)
        for epoch in range(epochs):
            for i, batch in enumerate(_iter_batches(train_data, batch_size)):
                loss = step_fn(*batch)
                history["loss"].append(float(np.asarray(raw(loss))))
                if verbose and i % log_freq == 0:
                    print(f"[Engine] epoch {epoch} step {i} loss "
                          f"{history['loss'][-1]:.5f}", file=sys.stderr)
                if steps_per_epoch is not None and i + 1 >= steps_per_epoch:
                    break
        return history

    def evaluate(self, valid_data, batch_size=None, steps=None, **kwargs):
        was_training = self._model.training
        self._model.eval()
        losses = []
        try:
            for i, batch in enumerate(_iter_batches(valid_data, batch_size)):
                *xs, y = batch
                out = self._model(*xs)
                losses.append(float(np.asarray(raw(self._loss(out, y)))))
                if steps is not None and i + 1 >= steps:
                    break
        finally:
            if was_training:
                self._model.train()
        return {"loss": float(np.mean(losses)) if losses else None}

    def predict(self, test_data, batch_size=None, steps=None, **kwargs):
        was_training = self._model.training
        self._model.eval()
        outs = []
        try:
            for i, batch in enumerate(_iter_batches(test_data, batch_size)):
                xs = batch if isinstance(batch, (list, tuple)) else (batch,)
                outs.append(self._model(*xs))
                if steps is not None and i + 1 >= steps:
                    break
        finally:
            if was_training:
                self._model.train()
        return outs

    def save(self, path, training=True):
        from ... import save as _save

        _save(self._model.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, strict=True, load_optimizer=True):
        from ... import load as _load

        self._model.set_state_dict(_load(path + ".pdparams"))
        if load_optimizer and self._optimizer is not None:
            import os

            if os.path.exists(path + ".pdopt"):
                self._optimizer.set_state_dict(_load(path + ".pdopt"))


def _iter_batches(data, batch_size):
    """Accept a DataLoader-like iterable, a list of batch tuples, or an
    (x, y) pair of whole arrays (sliced by batch_size)."""
    if hasattr(data, "__iter__") and not isinstance(data, (tuple, list)):
        yield from data
        return
    if isinstance(data, (tuple, list)):
        if data and isinstance(data[0], (tuple, list)):
            # materialized loader: [(x1, y1), (x2, y2), ...]
            yield from data
            return
        xs = [raw(d) if isinstance(d, Tensor) else np.asarray(d) for d in data]
        n = xs[0].shape[0]
        bs = batch_size or n
        for i in range(0, n, bs):
            yield tuple(Tensor(jax.numpy.asarray(x[i : i + bs])) for x in xs)
