"""Cost-model auto-parallel planner: pick (dp, mp, pp, sharding, schedule,
virtual_pp_degree, grad-comm bucket) layouts analytically.

Mesh choice has been manual via ``DistributedStrategy`` since fleet landed,
and every wrong guess costs a 4.7–7 s XLA compile (MULTICHIP_SCALING.json
``compile_s``) before the first step time can even be observed. Following
the Mesh-TensorFlow layout-cost formulation (arXiv:1811.02084) and the
weight-update sharding analysis of arXiv:2004.13336, this module scores
every divisibility-legal layout with a closed-form alpha-beta-gamma model

    step ≈ x · compute  +  y · wire_bytes  +  z · collective_launches

whose three constants are calibrated once against the measured proxy
entries in MULTICHIP_SCALING.json (``calibrate``). The terms per candidate:

  * **compute** — 6·params·tokens FLOPs inflated by the analytic pipeline
    bubble of the candidate's schedule, the exact formulas of
    ``SpmdPipeline.schedule_info`` (PR 8): fill = (S−1)/V,
    fb_total = 3M + 3·fill (gpipe/1f1b) or 3M + max(0, 2·fill − M)
    (zero_bubble), bubble = 1 − 3M/fb_total.
  * **wire_bytes** — per-axis analytic collective payloads mirroring the
    ``comm_analysis`` axis attribution recorded per entry: mp activation
    all-reduces, ZeRO all-gather/reduce-scatter on the sharding axis, dp
    gradient all-reduce, pp boundary activations (× virtual chunks). Axes
    that cross the slice boundary are charged at the ICI/DCN bandwidth
    ratio (``Topology.dcn_penalty``).
  * **collective_launches** — per-step collective count; the latency/
    dispatch term that separates many-small from few-large layouts.

The calibration entries are weak-scaling runs of ONE host emulating all n
devices, so the fitted constants are host-aggregate (cost terms sum over
devices, not per-device); the model form is identical on real hardware,
only the constants change.

``plan(model_config, topology)`` enumerates legal meshes (degrees divide
the device count, mp divides heads and hidden, pp divides layers, the
batch splits over dp·sharding), prunes candidates whose analytic
params + optimizer-state + activation footprint (remat-granularity aware)
exceeds the per-device memory bound, ranks the rest, and returns a
``Plan``. ``apply_auto_plan`` merges the winner into a
``DistributedStrategy`` — **manual settings always win**: any knob the
user moved off its default is pinned and constrains the search instead of
being overwritten. Opt-in via ``DistributedStrategy.auto()`` or
``PADDLE_TPU_AUTO_PLAN=1``.
"""
from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field, asdict, replace
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ... import observability as _obs

__all__ = [
    "ModelConfig", "Topology", "Candidate", "Plan", "CostConstants",
    "plan", "score", "calibrate", "load_calibration", "apply_auto_plan",
    "enumerate_candidates", "memory_bytes",
]


# ---------------------------------------------------------------------------
# inputs
# ---------------------------------------------------------------------------
@dataclass
class ModelConfig:
    """Workload shape for the cost model. Defaults mirror the scaling
    proxy's tiny GPT (``__graft_entry__._tiny_cfg``) so the calibration
    entries and the planner score the same model out of the box."""

    hidden: int = 64
    layers: int = 4
    heads: int = 4
    vocab: int = 256
    seq_len: int = 32
    global_batch: int = 16
    dtype_bytes: int = 4           # f32 master math on the proxy
    remat: str = "none"            # none | selective | full
    # assumed WIRE dtypes per collective family (f32 | bf16 | int8): what
    # actually crosses the mesh when grad_comm / mp_comm quantize the
    # exchange. Defaults are the exact f32 program, so the calibration
    # entries (measured unquantized) fit the same features as before;
    # ``apply_auto_plan`` fills them from the resolved strategy configs.
    mp_wire: str = "f32"           # mp activation recombination (mp_comm)
    grad_wire: str = "f32"         # dp grad exchange (grad_comm)
    zero_gather_wire: str = "f32"  # ZeRO param all-gather (mp_comm floor)

    @property
    def params(self) -> int:
        # transformer block ≈ 12·h² (qkv+proj 4h², mlp 8h²) + tied embed
        return 12 * self.layers * self.hidden ** 2 + self.vocab * self.hidden

    @property
    def tokens(self) -> int:
        return self.global_batch * self.seq_len

    @property
    def flops(self) -> float:
        # fwd+bwd ≈ 6 FLOPs per param per token (the PaLM rule of thumb)
        return 6.0 * self.params * self.tokens


@dataclass
class Topology:
    """Device fabric description. Bandwidths are per-chip link rates; the
    defaults are the TPU v4 constants used by scripts/scaling_model.py.
    ``host_serialized`` marks the CPU-emulation regime of the calibration
    proxy (all devices share one host, costs sum instead of parallelize) —
    it is informational; the fitted constants already absorb it."""

    n_devices: int = 8
    num_slices: int = 1
    ici_bw: float = 1.6e11         # bytes/s per chip over ICI
    dcn_bw: float = 3.1e9          # bytes/s per chip across slices
    peak_flops: float = 197e12     # bf16 per chip
    hbm_bytes: float = 32e9        # per chip
    hbm_bw: float = 1.2e12         # bytes/s per chip HBM (decode is bound
    #                                by it — prices the attn kernel choice)
    host_serialized: bool = True

    @property
    def dcn_penalty(self) -> float:
        """ICI-equivalent byte multiplier for slice-crossing traffic."""
        return self.ici_bw / self.dcn_bw


@dataclass
class Candidate:
    dp: int = 1
    mp: int = 1
    pp: int = 1
    sharding: int = 1
    schedule: str = "gpipe"
    virtual_pp_degree: int = 1
    microbatches: int = 1
    bucket_mb: int = 32
    # filled by score()
    predicted_step_s: float = 0.0
    breakdown: Dict[str, float] = field(default_factory=dict)
    # the wire dtypes the byte model priced each axis at (ModelConfig)
    wire_dtypes: Dict[str, str] = field(default_factory=dict)

    @property
    def ndev(self) -> int:
        return self.dp * self.mp * self.pp * self.sharding

    def mesh_dict(self) -> Dict[str, int]:
        return {"dp": self.dp, "mp": self.mp, "pp": self.pp,
                "sharding": self.sharding}


@dataclass
class CostConstants:
    """The calibrated cost constants (see module docstring).

    ``fixed_s`` is the per-step dispatch floor (host launch + program
    setup — why 8→16 devices scales sublinearly on the emulation host);
    ``sec_per_dp_over_byte`` charges the data-parallel gradient exchange
    at (dp−1)·payload per device, the all-gather-style overcommit the
    measured ``dp+sharding`` attribution shows (per-device bytes roughly
    double from dp=2 to dp=4) — why 16→32 scales superlinearly."""

    fixed_s: float = 0.0
    sec_per_flop: float = 2.0e-10       # CPU-proxy scale fallbacks
    sec_per_byte: float = 1.0e-8
    sec_per_collective: float = 1.0e-4
    sec_per_dp_over_byte: float = 0.0
    source: str = "defaults"
    max_rel_error: float = float("nan")

    def as_vector(self) -> np.ndarray:
        return np.asarray([self.fixed_s, self.sec_per_flop,
                           self.sec_per_byte, self.sec_per_collective,
                           self.sec_per_dp_over_byte], float)


@dataclass
class Plan:
    best: Candidate
    candidates: List[Candidate]
    pruned_memory: int
    constants: CostConstants
    plan_seconds: float


# ---------------------------------------------------------------------------
# analytic terms
# ---------------------------------------------------------------------------
def _bubble(cand: Candidate, mc: ModelConfig) -> float:
    """Analytic bubble fraction — the exact ``schedule_info`` formulas."""
    S, V = cand.pp, cand.virtual_pp_degree
    M = max(1, cand.microbatches)
    if S <= 1:
        return 0.0
    fill = (S - 1) / V
    if cand.schedule == "zero_bubble":
        fb_total = 3.0 * M + max(0.0, 2.0 * fill - M)
    else:
        fb_total = 3.0 * M + 3.0 * fill
    return 1.0 - 3.0 * M / fb_total


def _choose_microbatches(batch: int, requested: int) -> int:
    m = max(1, min(int(requested), int(batch)))
    while batch % m != 0:
        m -= 1
    return m


_WIRE_ITEMSIZE = {"f32": 4.0, "bf16": 2.0, "int8": 1.0}


def _axis_bytes(cand: Candidate, mc: ModelConfig) -> Dict[str, float]:
    """Per-device wire bytes per step, by mesh axis — the analytic mirror
    of the ``comm_analysis`` ``per_axis`` attribution. Ring collective of
    size k moves 2·(k−1)/k of the payload per participant. Each axis is
    priced at ITS wire dtype (ModelConfig.mp_wire/grad_wire/
    zero_gather_wire): a quantized exchange moves the wire itemsize, not
    f32 — with the f32 defaults the model is byte-identical to the
    pre-wire-aware one, so the calibration fit is unchanged."""

    def ring(k: int) -> float:
        return 2.0 * (k - 1) / k if k > 1 else 0.0

    it_mp = _WIRE_ITEMSIZE[mc.mp_wire]
    it_dp = _WIRE_ITEMSIZE[mc.grad_wire]
    it_zg = _WIRE_ITEMSIZE[mc.zero_gather_wire]
    local_batch = mc.global_batch / max(1, cand.dp * cand.sharding)
    act_elems = local_batch * mc.seq_len * mc.hidden
    out: Dict[str, float] = {}
    # mp: 2 fwd + 2 bwd activation recombinations per layer (attn out +
    # mlp out), each moving act_elems at the activation wire dtype
    out["mp"] = 4.0 * mc.layers * act_elems * it_mp * ring(cand.mp)
    # sharding (ZeRO): all-gather params fwd (activation-wire gathered,
    # bf16-floored by mp_comm) + reduce-scatter grads bwd (grad wire) over
    # the model-parallel shard each device owns
    shard_params = mc.params / max(1, cand.mp * cand.pp)
    out["sharding"] = shard_params * (it_zg + it_dp) * ring(cand.sharding)
    # dp: gradient all-reduce of the per-device grad shard at the grad wire
    grad_pd = mc.params * it_dp / max(1, cand.mp * cand.pp * cand.sharding)
    out["dp"] = grad_pd * ring(cand.dp)
    # pp: boundary activations per microbatch, fwd + bwd, × virtual chunks
    # (point-to-point sends stay at the compute dtype — not quantized)
    act = act_elems * mc.dtype_bytes
    if cand.pp > 1:
        out["pp"] = 2.0 * act * cand.virtual_pp_degree
    else:
        out["pp"] = 0.0
    return out


def _collective_count(cand: Candidate, mc: ModelConfig) -> float:
    """Collective launches per step per device — the latency term."""
    M = max(1, cand.microbatches)
    n = 0.0
    if cand.mp > 1:
        n += 4.0 * mc.layers * M
    if cand.sharding > 1:
        n += 2.0 * _n_buckets(cand, mc)
    if cand.dp > 1:
        n += _n_buckets(cand, mc)
    if cand.pp > 1:
        n += 2.0 * M * cand.virtual_pp_degree
    return n


def _n_buckets(cand: Candidate, mc: ModelConfig) -> float:
    grad_mb = mc.params * 4 / (max(1, cand.mp * cand.pp) * 2 ** 20)
    return max(1.0, np.ceil(grad_mb / max(1, cand.bucket_mb)))


def _features(cand: Candidate, mc: ModelConfig,
              topo: Topology) -> np.ndarray:
    """Cost feature vector in host-aggregate units, aligned with
    ``CostConstants.as_vector``: [1, flops, wire_bytes, launches,
    dp_overcommit_bytes]. Every variable term is stretched by the
    analytic pipeline bubble — collectives idle through the fill/drain
    just like compute does."""
    stretch = 1.0 / max(1e-9, 1.0 - _bubble(cand, mc))
    ax = _axis_bytes(cand, mc)
    dcn_axes = _slice_crossing_axes(cand, topo)
    wire = sum(
        b * (topo.dcn_penalty if a in dcn_axes else 1.0)
        for a, b in ax.items())
    # dp overcommit: the gradient exchange observed on the emulated
    # fabric moves (dp-1)·payload per device, not the ring-optimal
    # 2(dp-1)/dp — charged separately so calibration can weigh it
    grad_pd = mc.params * mc.dtype_bytes / max(
        1, cand.mp * cand.pp * cand.sharding)
    dp_over = grad_pd * max(0, cand.dp - 1)
    if "dp" in dcn_axes:
        dp_over *= topo.dcn_penalty
    n = cand.ndev
    return np.asarray([
        1.0,
        mc.flops * stretch,
        wire * n * stretch,
        _collective_count(cand, mc) * n * stretch,
        dp_over * n * stretch,
    ], float)


def _slice_crossing_axes(cand: Candidate, topo: Topology) -> set:
    """Axes whose groups straddle the slice boundary. Mesh order is
    (dp, pp, sharding, sep, mp) with dp outermost — with ≥2 slices the
    boundary cuts the outermost non-trivial axis."""
    if topo.num_slices <= 1:
        return set()
    for a, k in (("dp", cand.dp), ("pp", cand.pp),
                 ("sharding", cand.sharding), ("mp", cand.mp)):
        if k > 1:
            return {a}
    return set()


def memory_bytes(cand: Candidate, mc: ModelConfig) -> float:
    """Analytic per-device footprint: params + grads + AdamW moments
    (ZeRO-sharded) + activations under the remat granularity."""
    pbytes = mc.params * mc.dtype_bytes
    model_shard = max(1, cand.mp * cand.pp)
    params = pbytes / model_shard
    grads = pbytes / model_shard
    # two f32 moments, weight-update-sharded over the sharding axis
    opt = 2.0 * mc.params * 4.0 / (model_shard * max(1, cand.sharding))
    local_batch = mc.global_batch / max(1, cand.dp * cand.sharding)
    per_layer = local_batch * mc.seq_len * mc.hidden * mc.dtype_bytes
    layers_live = mc.layers / max(1, cand.pp)
    act_factor = {"none": 8.0, "selective": 3.0, "full": 1.0}.get(
        mc.remat, 8.0)
    acts = per_layer * layers_live * act_factor
    return params + grads + opt + acts


# ---------------------------------------------------------------------------
# scoring + calibration
# ---------------------------------------------------------------------------
def score(cand: Candidate, mc: ModelConfig, topo: Topology,
          consts: CostConstants) -> Candidate:
    """Fill ``predicted_step_s`` (+ term breakdown) on a copy of ``cand``."""
    f = _features(cand, mc, topo)
    v = consts.as_vector()
    names = ("fixed_s", "compute_s", "comm_s", "latency_s", "dp_over_s")
    out = replace(cand)
    out.breakdown = {k: float(fi * vi) for k, fi, vi in zip(names, f, v)}
    # record what the byte model assumed crossed each axis's wire, so a
    # plan explains WHY a quantized layout ranked where it did
    out.wire_dtypes = {"mp": mc.mp_wire, "dp": mc.grad_wire,
                       "zero_gather": mc.zero_gather_wire}
    out.predicted_step_s = float(f @ v)
    return out


def _entry_candidate(entry: Dict[str, Any]) -> Candidate:
    mesh = entry.get("mesh", {})
    pipe = entry.get("pipeline") or {}
    return Candidate(
        dp=int(mesh.get("dp", 1)), mp=int(mesh.get("mp", 1)),
        pp=int(mesh.get("pp", 1)), sharding=int(mesh.get("sharding", 1)),
        schedule=str(pipe.get("schedule", "gpipe")),
        virtual_pp_degree=int(pipe.get("virtual_pp_degree", 1)),
        microbatches=int(pipe.get("microbatches", 1)))


def _entry_model(entry: Dict[str, Any], mc: ModelConfig) -> ModelConfig:
    # weak-scaling convention of the proxy: 2 sequences per device
    return replace(mc, global_batch=2 * int(entry["n"]))


def _solve_nonneg(A: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Least squares with nonnegative coefficients, by exhaustive column
    subsets (5 columns → 31 subsets): negative constants would invert the
    ranking (rewarding comm-heavy layouts), so they are inadmissible.
    Residual ties (several subsets fit the few calibration points exactly)
    prefer solutions that keep the wire-byte term (column 2) — it is the
    term that differentiates mp/pp/sharding layouts at a fixed device
    count — and then more active terms."""
    ncol = A.shape[1]
    best, best_key = np.zeros(ncol), (np.inf, 1, 0)
    for mask in range(1, 2 ** ncol):
        cols = [j for j in range(ncol) if mask >> j & 1]
        sol, *_ = np.linalg.lstsq(A[:, cols], b, rcond=None)
        if np.any(sol < 0):
            continue
        full = np.zeros(ncol)
        full[cols] = sol
        res = float(np.linalg.norm(A @ full - b))
        key = (round(res, 9), 0 if (2 in cols and full[2] > 0) else 1,
               -int(np.count_nonzero(full)))
        if key < best_key:
            best, best_key = full, key
    return best


def calibrate(entries: Iterable[Dict[str, Any]],
              mc: Optional[ModelConfig] = None,
              topo: Optional[Topology] = None) -> CostConstants:
    """Fit (sec_per_flop, sec_per_byte, sec_per_collective) to the
    measured single-slice proxy entries. Uses the same analytic features
    the predictor uses, so the fit IS the prediction error on the
    calibration set (recorded as ``max_rel_error``)."""
    mc = mc or ModelConfig()
    rows, targets = [], []
    for e in entries:
        if e.get("two_slice") or not e.get("ok", True):
            continue
        cand = _entry_candidate(e)
        emc = _entry_model(e, mc)
        t = Topology(n_devices=int(e["n"]),
                     host_serialized=(topo or Topology()).host_serialized)
        rows.append(_features(cand, emc, t))
        targets.append(float(e["step_s"]))
    if len(rows) < 2:
        return CostConstants()
    A = np.asarray(rows, float)
    b = np.asarray(targets, float)
    sol = _solve_nonneg(A, b)
    if not np.any(sol > 0):
        return CostConstants()
    pred = A @ sol
    rel = float(np.max(np.abs(pred - b) / np.maximum(b, 1e-12)))
    return CostConstants(
        fixed_s=float(sol[0]), sec_per_flop=float(sol[1]),
        sec_per_byte=float(sol[2]), sec_per_collective=float(sol[3]),
        sec_per_dp_over_byte=float(sol[4]),
        source=f"MULTICHIP_SCALING.json ({len(rows)} entries)",
        max_rel_error=rel)


def _repo_scaling_json() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    root = os.path.dirname(os.path.dirname(os.path.dirname(here)))
    return os.path.join(root, "MULTICHIP_SCALING.json")


_CALIBRATION: Optional[CostConstants] = None


def load_calibration(path: Optional[str] = None,
                     mc: Optional[ModelConfig] = None) -> CostConstants:
    """Constants calibrated against MULTICHIP_SCALING.json (cached after
    the first load); ``CostConstants()`` defaults when the file is absent
    or unusable — the planner still ranks, just uncalibrated."""
    global _CALIBRATION
    if path is None and mc is None and _CALIBRATION is not None:
        return _CALIBRATION
    p = path or _repo_scaling_json()
    try:
        with open(p) as f:
            entries = json.load(f).get("results", [])
        consts = calibrate(entries, mc)
    except (OSError, ValueError, KeyError):
        consts = CostConstants()
    if path is None and mc is None:
        _CALIBRATION = consts
    return consts


# ---------------------------------------------------------------------------
# enumeration + the plan
# ---------------------------------------------------------------------------
def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_candidates(mc: ModelConfig, topo: Topology,
                         pinned: Optional[Dict[str, Any]] = None
                         ) -> List[Candidate]:
    """Every divisibility-legal layout for ``topo.n_devices``. ``pinned``
    freezes knobs the user set manually ({"mp": 2, "schedule": "1f1b"} …)."""
    pinned = pinned or {}
    n = topo.n_devices
    out: List[Candidate] = []

    def ok(knob: str, v: int) -> bool:
        return knob not in pinned or int(pinned[knob]) == v

    for mp in _divisors(n):
        if not ok("mp", mp):
            continue
        if mp > 1 and (mc.heads % mp or mc.hidden % mp):
            continue
        for pp in _divisors(n // mp):
            if not ok("pp", pp):
                continue
            if pp > 1 and mc.layers % pp:
                continue
            for sh in _divisors(n // (mp * pp)):
                if not ok("sharding", sh):
                    continue
                dp = n // (mp * pp * sh)
                if not ok("dp", dp):
                    continue
                if mc.global_batch % (dp * sh):
                    continue
                for cand in _schedule_variants(mc, dp, mp, pp, sh, pinned):
                    out.append(cand)
    return out


def _schedule_variants(mc: ModelConfig, dp: int, mp: int, pp: int, sh: int,
                       pinned: Dict[str, Any]) -> Iterable[Candidate]:
    local_batch = mc.global_batch // max(1, dp * sh)
    if pp <= 1:
        # no pipeline: the schedule knobs are inert, but a user-pinned
        # value must ride through un-clobbered (manual settings win)
        yield Candidate(dp=dp, mp=mp, pp=pp, sharding=sh,
                        schedule=str(pinned.get("schedule", "gpipe")),
                        virtual_pp_degree=int(
                            pinned.get("virtual_pp_degree", 1)),
                        microbatches=1)
        return
    schedules = ("gpipe", "1f1b", "zero_bubble")
    if "schedule" in pinned:
        schedules = (str(pinned["schedule"]),)
    virtuals = (1, 2)
    if "virtual_pp_degree" in pinned:
        virtuals = (int(pinned["virtual_pp_degree"]),)
    for sched in schedules:
        for v in virtuals:
            if mc.layers % (pp * v):
                continue
            m = _choose_microbatches(local_batch, pp)
            yield Candidate(dp=dp, mp=mp, pp=pp, sharding=sh,
                            schedule=sched, virtual_pp_degree=v,
                            microbatches=m)


def plan(model_config: Optional[ModelConfig] = None,
         topology: Optional[Topology] = None,
         pinned: Optional[Dict[str, Any]] = None,
         constants: Optional[CostConstants] = None) -> Plan:
    """Enumerate → memory-prune → score → rank. Raises ValueError when no
    legal candidate survives (degrees that cannot divide the devices, or a
    memory bound nothing fits under)."""
    t0 = time.perf_counter()
    mc = model_config or ModelConfig()
    topo = topology or Topology()
    consts = constants or load_calibration(mc=None)
    cands = enumerate_candidates(mc, topo, pinned)
    n_enumerated = len(cands)
    fitting = [c for c in cands if memory_bytes(c, mc) <= topo.hbm_bytes]
    pruned = n_enumerated - len(fitting)
    if not fitting:
        raise ValueError(
            f"auto-plan found no legal layout for ndev={topo.n_devices} "
            f"(enumerated {n_enumerated}, memory-pruned {pruned})")
    scored = sorted((score(c, mc, topo, consts) for c in fitting),
                    key=lambda c: c.predicted_step_s)
    dt = time.perf_counter() - t0
    best = scored[0]
    _obs.set_gauge("autoplan_candidates", n_enumerated)
    _obs.set_gauge("autoplan_pruned_memory", pruned)
    _obs.set_gauge("autoplan_predicted_step_seconds", best.predicted_step_s)
    _obs.observe("autoplan_plan_seconds", dt)
    _obs.event("autoplan", mesh=best.mesh_dict(), schedule=best.schedule,
               virtual_pp_degree=best.virtual_pp_degree,
               microbatches=best.microbatches,
               predicted_step_s=round(best.predicted_step_s, 6),
               candidates=n_enumerated, pruned_memory=pruned,
               calibration=consts.source)
    return Plan(best=best, candidates=scored, pruned_memory=pruned,
                constants=consts, plan_seconds=dt)


# ---------------------------------------------------------------------------
# DistributedStrategy integration (manual settings always win)
# ---------------------------------------------------------------------------
def _pinned_from_strategy(strategy) -> Dict[str, Any]:
    """Knobs the user moved off their defaults — the planner must not
    touch them. dp_degree in (-1, 0, 1) is 'auto' (fleet.init fills it)."""
    pinned: Dict[str, Any] = {}
    hc = strategy.hybrid_configs
    for knob, key in (("dp", "dp_degree"), ("mp", "mp_degree"),
                      ("pp", "pp_degree"), ("sharding", "sharding_degree")):
        v = int(hc.get(key, 1))
        if v > 1:
            pinned[knob] = v
    pc = strategy.pipeline_configs
    if str(pc.get("schedule", "gpipe")) != "gpipe":
        pinned["schedule"] = str(pc["schedule"])
    if int(pc.get("virtual_pp_degree", 1)) != 1:
        pinned["virtual_pp_degree"] = int(pc["virtual_pp_degree"])
    return pinned


def _coerce_model_config(obj) -> ModelConfig:
    if isinstance(obj, ModelConfig):
        return obj
    if isinstance(obj, dict):
        known = {k: v for k, v in obj.items()
                 if k in ModelConfig.__dataclass_fields__}
        return ModelConfig(**known)
    return ModelConfig()


def apply_auto_plan(strategy, ndev: int,
                    topology: Optional[Topology] = None) -> Optional[Plan]:
    """Fill the un-set layout knobs of ``strategy`` from the cost model.

    Called by ``fleet.init`` when ``strategy.auto_plan`` or
    ``PADDLE_TPU_AUTO_PLAN=1``. Never raises: a planner failure leaves the
    strategy exactly as the user wrote it (and returns None)."""
    try:
        raw = getattr(strategy, "auto_plan_configs", {}).get("model_config")
        mc = _coerce_model_config(raw)
        explicit_batch = isinstance(raw, ModelConfig) or (
            isinstance(raw, dict) and "global_batch" in raw)
        if not explicit_batch:
            # weak-scaling default: 2 sequences per device, like the proxy
            mc = replace(mc, global_batch=max(mc.global_batch, 2 * ndev))
        # price the wires the strategy will actually run with: grad_comm's
        # dp gradient wire and mp_comm's activation/ZeRO-gather wires
        from .. import grad_comm as _gc
        from .. import mp_comm as _mpc

        gcfg = _gc.resolve_config(strategy)
        wcfg = _mpc.resolve_config(strategy)
        mc = replace(
            mc,
            grad_wire=(gcfg.wire_dtype if gcfg.enable else mc.grad_wire),
            mp_wire=wcfg.act_wire or mc.mp_wire,
            zero_gather_wire=wcfg.param_gather_wire or mc.zero_gather_wire)
        topo = topology or Topology(
            n_devices=ndev,
            num_slices=int(os.environ.get("PADDLE_TPU_NUM_SLICES", "1")))
        result = plan(mc, topo, pinned=_pinned_from_strategy(strategy))
    except Exception:  # noqa: BLE001 — planning must never block init
        return None
    best = result.best
    hc = strategy.hybrid_configs
    hc["dp_degree"] = best.dp
    hc["mp_degree"] = best.mp
    hc["pp_degree"] = best.pp
    hc["sharding_degree"] = best.sharding
    pc = strategy.pipeline_configs
    pc["schedule"] = best.schedule
    pc["virtual_pp_degree"] = best.virtual_pp_degree
    pc["accumulate_steps"] = best.microbatches
    strategy.pipeline = best.pp > 1
    _obs.inc("autoplan_applied_total", ndev=ndev)
    return result


# ---------------------------------------------------------------------------
# MPMD stage plans: per-stage width candidates
# ---------------------------------------------------------------------------
# The SPMD planner above picks ONE (dp, mp, pp, sharding) for the whole
# program, so every pipeline stage gets the same data-parallel width. The
# MPMD executor (distributed/mpmd.py) lifts that restriction: each stage
# is its own compiled program on its own device subset, so a stack whose
# layers are unevenly expensive can give the heavy stage more devices.
# ``plan_mpmd_stages`` enumerates those per-stage widths, prices the
# bottleneck-stage tick with the same calibrated constants, and charges
# boundary respec traffic through ``reshard.plan_boundary`` at the
# RESOLVED wire dtype — the moved bytes of an int8 boundary are a quarter
# of an f32 one, which is exactly what the tensor-queue transport ships.

@dataclass
class StagePlan:
    """One MPMD layout candidate: per-stage widths + the layer split the
    runtime will actually use (contiguous, remainder to the front — the
    mirror of ``mpmd._partition``)."""

    widths: List[int] = field(default_factory=list)
    layer_split: List[Tuple[int, int]] = field(default_factory=list)
    microbatches: int = 1
    wire: str = "f32"
    # filled by _score_stage_plan()
    predicted_step_s: float = 0.0
    breakdown: Dict[str, float] = field(default_factory=dict)
    boundary_bytes: float = 0.0          # wire bytes per step, all boundaries
    stage_tick_s: List[float] = field(default_factory=list)

    @property
    def equal_width(self) -> bool:
        return len(set(self.widths)) <= 1

    def to_json(self) -> Dict[str, Any]:
        return {
            "widths": list(self.widths),
            "layer_split": [list(s) for s in self.layer_split],
            "microbatches": self.microbatches,
            "wire": self.wire,
            "predicted_step_s": self.predicted_step_s,
            "boundary_bytes": self.boundary_bytes,
            "breakdown": dict(self.breakdown),
        }


@dataclass
class MpmdPlan:
    best: StagePlan
    best_equal: Optional[StagePlan]
    candidates: List[StagePlan]
    constants: CostConstants
    plan_seconds: float


def _split_layers(n_layers: int, n_stages: int) -> List[Tuple[int, int]]:
    """Contiguous layer ranges per stage, remainder to the FRONT stages —
    must stay in lockstep with ``mpmd._partition`` so the planner prices
    the split the executor actually builds."""
    base, rem = divmod(n_layers, n_stages)
    out, lo = [], 0
    for s in range(n_stages):
        hi = lo + base + (1 if s < rem else 0)
        out.append((lo, hi))
        lo = hi
    return out


def _stage_compositions(n_devices: int, n_stages: int) -> List[List[int]]:
    """All ways to split ``n_devices`` into ``n_stages`` positive widths
    (order matters: stage 0's width is the first entry)."""
    if n_stages == 1:
        return [[n_devices]]
    out: List[List[int]] = []
    for w in range(1, n_devices - n_stages + 2):
        for rest in _stage_compositions(n_devices - w, n_stages - 1):
            out.append([w] + rest)
    return out


def _score_stage_plan(sp: StagePlan, mc: ModelConfig, topo: Topology,
                      consts: CostConstants,
                      layer_costs: List[float]) -> StagePlan:
    """Fill ``predicted_step_s`` on a copy of ``sp``.

    Tick model: ``sec_per_flop`` is calibrated in host-aggregate units
    (all ``n_devices`` participating), so a stage running its share on
    ``dp_i`` devices ticks at ``sec_per_flop · n · flops_i / dp_i``. A
    1f1b step is ``M + S − 1`` ticks of the BOTTLENECK stage — widening
    the heavy stage shrinks the max, which is the whole point of MPMD.
    Boundary traffic is priced per microbatch through
    ``reshard.plan_boundary`` (activation forward + cotangent backward)
    at the wire itemsize, plus a per-send collective-launch charge."""
    from ..reshard import plan_boundary as _plan_boundary

    S = len(sp.widths)
    M = max(1, sp.microbatches)
    total_cost = sum(layer_costs) or 1.0
    it = _WIRE_ITEMSIZE[sp.wire]
    # the host-serialized calibration can fit sec_per_flop to exactly 0
    # (compute gets attributed to the fixed/byte terms); widths would
    # then be indistinguishable, so fall back to the default proxy-scale
    # flop rate for the WIDTH decision — relative stage weights are what
    # matter here, not the absolute seconds
    spf = consts.sec_per_flop or CostConstants().sec_per_flop
    out = replace(sp)
    out.stage_tick_s = []
    for (lo, hi), dp in zip(sp.layer_split, sp.widths):
        frac = sum(layer_costs[lo:hi]) / total_cost
        flops_mb = mc.flops * frac / M
        out.stage_tick_s.append(
            spf * topo.n_devices * flops_mb / max(1, dp))
    tick = max(out.stage_tick_s) if out.stage_tick_s else 0.0
    compute_s = (M + S - 1) * tick
    mb_shape = (max(1, mc.global_batch // M), mc.seq_len, mc.hidden)
    boundary_b = 0.0
    for b in range(S - 1):
        lp = _plan_boundary(
            mb_shape, "float32", sp.widths[b], sp.widths[b + 1],
            wire_itemsize=int(it), key=f"act{b}")
        # activation fwd + cotangent bwd, every microbatch
        boundary_b += 2.0 * M * lp.moved_bytes
    boundary_s = consts.sec_per_byte * boundary_b
    latency_s = consts.sec_per_collective * 2.0 * M * (S - 1)
    out.boundary_bytes = float(boundary_b)
    out.breakdown = {"fixed_s": consts.fixed_s,
                     "compute_s": float(compute_s),
                     "boundary_s": float(boundary_s),
                     "latency_s": float(latency_s)}
    out.predicted_step_s = float(
        consts.fixed_s + compute_s + boundary_s + latency_s)
    return out


def plan_mpmd_stages(model_config: Optional[ModelConfig] = None,
                     topology: Optional[Topology] = None, *,
                     num_stages: int = 2,
                     wire: str = "f32",
                     layer_costs: Optional[List[float]] = None,
                     microbatches: Optional[int] = None,
                     constants: Optional[CostConstants] = None) -> MpmdPlan:
    """Enumerate per-stage width compositions for an MPMD pipeline and
    rank them by predicted step time.

    ``layer_costs`` gives each layer's relative compute weight (default
    uniform). On a balanced stack the equal-width composition wins; on an
    unbalanced one the planner shifts devices onto the bottleneck stage —
    ``MpmdPlan.best_equal`` keeps the best equal-width candidate around
    so callers (scripts/scaling_model.py) can record the A/B delta."""
    t0 = time.perf_counter()
    mc = model_config or ModelConfig()
    topo = topology or Topology()
    consts = constants or load_calibration(mc=None)
    if wire not in _WIRE_ITEMSIZE:
        raise ValueError(f"unknown wire {wire!r}; want one of "
                         f"{sorted(_WIRE_ITEMSIZE)}")
    if not 1 <= num_stages <= topo.n_devices:
        raise ValueError(
            f"num_stages={num_stages} needs 1..{topo.n_devices} stages")
    if num_stages > mc.layers:
        raise ValueError(
            f"num_stages={num_stages} exceeds {mc.layers} layers")
    costs = list(layer_costs) if layer_costs else [1.0] * mc.layers
    if len(costs) != mc.layers:
        raise ValueError(
            f"layer_costs has {len(costs)} entries for {mc.layers} layers")
    M = _choose_microbatches(mc.global_batch,
                             microbatches or 2 * num_stages)
    split = _split_layers(mc.layers, num_stages)
    cands = [
        _score_stage_plan(
            StagePlan(widths=w, layer_split=split, microbatches=M,
                      wire=wire),
            mc, topo, consts, costs)
        for w in _stage_compositions(topo.n_devices, num_stages)
    ]
    # ties break toward balanced widths (smaller spread), then lexicographic
    cands.sort(key=lambda sp: (sp.predicted_step_s,
                               max(sp.widths) - min(sp.widths),
                               tuple(sp.widths)))
    best = cands[0]
    equal = [sp for sp in cands if sp.equal_width]
    best_equal = equal[0] if equal else None
    dt = time.perf_counter() - t0
    _obs.observe("autoplan_plan_seconds", dt)
    _obs.event("autoplan", variant="mpmd", widths=list(best.widths),
               microbatches=M, wire=wire,
               predicted_step_s=round(best.predicted_step_s, 6),
               candidates=len(cands), calibration=consts.source)
    return MpmdPlan(best=best, best_equal=best_equal, candidates=cands,
                    constants=consts, plan_seconds=dt)


# ---------------------------------------------------------------------------
# attention-kernel pricing (docs/AUTOPLAN.md §attention kernel,
# docs/SERVING.md §kernel plane)
# ---------------------------------------------------------------------------

@dataclass
class AttnKernelPlan:
    """Analytic HBM-traffic comparison of the two paged-attention
    implementations for ONE batched decode/verify step (all layers).

    ``einsum_bytes`` models the XLA oracle: (int8 only) a whole-pool
    dequant pass per layer, the gathered K/V pages written and re-read as
    f32, and the dense logits tensor round-tripped through HBM.
    ``pallas_bytes`` models the fused kernel: the gathered pages stream
    HBM→VMEM once at their STORED dtype (+ absmax scales when int8);
    logits, softmax stats, and the accumulator never leave VMEM.
    Decode is HBM-bound, so predicted step times are bytes / hbm_bw."""

    choice: str                    # cheaper side: "pallas" | "einsum"
    selected: Optional[str]        # what the engine actually resolved
    einsum_bytes: float
    pallas_bytes: float
    einsum_step_s: float
    pallas_step_s: float
    bytes_saved: float             # einsum_bytes - pallas_bytes

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


def plan_attn_kernel(*, num_slots: int, max_pages: int, kv_heads: int,
                     query_heads: int, page_size: int, head_dim: int,
                     layers: int, kv_dtype: str = "f32", t: int = 1,
                     num_pages: Optional[int] = None,
                     selected: Optional[str] = None,
                     topology: Optional[Topology] = None) -> AttnKernelPlan:
    """Price the engine's paged-attention kernel choice per decode step.

    Mirrors the serving geometry (EngineConfig + decode adapter): S slots
    each gathering ``max_pages`` pages of ``page_size`` tokens over
    ``kv_heads`` kv heads (GQA: ``query_heads`` fold onto them, free in
    both paths), ``t`` query rows per slot (1 = decode, k+1 = verify).
    ``num_pages`` sizes the int8 whole-pool dequant pass the einsum path
    pays (default: the slots' worst-case footprint). Emits the standard
    ``autoplan`` event with ``variant="attn_kernel"`` so the decision —
    and what the engine actually selected — lands in telemetry."""
    if kv_dtype not in _WIRE_ITEMSIZE:
        raise ValueError(f"unknown kv_dtype {kv_dtype!r}; want one of "
                         f"{sorted(_WIRE_ITEMSIZE)}")
    topo = topology or Topology()
    it = _WIRE_ITEMSIZE[kv_dtype]
    int8 = kv_dtype == "int8"
    pool_pages = num_pages if num_pages is not None else (
        1 + num_slots * max_pages)
    gathered = num_slots * max_pages * page_size * kv_heads * head_dim
    logits = num_slots * query_heads * t * max_pages * page_size

    # oracle: (int8) dequant pass reads the stored pool and writes it
    # f32; the gather writes + the einsum re-reads gathered f32 K AND V;
    # the masked logits round-trip HBM (write + softmax read)
    dequant = (2 * pool_pages * kv_heads * page_size * head_dim * (it + 4.0)
               if int8 else 0.0)
    gather_src = 4.0 if int8 else it   # gathers read the dequantized pool
    einsum_bytes = layers * (
        dequant
        + 2 * gathered * (gather_src + 2 * 4.0)
        + 2 * logits * 4.0)
    # fused kernel: pages stream once at stored width (+ scale vectors)
    scales = (2 * num_slots * max_pages * page_size * kv_heads * 4.0
              if int8 else 0.0)
    pallas_bytes = layers * (2 * gathered * it + scales)

    plan_ = AttnKernelPlan(
        choice="pallas" if pallas_bytes <= einsum_bytes else "einsum",
        selected=selected,
        einsum_bytes=float(einsum_bytes),
        pallas_bytes=float(pallas_bytes),
        einsum_step_s=float(einsum_bytes / topo.hbm_bw),
        pallas_step_s=float(pallas_bytes / topo.hbm_bw),
        bytes_saved=float(einsum_bytes - pallas_bytes),
    )
    _obs.event("autoplan", variant="attn_kernel", choice=plan_.choice,
               selected=selected, kv_dtype=kv_dtype,
               einsum_bytes=plan_.einsum_bytes,
               pallas_bytes=plan_.pallas_bytes,
               predicted_einsum_step_s=round(plan_.einsum_step_s, 9),
               predicted_pallas_step_s=round(plan_.pallas_step_s, 9))
    return plan_
