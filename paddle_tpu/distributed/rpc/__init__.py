"""paddle.distributed.rpc — worker-to-worker RPC.

Reference capability: ``python/paddle/distributed/rpc/`` (init_rpc /
rpc_sync / rpc_async / get_worker_info / shutdown), which Paddle builds on a
C++ brpc agent. TPU-native reshape: the control plane is host-side Python —
TPU compute never rides the RPC path (collectives compile into XLA programs;
SURVEY.md §2.3 "Comm APIs") — so the agent here is a thread-pool TCP server
per worker plus the existing TCPStore for endpoint rendezvous. Payloads are
pickled ``(fn, args, kwargs)``; results (or remote exceptions, re-raised at
the caller) are pickled back on the same connection.

Only functions importable at the callee (module-level functions, their
partials, and picklable callables) can be sent — same contract as the
reference, which serializes the function by qualified name via cloudpickle.

Trust model (same as the reference's brpc agent): every worker executes
callables sent by any peer that can reach its port — RPC is for workers of
ONE job on a trusted cluster network. Do not expose agent ports beyond the
job's network boundary.
"""
from __future__ import annotations

import os
import pickle
import socket
import socketserver
import struct
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass

__all__ = [
    "init_rpc",
    "rpc_sync",
    "rpc_async",
    "shutdown",
    "get_worker_info",
    "get_all_worker_infos",
    "get_current_worker_info",
    "WorkerInfo",
]

_HDR = struct.Struct("!Q")


@dataclass(frozen=True)
class WorkerInfo:
    """Mirrors the reference's WorkerInfo (name, rank, ip, port)."""

    name: str
    rank: int
    ip: str
    port: int


def _send_frame(sock, payload: bytes) -> None:
    # two sendalls instead of one concatenation: never copies the
    # (possibly multi-MB pickled) payload into a fresh buffer
    sock.sendall(_HDR.pack(len(payload)))
    sock.sendall(payload)


def _recv_frame(sock) -> bytes:
    buf = b""
    while len(buf) < _HDR.size:
        chunk = sock.recv(_HDR.size - len(buf))
        if not chunk:
            raise ConnectionError("rpc: peer closed during header")
        buf += chunk
    (n,) = _HDR.unpack(buf)
    out = bytearray()
    while len(out) < n:
        chunk = sock.recv(min(1 << 20, n - len(out)))
        if not chunk:
            raise ConnectionError("rpc: peer closed during body")
        out += chunk
    return bytes(out)


class _AgentServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class _AgentHandler(socketserver.BaseRequestHandler):
    def handle(self):
        try:
            req = _recv_frame(self.request)
        except ConnectionError:
            return
        try:
            fn, args, kwargs = pickle.loads(req)
            result = ("ok", fn(*args, **kwargs))
        except BaseException as e:  # remote exceptions travel to the caller
            result = ("err", e)
        try:
            reply = pickle.dumps(result)
        except BaseException as e:  # unpicklable result/exception (TypeError,
            # PicklingError, recursion, ...): report instead of dropping the
            # connection and surfacing an opaque ConnectionError at the caller
            reply = pickle.dumps(("err", RuntimeError(f"rpc reply failed: {e}")))
        try:
            _send_frame(self.request, reply)
        except OSError:
            pass


class _Agent:
    def __init__(self, name, rank, world_size, store, server, workers):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.store = store
        self.server = server
        self.workers = workers  # name -> WorkerInfo
        self.pool = ThreadPoolExecutor(
            max_workers=int(os.environ.get("PADDLE_RPC_CLIENT_THREADS", "8")),
            thread_name_prefix="rpc-client",
        )


_agent: _Agent | None = None
_lock = threading.Lock()


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """Start this worker's RPC agent and rendezvous with the others.

    ``name`` must be unique per worker. ``rank``/``world_size``/
    ``master_endpoint`` default to the launch env
    (``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM`` / ``PADDLE_MASTER``).
    Rank 0 hosts the TCPStore; every worker publishes its (name, ip, port)
    and blocks until the full worker table is known.
    """
    global _agent
    from ...runtime import TCPStore

    with _lock:
        if _agent is not None:
            raise RuntimeError("init_rpc called twice (call shutdown() first)")
        rank = int(os.environ["PADDLE_TRAINER_ID"] if rank is None else rank)
        world_size = int(
            os.environ["PADDLE_TRAINERS_NUM"] if world_size is None else world_size
        )
        if master_endpoint is None:
            # PADDLE_MASTER itself is the JAX distributed coordinator's
            # port and +1 is the launcher's rank-negotiation store (see
            # launch()); the rpc store rendezvous on +2 so all three can
            # coexist in one launch-managed job
            host, sport = os.environ["PADDLE_MASTER"].rsplit(":", 1)
            master_endpoint = f"{host}:{int(sport) + 2}"
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} outside world_size {world_size}")

        server = _AgentServer(("0.0.0.0", 0), _AgentHandler)
        port = server.server_address[1]
        threading.Thread(target=server.serve_forever, daemon=True).start()

        store = None
        try:
            host, sport = master_endpoint.rsplit(":", 1)
            store = TCPStore(
                host=host, port=int(sport), is_master=rank == 0
            )
            ip = _self_ip(host)
            store.set(f"__rpc/worker/{rank}", pickle.dumps((name, rank, ip, port)))

            workers = {}
            for r in range(world_size):
                info = WorkerInfo(
                    *pickle.loads(store.get(f"__rpc/worker/{r}", 120.0))
                )
                if info.name in workers:
                    raise ValueError(f"duplicate rpc worker name {info.name!r}")
                workers[info.name] = info
        except BaseException:
            # failed rendezvous must not leak the bound agent port / server
            # thread / store connection (a retry would stack leaked servers)
            server.shutdown()
            server.server_close()
            if store is not None:
                store.close()
            raise
        _agent = _Agent(name, rank, world_size, store, server, workers)


def _self_ip(master_host: str) -> str:
    if master_host in ("127.0.0.1", "localhost"):
        return "127.0.0.1"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((master_host, 1))
        return s.getsockname()[0]
    except OSError:
        return socket.gethostbyname(socket.gethostname())
    finally:
        s.close()


def _require_agent() -> _Agent:
    if _agent is None:
        raise RuntimeError("rpc is not initialized; call init_rpc first")
    return _agent


def _call(info: WorkerInfo, payload: bytes, timeout: float):
    with socket.create_connection(
        (info.ip, info.port), timeout=None if timeout <= 0 else timeout
    ) as sock:
        _send_frame(sock, payload)
        reply = _recv_frame(sock)
    try:
        status, value = pickle.loads(reply)
    except BaseException as e:
        # e.g. the remote exception's class isn't importable here — surface
        # a decodable error instead of losing the reply entirely
        raise RuntimeError(
            f"rpc reply from {info.name!r} undecodable: {type(e).__name__}: {e}"
        ) from e
    if status == "err":
        raise value
    return value


def rpc_sync(to, fn, args=None, kwargs=None, timeout=-1):
    """Run ``fn(*args, **kwargs)`` on worker ``to``; returns its result.

    Remote exceptions re-raise here. ``timeout`` <= 0 means wait forever
    (reference default ``timeout=-1``).
    """
    return rpc_async(to, fn, args, kwargs, timeout).result()


def rpc_async(to, fn, args=None, kwargs=None, timeout=-1) -> Future:
    """Like rpc_sync but returns a ``concurrent.futures.Future``.

    The reference returns its own FutureWrapper with ``.wait()``; a stdlib
    Future exposes ``.result()``, and ``.wait`` is aliased for parity.
    """
    agent = _require_agent()
    if to not in agent.workers:
        raise ValueError(f"unknown rpc worker {to!r} (have {sorted(agent.workers)})")
    payload = pickle.dumps((fn, tuple(args or ()), dict(kwargs or {})))
    fut = agent.pool.submit(_call, agent.workers[to], payload, float(timeout))
    fut.wait = fut.result  # reference-API alias
    return fut


def get_worker_info(name) -> WorkerInfo:
    return _require_agent().workers[name]


def get_all_worker_infos():
    return sorted(_require_agent().workers.values(), key=lambda w: w.rank)


def get_current_worker_info() -> WorkerInfo:
    agent = _require_agent()
    return agent.workers[agent.name]


def shutdown():
    """Graceful barrier + teardown: every worker arrives before any server
    stops, so no in-flight rpc can hit a dead agent (the reference's
    ``shutdown`` has the same all-gather semantics)."""
    global _agent
    with _lock:
        if _agent is None:
            return
        agent, _agent = _agent, None
    # drain OUR outbound calls before the barrier: a queued rpc_async must
    # reach its peer while every server is still guaranteed alive
    agent.pool.shutdown(wait=True)
    store = agent.store
    try:
        # master-closes-last rendezvous: the rank-0 store server must
        # outlive every client's final request
        store.asymmetric_handshake(
            "__rpc/shutdown", agent.rank, agent.world_size, 120.0
        )
    finally:
        # a crashed peer (handshake timeout) must not leak our server
        # thread / bound port / store connection
        agent.server.shutdown()
        agent.server.server_close()
        store.close()
