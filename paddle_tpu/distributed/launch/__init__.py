"""Launch CLI (python -m paddle_tpu.distributed.launch).

Reference (SURVEY.md §3.5): `paddle.distributed.launch` spawns one process
per GPU with PADDLE_TRAINER_ID / endpoints env and watches them.

TPU-native design: one process per *host*; devices are discovered by PJRT.
Single-host: exec the script directly (all local chips visible). Multi-host:
set the JAX coordination env (coordinator address, process id/count) from
the same PADDLE_* env names the reference launcher uses, so Paddle-style
cluster tooling keeps working, then exec the script — rendezvous happens in
`init_parallel_env` via `jax.distributed.initialize`.
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys


def build_parser():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--nnodes", type=str, default="1", help="number of hosts")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="accepted for parity; on TPU one process drives all local chips")
    p.add_argument("--master", type=str, default=None, help="coordinator host:port")
    p.add_argument("--rank", type=int, default=None, help="this host's process id")
    p.add_argument("--ips", type=str, default=None, help="comma-separated host ips (parity)")
    p.add_argument("--devices", "--gpus", "--xpus", type=str, default=None, dest="devices")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p


def launch(argv=None):
    args = build_parser().parse_args(argv)
    nnodes = int(str(args.nnodes).split(":")[0])
    if nnodes > 1:
        if not args.master:
            raise SystemExit("--master host:port is required for multi-host launch")
        os.environ.setdefault("JAX_COORDINATOR_ADDRESS", args.master)
        os.environ.setdefault("PADDLE_MASTER", args.master)
        os.environ.setdefault("PADDLE_TRAINERS_NUM", str(nnodes))
        os.environ.setdefault("JAX_NUM_PROCESSES", str(nnodes))
        rank = args.rank if args.rank is not None else int(os.environ.get("PADDLE_TRAINER_ID", 0))
        os.environ["PADDLE_TRAINER_ID"] = str(rank)
        os.environ["JAX_PROCESS_ID"] = str(rank)
    sys.argv = [args.training_script] + list(args.training_script_args)
    runpy.run_path(args.training_script, run_name="__main__")
