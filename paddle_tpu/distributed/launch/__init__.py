"""Launch CLI (python -m paddle_tpu.distributed.launch).

Reference (SURVEY.md §3.5): `paddle.distributed.launch` spawns one process
per GPU with PADDLE_TRAINER_ID / endpoints env and watches them.

TPU-native design: one process per *host*; devices are discovered by PJRT.
Single-host: exec the script directly (all local chips visible). Multi-host:
set the JAX coordination env (coordinator address, process id/count) from
the same PADDLE_* env names the reference launcher uses, so Paddle-style
cluster tooling keeps working, then exec the script — rendezvous happens in
`init_parallel_env` via `jax.distributed.initialize`.
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys


def build_parser():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--nnodes", type=str, default="1", help="number of hosts")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="accepted for parity; on TPU one process drives all local chips")
    p.add_argument("--master", type=str, default=None, help="coordinator host:port")
    p.add_argument("--rank", type=int, default=None, help="this host's process id")
    p.add_argument("--ips", type=str, default=None, help="comma-separated host ips (parity)")
    p.add_argument("--devices", "--gpus", "--xpus", type=str, default=None, dest="devices")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p


def negotiate_rank(master: str, nnodes: int, timeout: float = 300.0):
    """Negotiate this host's process id through the TCPStore at `master`.

    Mirrors the reference's rank-table exchange (SURVEY.md §3.5 step 2: the
    master negotiates a global rank table over its KV endpoint). The host
    that binds the master port becomes process 0 and runs the store server;
    every other host draws the next id from a shared counter. Returns
    (rank, store) — the store stays alive for user-level barriers.
    """
    from ...runtime import TCPStore

    host, port = master.rsplit(":", 1)
    try:
        store = TCPStore(host=host, port=int(port), is_master=True, timeout=5.0)
        rank = 0
    except (ConnectionError, OSError):
        store = TCPStore(host=host, port=int(port), is_master=False, timeout=timeout)
        rank = store.add("__launch/rank_counter", 1)
    if rank >= nnodes:
        store.close()
        raise RuntimeError(
            f"negotiate_rank: {rank + 1} processes joined a {nnodes}-node job "
            f"at {master} — stale store or wrong --nnodes"
        )
    # Asymmetric handshake: clients finish with an acknowledged `set` (no
    # trailing request left in flight), the master finishes with `wait`s for
    # every client ack — so the master cannot tear the store down (by
    # exiting) while any client still has an unanswered request. A symmetric
    # counter barrier is racy here: the master may pass it and exit before a
    # slow client's final wait reaches the server.
    if rank == 0:
        for r in range(1, nnodes):
            store.wait(f"__launch/arrived/{r}", timeout)
        store.set("__launch/go", b"1")
        for r in range(1, nnodes):
            store.wait(f"__launch/ack/{r}", timeout)
    else:
        store.set(f"__launch/arrived/{rank}", b"1")
        store.wait("__launch/go", timeout)
        store.set(f"__launch/ack/{rank}", b"1")
    return rank, store


def launch(argv=None):
    args = build_parser().parse_args(argv)
    nnodes = int(str(args.nnodes).split(":")[0])
    if nnodes > 1:
        if not args.master:
            raise SystemExit("--master host:port is required for multi-host launch")
        os.environ.setdefault("JAX_COORDINATOR_ADDRESS", args.master)
        os.environ.setdefault("PADDLE_MASTER", args.master)
        os.environ.setdefault("PADDLE_TRAINERS_NUM", str(nnodes))
        os.environ.setdefault("JAX_NUM_PROCESSES", str(nnodes))
        if args.rank is not None:
            rank = args.rank
        elif "PADDLE_TRAINER_ID" in os.environ:
            rank = int(os.environ["PADDLE_TRAINER_ID"])
        else:
            # Negotiate on master_port+1: the master port itself belongs to
            # the JAX distributed coordinator that init_parallel_env starts
            # (a store server still bound there would make process 0's
            # coordinator bind fail). The store is closed before the user
            # script runs — the asymmetric handshake guarantees no client
            # has an outstanding request by then.
            host, port = args.master.rsplit(":", 1)
            rank, _store = negotiate_rank(f"{host}:{int(port) + 1}", nnodes)
            _store.close()
        os.environ["PADDLE_TRAINER_ID"] = str(rank)
        os.environ["JAX_PROCESS_ID"] = str(rank)
    sys.argv = [args.training_script] + list(args.training_script_args)
    runpy.run_path(args.training_script, run_name="__main__")
