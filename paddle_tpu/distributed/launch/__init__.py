"""Launch CLI (python -m paddle_tpu.distributed.launch).

Reference (SURVEY.md §3.5): `paddle.distributed.launch` spawns worker
processes with PADDLE_TRAINER_ID / endpoints env, installs a watch loop,
and (elastic mode, `launch/controllers/`) relaunches failed pods with
bounded retries; training resumes from the latest checkpoint.

TPU-native design: one worker process per *host*; devices are discovered
by PJRT. The launcher negotiates this host's rank (multi-host), sets the
JAX coordination env from the same PADDLE_* names the reference uses, then
SPAWNS the script as a child process and watches it: nonzero exit →
bounded-retry relaunch (``--max_restarts``, PADDLE_RESTART_COUNT exported
to the worker), rc=0 → clean exit. Fault recovery is checkpoint-resume
(`fleet.elastic.ElasticManager` in the training script), not rank
replacement — TPU slices fail as a unit (SURVEY.md §7 "Elastic").
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

from ... import observability as _obs


def build_parser():
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--nnodes", type=str, default="1", help="number of hosts")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="accepted for parity; on TPU one process drives all local chips")
    p.add_argument("--master", type=str, default=None, help="coordinator host:port")
    p.add_argument("--rank", type=int, default=None, help="this host's process id")
    p.add_argument("--ips", type=str, default=None, help="comma-separated host ips (parity)")
    p.add_argument("--devices", "--gpus", "--xpus", type=str, default=None, dest="devices")
    p.add_argument("--job_id", type=str, default="default")
    p.add_argument("--log_dir", type=str, default="log")
    p.add_argument("--max_restarts", type=int,
                   default=int(os.environ.get("PADDLE_ELASTIC_MAX_RESTARTS", "0")),
                   help="bounded-retry relaunch count on nonzero worker exit "
                        "(reference: elastic controllers' restart budget)")
    p.add_argument("--restart_backoff", type=float, default=1.0,
                   help="base seconds between relaunches (doubles per retry, capped)")
    p.add_argument("--rdzv_timeout", type=float,
                   default=float(os.environ.get("PADDLE_RDZV_TIMEOUT", "300")),
                   help="seconds to wait for all hosts at the rank-negotiation "
                        "rendezvous before failing with a diagnosis")
    p.add_argument("--heartbeat_interval", type=float,
                   default=float(os.environ.get("PADDLE_HEARTBEAT_INTERVAL", "0")),
                   help="seconds between liveness beats; >0 arms the hung-rank "
                        "watchdog in every worker (PADDLE_HEARTBEAT_MISS beats "
                        "of silence fail the job loudly). 0 disables.")
    p.add_argument("--serving_master", type=str, default=None,
                   help="host:port of a serving coordination store; exported "
                        "as PADDLE_SERVING_MASTER so a supervised "
                        "serving.worker registers there (a relaunch after "
                        "--max_restarts joins as a FRESH engine index — the "
                        "router fails over the dead one's work meanwhile)")
    p.add_argument("--mpmd_stages", type=str, default=None,
                   help="comma-separated per-stage device widths for the "
                        "MPMD pipeline executor (e.g. '3,1'); exported as "
                        "PADDLE_TPU_MPMD_STAGES so distributed.mpmd."
                        "MpmdPipeline picks the stage widths up without "
                        "a script change — and a relaunch after a stage "
                        "failure re-enters with the SAME stage layout")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p


def negotiate_rank(master: str, nnodes: int, timeout: float = 300.0):
    """Negotiate this host's process id through the TCPStore at `master`.

    Mirrors the reference's rank-table exchange (SURVEY.md §3.5 step 2: the
    master negotiates a global rank table over its KV endpoint). The host
    that binds the master port becomes process 0 and runs the store server;
    every other host draws the next id from a shared counter. Returns
    (rank, store) — the store stays alive for user-level barriers.
    """
    from ...runtime import TCPStore

    host, port = master.rsplit(":", 1)
    try:
        store = TCPStore(host=host, port=int(port), is_master=True, timeout=5.0)
        rank = 0
    except (ConnectionError, OSError):
        store = TCPStore(host=host, port=int(port), is_master=False, timeout=timeout)
        rank = store.add("__launch/rank_counter", 1)
    if rank >= nnodes:
        store.close()
        raise RuntimeError(
            f"negotiate_rank: {rank + 1} processes joined a {nnodes}-node job "
            f"at {master} — stale store or wrong --nnodes"
        )
    # master-closes-last rendezvous (see TCPStore.asymmetric_handshake for
    # why a symmetric counter barrier is racy here)
    store.asymmetric_handshake("__launch", rank, nnodes, timeout)
    return rank, store


def _supervise(cmd, env, max_restarts: int, backoff: float) -> int:
    """Spawn the worker, watch it, relaunch on nonzero exit with bounded
    retries (the reference launch controllers' watch loop, SURVEY.md §3.5
    steps 3-4). SIGTERM/SIGINT are forwarded to the worker AND latched:
    an operator kill tears the job down (no relaunch of a deliberately
    killed worker) instead of orphaning or restarting it."""
    attempt = 0
    child = None
    stop: dict = {}

    def forward(signum, frame):
        stop["sig"] = signum
        if child is not None and child.poll() is None:
            child.send_signal(signum)

    old_term = signal.signal(signal.SIGTERM, forward)
    old_int = signal.signal(signal.SIGINT, forward)
    try:
        while True:
            env["PADDLE_RESTART_COUNT"] = str(attempt)
            child = subprocess.Popen(cmd, env=env)
            if stop:
                # a kill latched between handler installation / the backoff
                # check and Popen would otherwise leave this worker running
                # to completion
                child.send_signal(stop["sig"])
            rc = child.wait()
            if stop:
                return 128 + stop["sig"]
            if rc == 0:
                return 0
            if attempt >= max_restarts:
                if max_restarts:
                    print(
                        f"[launch] worker exited rc={rc}; restart budget "
                        f"({max_restarts}) exhausted", file=sys.stderr)
                # conventional status for signal deaths (e.g. 137 for OOM's
                # SIGKILL), not python's 256+rc wraparound
                return 128 - rc if rc < 0 else rc
            attempt += 1
            delay = min(backoff * (2 ** (attempt - 1)), 30.0)
            _obs.inc("elastic_relaunch_total")
            _obs.event("worker_relaunch", rc=rc, attempt=attempt,
                       max_restarts=max_restarts, backoff=round(delay, 3))
            print(
                f"[launch] worker exited rc={rc}; relaunching "
                f"({attempt}/{max_restarts}) in {delay:.1f}s — training "
                "should resume from the latest checkpoint "
                "(fleet.elastic.ElasticManager)", file=sys.stderr)
            # interruptible backoff: a kill during the wait must stop the
            # job, not be swallowed by PEP-475 sleep resumption
            deadline = time.monotonic() + delay
            while not stop and time.monotonic() < deadline:
                time.sleep(min(0.2, max(0.0, deadline - time.monotonic())))
            if stop:
                return 128 + stop["sig"]
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)


def launch(argv=None):
    args = build_parser().parse_args(argv)
    nnodes = int(str(args.nnodes).split(":")[0])
    if nnodes > 1:
        if not args.master:
            raise SystemExit("--master host:port is required for multi-host launch")
        os.environ.setdefault("JAX_COORDINATOR_ADDRESS", args.master)
        os.environ.setdefault("PADDLE_MASTER", args.master)
        os.environ.setdefault("PADDLE_TRAINERS_NUM", str(nnodes))
        os.environ.setdefault("JAX_NUM_PROCESSES", str(nnodes))
        if args.rank is not None:
            rank = args.rank
        elif "PADDLE_TRAINER_ID" in os.environ:
            rank = int(os.environ["PADDLE_TRAINER_ID"])
        else:
            # Negotiate on master_port+1: the master port itself belongs to
            # the JAX distributed coordinator that init_parallel_env starts
            # (a store server still bound there would make process 0's
            # coordinator bind fail). The store is closed before the user
            # script runs — the asymmetric handshake guarantees no client
            # has an outstanding request by then.
            host, port = args.master.rsplit(":", 1)
            try:
                rank, _store = negotiate_rank(f"{host}:{int(port) + 1}",
                                              nnodes, timeout=args.rdzv_timeout)
            except TimeoutError as e:
                raise SystemExit(
                    f"[launch] rendezvous failed after {args.rdzv_timeout:.0f}s: "
                    f"{e}\n[launch] every host must run the same launch command "
                    f"with --nnodes={nnodes} and --master={args.master} "
                    "(raise PADDLE_RDZV_TIMEOUT / --rdzv_timeout for slow "
                    "cluster starts)") from e
            _store.close()
        os.environ["PADDLE_TRAINER_ID"] = str(rank)
        os.environ["JAX_PROCESS_ID"] = str(rank)
    if args.heartbeat_interval > 0:
        # workers read these in init_parallel_env (runtime.watchdog)
        os.environ["PADDLE_HEARTBEAT_INTERVAL"] = str(args.heartbeat_interval)
        os.environ.setdefault("PADDLE_HEARTBEAT_MISS", "5")
    if args.serving_master:
        # serving.worker's --master defaults to this env var
        os.environ["PADDLE_SERVING_MASTER"] = args.serving_master
    if args.mpmd_stages:
        # validate here so a typo fails the LAUNCH, not the Nth relaunch
        widths = [int(w) for w in args.mpmd_stages.split(",") if w.strip()]
        if not widths or any(w < 1 for w in widths):
            raise SystemExit(
                f"--mpmd_stages={args.mpmd_stages!r}: want comma-separated "
                "positive per-stage widths, e.g. '2,2' or '3,1'")
        os.environ["PADDLE_TPU_MPMD_STAGES"] = ",".join(str(w) for w in widths)
    cmd = [sys.executable, args.training_script] + list(args.training_script_args)
    env = os.environ.copy()
    # the worker is a fresh interpreter: propagate the launcher's import
    # environment so an uninstalled checkout (imported via cwd/sys.path)
    # stays importable in the child, as it was under in-process runpy
    inherited = [p for p in sys.path if p]
    if env.get("PYTHONPATH"):
        inherited.append(env["PYTHONPATH"])
    env["PYTHONPATH"] = os.pathsep.join(inherited)
    rc = _supervise(cmd, env, args.max_restarts, args.restart_backoff)
    if rc:
        sys.exit(rc)
