"""Elastic resharding: portable collective-based array redistribution.

Re-lays-out sharded pytrees across mesh changes — restore-anywhere
checkpoints and live fleet resizes — following "Memory-efficient array
redistribution through portable collective communication" (arXiv:2112.01075):
every redistribution decomposes into the three portable per-axis moves

  * **slice**    — a mesh axis starts sharding a dimension it did not shard
                   before (XLA dynamic-slice; per-device memory SHRINKS);
  * **all-to-all** — a mesh axis moves from sharding one dimension to
                   sharding another (per-device memory is FLAT);
  * **all-gather** — a mesh axis stops sharding a dimension (per-device
                   memory GROWS).

The planner orders the moves slice -> all-to-all -> gather so the
per-device footprint first shrinks, stays flat, and only grows at the very
end: the analytic peak is ~``local_src + local_dst`` bytes instead of the
naive unshard-everything bound of one FULL copy of the array per device.
Plans are computed from serializable layout records (``MeshSpec`` /
``LeafLayout``), so the same machinery drives

  * **offline restore-anywhere** — checkpoint manifests record the source
    mesh + per-leaf PartitionSpec (``record_layouts``); restore onto a
    different topology reads each leaf onto a memory-bounded "read spec"
    on the TARGET mesh and walks the planned steps to the live placement
    (``plan_restore_spec`` + ``apply_steps``);
  * **live resize** — ``reshard_state`` moves a whole captured state dict
    from the old mesh's arrays onto the new mesh's placements via
    collectives, never round-tripping through disk
    (``fleet.elastic.ElasticManager.live_resize``).

Named-axis meshes (Mesh-TensorFlow, arXiv:1811.02084) stay the layout
language throughout: a plan is just a walk through PartitionSpecs.

Robustness contract (docs/RESHARDING.md):
  * every collective/transfer executes inside ``deadline_guard`` — a stall
    past the deadline emits a ``reshard_stall`` event (and optionally
    SIGABRTs so the launch supervisor relaunches instead of hanging
    forever); ``scripts/check_robustness.py`` enforces the wrapping
    statically;
  * execution is two-phase: all new arrays are materialized BEFORE any
    caller state is rebound, so a fault mid-reshard (see
    ``chaos.reshard_fence``) leaves the source state — and every committed
    checkpoint — untouched and the job restorable from the newest verified
    step;
  * every reshard emits ``reshard_*`` telemetry (single-writer: this
    module) — plan size, analytic peak bytes, moved bytes, duration,
    fallbacks.
"""
from __future__ import annotations

import contextlib
import os
import signal
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import observability as _obs

P = PartitionSpec

#: manifest-meta key the layout record is stored under
LAYOUT_KEY = "reshard"
#: layout record format version (bump on incompatible changes)
LAYOUT_FORMAT = 1


# ---------------------------------------------------------------------------
# serializable layout records
# ---------------------------------------------------------------------------
def _norm_spec(spec, ndim: int) -> Tuple[Tuple[str, ...], ...]:
    """PartitionSpec -> per-dimension tuples of axis names, padded to ndim.
    (Normalized form: every entry is a tuple, replicated dims are ().)"""
    entries = list(spec) if spec is not None else []
    entries += [None] * (ndim - len(entries))
    out = []
    for e in entries[:ndim]:
        if e is None:
            out.append(())
        elif isinstance(e, (tuple, list)):
            out.append(tuple(str(a) for a in e))
        else:
            out.append((str(e),))
    return tuple(out)


def _to_pspec(norm: Sequence[Sequence[str]]) -> PartitionSpec:
    entries = []
    for e in norm:
        if not e:
            entries.append(None)
        elif len(e) == 1:
            entries.append(e[0])
        else:
            entries.append(tuple(e))
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


@dataclass(frozen=True)
class MeshSpec:
    """Serializable mesh shape: ordered (axis name, size) pairs."""

    names: Tuple[str, ...]
    sizes: Tuple[int, ...]

    @classmethod
    def from_mesh(cls, mesh: Mesh) -> "MeshSpec":
        return cls(tuple(mesh.axis_names),
                   tuple(int(mesh.shape[n]) for n in mesh.axis_names))

    @property
    def axis_sizes(self) -> Dict[str, int]:
        return dict(zip(self.names, self.sizes))

    @property
    def device_count(self) -> int:
        return int(np.prod(self.sizes)) if self.sizes else 1

    def to_doc(self) -> dict:
        return {"names": list(self.names), "sizes": list(self.sizes)}

    @classmethod
    def from_doc(cls, doc: dict) -> "MeshSpec":
        return cls(tuple(doc["names"]), tuple(int(s) for s in doc["sizes"]))


@dataclass(frozen=True)
class LeafLayout:
    """Serializable per-leaf layout: global shape, dtype, normalized spec."""

    shape: Tuple[int, ...]
    dtype: str
    spec: Tuple[Tuple[str, ...], ...]

    @classmethod
    def from_array(cls, arr) -> Optional["LeafLayout"]:
        sh = getattr(arr, "sharding", None)
        if not isinstance(sh, NamedSharding):
            return None
        return cls(tuple(int(d) for d in arr.shape), str(arr.dtype),
                   _norm_spec(sh.spec, arr.ndim))

    def pspec(self) -> PartitionSpec:
        return _to_pspec(self.spec)

    def dim_factor(self, dim: int, axis_sizes: Dict[str, int]) -> int:
        f = 1
        for a in self.spec[dim]:
            f *= int(axis_sizes.get(a, 1))
        return f

    def local_bytes(self, axis_sizes: Dict[str, int]) -> int:
        total = int(np.prod(self.shape)) if self.shape else 1
        nbytes = total * np.dtype(self.dtype).itemsize
        for d in range(len(self.shape)):
            nbytes //= max(1, self.dim_factor(d, axis_sizes))
        return nbytes

    def to_doc(self) -> dict:
        return {"shape": list(self.shape), "dtype": self.dtype,
                "spec": [list(e) for e in self.spec]}

    @classmethod
    def from_doc(cls, doc: dict) -> "LeafLayout":
        return cls(tuple(int(d) for d in doc["shape"]), str(doc["dtype"]),
                   tuple(tuple(str(a) for a in e) for e in doc["spec"]))


def _flat_items(tree: Dict[str, Any], prefix: str = ""):
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            yield from _flat_items(v, key)
        else:
            yield key, v


def record_layouts(arrays: Dict[str, Any],
                   mesh: Optional[Mesh] = None) -> Optional[dict]:
    """Layout record for a checkpoint's manifest meta: the source mesh and
    one ``LeafLayout`` per mesh-sharded leaf (host/numpy leaves carry shape
    + dtype only). Returns None when nothing is mesh-placed AND no mesh is
    known — a plain single-device checkpoint stays format-compatible."""
    from . import mesh as _mesh

    leaves: Dict[str, dict] = {}
    seen_mesh: Optional[Mesh] = None
    for key, v in _flat_items(arrays):
        lay = LeafLayout.from_array(v)
        if lay is not None:
            leaves[key] = lay.to_doc()
            if seen_mesh is None:
                seen_mesh = v.sharding.mesh
        elif hasattr(v, "shape") and hasattr(v, "dtype"):
            leaves[key] = LeafLayout(
                tuple(int(d) for d in np.shape(v)), str(np.dtype(v.dtype)),
                _norm_spec(None, len(np.shape(v)))).to_doc()
    m = mesh or seen_mesh or _mesh.get_global_mesh()
    if m is None and not leaves:
        return None
    doc = {"format": LAYOUT_FORMAT, "leaves": leaves}
    if m is not None:
        doc["mesh"] = MeshSpec.from_mesh(m).to_doc()
    return doc


def read_layout_record(path: str):
    """(MeshSpec | None, {leaf key: LeafLayout}) from a checkpoint dir's
    commit manifest, or None for legacy checkpoints (no record)."""
    from .checkpoint import manifest as _manifest

    doc = _manifest.read_manifest(path)
    if not doc:
        return None
    rec = (doc.get("meta") or {}).get(LAYOUT_KEY)
    if not isinstance(rec, dict):
        return None
    mesh_doc = rec.get("mesh")
    mesh_spec = MeshSpec.from_doc(mesh_doc) if mesh_doc else None
    leaves = {k: LeafLayout.from_doc(v)
              for k, v in (rec.get("leaves") or {}).items()}
    return mesh_spec, leaves


# ---------------------------------------------------------------------------
# planning
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class PlanStep:
    """One portable move: the spec AFTER the step plus its footprint."""

    kind: str                       # slice | all_to_all | all_gather | align
    axis: str                       # mesh axis being moved ("" for align)
    spec: Tuple[Tuple[str, ...], ...]  # layout after this step
    in_bytes: int                   # per-device input footprint
    out_bytes: int                  # per-device output footprint


@dataclass
class LeafPlan:
    key: str
    shape: Tuple[int, ...]
    dtype: str
    steps: List[PlanStep] = field(default_factory=list)
    transfer: bool = False          # crosses to a different mesh
    peak_bytes: int = 0             # max over steps of in+out per device
    moved_bytes: int = 0            # sum of per-device output bytes


def _axis_dim(spec: Tuple[Tuple[str, ...], ...], axis: str) -> Optional[int]:
    for d, e in enumerate(spec):
        if axis in e:
            return d
    return None


def _local_bytes(shape, dtype, spec, axis_sizes) -> int:
    return LeafLayout(tuple(shape), str(dtype), tuple(spec)).local_bytes(
        axis_sizes)


def plan_same_mesh(shape, dtype, src_spec: PartitionSpec,
                   dst_spec: PartitionSpec, axis_sizes: Dict[str, int],
                   key: str = "?") -> LeafPlan:
    """Decompose src_spec -> dst_spec on ONE mesh into per-axis portable
    moves, ordered slice -> all-to-all -> all-gather so per-device memory
    shrinks before it grows (the arXiv:2112.01075 ordering)."""
    ndim = len(shape)
    src = _norm_spec(src_spec, ndim)
    dst = _norm_spec(dst_spec, ndim)
    plan = LeafPlan(key=key, shape=tuple(int(d) for d in shape),
                    dtype=str(dtype))
    if src == dst:
        plan.peak_bytes = _local_bytes(shape, dtype, src, axis_sizes)
        return plan

    src_of = {a: d for d, e in enumerate(src) for a in e}
    dst_of = {a: d for d, e in enumerate(dst) for a in e}
    slices = sorted([a for a in dst_of if a not in src_of],
                    key=lambda a: -axis_sizes.get(a, 1))   # biggest shrink 1st
    moves = sorted([a for a in src_of if a in dst_of
                    and src_of[a] != dst_of[a]])
    gathers = sorted([a for a in src_of if a not in dst_of],
                     key=lambda a: axis_sizes.get(a, 1))   # biggest growth last

    cur = [list(e) for e in src]
    steps: List[PlanStep] = []

    def emit(kind, axis):
        nonlocal cur
        spec_t = tuple(tuple(e) for e in cur)
        in_b = steps[-1].out_bytes if steps else _local_bytes(
            shape, dtype, src, axis_sizes)
        out_b = _local_bytes(shape, dtype, spec_t, axis_sizes)
        steps.append(PlanStep(kind, axis, spec_t, in_b, out_b))

    for a in slices:
        cur[dst_of[a]].append(a)
        emit("slice", a)
    for a in moves:
        cur[src_of[a]].remove(a)
        cur[dst_of[a]].append(a)
        emit("all_to_all", a)
    for a in gathers:
        cur[src_of[a]].remove(a)
        emit("all_gather", a)
    # final exact constraint: fixes intra-dimension axis ORDER (a tuple spec
    # like ('dp','mp') is dp-major — the greedy appends above may land the
    # axes out of order) at flat per-device cost
    if tuple(tuple(e) for e in cur) != dst or not steps:
        cur = [list(e) for e in dst]
        emit("align", "")

    plan.steps = steps
    plan.peak_bytes = max(s.in_bytes + s.out_bytes for s in steps)
    plan.moved_bytes = sum(s.out_bytes for s in steps)
    return plan


def plan_cross_mesh(shape, dtype, src_spec, src_axis_sizes,
                    dst_spec, dst_axis_sizes, key: str = "?") -> LeafPlan:
    """Plan across two DIFFERENT meshes (a fleet resize): per-shard
    transfer from the source placement onto the destination placement.
    Peak per device is max(local_src, local_dst) + the destination local
    block being assembled — never a full replica unless the destination
    itself is replicated."""
    ndim = len(shape)
    src = _norm_spec(src_spec, ndim)
    dst = _norm_spec(dst_spec, ndim)
    in_b = _local_bytes(shape, dtype, src, src_axis_sizes)
    out_b = _local_bytes(shape, dtype, dst, dst_axis_sizes)
    plan = LeafPlan(key=key, shape=tuple(int(d) for d in shape),
                    dtype=str(dtype), transfer=True)
    plan.steps = [PlanStep("transfer", "", dst, in_b, out_b)]
    plan.peak_bytes = in_b + out_b
    plan.moved_bytes = out_b
    return plan


def plan_boundary(shape, dtype, src_dp: int, dst_dp: int, *,
                  wire_itemsize: Optional[int] = None,
                  key: str = "?") -> LeafPlan:
    """MPMD stage-boundary respec: one activation/cotangent micro-batch
    crossing from a stage of width ``src_dp`` onto a stage of width
    ``dst_dp`` (batch dim 0 data-sharded on both sides, widths chosen
    independently per stage).

    The boundary is a cross-mesh move — the tensor leaves the source
    stage's mesh entirely, rides the tensor-queue wire, and is laid out
    fresh on the destination mesh — so the whole tensor crosses exactly
    once whatever the two widths are; ``wire_itemsize`` prices it at the
    resolved wire dtype (f32/bf16/int8), which is what the auto-parallel
    planner charges for unequal-width candidates. Peak per device is the
    larger side's local block plus the wire copy being assembled.
    """
    ndim = len(shape)
    spec_src = _norm_spec(PartitionSpec("dp"), ndim)
    spec_dst = _norm_spec(PartitionSpec("dp"), ndim)
    in_b = _local_bytes(shape, dtype, spec_src, {"dp": max(int(src_dp), 1)})
    out_b = _local_bytes(shape, dtype, spec_dst, {"dp": max(int(dst_dp), 1)})
    it = int(wire_itemsize) if wire_itemsize else np.dtype(dtype).itemsize
    wire_b = int(np.prod([int(d) for d in shape])) * it
    plan = LeafPlan(key=key, shape=tuple(int(d) for d in shape),
                    dtype=str(dtype), transfer=True)
    plan.steps = [PlanStep("transfer", "dp", spec_dst, in_b, out_b)]
    plan.peak_bytes = max(in_b, out_b) + wire_b
    plan.moved_bytes = wire_b
    return plan


def naive_gather_bytes(shape, dtype) -> int:
    """The bound the planner beats: unshard-everything puts one full copy
    of the leaf on every device."""
    total = int(np.prod(shape)) if len(shape) else 1
    return total * np.dtype(dtype).itemsize


def plan_restore_spec(rec: LeafLayout, rec_mesh: Optional[MeshSpec],
                      dst_mesh: Mesh,
                      dst_spec: PartitionSpec) -> PartitionSpec:
    """Memory/IO-bounded READ spec for restoring one leaf onto `dst_mesh`:
    re-express the SOURCE shard granularity with the target mesh's axes so
    every device reads only ~its source-local bytes, then the planned
    collective steps (slice/all-to-all/gather) carry it to `dst_spec`.
    Falls back to reading directly at `dst_spec` whenever the source
    granularity cannot be expressed (or would read more than the direct
    restore already does)."""
    if rec_mesh is None:
        return dst_spec
    ndim = len(rec.shape)
    src_sizes = rec_mesh.axis_sizes
    dst_sizes = {n: int(dst_mesh.shape[n]) for n in dst_mesh.axis_names}
    want = [rec.dim_factor(d, src_sizes) for d in range(ndim)]
    if all(f == 1 for f in want):
        return dst_spec
    free = dict(dst_sizes)
    out: List[Tuple[str, ...]] = []
    for d in range(ndim):
        f = want[d]
        if f == 1 or rec.shape[d] % f != 0:
            out.append(())
            continue
        pick = next((a for a, s in free.items() if s == f), None)
        if pick is None:
            return dst_spec  # inexpressible on this mesh: direct read
        del free[pick]
        out.append((pick,))
    read = _to_pspec(out)
    read_b = _local_bytes(rec.shape, rec.dtype, _norm_spec(read, ndim),
                          dst_sizes)
    dst_b = _local_bytes(rec.shape, rec.dtype, _norm_spec(dst_spec, ndim),
                         dst_sizes)
    return read if read_b <= dst_b else dst_spec


# ---------------------------------------------------------------------------
# deadline guard — the PR 1 deadline/backoff discipline for collectives
# ---------------------------------------------------------------------------
def _deadline_seconds() -> float:
    try:
        return float(os.environ.get("PADDLE_TPU_RESHARD_TIMEOUT", "300"))
    except ValueError:
        return 300.0


@contextlib.contextmanager
def deadline_guard(what: str, seconds: Optional[float] = None):
    """Bound a collective/transfer the way py_store bounds its socket ops
    (docs/FAULT_TOLERANCE.md): a watchdog timer fires if the wrapped op
    stalls past the deadline, emits a ``reshard_stall`` event + stderr
    diagnosis naming the op, and — under
    ``PADDLE_TPU_RESHARD_KILL_ON_STALL=1`` — SIGABRTs so the launch
    supervisor relaunches from the newest verified checkpoint instead of
    the fleet hanging on a dead peer forever. ``check_robustness.py``
    statically requires every collective call site in this module to sit
    inside this guard."""
    limit = _deadline_seconds() if seconds is None else float(seconds)
    fired = threading.Event()

    def _stall():
        fired.set()
        _obs.event("reshard_stall", what=what, deadline_s=limit)
        print(f"[reshard] {what!r} exceeded its {limit:.0f}s deadline — "
              "peer dead or collective wedged; restore from the newest "
              "verified checkpoint if this rank is relaunched",
              file=sys.stderr, flush=True)
        if os.environ.get("PADDLE_TPU_RESHARD_KILL_ON_STALL", "0") == "1":
            os.kill(os.getpid(), signal.SIGABRT)

    timer = threading.Timer(limit, _stall)
    timer.daemon = True
    timer.start()
    try:
        yield
    finally:
        timer.cancel()
    if fired.is_set():
        raise TimeoutError(
            f"reshard op {what!r} exceeded its {limit:.0f}s deadline")


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------
def _raw(v):
    from ..framework.core import Tensor
    from ..framework.op import raw as _r

    return _r(v) if isinstance(v, Tensor) else v


_IDENTITY_CACHE: Dict[Any, Any] = {}


def _constrain(arr, sharding: NamedSharding):
    """One planned step on the CURRENT mesh: a jitted identity whose
    out_sharding makes GSPMD emit exactly the step's collective
    (dynamic-slice / all-to-all / all-gather). The jit object is cached
    per target sharding so repeated reshards reuse compiled programs."""
    fn = _IDENTITY_CACHE.get(sharding)
    if fn is None:
        fn = jax.jit(lambda x: x, out_shardings=sharding)
        _IDENTITY_CACHE[sharding] = fn
    return fn(arr)


def apply_steps(arr, plan: LeafPlan, mesh: Mesh, *, fence_base: int = 0):
    """Walk one leaf's planned steps on `mesh`. Each step runs under the
    deadline guard with a chaos fence at the mid-step barrier."""
    from ..testing import chaos

    for i, step in enumerate(plan.steps):
        if step.kind == "transfer":
            continue  # cross-mesh hop: executed by the caller's device_put
        chaos.reshard_fence(fence_base + i, f"{plan.key}:{step.kind}")
        sh = NamedSharding(mesh, _to_pspec(step.spec))
        with deadline_guard(f"{step.kind}[{step.axis}] {plan.key}"):
            arr = _constrain(arr, sh)
    return arr


def _transfer(arr, sharding: NamedSharding, key: str):
    """Cross-mesh hop (fleet resize): per-shard device transfer. A failed
    direct transfer degrades to a host round-trip rather than crashing —
    correctness first, the fast path is telemetry-visible either way."""
    try:
        with deadline_guard(f"transfer {key}"):
            return jax.device_put(arr, sharding)
    except TimeoutError:
        raise
    except Exception as e:
        _obs.inc("reshard_fallback_total", why="host_roundtrip")
        print(f"[reshard] direct transfer of {key!r} failed ({e!r}); "
              "degrading to a per-shard host round-trip", file=sys.stderr)
        # Bounded round-trip: materialize only each target shard's slice
        # on the host (make_array_from_callback pulls arr[idx] per
        # device) instead of gathering the FULL leaf — the old
        # np.asarray(arr) path put one complete copy on the host and
        # re-shipped it whole to every device, defeating the planned
        # shard spec exactly when memory is tightest. Basic indexing
        # cannot run at all on a non-fully-addressable source, so that
        # case takes one whole-leaf gather up front (it either works or
        # raises its own clear error) and the callback slices the host
        # copy. ``reshard_peak_bytes`` observes the bytes ACTUALLY
        # materialized per callback, not the planned shard size, so an
        # indexing path that secretly gathers more than the plan shows
        # up in telemetry (``reshard_fallback_total{why=overshot_plan}``).
        shard_shape = sharding.shard_shape(tuple(arr.shape))
        shard_b = (int(np.prod(shard_shape)) if shard_shape else 1) \
            * np.dtype(arr.dtype).itemsize
        if not getattr(arr, "is_fully_addressable", True):
            with deadline_guard(f"host gather {key}"):
                host = np.asarray(arr)
            _obs.observe("reshard_peak_bytes", int(host.nbytes))
            _obs.inc("reshard_fallback_total", why="whole_leaf")
            with deadline_guard(f"host transfer {key}"):
                return jax.make_array_from_callback(
                    tuple(arr.shape), sharding, lambda idx: host[idx])
        peak = {"b": 0}

        def _pull(idx):
            out = np.asarray(arr[idx])
            peak["b"] = max(peak["b"], int(out.nbytes))
            return out

        with deadline_guard(f"host transfer {key}"):
            result = jax.make_array_from_callback(
                tuple(arr.shape), sharding, _pull)
        _obs.observe("reshard_peak_bytes", peak["b"] or shard_b)
        if peak["b"] > shard_b:
            _obs.inc("reshard_fallback_total", why="overshot_plan")
        return result


def _target_sharding(v) -> Optional[NamedSharding]:
    sh = getattr(_raw(v), "sharding", None)
    return sh if isinstance(sh, NamedSharding) else None


def reshard_array(arr, dst: NamedSharding, *, key: str = "?"):
    """Re-lay-out ONE live array onto `dst` (same or different mesh) via
    the planned decomposition. Returns (new_array, LeafPlan)."""
    arr = _raw(arr)
    src = _target_sharding(arr)
    dst_sizes = {n: int(dst.mesh.shape[n]) for n in dst.mesh.axis_names}
    if src is None:
        # unplaced/host source: a straight placement, no collective plan
        plan = LeafPlan(key=key, shape=tuple(arr.shape), dtype=str(arr.dtype),
                        transfer=True)
        nbytes = naive_gather_bytes(arr.shape, arr.dtype)
        out_b = _local_bytes(arr.shape, arr.dtype,
                             _norm_spec(dst.spec, arr.ndim), dst_sizes)
        plan.steps = [PlanStep("transfer", "",
                               _norm_spec(dst.spec, arr.ndim), nbytes, out_b)]
        plan.peak_bytes = nbytes + out_b
        plan.moved_bytes = out_b
        return _transfer(arr, dst, key), plan
    same_mesh = (tuple(src.mesh.axis_names) == tuple(dst.mesh.axis_names)
                 and src.mesh.devices.shape == dst.mesh.devices.shape
                 and bool(np.all(src.mesh.devices == dst.mesh.devices)))
    if same_mesh:
        plan = plan_same_mesh(arr.shape, arr.dtype, src.spec, dst.spec,
                              dst_sizes, key=key)
        return apply_steps(arr, plan, dst.mesh), plan
    src_sizes = {n: int(src.mesh.shape[n]) for n in src.mesh.axis_names}
    plan = plan_cross_mesh(arr.shape, arr.dtype, src.spec, src_sizes,
                           dst.spec, dst_sizes, key=key)
    return _transfer(arr, dst, key), plan


def reshard_state(src_state: Dict[str, Any], dst_state: Dict[str, Any],
                  *, what: str = "live") -> Dict[str, Any]:
    """Re-lay-out a whole (flat) state dict from its current placements
    onto the placements of `dst_state`'s live values — the live-resize
    path: collectives/transfers only, no disk. Two-phase: every output
    array is materialized before the caller rebinds anything, so a fault
    mid-reshard leaves the source state intact. Returns {key: new array}
    for every key in dst_state (raises KeyError listing what the source
    cannot supply — the caller degrades to a checkpoint restore)."""
    from ..testing import chaos

    t0 = time.perf_counter()
    missing = [k for k in dst_state if k not in src_state]
    if missing:
        raise KeyError(
            f"live reshard source is missing {len(missing)} leaves "
            f"(cannot host the state): {sorted(missing)[:5]}"
            f"{' ...' if len(missing) > 5 else ''}")
    out: Dict[str, Any] = {}
    plans: List[LeafPlan] = []
    fence = 0
    amb = next((s.mesh for t in dst_state.values()
                if (s := _target_sharding(t)) is not None), None)
    for key, tgt in dst_state.items():
        src_v = _raw(src_state[key])
        if not hasattr(src_v, "shape"):
            out[key] = src_v  # host leaf (python scalar, counter)
            continue
        dst_sh = _target_sharding(tgt)
        if dst_sh is None:
            if amb is None or not isinstance(src_v, jax.Array):
                out[key] = src_v
                continue
            # auxiliary leaf (scalar accumulator, step counter) with no
            # placement of its own: replicate it on the destination mesh,
            # or it stays committed to the OLD fleet's devices and the
            # next jitted step rejects the mixed device sets
            dst_sh = NamedSharding(amb, P())
        tgt_shape = tuple(_raw(tgt).shape)
        if tuple(src_v.shape) != tgt_shape:
            raise ValueError(
                f"live reshard leaf {key!r}: source shape "
                f"{tuple(src_v.shape)} != target {tgt_shape}")
        chaos.reshard_fence(fence, f"{key}:begin")
        new, plan = reshard_array(src_v, dst_sh, key=key)
        fence += max(1, len(plan.steps))
        plans.append(plan)
        out[key] = new
    record_plan_metrics(plans, what=what, seconds=time.perf_counter() - t0)
    return out


def record_plan_metrics(plans: Sequence[LeafPlan], *, what: str,
                        seconds: float) -> None:
    """One telemetry record per reshard op (single-writer for the
    ``reshard_*`` family lives here)."""
    if not plans:
        return
    nsteps = sum(len(p.steps) for p in plans)
    peak = max((p.peak_bytes for p in plans), default=0)
    moved = sum(p.moved_bytes for p in plans)
    _obs.inc("reshard_total", what=what)
    _obs.observe("reshard_plan_steps", nsteps)
    _obs.observe("reshard_peak_bytes", peak)
    _obs.observe("reshard_seconds", seconds)
    _obs.inc("reshard_bytes_total", moved)
    _obs.event("reshard", what=what, leaves=len(plans), steps=nsteps,
               peak_bytes=peak, moved_bytes=moved,
               seconds=round(seconds, 6))
    _obs.record_span("reshard_exec", dur_s=seconds, what=what,
                     leaves=len(plans), steps=nsteps)


def record_fallback(why: str, **fields) -> None:
    """A reshard degraded to a slower/safer path (disk restore, host
    round-trip, coarse read). Counted here so the family stays
    single-writer."""
    _obs.inc("reshard_fallback_total", why=why)
    _obs.event("reshard", what="fallback", why=why, **fields)


def legacy_error(path: str, cause: Exception) -> RuntimeError:
    """The clear cross-mesh-restore-of-a-legacy-checkpoint diagnosis (the
    alternative is a shape-mismatch assertion deep inside jax/orbax)."""
    return RuntimeError(
        f"checkpoint {path!r} predates mesh/layout records (manifest "
        "without a 'reshard' meta entry): it can only be restored onto "
        "the SAME topology it was saved on. Restore on the original "
        "mesh and re-save to upgrade it, or rebuild the checkpoint with "
        f"the current writer. (underlying error: {cause!r})")


# ---------------------------------------------------------------------------
# dual identity: importing this submodule rebinds the package attribute
# `paddle_tpu.distributed.reshard` from the paddle-parity placement API
# (auto_parallel.reshard) to this module — so the module itself is made
# callable with that function's signature and both uses keep working:
#   dist.reshard(tensor, mesh, placements)   # paddle API
#   dist.reshard.plan_same_mesh(...)         # this subsystem
# ---------------------------------------------------------------------------
import types as _types  # noqa: E402


class _ReshardModule(_types.ModuleType):
    def __call__(self, tensor, mesh, placements):
        from .auto_parallel import reshard as _placement_reshard

        return _placement_reshard(tensor, mesh, placements)


sys.modules[__name__].__class__ = _ReshardModule
