"""paddle_tpu.distributed — the Fleet-parity distributed stack, TPU-native.

Layer map (SURVEY.md §2.3): collectives are XLA collectives over ICI/DCN
named by mesh axes; groups are mesh slices; hybrid parallelism is one named
mesh [dp, pp, sharding, sep, mp]; ZeRO is placement; pipeline is a compiled
collective-permute schedule; auto-parallel is the native execution model.
"""
from __future__ import annotations

from . import mesh  # noqa: F401
from .mesh import (  # noqa: F401
    build_mesh,
    get_global_mesh,
    global_mesh,
    set_global_mesh,
    sharding_constraint,
)
from .env import (  # noqa: F401
    Group,
    ParallelEnv,
    destroy_process_group,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
    new_group,
)
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    broadcast,
    broadcast_object_list,
    gather,
    get_backend,
    irecv,
    isend,
    ppermute,
    recv,
    scatter_object_list,
    reduce,
    reduce_scatter,
    scatter,
    send,
    stream,
    wait,
)
from .parallel import DataParallel, spawn  # noqa: F401
from . import fleet  # noqa: F401
from . import sharding as sharding_api  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel import (  # noqa: F401
    Partial,
    Placement,
    ProcessMesh,
    Replicate,
    Shard,
    dtensor_from_fn,
    get_mesh,
    reshard,
    set_mesh,
    shard_layer,
    shard_tensor,
)
from .fleet.utils.recompute_helper import recompute  # noqa: F401


def get_group(gid=None):
    from .env import _default_group, _groups

    if gid is None:
        return _default_group
    for g in _groups:
        if g.id == gid:
            return g
    return None


# `shard_map` convenience re-export: the explicit-SPMD escape hatch
# (reference analogue: writing custom collective ops).
def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kwargs):
    from .._jax_compat import shard_map as _shard_map
    from .mesh import require_global_mesh

    return _shard_map(
        f,
        mesh=mesh or require_global_mesh(),
        in_specs=in_specs,
        out_specs=out_specs,
        **kwargs,
    )


QueueDataset = None  # PS-mode datasets: deliberate non-goal (SURVEY.md §2.3 PS)

from .collective import P2POp, batch_isend_irecv  # noqa: E402,F401
from . import launch  # noqa: E402,F401  (paddle.distributed.launch module)
from . import rpc  # noqa: E402,F401  (paddle.distributed.rpc module)
from . import utils  # noqa: E402,F401  (paddle.distributed.utils module)
from . import communication  # noqa: E402,F401  (reference package path)
from . import checkpoint  # noqa: E402,F401
from .auto_parallel import shard_dataloader  # noqa: E402,F401
from .parallelize import (  # noqa: E402,F401
    ColWiseParallel,
    RowWiseParallel,
    SequenceParallelBegin,
    SequenceParallelEnd,
    parallelize,
    to_distributed,
)
from .checkpoint import (  # noqa: E402,F401  (paddle.distributed.* parity)
    load_state_dict,
    save_state_dict,
)
all_to_all = alltoall  # reference alias



def split(x, size, operation, axis=0, num_partitions=None, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """paddle.distributed.split parity: build a tensor-parallel embedding or
    linear whose weight is partitioned over the mp axis (reference:
    python/paddle/distributed/collective.py::split). Under SPMD the
    partitioning is a sharding annotation on the parallel layer."""
    from .fleet import meta_parallel as mp_layers

    if operation == "embedding":
        layer = mp_layers.VocabParallelEmbedding(size[0], size[1])
        return layer(x)
    if operation == "linear":
        if axis == 0:
            layer = mp_layers.RowParallelLinear(
                size[0], size[1], input_is_parallel=False
            )
        else:
            layer = mp_layers.ColumnParallelLinear(
                size[0], size[1], gather_output=gather_out
            )
        return layer(x)
    raise ValueError(f"unsupported split operation {operation!r}")
