"""paddle_tpu.distributed — the Fleet-parity distributed stack, TPU-native.

Layer map (SURVEY.md §2.3): collectives are XLA collectives over ICI/DCN
named by mesh axes; groups are mesh slices; hybrid parallelism is one named
mesh [dp, pp, sharding, sep, mp]; ZeRO is placement; pipeline is a compiled
collective-permute schedule; auto-parallel is the native execution model.
"""
from __future__ import annotations

from . import mesh  # noqa: F401
from .mesh import (  # noqa: F401
    build_mesh,
    get_global_mesh,
    global_mesh,
    set_global_mesh,
    sharding_constraint,
)
from .env import (  # noqa: F401
    Group,
    ParallelEnv,
    destroy_process_group,
    get_rank,
    get_world_size,
    init_parallel_env,
    is_initialized,
    new_group,
)
from .collective import (  # noqa: F401
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    broadcast,
    irecv,
    isend,
    ppermute,
    recv,
    reduce,
    reduce_scatter,
    scatter,
    send,
    stream,
    wait,
)
from .parallel import DataParallel, spawn  # noqa: F401
from . import fleet  # noqa: F401
from . import sharding as sharding_api  # noqa: F401
from . import auto_parallel  # noqa: F401
from .auto_parallel import (  # noqa: F401
    Partial,
    Placement,
    ProcessMesh,
    Replicate,
    Shard,
    dtensor_from_fn,
    get_mesh,
    reshard,
    set_mesh,
    shard_layer,
    shard_tensor,
)
from .fleet.utils.recompute_helper import recompute  # noqa: F401


def get_group(gid=None):
    from .env import _default_group, _groups

    if gid is None:
        return _default_group
    for g in _groups:
        if g.id == gid:
            return g
    return None


# `shard_map` convenience re-export: the explicit-SPMD escape hatch
# (reference analogue: writing custom collective ops).
def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kwargs):
    import jax

    from .mesh import require_global_mesh

    return jax.shard_map(
        f,
        mesh=mesh or require_global_mesh(),
        in_specs=in_specs,
        out_specs=out_specs,
        **kwargs,
    )


QueueDataset = None  # PS-mode datasets: deliberate non-goal (SURVEY.md §2.3 PS)
