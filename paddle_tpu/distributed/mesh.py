"""Global device-mesh context — the TPU-native "communicator" layer.

Reference capability replaced here (SURVEY.md §2.3): Paddle manages NCCL
communicators per process subgroup (`ProcessGroupNCCL`, `NCCLCommContext`,
unique-id rendezvous over TCPStore). On TPU there are no user-managed
communicators: collectives are compiled into the XLA program and ride the
ICI/DCN fabric. The analogue of "creating communicators" is *constructing a
named device mesh* (`jax.sharding.Mesh`) whose axes map onto the physical
topology; every collective is then named by mesh axis instead of by
communicator handle.

Axis order convention (mirrors the reference's HybridCommunicateGroup order
[dp, pp, sharding, sep, mp] — `fleet/base/topology.py`): the *last* axes are
the fastest-varying over devices, so `mp` (the most bandwidth-hungry axis)
lands on adjacent devices / same-host ICI, `dp` on the slowest links — the
same locality goal the reference encodes in its topology ordering.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_state = threading.local()

# Canonical hybrid axis names, outermost → innermost.
HYBRID_AXES = ("dp", "pp", "sharding", "sep", "mp")


def build_mesh(
    axis_dims: Sequence[int],
    axis_names: Sequence[str],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over `devices` (default: all) with the given axis shape.

    Degenerate (size-1) axes are kept so sharding specs can always name any
    hybrid axis regardless of the configured degree.
    """
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(axis_dims))
    if n != len(devices):
        raise ValueError(
            f"mesh axis dims {tuple(axis_dims)} require {n} devices, "
            f"got {len(devices)}"
        )
    dev_array = np.array(devices).reshape(tuple(axis_dims))
    return Mesh(dev_array, tuple(axis_names))


def _device_slice_ids(devices, num_slices: Optional[int]):
    """Slice id per device. Real multi-slice TPU devices expose
    `.slice_index`; `num_slices` (or PADDLE_TPU_NUM_SLICES) overrides with a
    contiguous split for simulation/testing."""
    import os

    if num_slices is None:
        env = os.environ.get("PADDLE_TPU_NUM_SLICES")
        if env:
            num_slices = int(env)
    if num_slices is not None and num_slices > 1:
        if len(devices) % num_slices != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible by "
                f"num_slices={num_slices}"
            )
        per = len(devices) // num_slices
        return [i // per for i in range(len(devices))]
    return [getattr(d, "slice_index", 0) or 0 for d in devices]


def _ici_device_array(dims, devices) -> np.ndarray:
    """Arrange `devices` (one slice) into `dims` honoring the physical ICI
    torus when coords are available (TPU); plain reshape otherwise (CPU)."""
    try:
        from jax.experimental import mesh_utils

        return np.asarray(
            mesh_utils.create_device_mesh(
                tuple(dims), devices=list(devices),
                allow_split_physical_axes=True,
            )
        )
    except Exception:
        return np.array(devices).reshape(tuple(dims))


# Axes allowed to cross DCN (slice boundaries), in preference order. The
# reference encodes the same rule by axis ordering in
# `fleet/base/topology.py`: gradient-sync (dp) tolerates the slow fabric,
# pipeline stage hops tolerate it next, ZeRO gathers after that; sep/mp
# collectives are per-layer and must stay on ICI.
DCN_CAPABLE_AXES = ("dp", "pp", "sharding")


def build_hybrid_mesh(
    axis_dims: Sequence[int],
    axis_names: Sequence[str],
    devices: Optional[Sequence[jax.Device]] = None,
    num_slices: Optional[int] = None,
) -> Mesh:
    """ICI/DCN-topology-aware hybrid mesh (SURVEY.md §2.3 "Hybrid topology":
    "ICI-aware axis assignment is the key added value").

    Single slice: devices are arranged so the innermost axes (mp, sep) land
    on physically adjacent chips of the ICI torus.

    Multi-slice (slice_index present, or simulated): the slice count is
    factored into the outermost DCN-capable axes ([dp, pp, sharding] in that
    order) so ONLY those axes' collectives cross DCN; each slice internally
    holds a contiguous ICI-arranged sub-mesh for the remaining axis extents.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    dims = [int(d) for d in axis_dims]
    if int(np.prod(dims)) != len(devices):
        raise ValueError(
            f"mesh axis dims {tuple(dims)} require {int(np.prod(dims))} "
            f"devices, got {len(devices)}"
        )
    slice_ids = _device_slice_ids(devices, num_slices)
    uniq = sorted(set(slice_ids))
    n_slices = len(uniq)
    if n_slices <= 1:
        return Mesh(_ici_device_array(dims, devices), tuple(axis_names))

    by_slice = {s: [] for s in uniq}
    for d, sid in zip(devices, slice_ids):
        by_slice[sid].append(d)
    per_slice_n = len(devices) // n_slices
    if any(len(g) != per_slice_n for g in by_slice.values()):
        raise ValueError(
            f"uneven slices: {[len(by_slice[s]) for s in uniq]} devices per "
            "slice; hybrid mesh needs equal slice sizes"
        )

    # factor n_slices into the outer DCN-capable axes, in order
    import math

    dcn = [1] * len(dims)
    rem = n_slices
    for i, (name, dim) in enumerate(zip(axis_names, dims)):
        if rem == 1:
            break
        if name in DCN_CAPABLE_AXES:
            f = math.gcd(dim, rem)
            dcn[i] = f
            rem //= f
    if rem != 1:
        raise ValueError(
            f"cannot place {n_slices} slices onto DCN-capable axes "
            f"{DCN_CAPABLE_AXES} with degrees "
            f"{dict(zip(axis_names, dims))}: the slice count must divide "
            "their product (dp/pp/sharding are the axes allowed to span DCN)"
        )
    per_dims = [d // f for d, f in zip(dims, dcn)]

    # per-slice ICI sub-meshes, composed so dcn coords are the OUTER part of
    # each axis: axis i index = dcn_i * per_dims[i] + ici_i
    subs = np.stack(
        [_ici_device_array(per_dims, by_slice[s]) for s in uniq]
    )  # [n_slices, *per_dims]
    k = len(dims)
    subs = subs.reshape(tuple(dcn) + tuple(per_dims))
    perm = [j for i in range(k) for j in (i, k + i)]
    arr = subs.transpose(perm).reshape(tuple(dims))
    return Mesh(arr, tuple(axis_names))


def set_global_mesh(mesh: Optional[Mesh]):
    _state.mesh = mesh


def get_global_mesh() -> Optional[Mesh]:
    return getattr(_state, "mesh", None)


def require_global_mesh() -> Mesh:
    m = get_global_mesh()
    if m is None:
        raise RuntimeError(
            "no global device mesh: call paddle_tpu.distributed.fleet.init() "
            "or init_parallel_env() first"
        )
    return m


@contextlib.contextmanager
def global_mesh(mesh: Mesh):
    prev = get_global_mesh()
    set_global_mesh(mesh)
    try:
        yield mesh
    finally:
        set_global_mesh(prev)


def named_sharding(spec: PartitionSpec, mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or require_global_mesh(), spec)


def global_device_put(value, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """Place host data onto the (possibly multi-host) global mesh.

    Single-process: a plain ``jax.device_put``. Multi-process SPMD
    (``jax.process_count() > 1``): ``device_put`` would fail on the
    non-addressable remote devices, so build the global array from a
    callback — every process holds the SAME full-value host copy (model
    init and batch loading are same-seeded on each host, the reference's
    `test_dist_base` contract) and contributes just its addressable
    shards. This is the TPU-native stand-in for the reference's
    per-rank scatter in `DistributedDataParallel` / data loaders.
    """
    m = mesh or require_global_mesh()
    sh = NamedSharding(m, spec)
    if jax.process_count() == 1:
        return jax.device_put(value, sh)
    arr = np.asarray(value)
    return jax.make_array_from_callback(arr.shape, sh, lambda idx: arr[idx])


def _sanitize_spec(spec: PartitionSpec, shape, mesh: Mesh) -> PartitionSpec:
    """Drop axis names from dims they don't divide evenly (correctness first:
    an indivisible dim stays replicated rather than erroring)."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    out = []
    for dim, e in zip(shape, entries):
        names = e if isinstance(e, tuple) else ((e,) if e is not None else ())
        size = 1
        for n in names:
            size *= mesh.shape.get(n, 1)
        if size > 1 and dim % size != 0:
            out.append(None)
        else:
            out.append(e)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def sharding_constraint(value, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """Pin `value`'s layout to `spec` on the (global) mesh.

    Inside a jit trace this becomes an XLA sharding annotation (GSPMD inserts
    whatever collectives are needed to honor it — the TPU-native equivalent of
    the reference's explicit c_allgather/c_reducescatter ops). Eagerly it is a
    device_put (a real resharding transfer).
    """
    m = mesh or get_global_mesh()
    if m is None or m.empty:
        return value
    spec = _sanitize_spec(spec, tuple(value.shape), m)
    # Inside a shard_map/pmap region the bound axes are MANUAL for this
    # trace: data is already rank-local along them, so a GSPMD hint naming
    # them is moot — and rejected at LOWERING time (too late for a
    # try/except here). Strip them from the spec up front.
    from .._jax_compat import bound_axis_names

    manual = bound_axis_names()
    if manual:
        entries = [
            None
            if e is not None and any(
                n in manual for n in (e if isinstance(e, tuple) else (e,))
            )
            else e
            for e in spec
        ]
        while entries and entries[-1] is None:
            entries.pop()
        spec = PartitionSpec(*entries)
    try:
        from jax import lax

        return lax.with_sharding_constraint(value, NamedSharding(m, spec))
    except Exception:
        return jax.device_put(value, NamedSharding(m, spec))


def mesh_axis_size(name: str, mesh: Optional[Mesh] = None) -> int:
    m = mesh or get_global_mesh()
    if m is None or name not in m.shape:
        return 1
    return m.shape[name]
