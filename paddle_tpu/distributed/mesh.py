"""Global device-mesh context — the TPU-native "communicator" layer.

Reference capability replaced here (SURVEY.md §2.3): Paddle manages NCCL
communicators per process subgroup (`ProcessGroupNCCL`, `NCCLCommContext`,
unique-id rendezvous over TCPStore). On TPU there are no user-managed
communicators: collectives are compiled into the XLA program and ride the
ICI/DCN fabric. The analogue of "creating communicators" is *constructing a
named device mesh* (`jax.sharding.Mesh`) whose axes map onto the physical
topology; every collective is then named by mesh axis instead of by
communicator handle.

Axis order convention (mirrors the reference's HybridCommunicateGroup order
[dp, pp, sharding, sep, mp] — `fleet/base/topology.py`): the *last* axes are
the fastest-varying over devices, so `mp` (the most bandwidth-hungry axis)
lands on adjacent devices / same-host ICI, `dp` on the slowest links — the
same locality goal the reference encodes in its topology ordering.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

_state = threading.local()

# Canonical hybrid axis names, outermost → innermost.
HYBRID_AXES = ("dp", "pp", "sharding", "sep", "mp")


def build_mesh(
    axis_dims: Sequence[int],
    axis_names: Sequence[str],
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a Mesh over `devices` (default: all) with the given axis shape.

    Degenerate (size-1) axes are kept so sharding specs can always name any
    hybrid axis regardless of the configured degree.
    """
    if devices is None:
        devices = jax.devices()
    n = int(np.prod(axis_dims))
    if n != len(devices):
        raise ValueError(
            f"mesh axis dims {tuple(axis_dims)} require {n} devices, "
            f"got {len(devices)}"
        )
    dev_array = np.array(devices).reshape(tuple(axis_dims))
    return Mesh(dev_array, tuple(axis_names))


def set_global_mesh(mesh: Optional[Mesh]):
    _state.mesh = mesh


def get_global_mesh() -> Optional[Mesh]:
    return getattr(_state, "mesh", None)


def require_global_mesh() -> Mesh:
    m = get_global_mesh()
    if m is None:
        raise RuntimeError(
            "no global device mesh: call paddle_tpu.distributed.fleet.init() "
            "or init_parallel_env() first"
        )
    return m


@contextlib.contextmanager
def global_mesh(mesh: Mesh):
    prev = get_global_mesh()
    set_global_mesh(mesh)
    try:
        yield mesh
    finally:
        set_global_mesh(prev)


def named_sharding(spec: PartitionSpec, mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or require_global_mesh(), spec)


def _sanitize_spec(spec: PartitionSpec, shape, mesh: Mesh) -> PartitionSpec:
    """Drop axis names from dims they don't divide evenly (correctness first:
    an indivisible dim stays replicated rather than erroring)."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    out = []
    for dim, e in zip(shape, entries):
        names = e if isinstance(e, tuple) else ((e,) if e is not None else ())
        size = 1
        for n in names:
            size *= mesh.shape.get(n, 1)
        if size > 1 and dim % size != 0:
            out.append(None)
        else:
            out.append(e)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def sharding_constraint(value, spec: PartitionSpec, mesh: Optional[Mesh] = None):
    """Pin `value`'s layout to `spec` on the (global) mesh.

    Inside a jit trace this becomes an XLA sharding annotation (GSPMD inserts
    whatever collectives are needed to honor it — the TPU-native equivalent of
    the reference's explicit c_allgather/c_reducescatter ops). Eagerly it is a
    device_put (a real resharding transfer).
    """
    m = mesh or get_global_mesh()
    if m is None or m.empty:
        return value
    spec = _sanitize_spec(spec, tuple(value.shape), m)
    try:
        from jax import lax

        return lax.with_sharding_constraint(value, NamedSharding(m, spec))
    except Exception:
        return jax.device_put(value, NamedSharding(m, spec))


def mesh_axis_size(name: str, mesh: Optional[Mesh] = None) -> int:
    m = mesh or get_global_mesh()
    if m is None or name not in m.shape:
        return 1
    return m.shape[name]
