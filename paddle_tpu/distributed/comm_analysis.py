"""Per-step collective-traffic analysis from compiled SPMD programs.

VERDICT r4 weak #5: the virtual CPU mesh proves correctness, not scaling
— emulated collective timings are meaningless. What CAN be measured
without hardware is the compiled program itself: every collective XLA
emitted, its payload bytes, and which mesh axis its replica groups span.
From those, a bandwidth model projects scaling efficiency at real chip
counts (the per-axis byte counts are exact; only the bandwidths are
assumptions).

Reference anchor: `fleet/base/topology.py::CommunicateTopology` orders
axes by communication locality for exactly this reason — mp on the
fastest links, dp on the slowest (SURVEY.md §2.3 "Hybrid topology").
Here the same design claim becomes checkable: in a multi-slice mesh the
only cross-slice (DCN) traffic must be dp-axis gradient reduction.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional

import numpy as np

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

_COLL_RE = re.compile(
    r"=\s*(?:\(([^)]*)\)|(\w+)\[([\d,]*)\](?:\{[^}]*\})?)\s*"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(?:-start)?\(", )
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")
_GROUPS_LIST_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def _parse_groups(line: str) -> Optional[List[List[int]]]:
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        g, s = int(m.group(1)), int(m.group(2))
        src = [int(x) for x in m.group(3).split(",")]
        iota = np.arange(int(np.prod(src))).reshape(src)
        if m.group(4):
            iota = iota.transpose([int(x) for x in m.group(4).split(",")])
        return iota.reshape(g, s).tolist()
    m = _GROUPS_LIST_RE.search(line)
    if m:
        return [[int(x) for x in grp.split(",") if x.strip()]
                for grp in re.findall(r"\{([\d,\s]*)\}", m.group(1))]
    return None


def _line_payload(line: str) -> tuple:
    """(payload_bytes, wire_dtype) for the collective on this line. The
    dtype is what actually crosses the wire — a bf16/s8 operand means the
    exchange moves half/quarter the f32 bytes (grad_comm's reduced-
    precision collectives show up here). Tuple shapes sum elements and
    report the first element's dtype."""
    m = _COLL_RE.search(line)
    if not m:
        return 0, None
    if m.group(1) is not None:  # tuple shape: sum element shapes
        total, dtype = 0, None
        for dt, dims in _SHAPE_RE.findall(m.group(1)):
            total += _shape_bytes(dt, dims)
            dtype = dtype or dt
        return total, dtype
    return _shape_bytes(m.group(2), m.group(3)), m.group(2)


def _line_payload_bytes(line: str, kind: str) -> int:
    """Payload bytes for the collective on this line. all-gather counts
    OUTPUT bytes (the gathered result), the others count the operand-side
    result shape — for all-reduce/permute in-shape == out-shape, for
    reduce-scatter the true wire cost is the pre-scatter input, i.e.
    out_bytes * group_size (handled by the traffic model, which gets the
    group size separately)."""
    return _line_payload(line)[0]


def _axes_of_group(group: List[int], mesh) -> tuple:
    """Mesh axis names along which this replica group's members vary."""
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    coords = {}
    for dev in group:
        pos = np.argwhere(ids == dev)
        if len(pos) != 1:
            return ("unknown",)
        coords[dev] = tuple(pos[0])
    axes = []
    for k, name in enumerate(mesh.axis_names):
        if len({c[k] for c in coords.values()}) > 1:
            axes.append(name)
    return tuple(axes) if axes else ("self",)


def collective_traffic(hlo_text: str, mesh) -> List[Dict]:
    """Every collective in a compiled HLO module: kind, payload bytes,
    group size, the mesh axes the groups span, and modeled per-device
    wire bytes (ring algorithms):

      all-reduce          2 * (n-1)/n * payload
      all-gather          (n-1)/n * payload          (payload = output)
      reduce-scatter      (n-1)/n * payload * n      (payload = shard out)
      collective-permute  payload
      all-to-all          (n-1)/n * payload
    """
    out = []
    for line in hlo_text.splitlines():
        m = _COLL_RE.search(line)
        if not m or "-done" in line:
            continue
        kind = m.group(4)
        payload, dtype = _line_payload(line)
        groups = _parse_groups(line)
        n = len(groups[0]) if groups else 1
        if n <= 1:
            continue
        if kind == "all-reduce":
            wire = 2 * (n - 1) / n * payload
        elif kind == "reduce-scatter":
            wire = (n - 1) / n * payload * n
        elif kind == "collective-permute":
            wire = payload
        else:  # all-gather / all-to-all
            wire = (n - 1) / n * payload
        axes = _axes_of_group(groups[0], mesh) if groups else ("unknown",)
        out.append({
            "kind": kind, "payload_bytes": payload, "group_size": n,
            "axes": axes, "wire_bytes_per_device": int(wire),
            "wire_dtype": dtype,
        })
    return out


_GRAD_EXCHANGE_KINDS = ("all-reduce", "reduce-scatter")


def bucket_traffic(colls: List[Dict],
                   data_axes: tuple = ("dp", "sharding")) -> Dict:
    """Attribute the gradient exchange to its fusion buckets.

    A "bucket" is one reduction collective (all-reduce or reduce-scatter)
    whose replica groups span only data axes — exactly what grad_comm
    emits one of per fusion buffer (an unbucketed program shows one per
    parameter instead, which is the regression this report exists to
    catch). Returns per-bucket records plus the aggregate wire payload and
    its f32-equivalent, so reduced-precision wires are visible as
    ``payload_bytes < payload_bytes_f32`` (quantized_fraction > 0)."""
    data = set(data_axes)
    buckets = []
    for c in colls:
        axes = set(c["axes"]) - {"self"}
        if c["kind"] in _GRAD_EXCHANGE_KINDS and axes and axes <= data:
            buckets.append(c)
    payload = sum(c["payload_bytes"] for c in buckets)
    itemsize = {c["wire_dtype"]: _DTYPE_BYTES.get(c["wire_dtype"] or "f32", 4)
                for c in buckets}
    payload_f32 = sum(
        c["payload_bytes"] * 4 // itemsize[c["wire_dtype"]] for c in buckets)
    return {
        "buckets": buckets,
        "n_buckets": len(buckets),
        "payload_bytes": payload,
        "payload_bytes_f32": payload_f32,
        "quantized_fraction": (
            1.0 - payload / payload_f32 if payload_f32 else 0.0),
        "per_axis": axis_payload_summary(buckets),
    }


def axis_traffic_summary(colls: List[Dict]) -> Dict[str, int]:
    """Total modeled per-device wire bytes per mesh-axis combination."""
    agg: Dict[str, int] = {}
    for c in colls:
        key = "+".join(c["axes"])
        agg[key] = agg.get(key, 0) + c["wire_bytes_per_device"]
    return agg


def axis_wire_summary(colls: List[Dict]) -> Dict[str, Dict]:
    """Per axis-combination wire-dtype split — the activation-collective
    analogue of ``bucket_traffic``'s dp-bucket accounting. For every axis
    combo: payload bytes as they cross the wire, their f32 equivalent
    (what the same exchange would move unquantized), the quantized
    fraction, and the wire dtypes seen. mp_comm's blocked recombination
    shows up here as s8/bf16 payload on mp-involving axes; an exact
    program shows quantized_fraction == 0 everywhere."""
    agg: Dict[str, Dict] = {}
    for c in colls:
        key = "+".join(c["axes"])
        e = agg.setdefault(key, {
            "payload_bytes": 0, "payload_bytes_f32": 0,
            "wire_bytes_per_device": 0, "wire_dtypes": []})
        it = _DTYPE_BYTES.get(c["wire_dtype"] or "f32", 4)
        e["payload_bytes"] += c["payload_bytes"]
        e["payload_bytes_f32"] += c["payload_bytes"] * 4 // it
        e["wire_bytes_per_device"] += c["wire_bytes_per_device"]
        if c["wire_dtype"] and c["wire_dtype"] not in e["wire_dtypes"]:
            e["wire_dtypes"].append(c["wire_dtype"])
    for e in agg.values():
        p32 = e["payload_bytes_f32"]
        e["quantized_fraction"] = (
            1.0 - e["payload_bytes"] / p32 if p32 else 0.0)
    return agg


def axis_payload_summary(colls: List[Dict]) -> Dict[str, int]:
    """Total raw payload bytes per axis combination (pre-algorithm): what
    a hierarchical multi-slice schedule would move across the slice cut
    once per phase."""
    agg: Dict[str, int] = {}
    for c in colls:
        key = "+".join(c["axes"])
        agg[key] = agg.get(key, 0) + c["payload_bytes"]
    return agg


def slice_crossing_traffic(hlo_text: str, mesh, slice_of_device: Dict[int, int]) -> List[Dict]:
    """Collectives whose replica groups span more than one slice — the
    traffic that rides DCN in a multi-slice deployment. `slice_of_device`
    maps device id -> slice id (distributed.mesh._device_slice_ids)."""
    out = []
    for c_line in hlo_text.splitlines():
        m = _COLL_RE.search(c_line)
        if not m or "-done" in c_line:
            continue
        groups = _parse_groups(c_line)
        if not groups:
            continue
        crossing = any(
            len({slice_of_device.get(d, 0) for d in g}) > 1 for g in groups)
        if crossing:
            out.append({
                "kind": m.group(4),
                "payload_bytes": _line_payload_bytes(c_line, m.group(4)),
                "group_size": len(groups[0]),
                "axes": _axes_of_group(groups[0], mesh),
            })
    return out
