"""paddle.audio.features parity — Spectrogram / MelSpectrogram /
LogMelSpectrogram / MFCC layers.

Reference: ``python/paddle/audio/features/layers.py``. Each layer is a thin
Layer over signal.stft + the functional helpers, so the whole feature
pipeline fuses into one XLA program per input shape.
"""
from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..framework.core import Tensor
from ..nn.layer import Layer
from ..signal import stft
from . import functional as F


class Spectrogram(Layer):
    def __init__(
        self,
        n_fft: int = 512,
        hop_length: Optional[int] = None,
        win_length: Optional[int] = None,
        window: str = "hann",
        power: float = 2.0,
        center: bool = True,
        pad_mode: str = "reflect",
        dtype: str = "float32",
    ):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer("window", F.get_window(window, self.win_length, dtype=dtype))

    def forward(self, x):
        from ..tensor import abs as t_abs

        spec = stft(
            x,
            self.n_fft,
            hop_length=self.hop_length,
            win_length=self.win_length,
            window=self.window,
            center=self.center,
            pad_mode=self.pad_mode,
        )
        mag = t_abs(spec)
        if self.power != 1.0:
            mag = mag**self.power
        return mag


class MelSpectrogram(Layer):
    def __init__(
        self,
        sr: int = 22050,
        n_fft: int = 512,
        hop_length: Optional[int] = None,
        win_length: Optional[int] = None,
        window: str = "hann",
        power: float = 2.0,
        center: bool = True,
        pad_mode: str = "reflect",
        n_mels: int = 64,
        f_min: float = 50.0,
        f_max: Optional[float] = None,
        htk: bool = False,
        norm: Union[str, float] = "slaney",
        dtype: str = "float32",
    ):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length, window, power, center, pad_mode, dtype)
        self.register_buffer(
            "fbank",
            F.compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype),
        )

    def forward(self, x):
        from ..tensor import einsum

        spec = self.spectrogram(x)  # [..., freq, time]
        return einsum("mf,...ft->...mt", self.fbank, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None, window="hann",
                 power=2.0, center=True, pad_mode="reflect", n_mels=64, f_min=50.0,
                 f_max=None, htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.mel_spectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center, pad_mode,
            n_mels, f_min, f_max, htk, norm, dtype,
        )
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self.mel_spectrogram(x)
        return F.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect", n_mels=64,
                 f_min=50.0, f_max=None, htk=False, norm="slaney", ref_value=1.0,
                 amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self.log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center, pad_mode,
            n_mels, f_min, f_max, htk, norm, ref_value, amin, top_db, dtype,
        )
        self.register_buffer("dct", F.create_dct(n_mfcc, n_mels, dtype=dtype))

    def forward(self, x):
        from ..tensor import einsum

        logmel = self.log_mel(x)  # [..., mel, time]
        return einsum("mk,...mt->...kt", self.dct, logmel)
