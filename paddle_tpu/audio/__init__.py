"""paddle.audio parity — spectral feature layers and functional helpers.

Reference: ``python/paddle/audio/`` (features: Spectrogram/MelSpectrogram/
LogMelSpectrogram/MFCC layers; functional: mel scale + window + dct
helpers; backends for file IO). Feature compute rides paddle_tpu.signal.stft
(one fused frame→window→rfft XLA program); file IO is the pure-numpy WAV
codec in ``backends`` (mirrors upstream's dependency-free wave_backend,
plus float32/24-bit support it lacks).
"""
from . import backends, functional  # noqa: F401
from .backends import info, load, save  # noqa: F401
from .features import LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram  # noqa: F401
