"""paddle.audio parity — spectral feature layers and functional helpers.

Reference: ``python/paddle/audio/`` (features: Spectrogram/MelSpectrogram/
LogMelSpectrogram/MFCC layers; functional: mel scale + window + dct
helpers; backends for file IO). Feature compute rides paddle_tpu.signal.stft
(one fused frame→window→rfft XLA program); file-IO backends are gated (no
soundfile in this image).
"""
from . import functional  # noqa: F401
from .features import LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram  # noqa: F401


def load(*args, **kwargs):
    raise NotImplementedError(
        "paddle_tpu.audio.load: no audio IO backend in this build; decode "
        "with soundfile/scipy.io.wavfile and pass arrays to the feature layers"
    )


def save(*args, **kwargs):
    raise NotImplementedError("paddle_tpu.audio.save: no audio IO backend in this build")
