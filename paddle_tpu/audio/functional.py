"""paddle.audio.functional parity (mel scale, fbank, dct, windows).

Reference: ``python/paddle/audio/functional/functional.py``, ``window.py``.
"""
from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp

from ..framework.core import Tensor


def _val(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def hz_to_mel(freq, htk: bool = False):
    f = _val(freq) if isinstance(freq, Tensor) else jnp.asarray(freq, jnp.float32)
    if htk:
        out = 2595.0 * jnp.log10(1.0 + f / 700.0)
    else:
        # Slaney formula (librosa/paddle default)
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mels = jnp.where(
            f >= min_log_hz, min_log_mel + jnp.log(f / min_log_hz) / logstep, mels
        )
        out = mels
    return Tensor(out) if isinstance(freq, Tensor) else out


def mel_to_hz(mel, htk: bool = False):
    m = _val(mel) if isinstance(mel, Tensor) else jnp.asarray(mel, jnp.float32)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        freqs = jnp.where(
            m >= min_log_mel, min_log_hz * jnp.exp(logstep * (m - min_log_mel)), freqs
        )
        out = freqs
    return Tensor(out) if isinstance(mel, Tensor) else out


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0, f_max: float = 11025.0, htk: bool = False, dtype="float32"):
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return Tensor(mel_to_hz(mels, htk).astype(dtype))


def fft_frequencies(sr: int, n_fft: int, dtype="float32"):
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(
    sr: int,
    n_fft: int,
    n_mels: int = 64,
    f_min: float = 0.0,
    f_max: Optional[float] = None,
    htk: bool = False,
    norm: Union[str, float] = "slaney",
    dtype="float32",
):
    """[n_mels, n_fft//2+1] triangular mel filterbank (librosa-compatible)."""
    f_max = f_max or sr / 2.0
    fftfreqs = _val(fft_frequencies(sr, n_fft))
    melfreqs = _val(mel_frequencies(n_mels + 2, f_min, f_max, htk))
    fdiff = jnp.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2 : n_mels + 2] - melfreqs[:n_mels])
        weights = weights * enorm[:, None]
    elif isinstance(norm, (int, float)):
        weights = weights / jnp.maximum(
            jnp.linalg.norm(weights, ord=norm, axis=-1, keepdims=True), 1e-10
        )
    return Tensor(weights.astype(dtype))


from ..framework.op import defop as _defop


@_defop(name="power_to_db_op")
def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10, top_db: Optional[float] = 80.0):
    """Registered as a framework op so gradients flow through log-mel
    pipelines (the tape records the vjp)."""
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, spect))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return log_spec


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho", dtype="float32"):
    """[n_mels, n_mfcc] DCT-II matrix (paddle layout: mels @ dct)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct = dct * jnp.where(k == 0, 1.0 / math.sqrt(n_mels), math.sqrt(2.0 / n_mels))
    else:
        dct = dct * 2.0
    return Tensor(dct.astype(dtype))


def get_window(window: str, win_length: int, fftbins: bool = True, dtype="float32"):
    """hann/hamming/blackman/bartlett/kaiser/gaussian(std)/taylor→gated."""
    n = win_length
    sym = not fftbins
    M = n + 1 if not sym else n

    def trim(w):
        return w[:-1] if not sym else w

    i = jnp.arange(M, dtype=jnp.float32)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * jnp.cos(2 * math.pi * i / (M - 1))
    elif window == "hamming":
        w = 0.54 - 0.46 * jnp.cos(2 * math.pi * i / (M - 1))
    elif window == "blackman":
        w = (
            0.42
            - 0.5 * jnp.cos(2 * math.pi * i / (M - 1))
            + 0.08 * jnp.cos(4 * math.pi * i / (M - 1))
        )
    elif window == "bartlett":
        w = 1.0 - jnp.abs(2 * i / (M - 1) - 1.0)
    elif window == "rectangular" or window == "boxcar":
        w = jnp.ones(M)
    elif isinstance(window, tuple) and window[0] == "gaussian":
        std = window[1]
        w = jnp.exp(-0.5 * ((i - (M - 1) / 2) / std) ** 2)
    else:
        raise ValueError(f"unsupported window {window!r}")
    return Tensor(trim(w).astype(dtype))
