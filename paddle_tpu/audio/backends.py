"""paddle.audio.backends parity — pure-numpy WAV codec.

Reference: ``python/paddle/audio/backends/`` (wave_backend.py is upstream's
no-dependency default backend: ``load``/``save``/``info`` over the stdlib
``wave`` module, PCM WAV only; soundfile is an optional richer backend).
This build ships the same capability with a self-contained RIFF/WAVE codec
(stdlib ``wave`` cannot do float32 or 24-bit; this can): PCM_U8 / PCM_16 /
PCM_24 / PCM_32 / IEEE-float32, mono or multichannel, read and write, with
``normalize`` and ``channels_first`` matching the reference semantics.
"""
from __future__ import annotations

import struct

import numpy as np

__all__ = [
    "list_available_backends",
    "get_current_backend",
    "set_backend",
    "load",
    "save",
    "info",
    "AudioInfo",
]

_BACKEND = "wave_backend"


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return _BACKEND


def set_backend(backend_name: str):
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"backend {backend_name!r} unavailable in this build; "
            f"available: {list_available_backends()}"
        )


class AudioInfo:
    """Mirror of the reference backend's info record."""

    def __init__(self, sample_rate, num_frames, num_channels, bits_per_sample,
                 encoding):
        self.sample_rate = sample_rate
        self.num_frames = num_frames
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding

    def __repr__(self):  # pragma: no cover - debugging aid
        return (
            f"AudioInfo(sample_rate={self.sample_rate}, "
            f"num_frames={self.num_frames}, num_channels={self.num_channels}, "
            f"bits_per_sample={self.bits_per_sample}, encoding={self.encoding!r})"
        )


_ENCODINGS = {
    # encoding -> (format_tag, bits, numpy dtype)
    "PCM_U8": (1, 8, np.uint8),
    "PCM_16": (1, 16, np.dtype("<i2")),
    "PCM_24": (1, 24, None),  # packed 3-byte little-endian, no numpy dtype
    "PCM_32": (1, 32, np.dtype("<i4")),
    "PCM_F32": (3, 32, np.dtype("<f4")),
}
_ENC_BY_FMT = {(tag, bits): enc for enc, (tag, bits, _) in _ENCODINGS.items()}


def _parse_riff(data: bytes):
    if len(data) < 12 or data[:4] != b"RIFF" or data[8:12] != b"WAVE":
        raise ValueError("not a RIFF/WAVE file")
    pos, fmt, frames = 12, None, None
    while pos + 8 <= len(data):
        cid, size = data[pos:pos + 4], struct.unpack_from("<I", data, pos + 4)[0]
        body = data[pos + 8:pos + 8 + size]
        if cid == b"fmt ":
            tag, nch, rate, _br, block, bits = struct.unpack_from("<HHIIHH", body)
            if tag == 0xFFFE and size >= 40:  # WAVE_FORMAT_EXTENSIBLE
                tag = struct.unpack_from("<H", body, 24)[0]
            fmt = (tag, nch, rate, block, bits)
        elif cid == b"data":
            frames = body
        pos += 8 + size + (size & 1)  # chunks are word-aligned
    if fmt is None or frames is None:
        raise ValueError("WAV missing fmt/data chunk")
    return fmt, frames


def _decode(fmt, raw):
    tag, nch, rate, _block, bits = fmt
    enc = _ENC_BY_FMT.get((tag, bits))
    if enc == "PCM_24":
        b = np.frombuffer(raw, np.uint8)[: (len(raw) // 3) * 3].reshape(-1, 3)
        # sign-extend 3-byte little-endian into int32
        arr = (
            b[:, 0].astype(np.int32)
            | (b[:, 1].astype(np.int32) << 8)
            | (b[:, 2].astype(np.int8).astype(np.int32) << 16)
        )
    elif enc is not None:
        arr = np.frombuffer(raw, _ENCODINGS[enc][2])
    else:
        raise NotImplementedError(f"WAV format tag={tag} bits={bits} unsupported")
    n = (arr.size // nch) * nch
    return arr[:n].reshape(-1, nch), rate, enc, bits


def _normalize(arr, enc):
    if enc == "PCM_F32":
        return arr.astype(np.float32)
    if enc == "PCM_U8":
        return (arr.astype(np.float32) - 128.0) / 128.0
    scale = float(2 ** {"PCM_16": 15, "PCM_24": 23, "PCM_32": 31}[enc])
    return arr.astype(np.float32) / scale


def info(filepath) -> AudioInfo:
    # streaming header walk: O(chunk headers), never reads sample data
    with open(filepath, "rb") as f:
        head = f.read(12)
        if len(head) < 12 or head[:4] != b"RIFF" or head[8:12] != b"WAVE":
            raise ValueError("not a RIFF/WAVE file")
        fmt = data_size = None
        while True:
            hdr = f.read(8)
            if len(hdr) < 8:
                break
            cid, size = hdr[:4], struct.unpack("<I", hdr[4:])[0]
            if cid == b"fmt ":
                body = f.read(size + (size & 1))
                tag, nch, rate, _br, block, bits = struct.unpack_from("<HHIIHH", body)
                if tag == 0xFFFE and size >= 40:
                    tag = struct.unpack_from("<H", body, 24)[0]
                fmt = (tag, nch, rate, block, bits)
            else:
                if cid == b"data":
                    data_size = size
                f.seek(size + (size & 1), 1)
    if fmt is None or data_size is None:
        raise ValueError("WAV missing fmt/data chunk")
    tag, nch, rate, _block, bits = fmt
    enc = _ENC_BY_FMT.get((tag, bits))
    if enc is None:
        raise NotImplementedError(f"WAV format tag={tag} bits={bits} unsupported")
    return AudioInfo(rate, data_size // (nch * bits // 8), nch, bits, enc)


def load(filepath, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """Returns ``(waveform, sample_rate)``; waveform is float32 in [-1, 1]
    when ``normalize`` (always float32 for float files, matching the
    reference wave_backend), else the integer PCM values."""
    with open(filepath, "rb") as f:
        fmt, raw = _parse_riff(f.read())
    frames, rate, enc, _bits = _decode(fmt, raw)
    end = None if num_frames < 0 else frame_offset + num_frames
    frames = frames[frame_offset:end]
    out = _normalize(frames, enc) if (normalize or enc == "PCM_F32") else frames
    if channels_first:
        out = np.ascontiguousarray(out.T)
    from ..framework.core import Tensor

    return Tensor(out), rate


def save(filepath, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample=None):
    """Write ``src`` (Tensor/ndarray, float in [-1,1] or integer PCM) as WAV."""
    arr = np.asarray(getattr(src, "numpy", lambda: src)())
    if arr.ndim == 1:
        arr = arr[None, :] if channels_first else arr[:, None]
    if arr.ndim != 2:
        raise ValueError(f"expected 1-D or 2-D waveform, got shape {arr.shape}")
    if channels_first:
        arr = arr.T  # -> (frames, channels)
    if bits_per_sample is not None and encoding != "PCM_F32":
        by_bits = {8: "PCM_U8", 16: "PCM_16", 24: "PCM_24", 32: "PCM_32"}
        encoding = by_bits.get(int(bits_per_sample), encoding)
    if encoding not in _ENCODINGS:
        raise NotImplementedError(f"encoding {encoding!r}; use {list(_ENCODINGS)}")
    tag, bits, dtype = _ENCODINGS[encoding]

    if np.issubdtype(arr.dtype, np.floating):
        x = np.clip(arr.astype(np.float64), -1.0, 1.0)
        if encoding == "PCM_F32":
            data = x.astype("<f4").tobytes()
        elif encoding == "PCM_U8":
            data = (np.round(x * 128.0) + 128.0).clip(0, 255).astype(np.uint8).tobytes()
        else:
            hi = float(2 ** (bits - 1) - 1)
            q = np.round(x * (2 ** (bits - 1))).clip(-(2 ** (bits - 1)), hi)
            if encoding == "PCM_24":
                q = q.astype(np.int32)
                b = np.empty(q.shape + (3,), np.uint8)
                b[..., 0], b[..., 1], b[..., 2] = q & 0xFF, (q >> 8) & 0xFF, (q >> 16) & 0xFF
                data = b.tobytes()
            else:
                data = q.astype(dtype).tobytes()
    else:
        if encoding == "PCM_24":
            q = arr.astype(np.int32)
            b = np.empty(q.shape + (3,), np.uint8)
            b[..., 0], b[..., 1], b[..., 2] = q & 0xFF, (q >> 8) & 0xFF, (q >> 16) & 0xFF
            data = b.tobytes()
        else:
            data = arr.astype(dtype).tobytes()

    nch = arr.shape[1]
    block = nch * bits // 8
    hdr = struct.pack(
        "<4sI4s4sIHHIIHH4sI",
        b"RIFF", 36 + len(data), b"WAVE", b"fmt ", 16,
        tag, nch, int(sample_rate), int(sample_rate) * block, block, bits,
        b"data", len(data),
    )
    with open(filepath, "wb") as f:
        f.write(hdr + data)
