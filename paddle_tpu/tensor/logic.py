"""Comparison / logical / bitwise ops (paddle.tensor.logic parity).

Reference: ``python/paddle/tensor/logic.py`` (SURVEY.md §2.2).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework.op import defop, raw


@defop
def equal(x, y, name=None):
    return jnp.equal(x, y)


@defop
def not_equal(x, y, name=None):
    return jnp.not_equal(x, y)


@defop
def greater_than(x, y, name=None):
    return jnp.greater(x, y)


@defop
def greater_equal(x, y, name=None):
    return jnp.greater_equal(x, y)


@defop
def less_than(x, y, name=None):
    return jnp.less(x, y)


@defop
def less_equal(x, y, name=None):
    return jnp.less_equal(x, y)


@defop
def logical_and(x, y, out=None, name=None):
    return jnp.logical_and(x, y)


@defop
def logical_or(x, y, out=None, name=None):
    return jnp.logical_or(x, y)


@defop
def logical_xor(x, y, out=None, name=None):
    return jnp.logical_xor(x, y)


@defop
def logical_not(x, out=None, name=None):
    return jnp.logical_not(x)


@defop
def bitwise_and(x, y, out=None, name=None):
    return jnp.bitwise_and(x, y)


@defop
def bitwise_or(x, y, out=None, name=None):
    return jnp.bitwise_or(x, y)


@defop
def bitwise_xor(x, y, out=None, name=None):
    return jnp.bitwise_xor(x, y)


@defop
def bitwise_not(x, out=None, name=None):
    return jnp.bitwise_not(x)


@defop
def bitwise_left_shift(x, y, is_arithmetic=True, out=None, name=None):
    return jnp.left_shift(x, y)


@defop
def bitwise_right_shift(x, y, is_arithmetic=True, out=None, name=None):
    x = jnp.asarray(x)
    if not is_arithmetic and jnp.issubdtype(x.dtype, jnp.signedinteger):
        # logical shift: zero-fill from the left (reference semantics);
        # keep BOTH operands unsigned so promotion cannot reintroduce sign
        udt = jnp.dtype(f"uint{x.dtype.itemsize * 8}")
        u = x.view(udt)
        yu = jnp.asarray(y).astype(udt)
        return jnp.right_shift(u, yu).view(x.dtype)
    return jnp.right_shift(x, y)


@defop(name="isclose_op")
def _isclose(x, y, rtol, atol, equal_nan):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _isclose(x, y, rtol=float(raw(rtol)), atol=float(raw(atol)), equal_nan=bool(equal_nan))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return _allclose(x, y, rtol=float(raw(rtol)), atol=float(raw(atol)), equal_nan=bool(equal_nan))


@defop(name="allclose_op")
def _allclose(x, y, rtol, atol, equal_nan):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@defop
def equal_all(x, y, name=None):
    return jnp.array_equal(x, y)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(raw(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


@defop
def isin(x, test_x, assume_unique=False, invert=False, name=None):
    """paddle.isin parity: elementwise membership of ``x`` in ``test_x``."""
    out = jnp.isin(x, test_x, assume_unique=assume_unique, invert=invert)
    return out


# paddle 3.x aliases (operator-name spellings)
bitwise_invert = bitwise_not
