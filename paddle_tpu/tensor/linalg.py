"""Linear algebra ops (paddle.tensor.linalg / paddle.linalg parity).

Reference: ``python/paddle/tensor/linalg.py`` (SURVEY.md §2.2). matmul is the
MXU hot path: it is AMP-"white" (runs in bfloat16 under auto_cast) and XLA
tiles it onto the 128x128 systolic array; decompositions lower to XLA's
LAPACK-equivalent HLO custom calls.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework.op import defop, raw


@defop(amp="white")
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim >= 2 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim >= 2 else y
    return jnp.matmul(x, y)


@defop(amp="white")
def bmm(x, y, name=None):
    return jnp.matmul(x, y)


@defop
def dot(x, y, name=None):
    return jnp.sum(x * y, axis=-1)


@defop(amp="white")
def mv(x, vec, name=None):
    return jnp.matmul(x, vec)


@defop
def cross(x, y, axis=9, name=None):
    if axis == 9:
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    return jnp.cross(x, y, axis=axis)


@defop(name="norm_op")
def _norm(x, p, axis, keepdim):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    if p == "fro":
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == np.inf:
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == -np.inf:
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.power(
        jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p
    )


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    elif axis is not None:
        axis = int(axis)
    return _norm(x, p=p, axis=axis, keepdim=bool(keepdim))


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


@defop
def matrix_norm_op(x, p, axis, keepdim):
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    return matrix_norm_op(x, p=p, axis=tuple(axis), keepdim=bool(keepdim))


@defop
def dist(x, y, p=2, name=None):
    d = x - y
    d = jnp.reshape(d, (-1,))
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    if p == np.inf:
        return jnp.max(jnp.abs(d))
    if p == -np.inf:
        return jnp.min(jnp.abs(d))
    return jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)


@defop
def cholesky(x, upper=False, name=None):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2) if upper else L


@defop
def cholesky_solve(x, y, upper=False, name=None):
    return jax.scipy.linalg.cho_solve((y, not upper), x)


@defop(name="qr_op")
def _qr(x, mode):
    return jnp.linalg.qr(x, mode=mode)


def qr(x, mode="reduced", name=None):
    return _qr(x, mode=mode)


@defop(name="svd_op")
def _svd(x, full_matrices):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def svd(x, full_matrices=False, name=None):
    return _svd(x, full_matrices=bool(full_matrices))


@defop
def eigh(x, UPLO="L", name=None):
    w, v = jnp.linalg.eigh(x, UPLO=UPLO)
    return w, v


@defop
def eigvalsh(x, UPLO="L", name=None):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def eig(x, name=None):
    # general eig is CPU-only in jax; run on host
    from ..framework.core import is_tracer_value

    if is_tracer_value(raw(x)):
        raise RuntimeError("eig (non-symmetric) is host-only; run eagerly")
    w, v = np.linalg.eig(np.asarray(raw(x)))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    w, _ = eig(x)
    return w


@defop
def inverse(x, name=None):
    return jnp.linalg.inv(x)


inv = inverse


@defop
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def pinverse(x, rcond=1e-15, name=None):
    """Alias of pinv (torch-style name, probed by migration scripts)."""
    return pinv(x, rcond=rcond)


@defop
def solve(x, y, name=None):
    return jnp.linalg.solve(x, y)


@defop
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    if transpose:
        x = jnp.swapaxes(x, -1, -2)
        upper = not upper
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, unit_diagonal=unitriangular
    )


@defop
def lu_op(x):
    import jax.scipy.linalg as jsl

    lu, piv = jsl.lu_factor(x)
    return lu, piv


def lu(x, pivot=True, get_infos=False, name=None):
    lu_mat, piv = lu_op(x)
    piv = piv.astype("int32")
    if get_infos:
        info = Tensor(jnp.zeros((), jnp.int32))
        return lu_mat, piv, info
    return lu_mat, piv


@defop
def matrix_power(x, n, name=None):
    return jnp.linalg.matrix_power(x, n)


@defop
def slogdet(x, name=None):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


@defop
def det(x, name=None):
    return jnp.linalg.det(x)


@defop
def matrix_rank_op(x, tol, hermitian):
    return jnp.linalg.matrix_rank(x, tol=tol)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return matrix_rank_op(x, tol=raw(tol) if tol is not None else None, hermitian=bool(hermitian)).astype("int64")


def multi_dot(x, name=None):
    vals = [raw(v) for v in x]
    return _multi_dot_op(list(x))


@defop(name="multi_dot_op")
def _multi_dot_op(xs):
    return jnp.linalg.multi_dot(xs)


@defop
def householder_product(x, tau, name=None):
    m, n = x.shape[-2], x.shape[-1]
    eye = jnp.eye(m, dtype=x.dtype)

    def body(i, Q):
        v = jnp.where(jnp.arange(m) > i, x[..., :, i], jnp.where(jnp.arange(m) == i, 1.0, 0.0))
        H = eye - tau[..., i] * jnp.outer(v, v)
        return Q @ H

    Q = eye
    for i in range(n):
        Q = body(i, Q)
    return Q[..., :, :n]


@defop
def corrcoef(x, rowvar=True, name=None):
    return jnp.corrcoef(x, rowvar=rowvar)


@defop
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0, fweights=fweights, aweights=aweights)


@defop
def lstsq_op(x, y, rcond):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


def lstsq(x, y, rcond=None, driver=None, name=None):
    sol, res, rank, sv = lstsq_op(x, y, rcond=rcond)
    return sol, res, rank.astype("int32"), sv


@defop
def pca_lowrank_helper(x, q):
    u, s, vt = jnp.linalg.svd(x - jnp.mean(x, axis=-2, keepdims=True), full_matrices=False)
    return u[..., :q], s[..., :q], jnp.swapaxes(vt, -1, -2)[..., :q]


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    if q is None:
        q = min(6, raw(x).shape[-2], raw(x).shape[-1])
    return pca_lowrank_helper(x, q=int(q))


def mm(input, mat2, name=None):
    """Alias of matmul (paddle keeps both)."""
    return matmul(input, mat2)


@defop
def svdvals(x, name=None):
    """Singular values only (paddle.linalg.svdvals)."""
    return jnp.linalg.svd(x, compute_uv=False)


@defop
def matrix_exp(x, name=None):
    """Matrix exponential (paddle.linalg.matrix_exp; upstream lowers to a
    Padé kernel — XLA gets jax.scipy's squaring-and-scaling expm)."""
    return jax.scipy.linalg.expm(x)


@defop(name="cond_op")
def _cond_op(x, p):
    if p in (None, 2, -2):
        s = jnp.linalg.svd(x, compute_uv=False)
        smax, smin = s[..., 0], s[..., -1]
        return smax / smin if p != -2 else smin / smax
    if p == "fro":
        nx = jnp.sqrt(jnp.sum(jnp.square(jnp.abs(x)), axis=(-2, -1)))
        ni = jnp.sqrt(jnp.sum(jnp.square(jnp.abs(jnp.linalg.inv(x))), axis=(-2, -1)))
        return nx * ni
    if p == "nuc":
        nx = jnp.sum(jnp.linalg.svd(x, compute_uv=False), axis=-1)
        ni = jnp.sum(jnp.linalg.svd(jnp.linalg.inv(x), compute_uv=False), axis=-1)
        return nx * ni
    ord_ = {1: 1, -1: -1, np.inf: np.inf, -np.inf: -np.inf}[p]
    return jnp.linalg.cond(x, p=ord_)


def cond(x, p=None, name=None):
    """Condition number in the norm `p` (paddle.linalg.cond)."""
    return _cond_op(x, p=p)


@defop
def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """(LU-packed, pivots) -> (P, L, U) with A = P @ L @ U
    (paddle.linalg.lu_unpack; pivots are the 0-based LAPACK ipiv that
    `lu()` returns)."""
    m, n = x.shape[-2], x.shape[-1]
    k = min(m, n)
    L = U = None
    if unpack_ludata:
        L = jnp.tril(x[..., :, :k], -1) + jnp.eye(m, k, dtype=x.dtype)
        U = jnp.triu(x[..., :k, :])
    P = None
    if unpack_pivots:
        perm = jnp.broadcast_to(jnp.arange(m), x.shape[:-2] + (m,))
        for i in range(y.shape[-1]):  # replay LAPACK row swaps (static count)
            pi = y[..., i].astype(jnp.int32)
            vi = jnp.take_along_axis(perm, jnp.full(perm.shape[:-1] + (1,), i), -1)
            vp = jnp.take_along_axis(perm, pi[..., None], -1)
            perm = jnp.put_along_axis(
                perm, jnp.full(perm.shape[:-1] + (1,), i), vp, -1,
                inplace=False)
            perm = jnp.put_along_axis(perm, pi[..., None], vi, -1, inplace=False)
        P = jax.nn.one_hot(perm, m, dtype=x.dtype)  # [..., m, m]; row j = e_perm[j]
        P = jnp.swapaxes(P, -1, -2)  # A = P L U  =>  P[:, perm] = I
    return P, L, U


def solve_triangular(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    """paddle.linalg.solve_triangular — same op as triangular_solve."""
    return triangular_solve(x, y, upper=upper, transpose=transpose,
                            unitriangular=unitriangular)


@defop(name="ormqr_op")
def _ormqr_op(x, tau, y, left, transpose):
    m = x.shape[-2]
    k = tau.shape[-1]
    idx = jnp.arange(m)

    def reflect_left(vec_i, acc):
        # H = I - tau_i v v^H applied from the left: acc -= tau_i v (v^H acc)
        v = jnp.where(idx > vec_i, x[..., :, vec_i],
                      jnp.where(idx == vec_i, 1.0, 0.0))
        coef = tau[..., vec_i] * (v @ acc)
        return acc - v[:, None] * coef[None, :]

    def reflect_right(vec_i, acc):
        v = jnp.where(idx > vec_i, x[..., :, vec_i],
                      jnp.where(idx == vec_i, 1.0, 0.0))
        coef = tau[..., vec_i] * (acc @ v)
        return acc - coef[:, None] * v[None, :]

    order = range(k)
    if left:
        # Q y = H_0 (H_1 (... y));  Q^T y = H_{k-1} (... (H_0 y))
        for i in (order if transpose else reversed(order)):
            y = reflect_left(i, y)
    else:
        # y Q = ((y H_0) H_1) ...;  y Q^T applies in reverse
        for i in (reversed(order) if transpose else order):
            y = reflect_right(i, y)
    return y


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply by the implicit Q of a QR factorization without forming it
    (paddle.linalg.ormqr): y <- op(Q) @ y (left) or y @ op(Q)."""
    return _ormqr_op(x, tau, y, left=bool(left), transpose=bool(transpose))


@defop(name="svd_lowrank_op")
def _svd_lowrank_op(x, rng01, q, niter):
    y = x @ rng01  # [..., m, q]
    qm, _ = jnp.linalg.qr(y)
    for _ in range(niter):  # subspace (power) iteration sharpens spectrum
        qm, _ = jnp.linalg.qr(jnp.swapaxes(x, -1, -2) @ qm)
        qm, _ = jnp.linalg.qr(x @ qm)
    b = jnp.swapaxes(qm, -1, -2) @ x  # [..., q, n]
    ub, s, vt = jnp.linalg.svd(b, full_matrices=False)
    return qm @ ub, s, jnp.swapaxes(vt, -1, -2)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (paddle.linalg.svd_lowrank): returns
    (U [m,q], S [q], V [n,q]) of x (or x - M)."""
    from ..framework import rng as _rng

    xv = raw(x)
    if M is not None:
        xv = xv - raw(M)
    q = int(min(q, xv.shape[-2], xv.shape[-1]))
    key = _rng.next_key()
    g = jax.random.normal(key, xv.shape[:-2] + (xv.shape[-1], q), xv.dtype)
    return _svd_lowrank_op(Tensor(xv), Tensor(g), q=q, niter=int(niter))


@defop
def vecdot(x, y, axis=-1, name=None):
    """paddle.linalg.vecdot parity: batched vector dot along ``axis``
    (broadcasts like the reference; conjugates nothing — paddle semantics)."""
    return jnp.sum(x * y, axis=axis)


@defop
def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """paddle.cdist parity: pairwise p-norm distances between the rows of
    the last-2-dim matrices of x [.., n, d] and y [.., m, d] -> [.., n, m].
    p=2 uses the GEMM form ||a-b||^2 = ||a||^2 + ||b||^2 - 2ab (the MXU
    path, matching compute_mode's default)."""
    if p == 2.0 and not str(compute_mode).startswith("donot"):
        x2 = jnp.sum(x * x, axis=-1)[..., :, None]
        y2 = jnp.sum(y * y, axis=-1)[..., None, :]
        xy = jnp.matmul(x, jnp.swapaxes(y, -1, -2))
        return jnp.sqrt(jnp.maximum(x2 + y2 - 2 * xy, 0.0))
    diff = jnp.abs(x[..., :, None, :] - y[..., None, :, :])
    if p == 0:
        return jnp.sum((diff != 0).astype(x.dtype), axis=-1)
    if jnp.isinf(p):
        return jnp.max(diff, axis=-1)
    return jnp.power(jnp.sum(jnp.power(diff, p), axis=-1), 1.0 / p)
