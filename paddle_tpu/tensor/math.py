"""Elementwise & reduction math ops (paddle.tensor.math / stat parity).

Reference: ``python/paddle/tensor/math.py``, ``stat.py`` (SURVEY.md §2.2).
Each op is a pure jnp function registered through ``defop`` — eager mode gets
tape recording via jax.vjp, captured mode gets plain XLA tracing, and XLA
fuses the elementwise chains into surrounding matmuls (HBM-bandwidth
optimization the reference does with hand-written fusion passes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.op import defop, raw
from ..framework.core import Tensor

# ---------------------------------------------------------------- binary ----


@defop
def add(x, y, name=None):
    return jnp.add(x, y)


@defop
def subtract(x, y, name=None):
    return jnp.subtract(x, y)


@defop
def multiply(x, y, name=None):
    return jnp.multiply(x, y)


@defop
def divide(x, y, name=None):
    return jnp.divide(x, y)


@defop
def floor_divide(x, y, name=None):
    return jnp.floor_divide(x, y)


@defop
def remainder(x, y, name=None):
    return jnp.remainder(x, y)


mod = remainder
floor_mod = remainder


@defop
def pow(x, y, name=None):
    return jnp.power(x, y)


@defop
def maximum(x, y, name=None):
    return jnp.maximum(x, y)


@defop
def minimum(x, y, name=None):
    return jnp.minimum(x, y)


@defop
def fmax(x, y, name=None):
    return jnp.fmax(x, y)


@defop
def fmin(x, y, name=None):
    return jnp.fmin(x, y)


@defop
def atan2(x, y, name=None):
    return jnp.arctan2(x, y)


@defop
def lerp(x, y, weight, name=None):
    return x + weight * (y - x)


@defop
def inner(x, y, name=None):
    return jnp.inner(x, y)


@defop
def outer(x, y, name=None):
    return jnp.outer(x, y)


@defop
def kron(x, y, name=None):
    return jnp.kron(x, y)


@defop
def logaddexp(x, y, name=None):
    return jnp.logaddexp(x, y)


@defop
def heaviside(x, y, name=None):
    return jnp.heaviside(x, y)


@defop
def copysign(x, y, name=None):
    return jnp.copysign(x, y)


@defop
def gcd(x, y, name=None):
    return jnp.gcd(x, y)


@defop
def lcm(x, y, name=None):
    return jnp.lcm(x, y)


# ----------------------------------------------------------------- unary ----


@defop
def sqrt(x, name=None):
    return jnp.sqrt(x)


@defop
def rsqrt(x, name=None):
    return jax.lax.rsqrt(x)


@defop
def square(x, name=None):
    return jnp.square(x)


@defop(amp="black")
def exp(x, name=None):
    return jnp.exp(x)


@defop
def expm1(x, name=None):
    return jnp.expm1(x)


@defop(amp="black")
def log(x, name=None):
    return jnp.log(x)


@defop
def log2(x, name=None):
    return jnp.log2(x)


@defop
def log10(x, name=None):
    return jnp.log10(x)


@defop
def log1p(x, name=None):
    return jnp.log1p(x)


@defop
def abs(x, name=None):
    return jnp.abs(x)


@defop
def neg(x, name=None):
    return jnp.negative(x)


@defop
def sign(x, name=None):
    return jnp.sign(x)


@defop
def floor(x, name=None):
    return jnp.floor(x)


@defop
def ceil(x, name=None):
    return jnp.ceil(x)


@defop
def round(x, name=None):
    return jnp.round(x)


@defop
def trunc(x, name=None):
    return jnp.trunc(x)


@defop
def frac(x, name=None):
    return x - jnp.trunc(x)


@defop
def sin(x, name=None):
    return jnp.sin(x)


@defop
def cos(x, name=None):
    return jnp.cos(x)


@defop
def tan(x, name=None):
    return jnp.tan(x)


@defop
def asin(x, name=None):
    return jnp.arcsin(x)


@defop
def acos(x, name=None):
    return jnp.arccos(x)


@defop
def atan(x, name=None):
    return jnp.arctan(x)


@defop
def sinh(x, name=None):
    return jnp.sinh(x)


@defop
def cosh(x, name=None):
    return jnp.cosh(x)


@defop
def tanh(x, name=None):
    return jnp.tanh(x)


@defop
def asinh(x, name=None):
    return jnp.arcsinh(x)


@defop
def acosh(x, name=None):
    return jnp.arccosh(x)


@defop
def atanh(x, name=None):
    return jnp.arctanh(x)


@defop
def reciprocal(x, name=None):
    return jnp.reciprocal(x)


@defop
def erf(x, name=None):
    return jax.scipy.special.erf(x)


@defop
def erfinv(x, name=None):
    return jax.scipy.special.erfinv(x)


@defop
def digamma(x, name=None):
    return jax.scipy.special.digamma(x)


@defop
def lgamma(x, name=None):
    return jax.scipy.special.gammaln(x)


@defop
def sigmoid(x, name=None):
    return jax.nn.sigmoid(x)


@defop
def logit(x, eps=None, name=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


@defop
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


@defop
def clip(x, min=None, max=None, name=None):
    return jnp.clip(x, min, max)


@defop
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    return out


@defop
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return scale_b * jnp.tanh(scale_a * x)


@defop
def rad2deg(x, name=None):
    return jnp.rad2deg(x)


@defop
def deg2rad(x, name=None):
    return jnp.deg2rad(x)


@defop
def angle(x, name=None):
    return jnp.angle(x)


@defop
def conj(x, name=None):
    return jnp.conj(x)


@defop
def real(x, name=None):
    return jnp.real(x)


@defop
def imag(x, name=None):
    return jnp.imag(x)


# ------------------------------------------------------------- logic-ish ----


@defop
def isnan(x, name=None):
    return jnp.isnan(x)


@defop
def isinf(x, name=None):
    return jnp.isinf(x)


@defop
def isfinite(x, name=None):
    return jnp.isfinite(x)


# ------------------------------------------------------------ reductions ----


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.numpy().tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@defop(name="sum")
def _sum(x, axis=None, dtype=None, keepdim=False, name=None):
    if dtype is None and jnp.issubdtype(x.dtype, jnp.bool_):
        dtype = jnp.int64
    return jnp.sum(x, axis=axis, dtype=dtype, keepdims=keepdim)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    from ..framework.dtypes import convert_dtype

    return _sum(x, axis=_axis(axis), dtype=convert_dtype(dtype), keepdim=keepdim)


@defop(name="mean")
def _mean(x, axis=None, keepdim=False, name=None):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def mean(x, axis=None, keepdim=False, name=None):
    return _mean(x, axis=_axis(axis), keepdim=keepdim)


@defop(name="max")
def _max(x, axis=None, keepdim=False, name=None):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim=False, name=None):
    return _max(x, axis=_axis(axis), keepdim=keepdim)


@defop(name="min")
def _min(x, axis=None, keepdim=False, name=None):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False, name=None):
    return _min(x, axis=_axis(axis), keepdim=keepdim)


amax = max
amin = min


@defop(name="prod")
def _prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return jnp.prod(x, axis=axis, keepdims=keepdim, dtype=dtype)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    from ..framework.dtypes import convert_dtype

    return _prod(x, axis=_axis(axis), keepdim=keepdim, dtype=convert_dtype(dtype))


@defop(name="all")
def _all(x, axis=None, keepdim=False, name=None):
    return jnp.all(x, axis=axis, keepdims=keepdim)


def all(x, axis=None, keepdim=False, name=None):
    return _all(x, axis=_axis(axis), keepdim=keepdim)


@defop(name="any")
def _any(x, axis=None, keepdim=False, name=None):
    return jnp.any(x, axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim=False, name=None):
    return _any(x, axis=_axis(axis), keepdim=keepdim)


@defop(name="var_op")
def _var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _var(x, axis=_axis(axis), unbiased=unbiased, keepdim=keepdim)


@defop(name="std_op")
def _std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return _std(x, axis=_axis(axis), unbiased=unbiased, keepdim=keepdim)


@defop(name="median_op")
def _median(x, axis=None, keepdim=False, name=None):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def median(x, axis=None, keepdim=False, name=None):
    return _median(x, axis=_axis(axis), keepdim=keepdim)


@defop(name="quantile_op")
def _quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False, name=None):
    return _quantile(x, raw(q), axis=_axis(axis), keepdim=keepdim)


@defop
def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return jnp.nansum(x, axis=axis, dtype=dtype, keepdims=keepdim)


@defop
def nanmean(x, axis=None, keepdim=False, name=None):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


@defop(name="logsumexp_op")
def _logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _logsumexp(x, axis=_axis(axis), keepdim=keepdim)


@defop
def count_nonzero(x, axis=None, keepdim=False, name=None):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim)


# ------------------------------------------------------------- cumulative ----


@defop
def cumsum(x, axis=None, dtype=None, name=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=dtype)


@defop
def cumprod(x, dim=None, dtype=None, name=None):
    return jnp.cumprod(x, axis=dim, dtype=dtype)


@defop
def cummax_op(x, axis):
    return jax.lax.associative_scan(jnp.maximum, x, axis=axis)


@defop
def cummin_op(x, axis):
    return jax.lax.associative_scan(jnp.minimum, x, axis=axis)


def cummax(x, axis=None, dtype="int64", name=None):
    if axis is None:
        x = x.reshape([-1])
        axis = 0
    values = cummax_op(x, axis=int(axis))
    # paddle returns (values, indices); indices computed eagerly
    return values, None


def cummin(x, axis=None, dtype="int64", name=None):
    if axis is None:
        x = x.reshape([-1])
        axis = 0
    return cummin_op(x, axis=int(axis)), None


@defop
def logcumsumexp(x, axis=None, name=None):
    if axis is None:
        x = jnp.reshape(x, (-1,))
        axis = 0
    return jax.lax.associative_scan(jnp.logaddexp, x, axis=axis)


# ---------------------------------------------------------------- others ----


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    return _add_n(list(inputs))


@defop(name="add_n_op")
def _add_n(inputs):
    out = inputs[0]
    for v in inputs[1:]:
        out = out + v
    return out


@defop
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


@defop
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


@defop
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


@defop
def multiply_no_nan(x, y, name=None):
    return jnp.where(y == 0, jnp.zeros_like(x * y), x * y)


@defop
def increment(x, value=1.0, name=None):
    return x + jnp.asarray(value, x.dtype)


@defop
def histogram_op(x, bins, min, max):
    return jnp.histogram(x, bins=bins, range=(min, max))[0]


def histogram(x, bins=100, min=0, max=0, name=None):
    xv = raw(x)
    if min == 0 and max == 0:
        min, max = float(xv.min()), float(xv.max())
    out = histogram_op(x, bins=int(bins), min=float(min), max=float(max))
    return out.astype("int64")


@defop
def bincount(x, weights=None, minlength=0, name=None):
    return jnp.bincount(x, weights=weights, minlength=minlength, length=None)


@defop
def broadcast_shape_helper(x, y):
    return jnp.broadcast_arrays(x, y)[0]


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


@defop
def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return jnp.trapezoid(y, x=x, axis=axis)
    return jnp.trapezoid(y, dx=1.0 if dx is None else dx, axis=axis)


@defop
def nanmedian(x, axis=None, keepdim=False, name=None):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


@defop(name="renorm_op")
def _renorm(x, p, axis, max_norm):
    # p-norm over all dims except `axis`; rows exceeding max_norm are scaled
    dims = tuple(d for d in range(x.ndim) if d != axis)
    norms = (jnp.abs(x) ** p).sum(dims, keepdims=True) ** (1.0 / p)
    factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-12), 1.0)
    return x * factor


def renorm(x, p, axis, max_norm, name=None):
    return _renorm(x, p=float(p), axis=int(axis), max_norm=float(max_norm))


@jax.custom_vjp
def _frexp_impl(x):
    return jnp.frexp(x)


def _frexp_fwd(x):
    m, e = jnp.frexp(x)
    return (m, e), e


def _frexp_bwd(e, cot):
    # x = m * 2**e with e locally constant, so dm/dx = 2**-e almost
    # everywhere (binade boundaries have measure zero); the integer
    # exponent output carries no gradient (its cotangent is float0)
    gm = cot[0]
    return (gm * jnp.exp2(-e.astype(gm.dtype)),)


_frexp_impl.defvjp(_frexp_fwd, _frexp_bwd)


@defop
def frexp(x, name=None):
    return _frexp_impl(x)


@defop
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y)."""
    return beta * input + alpha * jnp.matmul(x, y)


@defop
def ldexp(x, y, name=None):
    return x * jnp.exp2(y.astype(jnp.float32)).astype(x.dtype if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else jnp.float32)


@defop
def nextafter(x, y, name=None):
    return jnp.nextafter(x, y)


@defop
def hypot(x, y, name=None):
    return jnp.hypot(x, y)


@defop
def signbit(x, name=None):
    return jnp.signbit(x)


@defop
def isposinf(x, name=None):
    return jnp.isposinf(x)


@defop
def isneginf(x, name=None):
    return jnp.isneginf(x)


@defop
def isreal(x, name=None):
    return jnp.isreal(x)


@defop
def sgn(x, name=None):
    """Complex-aware sign: x/|x| for complex, jnp.sign otherwise."""
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0, x / jnp.where(mag == 0, 1, mag))
    return jnp.sign(x)


@defop
def positive(x, name=None):
    return +jnp.asarray(x)


@defop
def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    y = jnp.asarray(y)
    n = y.shape[axis]
    y0 = jax.lax.slice_in_dim(y, 0, n - 1, axis=axis)
    y1 = jax.lax.slice_in_dim(y, 1, n, axis=axis)
    if x is not None:
        x = jnp.asarray(x)
        d = jax.lax.slice_in_dim(x, 1, n, axis=axis) - jax.lax.slice_in_dim(x, 0, n - 1, axis=axis)
    else:
        d = dx if dx is not None else 1.0
    return jnp.cumsum((y0 + y1) * 0.5 * d, axis=axis)


def combinations(x, r=2, with_replacement=False, name=None):
    """All r-combinations of a 1-D tensor's elements (host-computed index
    set; eager-only like the reference's op)."""
    import itertools as _it

    from ..framework.op import raw as _raw

    v = jnp.asarray(_raw(x))
    n = v.shape[0]
    gen = _it.combinations_with_replacement(range(n), r) if with_replacement \
        else _it.combinations(range(n), r)
    idx = np.asarray(list(gen), np.int32).reshape(-1, r)
    return Tensor(v[idx])


@defop
def polar(abs, angle, name=None):
    return abs * jnp.exp(1j * angle.astype(jnp.float32))


@defop
def as_complex(x, name=None):
    """[..., 2] float -> [...] complex."""
    x = jnp.asarray(x)
    return jax.lax.complex(x[..., 0], x[..., 1])


@defop
def as_real(x, name=None):
    """[...] complex -> [..., 2] float."""
    x = jnp.asarray(x)
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


# --------------------------------------------------------------------------
# special functions (paddle.i0/i0e/i1/i1e/polygamma/igamma/igammac parity;
# reference: python/paddle/tensor/math.py — phi Bessel/gamma kernels. XLA
# lowers the jax.scipy.special implementations to fused elementwise HLO.)
# --------------------------------------------------------------------------
@defop
def i0(x, name=None):
    return jax.scipy.special.i0(x)


@defop
def i0e(x, name=None):
    return jax.scipy.special.i0e(x)


@defop
def i1(x, name=None):
    return jax.scipy.special.i1(x)


@defop
def i1e(x, name=None):
    return jax.scipy.special.i1e(x)


@defop
def polygamma(x, n, name=None):
    return jax.scipy.special.polygamma(n, x)


@defop
def igamma(x, a, name=None):
    """Regularized upper incomplete gamma Q(x, a) (paddle.igamma)."""
    return jax.scipy.special.gammaincc(x, a)


@defop
def igammac(x, a, name=None):
    """Regularized lower incomplete gamma P(x, a) (paddle.igammac)."""
    return jax.scipy.special.gammainc(x, a)


@defop(name="histogramdd_op")
def _histogramdd_op(sample, bins, ranges, density, weights):
    h, edges = jnp.histogramdd(sample, bins=bins, range=ranges,
                               density=density, weights=weights)
    return h, list(edges)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    """N-D histogram (paddle.histogramdd): returns (hist, list of edges)."""
    w = raw(weights) if weights is not None else None
    if isinstance(bins, (list, tuple)) and len(bins) and hasattr(bins[0], "ndim"):
        bins = [raw(b) for b in bins]
    h, edges = _histogramdd_op(x, bins=bins, ranges=ranges,
                               density=bool(density), weights=w)
    return h, edges


@defop
def sinc(x, name=None):
    return jnp.sinc(x)


def fix(x, name=None):
    """Alias of trunc (paddle.fix)."""
    return trunc(x)


@defop(name="nanquantile_op")
def _nanquantile(x, q, axis, keepdim):
    return jnp.nanquantile(x, jnp.asarray(q), axis=axis, keepdims=keepdim)


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return _nanquantile(x, raw(q), axis=_axis(axis), keepdim=keepdim)


@defop
def gammaln(x, name=None):
    return jax.scipy.special.gammaln(x)


@defop
def gammainc(x, y, name=None):
    """Regularized lower incomplete gamma P(x, y) (paddle.gammainc)."""
    return jax.scipy.special.gammainc(x, y)


@defop
def gammaincc(x, y, name=None):
    """Regularized upper incomplete gamma Q(x, y) (paddle.gammaincc)."""
    return jax.scipy.special.gammaincc(x, y)


@defop
def multigammaln(x, p, name=None):
    return jax.scipy.special.multigammaln(x, p)


@defop
def logaddexp2(x, y, name=None):
    return jnp.logaddexp2(x, y)


@defop(name="histc_op")
def _histc(x, bins, min, max):
    lo, hi = min, max
    if lo == 0 and hi == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    h, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return h


def histc(input, bins=100, min=0, max=0, name=None):
    """Histogram counts (paddle.histc; min==max==0 -> data range)."""
    return _histc(input, bins=int(bins), min=float(min), max=float(max))


def msort(x, name=None):
    """Sort along axis 0 (paddle.msort)."""
    return _msort_op(x)


@defop(name="msort_op")
def _msort_op(x):
    return jnp.sort(x, axis=0)


@defop
def histogram_bin_edges(input, bins=100, min=0.0, max=0.0, name=None):
    """paddle.histogram_bin_edges parity: the bin edges histogram() would
    use (min==max==0 means use the data range)."""
    v = input.reshape(-1).astype(jnp.float32)
    lo, hi = float(min), float(max)
    if lo == 0.0 and hi == 0.0:
        lo_v, hi_v = jnp.min(v), jnp.max(v)
    else:
        lo_v = jnp.asarray(lo, jnp.float32)
        hi_v = jnp.asarray(hi, jnp.float32)
    # constant data: widen the empty range (the reference kernels expand by
    # 1 each side so downstream binning stays well-defined)
    same = lo_v == hi_v
    lo_v = jnp.where(same, lo_v - 1.0, lo_v)
    hi_v = jnp.where(same, hi_v + 1.0, hi_v)
    return jnp.linspace(lo_v, hi_v, int(bins) + 1)


@defop
def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """paddle.baddbmm parity: beta*input + alpha*(x @ y), batched. At
    beta==0 the input is IGNORED (contract: it may be an uninitialized
    buffer — 0*inf must not produce NaN)."""
    if beta == 0:
        return alpha * jnp.matmul(x, y)
    return beta * input + alpha * jnp.matmul(x, y)


def is_floating_point(x):
    """paddle.is_floating_point parity (dtype predicate)."""
    from ..framework import dtypes as _dt
    from ..framework.op import raw as _raw

    return _dt.is_floating_point(_raw(x).dtype)


def is_integer(x):
    """paddle.is_integer parity."""
    from ..framework import dtypes as _dt
    from ..framework.op import raw as _raw

    return _dt.is_integer(_raw(x).dtype)


def is_complex(x):
    """paddle.is_complex parity."""
    from ..framework import dtypes as _dt
    from ..framework.op import raw as _raw

    return _dt.is_complex(_raw(x).dtype)


def tolist(x):
    """paddle.tolist parity (one source of truth: Tensor.tolist)."""
    return x.tolist()
