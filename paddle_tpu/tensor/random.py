"""Random sampling ops (paddle.tensor.random parity).

Reference: ``python/paddle/tensor/random.py`` (SURVEY.md §2.2). TPU-native
design: every sample consumes a fresh splittable PRNG key from
``framework.rng`` — stateful-looking API (paddle.seed / paddle.rand) over a
counter-based stateless PRNG, so the same ops also work inside captured
programs where the jit machinery injects a trace-scoped key (see rng.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as _dtypes, rng as _rng
from ..framework.core import Tensor
from ..framework.op import defop, raw


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(raw(s)) if isinstance(s, Tensor) else int(s) for s in shape)


@defop(name="uniform_op")
def _uniform(key, shape, dtype, min, max):
    return jax.random.uniform(key, shape, dtype=dtype, minval=min, maxval=max)


def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0, name=None):
    dt = _dtypes.convert_dtype(dtype) or _dtypes.float32
    key = jax.random.key(seed) if seed else _rng.next_key()
    return _uniform(key, shape=_shape(shape), dtype=dt, min=float(raw(min)), max=float(raw(max)))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype or "float32", 0.0, 1.0)


@defop(name="normal_op")
def _normal(key, shape, dtype, mean, std):
    return jax.random.normal(key, shape, dtype=dtype) * std + mean


def standard_normal(shape, dtype=None, name=None):
    dt = _dtypes.convert_dtype(dtype) or _dtypes.float32
    return _normal(_rng.next_key(), shape=_shape(shape), dtype=dt, mean=0.0, std=1.0)


randn = standard_normal


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        shp = _shape(shape) if shape is not None else tuple(np.broadcast_shapes(
            tuple(raw(mean).shape) if isinstance(mean, Tensor) else (),
            tuple(raw(std).shape) if isinstance(std, Tensor) else (),
        ))
        return _normal_t(mean, std, _rng.next_key(), shape=shp)
    return _normal(_rng.next_key(), shape=_shape(shape if shape is not None else [1]), dtype=_dtypes.float32, mean=float(mean), std=float(std))


@defop(name="normal_tensor_op")
def _normal_t(mean, std, key, shape):
    return jax.random.normal(key, shape, dtype=jnp.float32) * std + mean


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    dt = _dtypes.convert_dtype(dtype) or _dtypes.float32
    key = jax.random.key(seed) if seed else _rng.next_key()
    return _normal(key, shape=_shape(shape), dtype=dt, mean=float(mean), std=float(std))


@defop(name="randint_op")
def _randint(key, shape, low, high, dtype):
    return jax.random.randint(key, shape, low, high, dtype=dtype)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    dt = _dtypes.convert_dtype(dtype) or _dtypes.int64
    return _randint(_rng.next_key(), shape=_shape(shape), low=int(raw(low)), high=int(raw(high)), dtype=dt)


def randint_like(x, low=0, high=None, dtype=None, name=None):
    dt = dtype or _dtypes.dtype_name(raw(x).dtype)
    return randint(low, high, tuple(raw(x).shape), dt)


@defop(name="randperm_op")
def _randperm(key, n, dtype):
    return jax.random.permutation(key, n).astype(dtype)


def randperm(n, dtype="int64", name=None):
    return _randperm(_rng.next_key(), n=int(n), dtype=_dtypes.convert_dtype(dtype))


@defop(name="bernoulli_op")
def _bernoulli(x, key):
    return jax.random.bernoulli(key, x).astype(x.dtype)


def bernoulli(x, name=None):
    return _bernoulli(x, _rng.next_key())


@defop(name="poisson_op")
def _poisson(x, key):
    return jax.random.poisson(key, x).astype(x.dtype)


def poisson(x, name=None):
    return _poisson(x, _rng.next_key())


@defop(name="multinomial_op")
def _multinomial(x, key, num_samples, replacement):
    logits = jnp.log(jnp.maximum(x, 1e-30))
    if replacement:
        batch = x.shape[:-1]
        out = jax.random.categorical(key, logits, axis=-1, shape=(num_samples,) + batch)
        return jnp.moveaxis(out, 0, -1) if batch else out
    # without replacement: Gumbel top-k trick
    g = jax.random.gumbel(key, x.shape, dtype=logits.dtype)
    _, idx = jax.lax.top_k(logits + g, num_samples)
    return idx


def multinomial(x, num_samples=1, replacement=False, name=None):
    out = _multinomial(x, _rng.next_key(), num_samples=int(num_samples), replacement=bool(replacement))
    out = out.astype("int64")
    if num_samples == 1 and not replacement:
        return out
    return out


def uniform_(x, min=-1.0, max=1.0, name=None):
    out = uniform(tuple(raw(x).shape), _dtypes.dtype_name(raw(x).dtype), min, max)
    return x._rebind(out._value)


def normal_(x, mean=0.0, std=1.0, name=None):
    out = gaussian(tuple(raw(x).shape), mean, std, dtype=_dtypes.dtype_name(raw(x).dtype))
    return x._rebind(out._value)


def exponential_(x, lam=1.0, name=None):
    key = _rng.next_key()
    u = jax.random.uniform(key, tuple(raw(x).shape), dtype=raw(x).dtype)
    return x._rebind(-jnp.log1p(-u) / lam)


def shuffle_(x, name=None):
    key = _rng.next_key()
    return x._rebind(jax.random.permutation(key, raw(x), axis=0))


def bernoulli_(x, p=0.5, name=None):
    """In-place Bernoulli fill (paddle.Tensor.bernoulli_)."""
    key = _rng.next_key()
    out = jax.random.bernoulli(key, p, tuple(raw(x).shape))
    return x._rebind(out.astype(raw(x).dtype))


@defop(name="log_normal_op")
def _log_normal(key, shape, mean, std, dtype):
    return jnp.exp(mean + std * jax.random.normal(key, shape, dtype))


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    """Samples with ln X ~ N(mean, std) (paddle.log_normal)."""
    return _log_normal(_rng.next_key(), shape=_shape(shape or [1]),
                       mean=float(mean), std=float(std), dtype=jnp.float32)


def log_normal_(x, mean=1.0, std=2.0, name=None):
    out = _log_normal(_rng.next_key(), shape=tuple(raw(x).shape),
                      mean=float(mean), std=float(std), dtype=raw(x).dtype)
    return x._rebind(raw(out))


@defop(name="standard_gamma_op")
def _standard_gamma(x, key):
    return jax.random.gamma(key, x)


def standard_gamma(x, name=None):
    """Gamma(alpha=x, rate=1) samples, elementwise (paddle.standard_gamma)."""
    return _standard_gamma(x, _rng.next_key())


@defop(name="binomial_op")
def _binomial(count, prob, key):
    return jax.random.binomial(key, count, prob).astype(jnp.int64)


def binomial(count, prob, name=None):
    """Binomial(count, prob) samples, elementwise-broadcast (paddle.binomial)."""
    return _binomial(count, prob, _rng.next_key())
