"""paddle.tensor namespace: op re-exports + Tensor method patching.

Reference pattern: upstream monkey-patches the pybind tensor with Python
methods (``python/paddle/base/dygraph/tensor_patch_methods.py``,
``python/paddle/tensor/__init__.py`` — SURVEY.md §2.2). We do the same onto
``framework.core.Tensor``.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework.op import defop, raw
from . import creation, linalg, logic, manipulation, math, random
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .manipulation import paddle_slice as slice  # noqa: F401,A001
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403


@defop(name="einsum_op")
def _einsum(operands, equation):
    return jnp.einsum(equation, *operands)


def einsum(equation, *operands):
    """paddle.einsum parity (reference: python/paddle/tensor/einsum.py)."""
    return _einsum(list(operands), equation=equation)


# --------------------------------------------------------------------------
# Tensor method patching
# --------------------------------------------------------------------------
def _binary(fn, swap=False):
    def method(self, other):
        if swap:
            return fn(other if isinstance(other, Tensor) else Tensor(jnp.asarray(other, self.dtype) if np.isscalar(other) else jnp.asarray(other)), self)
        return fn(self, other)

    return method


def _patch():
    T = Tensor
    m, mp, lg, la = math, manipulation, logic, linalg

    # arithmetic dunders
    T.__add__ = _binary(m.add)
    T.__radd__ = _binary(m.add, swap=True)
    T.__sub__ = _binary(m.subtract)
    T.__rsub__ = _binary(m.subtract, swap=True)
    T.__mul__ = _binary(m.multiply)
    T.__rmul__ = _binary(m.multiply, swap=True)
    T.__div__ = T.__truediv__ = _binary(m.divide)
    T.__rdiv__ = T.__rtruediv__ = _binary(m.divide, swap=True)
    T.__floordiv__ = _binary(m.floor_divide)
    T.__rfloordiv__ = _binary(m.floor_divide, swap=True)
    T.__mod__ = _binary(m.remainder)
    T.__rmod__ = _binary(m.remainder, swap=True)
    T.__pow__ = _binary(m.pow)
    T.__rpow__ = _binary(m.pow, swap=True)
    T.__matmul__ = _binary(la.matmul)
    T.__rmatmul__ = _binary(la.matmul, swap=True)
    T.__neg__ = lambda self: m.neg(self)
    T.__abs__ = lambda self: m.abs(self)

    # comparisons (elementwise, like paddle); keep identity hashing
    T.__eq__ = _binary(lg.equal)
    T.__ne__ = _binary(lg.not_equal)
    T.__lt__ = _binary(lg.less_than)
    T.__le__ = _binary(lg.less_equal)
    T.__gt__ = _binary(lg.greater_than)
    T.__ge__ = _binary(lg.greater_equal)
    T.__hash__ = object.__hash__

    # bitwise/logical
    T.__and__ = _binary(lg.bitwise_and)
    T.__or__ = _binary(lg.bitwise_or)
    T.__xor__ = _binary(lg.bitwise_xor)
    T.__invert__ = lambda self: lg.bitwise_not(self)

    # indexing
    T.__getitem__ = lambda self, idx: mp.tensor_getitem(self, idx)
    T.__setitem__ = lambda self, idx, v: mp.tensor_setitem(self, idx, v)

    # named methods: route to module functions with self as first arg
    names = {
        # math
        "add": m.add, "subtract": m.subtract, "multiply": m.multiply,
        "divide": m.divide, "floor_divide": m.floor_divide, "remainder": m.remainder,
        "mod": m.remainder, "pow": m.pow, "maximum": m.maximum, "minimum": m.minimum,
        "fmax": m.fmax, "fmin": m.fmin, "sqrt": m.sqrt, "rsqrt": m.rsqrt,
        "square": m.square, "exp": m.exp, "expm1": m.expm1, "log": m.log,
        "log2": m.log2, "log10": m.log10, "log1p": m.log1p, "abs": m.abs,
        "neg": m.neg, "sign": m.sign, "floor": m.floor, "ceil": m.ceil,
        "round": m.round, "trunc": m.trunc, "frac": m.frac, "sin": m.sin,
        "cos": m.cos, "tan": m.tan, "asin": m.asin, "acos": m.acos,
        "atan": m.atan, "sinh": m.sinh, "cosh": m.cosh, "tanh": m.tanh,
        "asinh": m.asinh, "acosh": m.acosh, "atanh": m.atanh,
        "reciprocal": m.reciprocal, "erf": m.erf, "erfinv": m.erfinv,
        "digamma": m.digamma, "lgamma": m.lgamma, "sigmoid": m.sigmoid,
        "clip": m.clip, "scale": m.scale, "isnan": m.isnan, "isinf": m.isinf,
        "isfinite": m.isfinite, "sum": m.sum, "mean": m.mean, "max": m.max,
        "min": m.min, "prod": m.prod, "all": m.all, "any": m.any, "var": m.var,
        "std": m.std, "median": m.median, "quantile": m.quantile,
        "nansum": m.nansum, "nanmean": m.nanmean, "logsumexp": m.logsumexp,
        "count_nonzero": m.count_nonzero, "cumsum": m.cumsum,
        "cumprod": m.cumprod, "trace": m.trace, "diagonal": m.diagonal,
        "diff": m.diff, "lerp": m.lerp, "atan2": m.atan2, "outer": m.outer,
        "inner": m.inner, "kron": m.kron, "nan_to_num": m.nan_to_num,
        "increment": m.increment, "logit": m.logit, "bincount": m.bincount,
        "amax": m.amax, "amin": m.amin, "conj": m.conj, "real": m.real,
        "imag": m.imag, "angle": m.angle, "rad2deg": m.rad2deg,
        "deg2rad": m.deg2rad, "heaviside": m.heaviside, "logaddexp": m.logaddexp,
        # manipulation
        "reshape": mp.reshape, "reshape_": mp.reshape_, "transpose": mp.transpose,
        "flatten": mp.flatten, "squeeze": mp.squeeze, "squeeze_": mp.squeeze_,
        "unsqueeze": mp.unsqueeze, "unsqueeze_": mp.unsqueeze_, "tile": mp.tile,
        "expand": mp.expand, "expand_as": mp.expand_as,
        "broadcast_to": mp.broadcast_to, "flip": mp.flip, "roll": mp.roll,
        "gather": mp.gather, "gather_nd": mp.gather_nd,
        "take_along_axis": mp.take_along_axis, "put_along_axis": mp.put_along_axis,
        "index_select": mp.index_select, "index_sample": mp.index_sample,
        "index_add": mp.index_add, "index_put": mp.index_put,
        "masked_select": mp.masked_select, "masked_fill": mp.masked_fill,
        "scatter": mp.scatter, "scatter_": mp.scatter_,
        "scatter_nd_add": mp.scatter_nd_add, "where": mp.where,
        "sort": mp.sort, "argsort": mp.argsort, "topk": mp.topk,
        "argmax": mp.argmax, "argmin": mp.argmin, "kthvalue": mp.kthvalue,
        "mode": mp.mode, "nonzero": mp.nonzero, "unique": mp.unique,
        "unique_consecutive": mp.unique_consecutive, "split": mp.split,
        "chunk": mp.chunk, "unbind": mp.unbind, "unstack": mp.unstack,
        "cast": mp.cast, "cast_": mp.cast_, "astype": mp.cast,
        "moveaxis": mp.moveaxis, "swapaxes": mp.swapaxes, "repeat_interleave": mp.repeat_interleave,
        "searchsorted": mp.searchsorted, "bucketize": mp.bucketize,
        "view": mp.view, "view_as": mp.view_as,
        "concat": mp.concat, "rot90": mp.rot90,
        # linalg
        "matmul": la.matmul, "bmm": la.bmm, "dot": la.dot, "mv": la.mv,
        "vecdot": la.vecdot, "isin": lg.isin, "cdist": la.cdist,
        "bitwise_invert": lg.bitwise_invert,
        "strided_slice": mp.strided_slice,
        "fill_diagonal": mp.fill_diagonal,
        "fill_diagonal_tensor": mp.fill_diagonal_tensor,
        "histogram_bin_edges": math.histogram_bin_edges,
        "norm": la.norm, "dist": la.dist, "cholesky": la.cholesky,
        "inverse": la.inverse, "cross": la.cross, "t": mp.t,
        "matrix_power": la.matrix_power,
        # logic
        "equal": lg.equal, "not_equal": lg.not_equal,
        "greater_than": lg.greater_than, "greater_equal": lg.greater_equal,
        "less_than": lg.less_than, "less_equal": lg.less_equal,
        "logical_and": lg.logical_and, "logical_or": lg.logical_or,
        "logical_xor": lg.logical_xor, "logical_not": lg.logical_not,
        "bitwise_and": lg.bitwise_and, "bitwise_or": lg.bitwise_or,
        "bitwise_xor": lg.bitwise_xor, "bitwise_not": lg.bitwise_not,
        "isclose": lg.isclose, "allclose": lg.allclose, "equal_all": lg.equal_all,
        # creation
        "tril": creation.tril, "triu": creation.triu, "clone": creation.clone,
        "zero_": None, "fill_": None,
    }
    for name, fn in names.items():
        if fn is not None:
            setattr(T, name, fn)

    # in-place helpers
    def zero_(self):
        return self._rebind(jnp.zeros_like(self._value))

    def fill_(self, value):
        return self._rebind(jnp.full_like(self._value, raw(value)))

    def add_(self, y):
        return self._rebind(self._value + (raw(y)))

    def subtract_(self, y):
        return self._rebind(self._value - raw(y))

    def multiply_(self, y):
        return self._rebind(self._value * raw(y))

    def divide_(self, y):
        return self._rebind(self._value / raw(y))

    def scale_(self, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
        v = self._value * scale + bias if bias_after_scale else (self._value + bias) * scale
        return self._rebind(v)

    def clip_(self, min=None, max=None):
        return self._rebind(jnp.clip(self._value, raw(min), raw(max)))

    def exponential_(self, lam=1.0, name=None):
        return random.exponential_(self, lam)

    def uniform_(self, min=-1.0, max=1.0, name=None):
        return random.uniform_(self, min, max)

    def normal_(self, mean=0.0, std=1.0, name=None):
        return random.normal_(self, mean, std)

    def _inplace_unary(fn):
        def method(self):
            return self._rebind(fn(self._value))
        return method

    for nm, fn in {
        "exp_": jnp.exp, "floor_": jnp.floor, "ceil_": jnp.ceil,
        "tanh_": jnp.tanh, "sqrt_": jnp.sqrt,
        "rsqrt_": lambda v: 1.0 / jnp.sqrt(v),
        "reciprocal_": lambda v: 1.0 / v, "round_": jnp.round,
    }.items():
        meth = _inplace_unary(fn)
        meth.__name__ = nm
        setattr(T, nm, meth)

    # remainder_ / pow_ come from the _inplace_of loop below (tape-recording)

    def flatten_(self, start_axis=0, stop_axis=-1):
        out = mp.flatten(self, start_axis, stop_axis)
        return self._rebind(out._value, out._node)

    T.dim = lambda self: self.ndim
    T.rank = lambda self: self.ndim
    T.ndimension = lambda self: self.ndim
    T.element_size = lambda self: self._value.dtype.itemsize
    T.nbytes = property(lambda self: self._value.dtype.itemsize * self.size)
    T.value = lambda self: self

    for f in (zero_, fill_, add_, subtract_, multiply_, divide_, scale_, clip_,
              exponential_, uniform_, normal_, flatten_,
              bernoulli_, log_normal_):
        setattr(T, f.__name__, f)

    # generic in-place variants: run the out-of-place op, rebind the value
    # (tape semantics identical to the reference's inplace ops: the result
    # participates in autograd as the op's output)
    def _inplace_of(op_name):
        def method(self, *a, **k):
            out = getattr(self, op_name)(*a, **k)
            return self._rebind(out._value, out._node)

        method.__name__ = op_name + "_"
        return method

    for base in ("lerp", "erfinv", "put_along_axis", "index_add",
                 "index_put", "masked_fill", "masked_scatter", "sigmoid",
                 "tanh", "sqrt", "rsqrt", "ceil", "floor", "round",
                 "reciprocal", "index_copy", "remainder", "pow",
                 "fill_diagonal", "fill_diagonal_tensor"):
        if hasattr(T, base):
            setattr(T, base + "_", _inplace_of(base))

    def index_copy(self, index, value, axis=0):
        """Write rows of `value` at `index` along `axis` (torch-style
        index_copy, exposed by paddle.Tensor)."""
        import builtins

        import jax.numpy as _jnp

        idx = [builtins.slice(None)] * self.ndim
        idx[axis] = _jnp.asarray(raw(index))
        return Tensor(self._value.at[tuple(idx)].set(raw(value)))

    if not hasattr(T, "index_copy"):
        T.index_copy = index_copy
        T.index_copy_ = _inplace_of("index_copy")

    def apply(self, func):
        """Apply a python callable to the tensor (paddle.Tensor.apply)."""
        return func(self)

    def apply_(self, func):
        out = func(self)
        return self._rebind(out._value if isinstance(out, Tensor) else out)

    T.apply = apply
    T.apply_ = apply_

    # device/dtype movement
    def cpu(self):
        import jax

        return Tensor(jax.device_put(self._value, jax.devices("cpu")[0]), stop_gradient=self.stop_gradient)

    def to(self, *args, **kwargs):
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu") or hasattr(a, "kind"):
                continue  # placement is managed by jax default device
            else:
                out = mp.cast(out, a)
        return out

    T.cpu = cpu
    T.cuda = lambda self, *a, **k: self
    T.to = to
    T.pin_memory = lambda self: self
    T.contiguous = lambda self: self
    T.is_contiguous = lambda self: True


_patch()
del _patch


# ---- top-level inplace function forms (paddle.clip_/masked_fill_/where_) ----
def clip_(x, min=None, max=None, name=None):
    return x.clip_(min, max)


def masked_fill_(x, mask, value, name=None):
    return x.masked_fill_(mask, value)


def where_(condition, x=None, y=None, name=None):
    """paddle.where_ parity: in-place select into ``x``."""
    out = manipulation.where(condition, x, y)
    return x._rebind(out._value, out._node)
