"""Tensor creation ops (paddle.tensor.creation parity).

Reference: ``python/paddle/tensor/creation.py`` (SURVEY.md §2.2). Creation ops
are ordinary jax constants; on TPU they materialize directly in HBM on the
default device.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework import dtypes as _dtypes
from ..framework.core import Tensor
from ..framework.op import defop, raw


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    dtype = _dtypes.convert_dtype(dtype)
    if isinstance(data, Tensor):
        v = data._value
        if dtype is not None and v.dtype != dtype:
            v = v.astype(dtype)
        return Tensor(v, stop_gradient=stop_gradient)
    if isinstance(data, (list, tuple)) and any(
        isinstance(x, Tensor) for x in np.asarray(data, dtype=object).flat
    ):
        data = [raw(x) for x in data]
    v = jnp.asarray(data, dtype=dtype)
    if dtype is None and v.dtype == jnp.float64:
        v = v.astype(jnp.float32)  # paddle default float dtype is float32
    return Tensor(v, stop_gradient=stop_gradient)


def _shape_list(shape):
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()
    if isinstance(shape, (int, np.integer)):
        return [int(shape)]
    return [int(raw(s)) if not isinstance(s, (int, np.integer)) else int(s) for s in shape]


def _float_default():
    return _dtypes.convert_dtype(_dtypes.get_default_dtype())


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape_list(shape), _dtypes.convert_dtype(dtype) or _float_default()))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape_list(shape), _dtypes.convert_dtype(dtype) or _float_default()))


def full(shape, fill_value, dtype=None, name=None):
    fill_value = raw(fill_value)
    return Tensor(jnp.full(_shape_list(shape), fill_value, _dtypes.convert_dtype(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype, name)


@defop
def zeros_like_op(x):
    return jnp.zeros_like(x)


@defop
def ones_like_op(x):
    return jnp.ones_like(x)


def zeros_like(x, dtype=None, name=None):
    out = zeros_like_op(x)
    return out.astype(dtype) if dtype is not None else out


def ones_like(x, dtype=None, name=None):
    out = ones_like_op(x)
    return out.astype(dtype) if dtype is not None else out


def full_like(x, fill_value, dtype=None, name=None):
    dtype = _dtypes.convert_dtype(dtype) or raw(x).dtype
    return Tensor(jnp.full(raw(x).shape, raw(fill_value), dtype))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype, name)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = raw(start), raw(end), raw(step)
    if end is None:
        start, end = 0, start
    dt = _dtypes.convert_dtype(dtype)
    if dt is None:
        py = (start, end, step)
        dt = jnp.int64 if all(isinstance(v, (int, np.integer)) for v in py) else jnp.float32
        dt = jnp.dtype(dt)
        if dt == jnp.int64:
            dt = jnp.dtype(jnp.int32) if jnp.arange(0).dtype == jnp.int32 else dt
    return Tensor(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(
        jnp.linspace(raw(start), raw(stop), int(raw(num)), dtype=_dtypes.convert_dtype(dtype))
    )


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(
        jnp.logspace(raw(start), raw(stop), int(raw(num)), base=raw(base), dtype=_dtypes.convert_dtype(dtype))
    )


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), num_columns if num_columns is None else int(num_columns), dtype=_dtypes.convert_dtype(dtype) or jnp.float32))


@defop
def diag_op(x, offset=0, padding_value=0):
    out = jnp.diag(x, offset)
    if x.ndim == 1 and padding_value != 0:
        n = out.shape[0]
        mask = jnp.eye(n, k=offset, dtype=bool)
        out = jnp.where(mask, out, jnp.asarray(padding_value, x.dtype))
    return out


def diag(x, offset=0, padding_value=0, name=None):
    return diag_op(x, offset=int(offset), padding_value=padding_value)


def diagflat(x, offset=0, name=None):
    return diag_op(reshape_raw(x), offset=int(offset))


@defop(name="diagflat_reshape")
def reshape_raw(x):
    return jnp.reshape(x, (-1,))


@defop
def tril(x, diagonal=0):
    return jnp.tril(x, diagonal)


@defop
def triu(x, diagonal=0):
    return jnp.triu(x, diagonal)


@defop
def assign_op(x):
    return jnp.asarray(x)


def assign(x, output=None):
    out = assign_op(x if isinstance(x, Tensor) else Tensor(jnp.asarray(x)))
    if output is not None:
        output._rebind(out._value, out._node)
        return output
    return out


def clone(x, name=None):
    return assign_op(x)


def numel(x, name=None):
    return Tensor(jnp.asarray(raw(x).size, dtype=jnp.int64 if False else jnp.int32))


def meshgrid(*args, **kwargs):
    arrs = [raw(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    outs = jnp.meshgrid(*arrs, indexing="ij")
    return [Tensor(o) for o in outs]


def tril_indices(row, col, offset=0, dtype="int64", name=None):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dtypes.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64", name=None):
    col = col if col is not None else row
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dtypes.convert_dtype(dtype)))


def one_hot(x, num_classes, name=None):
    import jax.nn as jnn

    return Tensor(jnn.one_hot(raw(x), num_classes, dtype=jnp.float32))


def complex(real, imag, name=None):
    return Tensor(jnp.asarray(raw(real)) + 1j * jnp.asarray(raw(imag)))


@defop(name="vander_op")
def _vander(x, n, increasing):
    return jnp.vander(x, N=n, increasing=increasing)


def vander(x, n=None, increasing=False, name=None):
    return _vander(x, n=n, increasing=bool(increasing))


def shape(input):
    """paddle.shape: the shape as an int32 tensor (static under trace)."""
    from ..framework.op import raw as _raw

    return Tensor(jnp.asarray(jnp.shape(_raw(input)), jnp.int32))
