"""Shape/layout manipulation ops (paddle.tensor.manipulation parity).

Reference: ``python/paddle/tensor/manipulation.py`` (SURVEY.md §2.2).
All static-shape ops trace cleanly under jit; the data-dependent-shape family
(nonzero/masked_select/unique) is eager-only by design — XLA requires static
shapes — and raises a clear error under a trace, mirroring how the reference's
dy2static marks such ops as unsupported-in-static.
"""
from __future__ import annotations

import builtins

from typing import List, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, is_tracer_value
from ..framework.op import defop, raw


def _ishape(shape):
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(raw(s)) if isinstance(s, Tensor) else int(s) for s in shape)


@defop(name="reshape_op")
def _reshape(x, shape):
    return jnp.reshape(x, shape)


def reshape(x, shape, name=None):
    return _reshape(x, shape=_ishape(shape))


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    return x._rebind(out._value, out._node)


@defop(name="transpose_op")
def _transpose(x, perm):
    return jnp.transpose(x, perm)


def transpose(x, perm, name=None):
    return _transpose(x, perm=tuple(int(p) for p in perm))


def t(x, name=None):
    xv = raw(x)
    if xv.ndim < 2:
        return x if isinstance(x, Tensor) else Tensor(xv)
    if xv.ndim == 2:
        return _transpose(x, perm=(1, 0))
    raise ValueError("paddle.t only supports tensors with ndim<=2; use transpose")


@defop
def moveaxis(x, source, destination, name=None):
    return jnp.moveaxis(x, source, destination)


def swapaxes(x, axis1, axis2, name=None):
    perm = list(range(raw(x).ndim))
    perm[axis1], perm[axis2] = perm[axis2], perm[axis1]
    return _transpose(x, perm=tuple(perm))


swapdims = swapaxes


@defop(name="flatten_op")
def _flatten(x, start_axis, stop_axis):
    shape = x.shape
    nd = len(shape)
    sa = start_axis % nd if nd else 0
    so = stop_axis % nd if nd else 0
    new_shape = shape[:sa] + (int(np.prod(shape[sa : so + 1])) if shape else 1,) + shape[so + 1 :]
    return jnp.reshape(x, new_shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _flatten(x, start_axis=int(start_axis), stop_axis=int(stop_axis))


@defop(name="squeeze_op")
def _squeeze(x, axis):
    if axis is None:
        return jnp.squeeze(x)
    axes = tuple(a for a in axis if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axes) if axes else x


def squeeze(x, axis=None, name=None):
    if axis is not None:
        if isinstance(axis, (int, np.integer)):
            axis = (int(axis),)
        else:
            axis = tuple(int(a) for a in axis)
    return _squeeze(x, axis=axis)


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    return x._rebind(out._value, out._node)


@defop(name="unsqueeze_op")
def _unsqueeze(x, axis):
    for a in sorted(axis):
        x = jnp.expand_dims(x, a)
    return x


def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = axis.numpy().tolist()
    if isinstance(axis, (int, np.integer)):
        axis = [int(axis)]
    return _unsqueeze(x, axis=tuple(int(a) for a in axis))


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    return x._rebind(out._value, out._node)


@defop(name="concat_op")
def _concat(xs, axis):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    axis = int(raw(axis)) if isinstance(axis, Tensor) else int(axis)
    return _concat(list(x), axis=axis)


@defop(name="stack_op")
def _stack(xs, axis):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    return _stack(list(x), axis=int(axis))


@defop(name="split_op")
def _split(x, sections, axis):
    if isinstance(sections, int):
        return tuple(jnp.split(x, sections, axis=axis))
    idx = np.cumsum(sections)[:-1].tolist()
    return tuple(jnp.split(x, idx, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    axis = int(raw(axis)) if isinstance(axis, Tensor) else int(axis)
    if isinstance(num_or_sections, (list, tuple)):
        secs = [int(raw(s)) if isinstance(s, Tensor) else int(s) for s in num_or_sections]
        total = raw(x).shape[axis]
        known = [s for s in secs if s >= 0]
        secs = [s if s >= 0 else total - int(np.sum(known)) for s in secs]
        return list(_split(x, sections=secs, axis=axis))
    return list(_split(x, sections=int(num_or_sections), axis=axis))


def chunk(x, chunks, axis=0, name=None):
    return split(x, int(chunks), axis)


def unbind(x, axis=0, name=None):
    n = raw(x).shape[axis]
    outs = split(x, n, axis)
    return [squeeze(o, axis) for o in outs]


unstack = unbind


@defop(name="tile_op")
def _tile(x, repeat_times):
    return jnp.tile(x, repeat_times)


def tile(x, repeat_times, name=None):
    return _tile(x, repeat_times=_ishape(repeat_times))


@defop(name="expand_op")
def _expand(x, shape):
    shape = tuple(
        x.shape[i - (len(shape) - x.ndim)] if s == -1 and i >= len(shape) - x.ndim else s
        for i, s in enumerate(shape)
    )
    return jnp.broadcast_to(x, shape)


def expand(x, shape, name=None):
    return _expand(x, shape=_ishape(shape))


def expand_as(x, y, name=None):
    return _expand(x, shape=tuple(raw(y).shape))


def broadcast_to(x, shape, name=None):
    return _expand(x, shape=_ishape(shape))


def broadcast_tensors(inputs, name=None):
    vals = jnp.broadcast_arrays(*[raw(i) for i in inputs])
    shape = tuple(vals[0].shape)
    return [_expand(i, shape=shape) for i in inputs]


@defop
def flip(x, axis, name=None):
    return jnp.flip(x, axis=axis if not isinstance(axis, list) else tuple(axis))


reverse = flip


@defop
def roll(x, shifts, axis=None, name=None):
    return jnp.roll(x, shifts, axis=axis)


@defop
def rot90(x, k=1, axes=(0, 1), name=None):
    return jnp.rot90(x, k=k, axes=tuple(axes))


@defop
def repeat_interleave(x, repeats, axis=None, name=None):
    return jnp.repeat(x, repeats, axis=axis)


# -------------------------------------------------------------- gather etc ---


@defop
def gather(x, index, axis=0, name=None):
    index = jnp.asarray(index)
    if index.ndim == 0:
        index = index[None]
    return jnp.take(x, index, axis=axis)


@defop
def gather_nd(x, index, name=None):
    index = jnp.asarray(index)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


@defop
def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    indices = jnp.asarray(indices)
    if broadcast:
        # paddle broadcasts indices against arr except on `axis`
        tgt = list(arr.shape)
        tgt[axis] = indices.shape[axis]
        indices = jnp.broadcast_to(indices, tgt)
    return jnp.take_along_axis(arr, indices, axis=axis)


@defop
def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    indices = jnp.asarray(indices)
    values = jnp.broadcast_to(jnp.asarray(values, arr.dtype), indices.shape)
    dims = [jnp.arange(s) for s in indices.shape]
    grids = jnp.meshgrid(*dims, indexing="ij")
    grids[axis] = indices
    idx = tuple(grids)
    if reduce == "assign":
        return arr.at[idx].set(values)
    if reduce in ("add", "sum"):
        return arr.at[idx].add(values)
    if reduce in ("mul", "multiply"):
        return arr.at[idx].multiply(values)
    raise ValueError(f"unsupported reduce mode {reduce}")


@defop
def index_select(x, index, axis=0, name=None):
    return jnp.take(x, jnp.asarray(index), axis=axis)


@defop
def index_sample(x, index):
    index = jnp.asarray(index)
    return jnp.take_along_axis(x, index, axis=1)


@defop
def index_add(x, index, axis, value, name=None):
    index = jnp.asarray(index)
    sl = [slice(None)] * x.ndim
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].add(value)


@defop
def index_put(x, indices, value, accumulate=False, name=None):
    idx = tuple(jnp.asarray(i) for i in indices)
    if accumulate:
        return x.at[idx].add(value)
    return x.at[idx].set(value)


@defop
def scatter_op(x, index, updates, overwrite=True):
    index = jnp.asarray(index)
    if index.ndim == 2:
        index = index[:, 0]
    if overwrite:
        return x.at[index].set(updates)
    # paddle: overwrite=False means accumulate (after zeroing the rows)
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter(x, index, updates, overwrite=True, name=None):
    return scatter_op(x, index, updates, overwrite=overwrite)


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    return x._rebind(out._value, out._node)


@defop
def scatter_nd_add(x, index, updates, name=None):
    index = jnp.asarray(index)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd(index, updates, shape, name=None):
    zeros = jnp.zeros(_ishape(shape), raw(updates).dtype)
    return scatter_nd_add(Tensor(zeros), index, updates)


@defop
def where_op(condition, x, y):
    return jnp.where(condition, x, y)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return where_op(condition, x, y)


@defop
def select_scatter(x, values, axis, index, name=None):
    sl = [slice(None)] * x.ndim
    sl[axis] = index
    return x.at[tuple(sl)].set(values)


# ----------------------------------------------- data-dependent (eager only) --


def _require_eager(x, opname):
    if is_tracer_value(raw(x)):
        raise RuntimeError(
            f"{opname} has a data-dependent output shape and cannot run inside a "
            "captured (jit) program on TPU. Run it eagerly, or restructure with "
            "masking (e.g. paddle_tpu.where with a fill value)."
        )


def nonzero(x, as_tuple=False, name=None):
    _require_eager(x, "nonzero")
    res = np.nonzero(np.asarray(raw(x)))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(r[:, None] if False else r)) for r in res)
    return Tensor(jnp.asarray(np.stack(res, axis=1)))


def masked_select(x, mask, name=None):
    _require_eager(x, "masked_select")
    return Tensor(jnp.asarray(np.asarray(raw(x))[np.asarray(raw(mask))]))


@defop
def masked_fill(x, mask, value, name=None):
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


def unique(
    x,
    return_index=False,
    return_inverse=False,
    return_counts=False,
    axis=None,
    dtype="int64",
    name=None,
):
    _require_eager(x, "unique")
    res = np.unique(
        np.asarray(raw(x)),
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if not (return_index or return_inverse or return_counts):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    _require_eager(x, "unique_consecutive")
    a = np.asarray(raw(x))
    if axis is None:
        a = a.reshape(-1)
        change = np.ones(len(a), bool)
        if len(a) > 1:
            change[1:] = a[1:] != a[:-1]
        out = a[change]
        outs = [Tensor(jnp.asarray(out))]
        if return_inverse:
            outs.append(Tensor(jnp.asarray(np.cumsum(change) - 1)))
        if return_counts:
            idx = np.nonzero(change)[0]
            counts = np.diff(np.append(idx, len(a)))
            outs.append(Tensor(jnp.asarray(counts)))
        return outs[0] if len(outs) == 1 else tuple(outs)
    raise NotImplementedError("unique_consecutive with axis is not supported yet")


# ------------------------------------------------------------------- sort ----


@defop(name="sort_op")
def _sort(x, axis, descending):
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


def sort(x, axis=-1, descending=False, name=None):
    return _sort(x, axis=int(axis), descending=bool(descending))


@defop(name="argsort_op")
def _argsort(x, axis, descending):
    idx = jnp.argsort(x, axis=axis)
    return jnp.flip(idx, axis=axis) if descending else idx


def argsort(x, axis=-1, descending=False, name=None):
    return _argsort(x, axis=int(axis), descending=bool(descending))


@defop(name="topk_op")
def _topk(x, k, axis, largest, sorted):
    if axis is None:
        axis = x.ndim - 1
    x_moved = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(x_moved, k)
    else:
        vals, idx = jax.lax.top_k(-x_moved, k)
        vals = -vals
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx, -1, axis)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    k = int(raw(k)) if isinstance(k, Tensor) else int(k)
    vals, idx = _topk(x, k=k, axis=axis if axis is None else int(axis), largest=bool(largest), sorted=bool(sorted))
    idx = idx.astype("int64")
    return vals, idx


@defop(name="kthvalue_op")
def _kthvalue(x, k, axis, keepdim):
    vals = jnp.sort(x, axis=axis)
    idxs = jnp.argsort(x, axis=axis)
    sl = [slice(None)] * x.ndim
    sl[axis] = k - 1
    v = vals[tuple(sl)]
    i = idxs[tuple(sl)]
    if keepdim:
        v = jnp.expand_dims(v, axis)
        i = jnp.expand_dims(i, axis)
    return v, i


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    axis = int(axis) % raw(x).ndim
    return _kthvalue(x, k=int(k), axis=axis, keepdim=bool(keepdim))


def mode(x, axis=-1, keepdim=False, name=None):
    _require_eager(x, "mode")
    a = np.asarray(raw(x))
    axis = int(axis) % a.ndim
    moved = np.moveaxis(a, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    ms = np.empty(flat.shape[0], a.dtype)
    idxs = np.empty(flat.shape[0], np.int64)
    for i, row in enumerate(flat):
        vals, counts = np.unique(row, return_counts=True)
        m = vals[np.argmax(counts)]
        ms[i] = m
        idxs[i] = int(np.nonzero(row == m)[0][-1])
    out_shape = moved.shape[:-1]
    ms = ms.reshape(out_shape)
    idxs = idxs.reshape(out_shape)
    if keepdim:
        ms = np.expand_dims(ms, axis)
        idxs = np.expand_dims(idxs, axis)
    return Tensor(jnp.asarray(ms)), Tensor(jnp.asarray(idxs))


@defop(name="argmax_op")
def _argmax(x, axis, keepdim):
    if axis is None:
        return jnp.argmax(jnp.reshape(x, (-1,)))
    out = jnp.argmax(x, axis=axis)
    return jnp.expand_dims(out, axis) if keepdim else out


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = _argmax(x, axis=axis if axis is None else int(axis), keepdim=bool(keepdim))
    return out.astype(dtype)


@defop(name="argmin_op")
def _argmin(x, axis, keepdim):
    if axis is None:
        return jnp.argmin(jnp.reshape(x, (-1,)))
    out = jnp.argmin(x, axis=axis)
    return jnp.expand_dims(out, axis) if keepdim else out


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    out = _argmin(x, axis=axis if axis is None else int(axis), keepdim=bool(keepdim))
    return out.astype(dtype)


@defop
def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        out = jnp.searchsorted(sorted_sequence, values, side=side)
    else:
        out = jax.vmap(lambda s, v: jnp.searchsorted(s, v, side=side))(
            sorted_sequence.reshape((-1, sorted_sequence.shape[-1])),
            values.reshape((-1, values.shape[-1])),
        ).reshape(values.shape)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


@defop
def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, x, side=side)
    return out.astype(jnp.int32 if out_int32 else jnp.int64)


# ------------------------------------------------------------------- cast ----


@defop(name="cast_op")
def _cast(x, dtype):
    return x.astype(dtype)


def cast(x, dtype):
    from ..framework.dtypes import convert_dtype

    return _cast(x, dtype=convert_dtype(dtype))


def cast_(x, dtype):
    out = cast(x, dtype)
    return x._rebind(out._value, out._node)


# -------------------------------------------------------------- getitem ------


def _norm_index(idx):
    """Convert a python/paddle index spec into a jnp-compatible one."""
    if isinstance(idx, tuple):
        return tuple(_norm_index(i) for i in idx)
    if isinstance(idx, Tensor):
        return raw(idx)
    if isinstance(idx, (list, np.ndarray)):
        return jnp.asarray(idx)
    return idx  # int / slice / None / Ellipsis


@defop(name="getitem_op")
def _getitem(x, idx):
    return x[idx]


def tensor_getitem(x, idx):
    nidx = _norm_index(idx)
    # boolean-mask indexing has a data-dependent shape → eager only
    def _has_bool(i):
        if isinstance(i, tuple):
            return any(_has_bool(j) for j in i)
        return hasattr(i, "dtype") and i.dtype == jnp.bool_

    if _has_bool(nidx):
        _require_eager(x, "boolean-mask indexing")
        return Tensor(jnp.asarray(np.asarray(raw(x))[np.asarray(nidx) if not isinstance(nidx, tuple) else tuple(np.asarray(i) if hasattr(i, "dtype") else i for i in nidx)]))
    return _getitem(x, idx=nidx)


@defop(name="setitem_op")
def _setitem(x, v, idx):
    v = jnp.asarray(v, x.dtype)
    return x.at[idx].set(v)


def tensor_setitem(x, idx, value):
    nidx = _norm_index(idx)
    vv = raw(value)
    out = _setitem(x, value if isinstance(value, Tensor) else vv, idx=nidx)
    x._rebind(out._value, out._node)
    if not out.stop_gradient:
        x.stop_gradient = False
    return x


@defop
def pad_nd(x, pad, mode="constant", value=0.0):
    return jnp.pad(x, pad, mode=mode, constant_values=value) if mode == "constant" else jnp.pad(x, pad, mode=mode)


def as_strided(x, shape, stride, offset=0, name=None):
    raise NotImplementedError(
        "as_strided is CUDA-pointer-specific; TPU tensors are not strided views"
    )


@defop
def view_op(x, shape):
    return jnp.reshape(x, shape)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return view_op(x, shape=_ishape(shape_or_dtype))
    return cast(x, shape_or_dtype)


def view_as(x, other, name=None):
    return view_op(x, shape=tuple(raw(other).shape))


@defop
def crop(x, shape=None, offsets=None, name=None):
    offsets = offsets or [0] * x.ndim
    sl = tuple(slice(o, o + s) for o, s in zip(offsets, shape))
    return x[sl]


@defop
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards
    lo = shard_id * shard_size
    in_shard = (input >= lo) & (input < lo + shard_size)
    return jnp.where(in_shard, input - lo, ignore_value)


@defop(name="take_op")
def _take(x, index, mode):
    flat = x.reshape(-1)
    idx = index.reshape(-1)
    n = flat.shape[0]
    if mode == "wrap":
        idx = ((idx % n) + n) % n
    elif mode == "clip":
        idx = jnp.clip(idx, 0, n - 1)
    return flat[idx].reshape(index.shape)


def take(x, index, mode="raise", name=None):
    """paddle.take: flat-index gather with wrap/clip OOB modes ('raise'
    checks host-side when values are concrete)."""
    if mode == "raise":
        import numpy as _np

        iv = raw(index)
        if not is_tracer_value(iv):
            n = int(_np.prod(raw(x).shape))
            if (_np.asarray(iv) >= n).any() or (_np.asarray(iv) < -n).any():
                raise IndexError("take: index out of range")
        mode = "wrap"  # negative indices behave pythonically
    return _take(x, index, mode=mode)


@defop(name="index_fill_op")
def _index_fill(x, index, axis, value):
    idx = [slice(None)] * x.ndim
    idx[axis] = index
    return x.at[tuple(idx)].set(value)


def index_fill(x, index, axis, value, name=None):
    return _index_fill(x, index, axis=int(axis), value=float(raw(value)) if not hasattr(raw(value), "ndim") else raw(value))


def index_fill_(x, index, axis, value, name=None):
    out = index_fill(x, index, axis, value)
    x._value = out._value
    return x


@defop(name="unfold_op")
def _unfold(x, axis, size, step):
    n = x.shape[axis]
    starts = jnp.arange(0, n - size + 1, step)
    windows = [jnp.take(x, starts + i, axis=axis) for i in range(size)]
    return jnp.stack(windows, axis=-1)


def unfold(x, axis, size, step, name=None):
    """paddle.unfold (Tensor.unfold): sliding windows along axis appended as
    a trailing dim."""
    return _unfold(x, axis=int(axis), size=int(size), step=int(step))


@defop(name="tensordot_op")
def _tensordot(x, y, axes):
    return jnp.tensordot(x, y, axes=axes)


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in axes)
    return _tensordot(x, y, axes=axes)


def cat(x, axis=0, name=None):
    """Alias of concat (torch-style name kept by paddle)."""
    return concat(x, axis=axis, name=name)


def permute(x, *perm, name=None):
    """torch-style transpose alias."""
    if len(perm) == 1 and isinstance(perm[0], (list, tuple)):
        perm = tuple(perm[0])
    return transpose(x, list(perm))


@defop(name="slice_op")
def paddle_slice(input, axes, starts, ends, name=None):
    """paddle.slice: slice `input` along `axes` with [starts, ends).

    (Named paddle_slice inside this module so the Python builtin stays
    usable; exported as `paddle.slice` from the package root.)"""
    x = jnp.asarray(input)
    idx = [builtins.slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        dim = x.shape[ax]
        s = int(s) if s >= 0 else int(s) + dim
        e = int(e) if e >= 0 else int(e) + dim
        idx[ax] = builtins.slice(max(s, 0), min(e, dim))
    return x[tuple(idx)]


def vsplit(x, num_or_indices, name=None):
    return [Tensor(v) for v in jnp.split(
        jnp.asarray(raw(x)),
        num_or_indices if isinstance(num_or_indices, int)
        else list(num_or_indices),
        axis=0,
    )]


@defop
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """Write `y` onto the (offset) diagonal of the (axis1, axis2) planes
    (paddle.diagonal_scatter)."""
    xv = jnp.moveaxis(x, (axis1, axis2), (-2, -1))
    m, n = xv.shape[-2], xv.shape[-1]
    if offset >= 0:
        rows = jnp.arange(min(m, n - offset))
        cols = rows + offset
    else:
        cols = jnp.arange(min(n, m + offset))
        rows = cols - offset
    # y's shape == x.diagonal(offset, axis1, axis2).shape: batch dims first,
    # diagonal length last — exactly how the advanced index below broadcasts
    out = xv.at[..., rows, cols].set(y)
    return jnp.moveaxis(out, (-2, -1), (axis1, axis2))


@defop
def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    """Write `value` into the strided slice of x (paddle.slice_scatter)."""
    idx = [slice(None)] * x.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        idx[ax] = slice(int(st), int(en), int(sr))
    return x.at[tuple(idx)].set(value)


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors -> [prod(len), n] (paddle.cartesian_prod)."""
    return _cartesian_prod_op(list(x))


@defop(name="cartesian_prod_op")
def _cartesian_prod_op(xs):
    grids = jnp.meshgrid(*xs, indexing="ij")
    return jnp.stack([g.ravel() for g in grids], axis=-1)


def _split_like(x, num_or_indices, axis):
    return [Tensor(v) for v in jnp.split(
        jnp.asarray(raw(x)),
        num_or_indices if isinstance(num_or_indices, int)
        else list(num_or_indices),
        axis=axis,
    )]


def hsplit(x, num_or_indices, name=None):
    xv = raw(x)
    return _split_like(x, num_or_indices, axis=0 if xv.ndim == 1 else 1)


def dsplit(x, num_or_indices, name=None):
    return _split_like(x, num_or_indices, axis=2)


def tensor_split(x, num_or_indices, axis=0, name=None):
    """numpy-style uneven split (paddle.tensor_split): an int that does not
    divide the axis produces first-longer pieces."""
    xv = jnp.asarray(raw(x))
    if isinstance(num_or_indices, int):
        return [Tensor(v) for v in jnp.array_split(xv, num_or_indices, axis=axis)]
    return [Tensor(v) for v in jnp.split(xv, list(num_or_indices), axis=axis)]


@defop
def unflatten(x, axis, shape, name=None):
    """Expand one axis into `shape` (paddle.unflatten; -1 infers)."""
    shape = list(int(s) for s in shape)
    if -1 in shape:
        known = 1
        for s in shape:
            if s != -1:
                known *= s
        shape[shape.index(-1)] = x.shape[axis] // known
    out_shape = list(x.shape)
    out_shape[axis : axis + 1] = shape
    return jnp.reshape(x, out_shape)


def atleast_1d(*inputs, name=None):
    out = [Tensor(jnp.atleast_1d(raw(v))) for v in inputs]
    return out[0] if len(out) == 1 else out


def atleast_2d(*inputs, name=None):
    out = [Tensor(jnp.atleast_2d(raw(v))) for v in inputs]
    return out[0] if len(out) == 1 else out


def atleast_3d(*inputs, name=None):
    out = [Tensor(jnp.atleast_3d(raw(v))) for v in inputs]
    return out[0] if len(out) == 1 else out


def column_stack(x, name=None):
    return _column_stack_op(list(x))


@defop(name="column_stack_op")
def _column_stack_op(xs):
    return jnp.column_stack(xs)


def row_stack(x, name=None):
    return _row_stack_op(list(x))


@defop(name="row_stack_op")
def _row_stack_op(xs):
    return jnp.vstack(xs)


def block_diag(inputs, name=None):
    return _block_diag_op(list(inputs))


@defop(name="block_diag_op")
def _block_diag_op(xs):
    return jax.scipy.linalg.block_diag(*[jnp.atleast_2d(v) for v in xs])


@defop
def masked_scatter(x, mask, value, name=None):
    """Fill True positions of `mask` with consecutive elements of `value`
    (paddle.masked_scatter). jit-safe: a cumulative count over the mask
    turns the data-dependent packing into a static gather."""
    m = jnp.broadcast_to(mask, x.shape)
    src = jnp.ravel(value)
    # position among True elements, row-major (0 where False, clipped safe)
    k = jnp.cumsum(jnp.ravel(m)) - 1
    gathered = jnp.take(src, jnp.clip(k, 0, src.shape[0] - 1), axis=0)
    return jnp.where(m, jnp.reshape(gathered, x.shape), x)


@defop
def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    """Batched diagonal construction (paddle.diag_embed): the last axis of
    `input` becomes the (offset) diagonal of new (dim1, dim2) planes."""
    n = input.shape[-1] + builtins.abs(offset)
    out = jnp.zeros(input.shape[:-1] + (n, n), input.dtype)
    if offset >= 0:
        rows = jnp.arange(input.shape[-1])
        cols = rows + offset
    else:
        cols = jnp.arange(input.shape[-1])
        rows = cols - offset
    out = out.at[..., rows, cols].set(input)
    nd = out.ndim
    d1 = dim1 % nd
    d2 = dim2 % nd
    return jnp.moveaxis(out, (nd - 2, nd - 1), (d1, d2))


@defop
def scatter_nd(index, updates, shape, name=None):
    """Scatter-ADD updates into zeros of `shape` (paddle.scatter_nd)."""
    out = jnp.zeros(tuple(int(s) for s in shape), updates.dtype)
    idx = tuple(jnp.moveaxis(jnp.asarray(index), -1, 0))
    return out.at[idx].add(updates)


@defop
def strided_slice(x, axes, starts, ends, strides, name=None):
    """paddle.strided_slice parity: python-slice semantics per axis
    (negative indices/strides as numpy)."""
    import builtins

    idx = [builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[int(ax)] = builtins.slice(int(st), int(en), int(sd))
    return x[tuple(idx)]


@defop
def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    """Out-of-place core of paddle.Tensor.fill_diagonal_ (2-D and the
    batched square case, as the reference): writes `value` on the
    diagonal."""
    if x.ndim < 2:
        raise ValueError("fill_diagonal needs at least 2 dims")
    if x.ndim > 2:
        # reference semantics: the single [i, i, ..., i] hyper-diagonal
        # (all dims must be equal, as numpy/torch/paddle require)
        if len(set(x.shape)) != 1:
            raise ValueError(
                "fill_diagonal on >2-D tensors requires all dims equal")
        n = x.shape[0]
        grids = jnp.meshgrid(*([jnp.arange(n)] * x.ndim), indexing="ij")
        mask = jnp.ones(x.shape, bool)
        for g in grids[1:]:
            mask = mask & (grids[0] == g)
        return jnp.where(mask, jnp.asarray(value, x.dtype), x)
    h, w = x.shape[-2], x.shape[-1]
    i = jnp.arange(h)[:, None]
    j = jnp.arange(w)[None, :]
    if wrap and h > w and int(offset) == 0:
        # numpy-style wrap for tall matrices: flat index steps of w+1
        mask = (i * w + j) % (w + 1) == 0
    else:
        mask = (j - i) == int(offset)
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


@defop
def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    """paddle.Tensor.fill_diagonal_tensor parity: write tensor `y` along
    the (dim1, dim2) diagonal of `x`."""
    nd = x.ndim
    d1, d2 = int(dim1) % nd, int(dim2) % nd
    perm = [a for a in range(nd) if a not in (d1, d2)] + [d1, d2]
    inv = [perm.index(a) for a in range(nd)]
    xt = jnp.transpose(x, perm)
    h, w = xt.shape[-2], xt.shape[-1]
    i = jnp.arange(h)[:, None]
    j = jnp.arange(w)[None, :]
    mask = (j - i) == int(offset)
    # y carries the diagonal entries in its LAST axis; the diagonal index
    # is the row (offset >= 0) or the column (offset < 0)
    k = i + jnp.zeros_like(j) if int(offset) >= 0 else j + jnp.zeros_like(i)
    yv = jnp.asarray(y, x.dtype)
    vals = jnp.take(yv, jnp.clip(k, 0, yv.shape[-1] - 1), axis=-1)
    out = jnp.where(mask, vals, xt)
    return jnp.transpose(out, inv)


@defop(name="hstack_op")
def _hstack_op(xs):
    return jnp.hstack(xs)


def hstack(x, name=None):
    """paddle.hstack parity (numpy semantics)."""
    return _hstack_op(list(x))


@defop(name="dstack_op")
def _dstack_op(xs):
    return jnp.dstack(xs)


def dstack(x, name=None):
    """paddle.dstack parity (numpy semantics)."""
    return _dstack_op(list(x))


vstack = row_stack  # paddle exposes both names for the same op


@defop
def matrix_transpose(x, name=None):
    """paddle.matrix_transpose parity: swap the last two dims."""
    if x.ndim < 2:
        raise ValueError("matrix_transpose needs at least 2 dims")
    return jnp.swapaxes(x, -1, -2)


@defop
def multiplex(inputs, index, name=None):
    """paddle.multiplex parity: row r of the output is row r of
    inputs[index[r]]."""
    stacked = jnp.stack(inputs, axis=0)  # [K, N, ...]
    idx = jnp.asarray(index).reshape(-1).astype(jnp.int32)
    rows = jnp.arange(stacked.shape[1])
    return stacked[idx, rows]
