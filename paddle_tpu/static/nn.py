"""paddle.static.nn parity shims (fc / conv2d / batch_norm / embedding ...).

Reference: ``python/paddle/static/nn/common.py`` — program-building ops that
create parameters in the Program's scope. TPU-native: our "static graph" is
the jit trace (see paddle_tpu.static), so these are functional wrappers that
create the corresponding nn Layer ONCE per (name) and reuse it across calls
— the parameter-reuse semantics of a static Program without a ProgramDesc.
Layers are registered on the default Program so they survive across steps.
"""
from __future__ import annotations

from typing import Optional

from .. import nn as _nn
from . import default_main_program


def _layer_cache():
    prog = default_main_program()
    if not hasattr(prog, "_static_nn_layers"):
        prog._static_nn_layers = {}
    return prog._static_nn_layers


def _get(name, factory):
    cache = _layer_cache()
    if name not in cache:
        cache[name] = factory()
    return cache[name]


def _auto(prefix, name):
    """Layer identity for unnamed calls: keyed by the CALLER'S code location,
    so the same static.nn call re-executed each step (our Executor re-runs
    the build function eagerly) reuses its parameters — the positional
    parameter identity a static Program gives for free."""
    if name:
        return name
    import sys

    f = sys._getframe(2)  # the user's call site (past _auto and the op fn)
    # f_lasti (bytecode offset) disambiguates multiple calls on ONE source
    # line, e.g. fc(fc(x, 32), 2) — same line, two distinct layers
    return f"{prefix}@{f.f_code.co_filename}:{f.f_lineno}:{f.f_lasti}"


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None, activation=None, name=None):
    in_f = 1
    for d in x.shape[num_flatten_dims:]:
        in_f *= d
    key = _auto("fc", name)
    layer = _get(key, lambda: _nn.Linear(in_f, size, weight_attr=weight_attr, bias_attr=bias_attr))
    h = x
    if len(x.shape) > num_flatten_dims + 1:
        h = x.reshape(list(x.shape[:num_flatten_dims]) + [in_f])
    out = layer(h)
    if activation:
        out = getattr(_nn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1, groups=1, param_attr=None, bias_attr=None, act=None, name=None, data_format="NCHW"):
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    key = _auto("conv2d", name)
    layer = _get(key, lambda: _nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                                         padding=padding, dilation=dilation, groups=groups,
                                         weight_attr=param_attr, bias_attr=bias_attr,
                                         data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None, stride=1, padding=0, groups=1, param_attr=None, bias_attr=None, act=None, name=None, data_format="NCHW"):
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    key = _auto("conv2d_transpose", name)
    layer = _get(key, lambda: _nn.Conv2DTranspose(in_ch, num_filters, filter_size, stride=stride,
                                                  padding=padding, groups=groups,
                                                  weight_attr=param_attr, bias_attr=bias_attr,
                                                  data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None, bias_attr=None, data_layout="NCHW", is_test=False, name=None, **kwargs):
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    key = _auto("batch_norm", name)
    layer = _get(key, lambda: _nn.BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                                              weight_attr=param_attr, bias_attr=bias_attr))
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5, param_attr=None, bias_attr=None, act=None, name=None):
    shape = list(input.shape[begin_norm_axis:])
    key = _auto("layer_norm", name)
    layer = _get(key, lambda: _nn.LayerNorm(shape, epsilon=epsilon,
                                            weight_attr=param_attr if scale else False,
                                            bias_attr=bias_attr if shift else False))
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None, dtype="float32", name=None):
    key = _auto("embedding", name)
    layer = _get(key, lambda: _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                                            weight_attr=param_attr))
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, is_test=False, entry=None,
                     param_attr=None, dtype="float32", name=None):
    """PS-mode distributed-table lookup (reference:
    python/paddle/static/nn/common.py::sparse_embedding over the PS
    DistributedLookupTable). TPU-native: a mesh-row-sharded table — see
    paddle_tpu.distributed.ps.ShardedEmbeddingTable."""
    from ..distributed.ps import sparse_embedding as impl

    # resolve the call-site key HERE: impl's own _auto would see this
    # wrapper frame, collapsing all unnamed call sites to one table
    key = _auto("sparse_embedding", name)
    return impl(input, size, padding_idx=padding_idx, is_test=is_test,
                entry=entry, param_attr=param_attr, dtype=dtype, name=key)


def static_parameters(program=None):
    """All parameters created by static.nn calls on `program` (default main)."""
    prog = program or default_main_program()
    params = []
    for layer in getattr(prog, "_static_nn_layers", {}).values():
        params.extend(layer.parameters())
    return params


# --------------------------------------------------------------------------
# Control flow (reference: ``paddle/fluid/operators/controlflow/`` —
# conditional_block_op, while_op, select/case).
#
# TPU-native guard semantics: when the predicate is CONCRETE (eager mode)
# the chosen branch runs as plain Python — the autograd tape records through
# it untouched. When the predicate is a TRACED value (inside jit/to_static),
# the op lowers to the XLA-native structured control flow (`lax.cond`,
# `lax.while_loop`, `lax.switch`) so data-dependent branching stays inside
# ONE compiled program — the capability the reference's control-flow ops
# provide to its static graph.
# --------------------------------------------------------------------------

def _is_tensor(x):
    from ..framework.core import Tensor

    return isinstance(x, Tensor)


def _unwrap(tree):
    import jax

    from ..framework.op import raw

    return jax.tree_util.tree_map(raw, tree, is_leaf=_is_tensor)


def _wrap(tree):
    import jax

    from ..framework.core import Tensor

    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if hasattr(v, "dtype") else v, tree
    )


def _pred_value(pred):
    from ..framework.core import is_tracer_value
    from ..framework.op import raw

    p = raw(pred)
    return p, is_tracer_value(p)


def cond(pred, true_fn=None, false_fn=None, name=None, return_names=None):
    """paddle.static.nn.cond parity (conditional_block_op capability).

    Eager predicate: runs the taken branch in Python (tape-recorded).
    Traced predicate: lowers to ``lax.cond`` — both branches trace, outputs
    must match in structure/shape/dtype (same contract as the reference's
    static-graph cond).
    """
    import jax
    import jax.numpy as jnp

    p, traced = _pred_value(pred)
    if not traced:
        taken = true_fn if bool(jnp.asarray(p).reshape(())) else false_fn
        return taken() if taken is not None else None

    def branch(fn):
        def inner(_):
            return _unwrap(fn() if fn is not None else ())

        return inner

    out = jax.lax.cond(
        jnp.asarray(p).reshape(()).astype(bool), branch(true_fn),
        branch(false_fn), 0,
    )
    return _wrap(out)


def case(pred_fn_pairs, default=None, name=None):
    """paddle.static.nn.case parity: first true predicate wins."""
    import functools as _ft

    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    chain = default
    for p, fn in reversed(list(pred_fn_pairs)):
        chain = _ft.partial(cond, p, fn, chain)
    return chain() if callable(chain) else chain


def switch_case(branch_index, branch_fns, default=None, name=None):
    """paddle.static.nn.switch_case parity (select-based dispatch).

    `branch_fns` is a dict {int: fn} or list of (int, fn) / fns. Traced
    index lowers to ``lax.switch``.
    """
    import jax
    import jax.numpy as jnp

    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(k), f) for k, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    if default is None:
        default = fns[-1]

    idx, traced = _pred_value(branch_index)
    if not traced:
        i = int(jnp.asarray(idx).reshape(()))
        return dict(items).get(i, default)()

    # map the sparse branch keys onto a dense lax.switch table; unmatched
    # indices hit the default in the final slot
    table = fns + [default]
    key_arr = jnp.asarray(keys, jnp.int32)
    dense = jnp.where(
        key_arr == jnp.asarray(idx, jnp.int32).reshape(()),
        jnp.arange(len(keys), dtype=jnp.int32),
        len(table) - 1,
    ).min()

    out = jax.lax.switch(dense, [lambda _, f=f: _unwrap(f()) for f in table], 0)
    return _wrap(out)


def while_loop(cond_fn, body, loop_vars, is_test=False, name=None):
    """paddle.static.nn.while_loop parity (while_op capability).

    Eager loop state: a plain Python while (tape-recorded, fully
    differentiable). Traced loop state: lowers to ``lax.while_loop`` —
    compiled, but like XLA itself, not reverse-mode differentiable; use a
    bounded loop + cond for training-time control flow.
    """
    import jax

    from ..framework.core import is_tracer_value

    loop_vars = list(loop_vars) if isinstance(loop_vars, (list, tuple)) else [loop_vars]
    flat0 = _unwrap(loop_vars)
    traced = any(
        is_tracer_value(l) for l in jax.tree_util.tree_leaves(flat0)
    )
    if not traced:
        # probe the predicate once; if it is concrete we can stay eager
        c0 = cond_fn(*loop_vars)
        p, p_traced = _pred_value(c0)
        if not p_traced:
            vars_ = loop_vars
            import jax.numpy as jnp

            while bool(jnp.asarray(_unwrap(cond_fn(*vars_))).reshape(())):
                out = body(*vars_)
                vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
            return vars_

    def lax_cond(carry):
        import jax.numpy as jnp

        return jnp.asarray(_unwrap(cond_fn(*_wrap(list(carry))))).reshape(())

    def lax_body(carry):
        out = body(*_wrap(list(carry)))
        out = list(out) if isinstance(out, (list, tuple)) else [out]
        return tuple(_unwrap(out))

    final = jax.lax.while_loop(lax_cond, lax_body, tuple(flat0))
    return _wrap(list(final))


__all__ = [
    "fc", "conv2d", "conv2d_transpose", "batch_norm", "layer_norm",
    "embedding", "sparse_embedding", "static_parameters",
    "cond", "case", "switch_case", "while_loop",
]


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    key = _auto("group_norm", name)
    layer = _get(key, lambda: _nn.GroupNorm(groups, ch, epsilon=epsilon,
                                            weight_attr=param_attr,
                                            bias_attr=bias_attr))
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    """static.nn.prelu: trainable negative slope ('all' = one scalar,
    'channel' = per channel, 'element' = per element)."""
    if mode == "all":
        n = 1
    elif mode == "channel":
        n = x.shape[1] if data_format == "NCHW" else x.shape[-1]
    else:
        n = 1
        for d in x.shape[1:]:
            n *= d
    key = _auto("prelu", name)
    layer = _get(key, lambda: _nn.PReLU(num_parameters=n, weight_attr=param_attr,
                                        data_format=data_format))
    return layer(x)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, name=None,
              **kwargs):
    """static.nn.data_norm: normalization by RUNNING statistics only (no
    learned scale/shift coupling across batch like batch_norm; the
    reference uses it for sparse/CTR features). Served by BatchNorm with
    affine disabled."""
    ch = input.shape[1]
    key = _auto("data_norm", name)
    layer = _get(key, lambda: _nn.BatchNorm1D(ch, epsilon=epsilon,
                                              weight_attr=False,
                                              bias_attr=False))
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def sequence_softmax(input, name=None):
    """Softmax over the last axis (dense-tensor form of the reference's
    LoD sequence op — LoD tensors don't exist here by design)."""
    return _nn.functional.softmax(input, axis=-1)
