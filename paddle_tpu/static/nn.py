"""paddle.static.nn parity shims (fc / conv2d / batch_norm / embedding ...).

Reference: ``python/paddle/static/nn/common.py`` — program-building ops that
create parameters in the Program's scope. TPU-native: our "static graph" is
the jit trace (see paddle_tpu.static), so these are functional wrappers that
create the corresponding nn Layer ONCE per (name) and reuse it across calls
— the parameter-reuse semantics of a static Program without a ProgramDesc.
Layers are registered on the default Program so they survive across steps.
"""
from __future__ import annotations

from typing import Optional

from .. import nn as _nn
from . import default_main_program


def _layer_cache():
    prog = default_main_program()
    if not hasattr(prog, "_static_nn_layers"):
        prog._static_nn_layers = {}
    return prog._static_nn_layers


def _get(name, factory):
    cache = _layer_cache()
    if name not in cache:
        cache[name] = factory()
    return cache[name]


def _auto(prefix, name):
    """Layer identity for unnamed calls: keyed by the CALLER'S code location,
    so the same static.nn call re-executed each step (our Executor re-runs
    the build function eagerly) reuses its parameters — the positional
    parameter identity a static Program gives for free."""
    if name:
        return name
    import sys

    f = sys._getframe(2)  # the user's call site (past _auto and the op fn)
    return f"{prefix}@{f.f_code.co_filename}:{f.f_lineno}"


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None, activation=None, name=None):
    in_f = 1
    for d in x.shape[num_flatten_dims:]:
        in_f *= d
    key = _auto("fc", name)
    layer = _get(key, lambda: _nn.Linear(in_f, size, weight_attr=weight_attr, bias_attr=bias_attr))
    h = x
    if len(x.shape) > num_flatten_dims + 1:
        h = x.reshape(list(x.shape[:num_flatten_dims]) + [in_f])
    out = layer(h)
    if activation:
        out = getattr(_nn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1, groups=1, param_attr=None, bias_attr=None, act=None, name=None, data_format="NCHW"):
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    key = _auto("conv2d", name)
    layer = _get(key, lambda: _nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                                         padding=padding, dilation=dilation, groups=groups,
                                         weight_attr=param_attr, bias_attr=bias_attr,
                                         data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None, stride=1, padding=0, groups=1, param_attr=None, bias_attr=None, act=None, name=None, data_format="NCHW"):
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    key = _auto("conv2d_transpose", name)
    layer = _get(key, lambda: _nn.Conv2DTranspose(in_ch, num_filters, filter_size, stride=stride,
                                                  padding=padding, groups=groups,
                                                  weight_attr=param_attr, bias_attr=bias_attr,
                                                  data_format=data_format))
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None, bias_attr=None, data_layout="NCHW", is_test=False, name=None, **kwargs):
    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    key = _auto("batch_norm", name)
    layer = _get(key, lambda: _nn.BatchNorm2D(ch, momentum=momentum, epsilon=epsilon,
                                              weight_attr=param_attr, bias_attr=bias_attr))
    if is_test:
        layer.eval()
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5, param_attr=None, bias_attr=None, act=None, name=None):
    shape = list(input.shape[begin_norm_axis:])
    key = _auto("layer_norm", name)
    layer = _get(key, lambda: _nn.LayerNorm(shape, epsilon=epsilon,
                                            weight_attr=param_attr if scale else False,
                                            bias_attr=bias_attr if shift else False))
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None, dtype="float32", name=None):
    key = _auto("embedding", name)
    layer = _get(key, lambda: _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                                            weight_attr=param_attr))
    return layer(input)


def static_parameters(program=None):
    """All parameters created by static.nn calls on `program` (default main)."""
    prog = program or default_main_program()
    params = []
    for layer in getattr(prog, "_static_nn_layers", {}).values():
        params.extend(layer.parameters())
    return params


__all__ = [
    "fc", "conv2d", "conv2d_transpose", "batch_norm", "layer_norm",
    "embedding", "static_parameters",
]
