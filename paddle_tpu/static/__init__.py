"""paddle.static parity shims.

Reference: ``python/paddle/static/`` — Program/Executor/scope machinery
(SURVEY.md §1 L5, §3.4). TPU-native design: the "static graph" IS a traced,
compiled XLA program (see paddle_tpu.jit); these classes keep the reference's
user-facing workflow (`Program`, `Executor.run(feed, fetch_list)`) working on
top of the jit cache so static-graph-style scripts port over.
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional

import numpy as np

from ..framework.core import Tensor
from ..framework.op import raw
from ..jit import InputSpec  # noqa: F401  (paddle.static.InputSpec)

# paddle.static.nn (imported lazily at the bottom to avoid a cycle with
# paddle_tpu.nn, which imports framework pieces this module also uses)


class Program:
    """A recorded computation — the ProgramDesc equivalent.

    Ops executed while this program is active (inside ``program_guard``)
    are captured by the defop dispatch gateway as replayable records
    (reference: op recording into ProgramDesc under static mode —
    SURVEY.md §2.1 "Legacy framework", §3.4 InterpreterCore). Executor.run
    replays the op list as ONE jit-compiled XLA program with feeds bound
    to their placeholders and parameters passed by live value.
    """

    def __init__(self):
        self._ops = []  # (f, in_treedef, input_descs, out_uids) records
        self._tensor_refs: Dict[int, Any] = {}  # uid -> weakref(Tensor)
        self._feed_specs: Dict[str, InputSpec] = {}
        self._feed_uids: Dict[str, int] = {}
        self._fetch: List[Tensor] = []
        self._exec_cache: Dict[Any, Any] = {}
        self.random_seed = None

    def global_block(self):
        return self

    def num_ops(self):
        return len(self._ops)

    def clone(self, for_test=False):
        import copy

        return copy.copy(self)


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    from ..framework import op as _op

    global _default_main, _default_startup
    prev_m, prev_s = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    prev_cap = _op.set_capture_program(main_program)
    try:
        yield
    finally:
        _op.set_capture_program(prev_cap)
        _default_main, _default_startup = prev_m, prev_s


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed variable. Returns a placeholder Tensor that records its
    name; Executor.run substitutes the fed value."""
    from ..framework.dtypes import convert_dtype
    import jax.numpy as jnp

    spec_shape = [1 if (s is None or s < 0) else s for s in shape]
    t = Tensor(jnp.zeros(spec_shape, convert_dtype(dtype)))
    t.name = name
    _default_main._feed_specs[name] = InputSpec(shape, dtype, name)
    _default_main._feed_uids[name] = t._uid
    return t


class Executor:
    """Replays a captured Program as one jit-compiled XLA program
    (the StandaloneExecutor/InterpreterCore role — SURVEY.md §3.4): first
    run per (feed-signature, fetch-set) compiles; steady state is a single
    cached executable call. Parameters enter by live value, so updates
    between runs are honored without re-capture."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        import jax
        import jax.numpy as jnp

        feed = feed or {}
        program = program or _default_main
        fetch_list = list(fetch_list or [])

        # legacy path: callables (or no captured ops) execute eagerly
        if not getattr(program, "_ops", None) or any(
            callable(f) and not isinstance(f, Tensor) for f in fetch_list
        ):
            results = []
            for f in fetch_list:
                out = f(**feed) if callable(f) else f
                if isinstance(out, Tensor):
                    results.append(np.asarray(raw(out)) if return_numpy else out)
                else:
                    results.append(out)
            return results

        import weakref

        fetch_uids = []
        for f in fetch_list:
            if isinstance(f, Tensor):
                program._tensor_refs.setdefault(f._uid, weakref.ref(f))
                fetch_uids.append(f._uid)
            elif isinstance(f, str):
                fetch_uids.append(self._resolve_name(program, f))
            else:
                raise TypeError(
                    f"fetch_list entries must be Tensors, names, or "
                    f"callables; got {type(f).__name__}"
                )
        fetch_uids = tuple(fetch_uids)
        feed_names = tuple(sorted(feed))
        feed_vals = [jnp.asarray(raw(feed[n])) for n in feed_names]
        key = (
            fetch_uids, feed_names,
            tuple((tuple(v.shape), str(v.dtype)) for v in feed_vals),
        )
        entry = program._exec_cache.get(key)
        if entry is None:
            entry = self._compile(program, feed_names, fetch_uids)
            program._exec_cache[key] = entry
        jitted, ext_uids = entry
        ext_vals = [self._live_value(program, u) for u in ext_uids]
        outs = jitted(feed_vals, ext_vals)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    @staticmethod
    def _resolve_name(program, name):
        for uid, ref in program._tensor_refs.items():
            t = ref()
            if t is not None and t.name == name:
                return uid
        raise ValueError(
            f"fetch name {name!r} does not match any tensor captured by "
            "this Program"
        )

    @staticmethod
    def _live_value(program, uid):
        ref = program._tensor_refs.get(uid)
        t = ref() if ref is not None else None
        if t is None:
            raise RuntimeError(
                f"static Program references tensor uid={uid} that no longer "
                "exists (was it created outside the program and deleted?)"
            )
        return t._value

    def _compile(self, program, feed_names, fetch_uids):
        import jax

        feed_uid_list = []
        for n in feed_names:
            uid = program._feed_uids.get(n)
            if uid is None:
                raise KeyError(
                    f"feed {n!r} does not name a paddle.static.data "
                    f"placeholder of this Program (have "
                    f"{sorted(program._feed_uids)})"
                )
            feed_uid_list.append(uid)
        produced = set()
        ext_uids = []
        seen_ext = set()
        placeholder_uids = {u: n for n, u in program._feed_uids.items()}

        def classify_ext(uid):
            if uid in produced or uid in feed_uid_list or uid in seen_ext:
                return
            if uid in placeholder_uids:
                raise KeyError(
                    f"program uses placeholder "
                    f"{placeholder_uids[uid]!r} but it was not fed"
                )
            seen_ext.add(uid)
            ext_uids.append(uid)

        for _, _, descs, out_uids in program._ops:
            for d in descs:
                if d[0] == "t":
                    classify_ext(d[1])
            produced.update(u for u in out_uids if u is not None)
        # fetches that no captured op produced (e.g. a tape gradient) enter
        # as live external values too — with the frozen-value warning below
        for u in fetch_uids:
            classify_ext(u)
        self._warn_frozen_externals(program, ext_uids)
        ops = list(program._ops)

        def replay(feed_vals, ext_vals):
            env = dict(zip(feed_uid_list, feed_vals))
            env.update(zip(ext_uids, ext_vals))

            for f, treedef, descs, out_uids in ops:
                rebuilt = [
                    # .astype: the dtype the op actually saw at capture
                    # (reproduces the wrapper's AMP cast under auto_cast)
                    env[d[1]].astype(d[2]) if d[0] == "t" else d[1]
                    for d in descs
                ]
                a, k = jax.tree_util.tree_unflatten(treedef, rebuilt)
                out = f(*a, **k)
                for uid, ov in zip(
                    out_uids, jax.tree_util.tree_leaves(out)
                ):
                    if uid is not None:
                        env[uid] = ov
            return [env[u] for u in fetch_uids]

        return jax.jit(replay), tuple(ext_uids)

    @staticmethod
    def _warn_frozen_externals(program, ext_uids):
        """Externals that are not Parameters/buffers were COMPUTED outside
        the capture (a jit/to_static call, a tape gradient): replay sees
        their live value, it does not recompute them. Say so loudly."""
        from ..nn.layer import Parameter

        sus = []
        for uid in ext_uids:
            ref = program._tensor_refs.get(uid)
            t = ref() if ref is not None else None
            if t is not None and not isinstance(t, Parameter) \
                    and not getattr(t, "persistable", False):
                sus.append(t.name or f"uid={uid}")
        if sus:
            import warnings

            warnings.warn(
                f"static Program uses externally-computed tensors {sus[:5]} "
                "as fixed inputs: Executor.run reads their CURRENT value "
                "but will NOT recompute them from feeds. Build every "
                "feed-dependent computation from captured ops (avoid "
                "jit/to_static calls and .backward() inside program_guard).",
                stacklevel=3,
            )

    def close(self):
        pass


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program


class BuildStrategy:
    def __init__(self):
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.enable_auto_fusion = True  # XLA always fuses


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1


def name_scope(prefix=None):
    return contextlib.nullcontext()


@contextlib.contextmanager
def scope_guard(scope):
    yield


def global_scope():
    return None


def cpu_places(device_count=None):
    from ..framework.core import CPUPlace

    return [CPUPlace()]


def cuda_places(device_ids=None):
    from ..framework.core import TPUPlace

    return [TPUPlace(0)]


def set_program_state(program, state):
    pass


# save/load of inference models ride the jit StableHLO-export path
# (reference: python/paddle/static/io.py save_inference_model → program +
# params files consumed by AnalysisPredictor; here jit.save → .pdmodel
# StableHLO + .pdiparams consumed by paddle_tpu.inference.Predictor).
def save(program, model_path, protocol=4):
    """Persist a static Program's parameter/buffer state (reference:
    paddle.static.save → <path>.pdparams). The op list itself is NOT
    serialized (it holds jax callables); a load re-binds values into a
    program rebuilt by re-running the user's build code — the same
    contract as the reference's save/load of persistables."""
    layers = getattr(program, "_static_nn_layers", {})
    if not layers:
        raise ValueError(
            "static.save found no parameters on this Program (build it "
            "with static.nn layers first)"
        )
    # keys are (stable layer key, param index): reordering same-shaped
    # layers in the build code becomes a loud key mismatch, not a silent
    # weight swap
    state = {}
    for lkey, layer in layers.items():
        for i, p in enumerate(layer.parameters()):
            state[f"{lkey}::{i}"] = np.asarray(raw(p))
    np.savez(model_path + ".pdparams.npz", **state)
    return list(state)


def load(program, model_path, executor=None, var_list=None):
    """Re-bind saved values into `program`'s parameters by stable key."""
    import jax.numpy as jnp

    if var_list is not None:
        raise NotImplementedError(
            "static.load(var_list=...) subset loading is not supported; "
            "load the full program state"
        )
    layers = getattr(program, "_static_nn_layers", {})
    want = {}
    for lkey, layer in layers.items():
        for i, p in enumerate(layer.parameters()):
            want[f"{lkey}::{i}"] = p
    with np.load(model_path + ".pdparams.npz") as data:
        if set(data.files) != set(want):
            missing = sorted(set(want) - set(data.files))[:3]
            extra = sorted(set(data.files) - set(want))[:3]
            raise ValueError(
                "checkpoint/program parameter keys differ — rebuild the "
                f"same program first (missing {missing}, extra {extra})"
            )
        for key, p in want.items():
            v = data[key]
            if tuple(v.shape) != tuple(p.shape):
                raise ValueError(
                    f"{key} shape mismatch: checkpoint {v.shape} vs "
                    f"program {tuple(p.shape)}"
                )
            if str(v.dtype) != str(np.dtype(str(raw(p).dtype))):
                raise ValueError(
                    f"{key} dtype mismatch: checkpoint {v.dtype} vs "
                    f"program {raw(p).dtype}"
                )
            p._rebind(jnp.asarray(v))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, *, model=None, input_spec=None, **kwargs):
    """Static-mode export. The TPU-native artifact needs the model object (the
    program IS the traced model): pass ``model=`` (a Layer) plus
    ``input_spec=`` (or feed_vars as InputSpecs/example Tensors)."""
    from .. import jit as _jit
    from ..nn.layer import Layer as _Layer

    target = model
    if target is None and isinstance(fetch_vars, _Layer):
        target = fetch_vars
    if target is None:
        raise ValueError(
            "save_inference_model needs model=<Layer> (TPU-native export "
            "serializes the traced model as StableHLO via paddle_tpu.jit.save)"
        )
    spec = input_spec if input_spec is not None else feed_vars
    return _jit.save(target, path_prefix, input_spec=spec)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (TranslatedLayer, feed_names, fetch_names) — the loaded layer
    plays the role of the inference Program."""
    from .. import jit as _jit

    layer = _jit.load(path_prefix)
    return layer, layer.input_names, None


from . import nn  # noqa: E402,F401  (paddle.static.nn)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Host-Python op inside a compiled program (paddle.static.py_func
    parity; reference: python/paddle/static/nn/common.py py_func over the
    C++ py_func op). TPU-native: ``jax.pure_callback`` — XLA calls back to
    host Python at execution time, under jit and in captured Programs.
    ``out`` supplies the static shape/dtype contract (a template Tensor or
    a list of them); ``backward_func`` (if given) defines the VJP, itself
    run as a host callback."""
    import jax
    import numpy as np

    from ..framework.core import Tensor
    from ..framework.op import raw

    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    multi_in, multi_out = isinstance(x, (list, tuple)), isinstance(out, (list, tuple))
    shapes = tuple(jax.ShapeDtypeStruct(tuple(raw(o).shape), raw(o).dtype)
                   for o in outs)

    def host_fwd(*vals):
        r = func(*[np.asarray(v) for v in vals])
        rs = r if isinstance(r, (list, tuple)) else [r]
        return tuple(np.asarray(v, s.dtype).reshape(s.shape)
                     for v, s in zip(rs, shapes))

    @jax.custom_vjp
    def call(*vals):
        return jax.pure_callback(host_fwd, shapes, *vals)

    def fwd(*vals):
        return call(*vals), vals

    def bwd(res, cts):
        if backward_func is None:
            return tuple(jax.numpy.zeros_like(v) for v in res)
        in_shapes = tuple(jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
                          for v in res)

        def host_bwd(*args):
            n = len(res)
            grads = backward_func(*[np.asarray(a) for a in args])
            gs = grads if isinstance(grads, (list, tuple)) else [grads]
            return tuple(np.asarray(g, s.dtype).reshape(s.shape)
                         for g, s in zip(gs, in_shapes))

        return jax.pure_callback(host_bwd, in_shapes, *res, *cts)

    call.defvjp(fwd, bwd)
    res = call(*[raw(v) for v in xs])
    res_t = [Tensor(r) for r in res]
    return res_t if multi_out else res_t[0]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """paddle.static.gradients parity: append backward computation for
    ``targets`` w.r.t. ``inputs`` to the active Program and return the
    gradient variables.

    Reference: ``python/paddle/base/backward.py::gradients`` — walks the
    ProgramDesc emitting one grad op per forward op. TPU-native design: the
    captured op list IS a pure jax program, so the backward is obtained in
    one shot with ``jax.vjp`` over a replay closure; the whole backward
    enters the Program as a single record (XLA CSEs its re-played forward
    against the already-captured one at compile time, so the compiled
    executable computes the forward once).
    """
    import weakref

    import jax
    import jax.numpy as jnp

    from ..framework import op as _op

    if no_grad_set:
        raise NotImplementedError(
            "static.gradients(no_grad_set=...): mark tensors "
            "stop_gradient=True before capture instead")
    prog = _op._capture_program or _default_main
    targets = list(targets) if isinstance(targets, (list, tuple)) else [targets]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is None:
        tgs = [None] * len(targets)
    else:
        tg_list = (list(target_gradients)
                   if isinstance(target_gradients, (list, tuple))
                   else [target_gradients])
        tgs = [None if t is None else raw(t) for t in tg_list]

    ops_snapshot = list(prog._ops)
    target_uids = [t._uid for t in targets]
    input_uids = [t._uid for t in inputs]

    # every tensor the subgraph reads that no captured op produces is an
    # external input of the grad record (feeds, parameters, buffers)
    produced, dep_uids, seen = set(), [], set(input_uids)
    for _f, _td, descs, out_uids in ops_snapshot:
        for d in descs:
            if d[0] == "t" and d[1] not in produced and d[1] not in seen:
                seen.add(d[1])
                dep_uids.append(d[1])
        produced.update(u for u in out_uids if u is not None)
    missing = [u for u in target_uids if u not in produced and u not in seen]
    if missing:
        raise ValueError(
            "static.gradients: target(s) were not computed by this "
            "Program's captured ops")
    all_uids = list(input_uids) + dep_uids

    def grad_record(*vals):
        base_env = dict(zip(all_uids, vals))

        def pure(in_vals):
            e = dict(base_env)
            e.update(zip(input_uids, in_vals))
            for f, treedef, descs, out_uids in ops_snapshot:
                rebuilt = [
                    e[d[1]].astype(d[2]) if d[0] == "t" else d[1]
                    for d in descs
                ]
                a, k = jax.tree_util.tree_unflatten(treedef, rebuilt)
                out = f(*a, **k)
                for uid, ov in zip(out_uids, jax.tree_util.tree_leaves(out)):
                    if uid is not None:
                        e[uid] = ov
            return [e[u] for u in target_uids]

        outs, vjp_fn = jax.vjp(pure, [base_env[u] for u in input_uids])
        cts = [jnp.ones_like(o) if tg is None else tg.astype(o.dtype)
               for o, tg in zip(outs, tgs)]
        (gin,) = vjp_fn(list(cts))
        return tuple(gin)

    # resolve live values for every needed uid (placeholders hold zeros of
    # the declared shape, so an eager evaluation is always possible)
    vals = []
    for u in all_uids:
        ref = prog._tensor_refs.get(u)
        t = ref() if ref is not None else None
        if t is None:
            t = next((x for x in inputs + targets if x._uid == u), None)
        if t is None:
            raise RuntimeError(
                f"static.gradients: captured tensor uid={u} is no longer "
                "alive; keep references to Program inputs")
        vals.append(t._value)
        prog._tensor_refs[u] = weakref.ref(t)

    # eager evaluation (capture suspended) gives the grad Tensors their
    # shapes/dtypes; Executor.run recomputes them from the record
    prev = _op.set_capture_program(None)
    try:
        gvals = grad_record(*vals)
    finally:
        _op.set_capture_program(prev)
    grads = [Tensor(g) for g in gvals]
    for g, inp in zip(grads, inputs):
        g.name = f"{getattr(inp, 'name', None) or 'var'}@GRAD"

    descs = tuple(("t", u, str(v.dtype)) for u, v in zip(all_uids, vals))
    treedef = jax.tree_util.tree_flatten(
        (tuple(range(len(all_uids))), {}))[1]
    out_uids = tuple(g._uid for g in grads)
    for g in grads:
        prog._tensor_refs[g._uid] = weakref.ref(g)
    prog._ops.append((grad_record, treedef, descs, out_uids))
    return grads


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """paddle.static.append_backward parity: appends the backward of
    ``loss`` for every trainable Parameter the Program references and
    returns ``[(param, param_grad), ...]`` (reference:
    ``python/paddle/base/backward.py::append_backward``)."""
    from ..framework import op as _op
    from ..nn.layer import Parameter

    prog = _op._capture_program or _default_main
    if parameter_list is None:
        params, seen = [], set()
        for _f, _td, descs, _o in prog._ops:
            for d in descs:
                if d[0] != "t" or d[1] in seen:
                    continue
                seen.add(d[1])
                ref = prog._tensor_refs.get(d[1])
                t = ref() if ref is not None else None
                if isinstance(t, Parameter) and t.trainable:
                    params.append(t)
    else:
        resolved = []
        for p in parameter_list:
            if isinstance(p, str):  # the reference accepts Parameter names
                hit = None
                for ref in prog._tensor_refs.values():
                    t = ref()
                    if isinstance(t, Parameter) and t.name == p:
                        hit = t
                        break
                if hit is None:
                    raise ValueError(
                        f"append_backward: no Parameter named {p!r} is "
                        "referenced by this Program")
                p = hit
            resolved.append(p)
        params = [p for p in resolved if getattr(p, "trainable", True)]
    if not params:
        return []
    grads = gradients(loss, params, no_grad_set=no_grad_set)
    return list(zip(params, grads))


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """paddle.static.Print parity: a debug print that survives compilation.

    Reference: the Print op (``paddle/fluid/operators/print_op.cc``) prints
    a variable's value at execution time. Here the op lowers to
    ``jax.debug.print`` — a host callback that fires every time the
    compiled program executes (not at trace time) — and returns the input
    unchanged so it composes inside expressions.
    """
    import jax as _jax

    from ..framework.op import defop as _defop

    msg = str(message or getattr(input, "name", None) or "var")

    @_defop(name="print_op")
    def _print_op(x):
        # debug.callback, not debug.print: the message is user text, not a
        # format template (braces in it would crash jax's formatter)
        _jax.debug.callback(lambda v: print(f"{msg} = {v}"), x)
        return x

    return _print_op(input)


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """paddle.static.normalize_program parity: prune/normalize a Program to
    the feed->fetch subgraph for inference export. Here the Executor
    compiles exactly the ops reachable from the requested fetches and XLA
    dead-code-eliminates the rest, so normalization is a clone that records
    the intended feeds/fetches."""
    out = program.clone(for_test=True)
    out._fetch = list(fetch_vars) if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    return out


@contextlib.contextmanager
def device_guard(device=None):
    """paddle.static.device_guard parity. The compiled program runs on the
    backend XLA selected; per-op device pinning (the reference's cpu/gpu
    placement of individual ops) has no analogue under one fused program —
    use ``static.py_func``/``jax.pure_callback`` for genuinely host-side
    ops. Accepted and recorded for script compatibility."""
    yield


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    """IPU-only in the reference; raises as upstream does without an IPU
    build."""
    raise RuntimeError(
        "ipu_shard_guard is IPU-specific; this build targets TPU "
        "(use paddle.distributed parallelism APIs instead)")
    yield  # pragma: no cover
