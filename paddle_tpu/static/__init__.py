"""paddle.static parity shims.

Reference: ``python/paddle/static/`` — Program/Executor/scope machinery
(SURVEY.md §1 L5, §3.4). TPU-native design: the "static graph" IS a traced,
compiled XLA program (see paddle_tpu.jit); these classes keep the reference's
user-facing workflow (`Program`, `Executor.run(feed, fetch_list)`) working on
top of the jit cache so static-graph-style scripts port over.
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional

import numpy as np

from ..framework.core import Tensor
from ..framework.op import raw
from ..jit import InputSpec  # noqa: F401  (paddle.static.InputSpec)

# paddle.static.nn (imported lazily at the bottom to avoid a cycle with
# paddle_tpu.nn, which imports framework pieces this module also uses)


class Program:
    """A recorded computation: ops are captured by running the build function
    lazily at first Executor.run (trace-on-first-use, like InterpreterCore's
    first-run instruction build — SURVEY.md §3.4)."""

    def __init__(self):
        self._build_fns = []  # callables invoked with feeds
        self._feed_specs: Dict[str, InputSpec] = {}
        self._fetch: List[Tensor] = []
        self.random_seed = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        import copy

        return copy.copy(self)


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _default_main, _default_startup
    prev_m, prev_s = _default_main, _default_startup
    _default_main = main_program
    if startup_program is not None:
        _default_startup = startup_program
    try:
        yield
    finally:
        _default_main, _default_startup = prev_m, prev_s


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed variable. Returns a placeholder Tensor that records its
    name; Executor.run substitutes the fed value."""
    from ..framework.dtypes import convert_dtype
    import jax.numpy as jnp

    spec_shape = [1 if (s is None or s < 0) else s for s in shape]
    t = Tensor(jnp.zeros(spec_shape, convert_dtype(dtype)))
    t.name = name
    _default_main._feed_specs[name] = InputSpec(shape, dtype, name)
    return t


class Executor:
    """Eager-executing Executor: feeds are bound to placeholder names and the
    model functions re-run; for compiled execution use paddle_tpu.jit."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        feed = feed or {}
        results = []
        for f in fetch_list or []:
            if callable(f):
                out = f(**feed)
            else:
                out = f
            if isinstance(out, Tensor):
                results.append(np.asarray(raw(out)) if return_numpy else out)
            else:
                results.append(out)
        return results

    def close(self):
        pass


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program


class BuildStrategy:
    def __init__(self):
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.enable_auto_fusion = True  # XLA always fuses


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1


def name_scope(prefix=None):
    return contextlib.nullcontext()


@contextlib.contextmanager
def scope_guard(scope):
    yield


def global_scope():
    return None


def cpu_places(device_count=None):
    from ..framework.core import CPUPlace

    return [CPUPlace()]


def cuda_places(device_ids=None):
    from ..framework.core import TPUPlace

    return [TPUPlace(0)]


def set_program_state(program, state):
    pass


# save/load of inference models ride the jit StableHLO-export path
# (reference: python/paddle/static/io.py save_inference_model → program +
# params files consumed by AnalysisPredictor; here jit.save → .pdmodel
# StableHLO + .pdiparams consumed by paddle_tpu.inference.Predictor).
def save(program, model_path, protocol=4):
    raise NotImplementedError("use paddle_tpu.save / paddle_tpu.jit.save")


def load(program, model_path, executor=None, var_list=None):
    raise NotImplementedError("use paddle_tpu.load / paddle_tpu.jit.load")


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None, *, model=None, input_spec=None, **kwargs):
    """Static-mode export. The TPU-native artifact needs the model object (the
    program IS the traced model): pass ``model=`` (a Layer) plus
    ``input_spec=`` (or feed_vars as InputSpecs/example Tensors)."""
    from .. import jit as _jit
    from ..nn.layer import Layer as _Layer

    target = model
    if target is None and isinstance(fetch_vars, _Layer):
        target = fetch_vars
    if target is None:
        raise ValueError(
            "save_inference_model needs model=<Layer> (TPU-native export "
            "serializes the traced model as StableHLO via paddle_tpu.jit.save)"
        )
    spec = input_spec if input_spec is not None else feed_vars
    return _jit.save(target, path_prefix, input_spec=spec)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns (TranslatedLayer, feed_names, fetch_names) — the loaded layer
    plays the role of the inference Program."""
    from .. import jit as _jit

    layer = _jit.load(path_prefix)
    return layer, layer.input_names, None


from . import nn  # noqa: E402,F401  (paddle.static.nn)
