"""paddle.inference parity: Config / create_predictor serving path.

Reference: ``paddle/fluid/inference/`` AnalysisPredictor + C API
(``paddle_inference_api.h``) — load a saved program + params, run IR
optimization passes, execute with zero-copy input/output handles
(SURVEY.md §2.1 "Inference engine", §2.4 item 14). TPU-native design: the
saved artifact is already the optimized program (StableHLO from jit.save);
"analysis passes" are XLA's compilation pipeline, so the predictor is a thin
executable cache with Paddle's handle-based API on top. Works on TPU or CPU
PJRT backends; batch-size changes just select a new cached executable (or
reuse one, if the model was exported batch-polymorphic).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..jit.save_load import TranslatedLayer, load as _jit_load


class Config:
    """paddle.inference.Config parity (GPU/TensorRT knobs are accepted and
    recorded but are no-ops: XLA owns optimization on TPU)."""

    def __init__(self, prog_file: Optional[str] = None, params_file: Optional[str] = None):
        # paddle accepts Config(model_dir) or Config(prog_file, params_file);
        # we accept a path PREFIX (as written by jit.save) in either slot.
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._memory_optim = True
        self._ir_optim = True
        self._device = None  # None → default jax backend
        self._num_threads = 1
        self._tensorrt = False

    # --- model location ---
    def set_model(self, prog_file, params_file=None):
        if prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file

    def model_dir(self):
        return self._prefix

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return (self._prefix or "") + ".pdiparams"

    # --- device selection ---
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # "GPU" slot maps to the accelerator backend (TPU here)
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "tpu"

    def set_cpu_math_library_num_threads(self, n):
        self._num_threads = n

    # --- optimization knobs (XLA always optimizes; recorded for parity) ---
    def enable_memory_optim(self, x=True):
        self._memory_optim = x

    def switch_ir_optim(self, x=True):
        self._ir_optim = x

    def switch_use_feed_fetch_ops(self, x=False):
        pass

    def switch_specify_input_names(self, x=True):
        pass

    def enable_tensorrt_engine(self, *args, **kwargs):
        self._tensorrt = True  # no-op: XLA fusion replaces TRT subgraphs

    def tensorrt_engine_enabled(self):
        return self._tensorrt

    # --- serving decode engine (inference/engine.py, docs/SERVING.md) ---
    def enable_decode_engine(self, num_slots: int = 8, max_length: int = 512,
                             kv_dtype: str = "f32", **kw):
        """Record decode-engine settings; `enable_decode_engine(model,
        config)` (module level) builds the engine from them and attaches
        it, after which text.generation.generate()/generate_padded() route
        through the KV-cached continuous-batching loop."""
        self._engine_kwargs = dict(
            num_slots=num_slots, max_length=max_length, kv_dtype=kv_dtype,
            **kw)

    def decode_engine_enabled(self) -> bool:
        return getattr(self, "_engine_kwargs", None) is not None

    def decode_engine_config(self):
        """EngineConfig built from enable_decode_engine() settings."""
        from .engine import EngineConfig

        return EngineConfig(**getattr(self, "_engine_kwargs", {}) or {})

    def summary(self):
        return (
            f"Config(prefix={self._prefix}, device={self._device or 'default'}, "
            f"memory_optim={self._memory_optim}, ir_optim={self._ir_optim})"
        )


class _IOHandle:
    """Zero-copy-style input/output handle (copy_from_cpu/copy_to_cpu parity).

    Reference: ``ZeroCopyTensor`` in paddle_inference_api.h — named handles
    that stage host buffers in and device buffers out.
    """

    def __init__(self, name):
        self.name = name
        self._value = None
        self._shape = None
        #: bumped on every copy_from_cpu — Predictor.run only device_puts
        #: handles whose version moved since the last call
        self._version = 0

    def reshape(self, shape):
        self._shape = tuple(shape)

    def copy_from_cpu(self, arr: np.ndarray):
        arr = np.asarray(arr)
        if self._shape is not None and tuple(arr.shape) != self._shape:
            arr = arr.reshape(self._shape)
        self._value = arr
        self._version += 1

    def copy_to_cpu(self) -> np.ndarray:
        # outputs stay device-resident until someone actually asks for the
        # host copy (np.asarray on a jax array is the D2H transfer)
        return np.asarray(self._value)

    def shape(self):
        v = self._value
        return list(v.shape) if v is not None else list(self._shape or [])


class Predictor:
    """paddle.inference predictor over a jit.save'd StableHLO artifact."""

    def __init__(self, config: Config, _layer: Optional[TranslatedLayer] = None):
        if _layer is None and not config._prefix:
            raise ValueError("Config has no model path; use Config(prefix) or set_model")
        self._config = config
        self._layer: TranslatedLayer = _layer if _layer is not None else _jit_load(config._prefix)
        self._input_names = self._layer.input_names
        self._inputs: Dict[str, _IOHandle] = {
            n: _IOHandle(n) for n in self._input_names
        }
        self._outputs: Dict[str, _IOHandle] = {}
        self._output_names: List[str] = []
        #: name -> (handle version, device-resident array). Params already
        #: live on device inside the TranslatedLayer; this closes the other
        #: half of the loop so repeated run() calls with unchanged inputs
        #: do zero H2D transfers.
        self._dev_inputs: Dict[str, tuple] = {}

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name) -> _IOHandle:
        return self._inputs[name]

    def _device_input(self, name):
        """The handle's value as a device array, re-transferred only when
        copy_from_cpu bumped its version since the previous run()."""
        import jax

        h = self._inputs[name]
        ver, arr = self._dev_inputs.get(name, (None, None))
        if ver != h._version:
            v = h._value
            arr = v if isinstance(v, jax.Array) else jax.device_put(v)
            self._dev_inputs[name] = (h._version, arr)
        return arr

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Execute. Either stage inputs via handles then run(), or pass a list
        of arrays positionally (newer paddle.inference allows both)."""
        if inputs is not None:
            if len(inputs) != len(self._input_names):
                raise ValueError(
                    f"run() got {len(inputs)} inputs, model expects "
                    f"{len(self._input_names)}: {self._input_names}"
                )
            for n, a in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(a)
        missing = [n for n in self._input_names if self._inputs[n]._value is None]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        out = self._layer.forward(
            *[self._device_input(n) for n in self._input_names])
        import jax

        leaves = jax.tree_util.tree_leaves(
            out, is_leaf=lambda x: hasattr(x, "_value")
        )
        self._output_names = [f"fetch_{i}" for i in range(len(leaves))]
        self._outputs = {}
        for n, leaf in zip(self._output_names, leaves):
            h = _IOHandle(n)
            # keep the DEVICE array; copy_to_cpu does the host transfer
            h._value = leaf._value if hasattr(leaf, "_value") else leaf
            self._outputs[n] = h
        if inputs is not None:
            return [self._outputs[n].copy_to_cpu() for n in self._output_names]
        return True

    def get_output_names(self) -> List[str]:
        return list(self._output_names)

    def get_output_handle(self, name) -> _IOHandle:
        return self._outputs[name]


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


def enable_decode_engine(model, config: Optional[Config] = None, **kw):
    """Attach a KV-cached continuous-batching decode engine to a live
    causal LM (a model exposing ``decode_adapter()``: GPTForCausalLM,
    LlamaForCausalLM). After this, ``text.generation.generate`` /
    ``generate_padded`` route through the engine automatically; the
    engine is also returned for direct ``submit()``/``step()``/``run()``
    driving. Settings come from ``config.enable_decode_engine(...)`` when
    a Config is given, else from keyword args (EngineConfig fields).

    See docs/SERVING.md."""
    from .engine import DecodeEngine

    if config is not None and config.decode_engine_enabled():
        engine = DecodeEngine(model, config.decode_engine_config())
    else:
        engine = DecodeEngine(model, **kw)
    model._decode_engine = engine
    return engine


def disable_decode_engine(model):
    """Detach the engine; generation falls back to the legacy loops."""
    if getattr(model, "_decode_engine", None) is not None:
        model._decode_engine = None


class PredictorPool:
    """paddle.inference.PredictorPool parity: N predictors over ONE loaded
    artifact — the deserialized module and its jit-compiled executable are
    shared; each pool member only has its own input/output handle staging."""

    def __init__(self, config: Config, size: int = 1):
        shared = _jit_load(config._prefix)
        self._preds = [Predictor(config, _layer=shared) for _ in range(size)]

    def retrieve(self, idx: int) -> Predictor:
        return self._preds[idx]


def get_version():
    import jax

    return f"paddle_tpu-inference (jax {jax.__version__})"
