"""Paged-KV decode engine: continuous batching, prefix sharing, speculation.

Reference capability: Paddle Inference's generation serving stack (fused
attention-with-cache kernels updating an in-place ``cache_kv`` per layer)
and PaddleNLP's ``llm/predictor.py`` batched serving loop, extended with
the vLLM-style block-granular cache discipline. TPU-native design (the
static-shape serving discipline on XLA):

* **Static shapes only.** Three compiled program families serve every
  request mix: one prefill per power-of-two *tail* bucket (batch 1,
  written through a page table), ONE single-token decode step over all
  ``num_slots`` slots, and (when ``speculate_k > 0``) ONE multi-token
  verify step. Nothing recompiles per request, per length, or per step.
* **Paged KV cache.** The cache is a page pool
  ``[L, num_pages, Hkv, page_size, D]`` plus a per-slot page table
  ``[S, max_pages]`` (host-maintained int32). Page 0 is a reserved trash
  page; a free-list allocator hands out the rest. A request holds only
  ``ceil(total_len / page_size)`` pages instead of a full ``max_length``
  ring, so short requests stop stranding HBM and the pool can serve far
  more concurrent requests per GB (``scripts/bench_serving.py`` churn
  scenario). ``F.paged_attention`` gathers K/V through the table; int8
  scales are paged identically.
* **Prefix caching.** Full prompt blocks are chain-hashed
  (``h_j = H(h_{j-1} || tokens_j)``) and registered in a bounded-LRU
  page registry with refcounts. A new prompt whose leading blocks hit
  the registry shares those pages (incref, never rewritten — decode and
  tail writes only touch pages past ``cached_len``, which is the
  copy-on-write discipline) and prefills ONLY the unique tail: an
  80 %-shared-prefix workload skips 80 % of its prefill FLOPs.
* **Speculative / multi-token decode.** A host-side prompt-lookup
  (n-gram) draft proposes ``k`` tokens per slot; one compiled verify
  program scores current + k draft tokens in a single target-model pass
  and per-position target tokens are accepted while they agree with the
  draft, emitting up to ``k + 1`` tokens per step. Acceptance compares
  against the SAME position-keyed sample streams the decode step uses
  (``fold_in(request_key, position)``), so greedy output stays bit-equal
  and sampled streams stay scheduling-invariant with speculation on or
  off.
* **Disaggregated prefill.** ``prefill_export`` runs a prompt's prefill
  here and returns its content KV pages as host arrays;
  ``try_import_prefill`` adopts them on a decode engine, seating the
  request straight into the decode batch. Raw transfer with a matching
  ``kv_dtype`` is bit-equal to a local prefill (the serving worker
  streams the payload through ``serving/transport.py``'s KV codec).
* **Continuous batching / on-device sampling / int8 KV** as before
  (PR 5): pure-Python scheduler admits into free slots between compiled
  steps, one int32 per slot per step host transfer (``k+1`` for verify),
  absmax-scaled int8 via grad_comm's quantize/dequantize helpers.

Models plug in through ``model.decode_adapter()`` (text/models/gpt.py,
llama.py). See docs/SERVING.md for the page-table invariants and the
accept/reject rule.
"""
from __future__ import annotations

import hashlib
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..distributed.grad_comm import dequantize_absmax, quantize_absmax
from ..runtime import compile_cache as _compile_cache
from ..framework.core import Tensor, no_grad
from ..framework.op import raw
from ..nn import functional as F

__all__ = [
    "DecodeEngine",
    "EngineConfig",
    "PagePool",
    "PrefixRegistry",
    "SamplingParams",
    "pow2_bucket",
]

KV_DTYPES = ("f32", "bf16", "int8")

#: the reserved all-garbage page every unallocated page-table entry (and
#: every masked scatter) points at; never handed out by the allocator
TRASH_PAGE = 0


def pow2_bucket(n: int, lo: int = 16, hi: Optional[int] = None) -> int:
    """Smallest power-of-two >= n (floored at `lo`, capped at `hi`)."""
    b = lo
    while b < n:
        b *= 2
    return min(b, hi) if hi is not None else b


@dataclass
class EngineConfig:
    """Engine geometry + cache policy (see docs/SERVING.md for tuning)."""

    num_slots: int = 8
    max_length: int = 512
    kv_dtype: str = "f32"  # f32 | bf16 | int8
    #: explicit prompt buckets; None = powers of two from min_bucket up to
    #: max_length. Only buckets a prompt tail actually lands in get
    #: compiled.
    prompt_buckets: Optional[Tuple[int, ...]] = None
    min_bucket: int = 16
    #: KV page size in tokens. A request holds ceil(total/page_size)
    #: pages; prefix sharing works at full-page granularity.
    page_size: int = 16
    #: total pages in the pool INCLUDING the reserved trash page 0.
    #: None = 1 + num_slots * ceil(max_length / page_size) (the same
    #: capacity the PR 5 contiguous cache reserved); set it lower to
    #: overcommit — admission blocks when the free list runs dry.
    num_pages: Optional[int] = None
    #: hash full prompt blocks and share hit pages across requests
    prefix_cache: bool = True
    #: bounded LRU capacity of the prefix registry, in blocks.
    #: None = num_pages (every page could be registered).
    prefix_registry_blocks: Optional[int] = None
    #: draft tokens per speculative step; 0 disables speculation
    speculate_k: int = 0
    #: longest n-gram the prompt-lookup draft matches on
    ngram: int = 3
    #: self-tuning speculation: track EMAs of decode/verify step wall time
    #: and draft acceptance, and only run the verify program when its
    #: expected tokens/s beats plain decode (verify is ~free on memory-
    #: bound TPU decode, ~(k+1)x on compute-bound CPU). Acceptance is
    #: timing-INDEPENDENT, so output stays bit-equal either way — the
    #: gate only changes how many tokens one step emits. False = always
    #: speculate when a draft exists (deterministic step pattern, what
    #: the bit-equality tests pin).
    spec_adaptive: bool = True
    #: while speculation is suppressed, re-probe with one verify step
    #: every this many decode steps (acceptance drifts with the workload)
    spec_probe_every: int = 32
    #: None = donate cache buffers on tpu/gpu only (CPU XLA cannot alias
    #: them and would warn on every step)
    donate: Optional[bool] = None
    #: base seed for requests that don't carry their own
    seed: int = 0
    #: wire dtype of the mp-sharded logit recombination (docs/SERVING.md
    #: §5): None resolves from the mp_comm activation-wire config
    #: (PADDLE_TPU_MP_COMM / DistributedStrategy.mp_comm), "off"/"f32"
    #: pins today's exact f32 all-gather byte-for-byte, "bf16"/"int8"
    #: quantize the replication payload while a per-shard (max, argmax)
    #: exchange keeps greedy decode bit-equal to the single-device
    #: engine. Ignored (always exact) when the mesh has no mp axis.
    logit_wire: Optional[str] = None
    #: paged-attention kernel for the decode/verify/prefill programs:
    #: None inherits PADDLE_TPU_ATTN_KERNEL (default "auto"), "pallas"
    #: pins the fused Pallas kernel (page gather + online softmax + int8
    #: dequant in one pass, docs/SERVING.md §kernel plane), "einsum" pins
    #: the XLA reference oracle, "auto" picks pallas on TPU. An mp-
    #: sharded pool always serves einsum (the GSPMD annotations live
    #: there) and counts an attn_kernel_fallback_total.
    attn_kernel: Optional[str] = None
    #: jax.sharding.Mesh to run the compiled programs on. An ``mp`` axis
    #: with degree > 1 shards the KV pools (and int8 scales) over kv
    #: heads — GQA groups stay whole per shard, so mp must divide
    #: num_kv_heads — while page tables, sampling, and everything outside
    #: attention stay replicated (greedy output is bit-equal to the
    #: single-device engine; docs/SERVING.md §mp sharding). Give each
    #: engine its OWN mesh slice: a dp axis here replicates the pools,
    #: engine replicas belong behind serving.Router instead.
    mesh: Optional[object] = None

    def resolved_buckets(self) -> List[int]:
        if self.prompt_buckets:
            bs = sorted({min(int(b), self.max_length)
                         for b in self.prompt_buckets})
        else:
            bs, b = [], self.min_bucket
            while b < self.max_length:
                bs.append(b)
                b *= 2
            bs.append(min(b, self.max_length))
        return bs

    @property
    def max_pages(self) -> int:
        """Page-table width: pages a max_length request spans."""
        return -(-self.max_length // self.page_size)

    def resolved_num_pages(self) -> int:
        if self.num_pages is not None:
            return int(self.num_pages)
        return 1 + self.num_slots * self.max_pages


@dataclass
class SamplingParams:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: Optional[int] = None

    def fields(self):
        """(temperature, top_k, top_p, greedy) in device form."""
        greedy = (not self.do_sample) or self.temperature <= 0.0
        return (max(float(self.temperature), 1e-6), int(self.top_k),
                float(self.top_p), bool(greedy))


@dataclass
class Request:
    req_id: int
    prompt: np.ndarray
    params: SamplingParams
    key_np: np.ndarray
    tokens: List[int] = field(default_factory=list)
    status: str = "waiting"  # waiting | running | done
    slot: int = -1
    submit_time: float = 0.0
    first_token_time: Optional[float] = None
    #: every page id this request holds a reference on (shared prefix
    #: pages first, then private pages), in virtual-sequence order
    page_ids: List[int] = field(default_factory=list)
    #: tokens served from the prefix registry (multiple of page_size)
    cached_len: int = 0
    #: distributed-trace context from the router wire record (spans are
    #: emitted only when trace_id is set — standalone engines stay quiet)
    trace_id: Optional[str] = None
    trace_parent: Optional[str] = None
    resubmitted: bool = False
    #: phase accounting (perf_counter stamps) behind the enriched
    #: serving_request_done event; maintained regardless of tracing
    prefill_t0: Optional[float] = None
    prefill_s: float = 0.0
    decode_t0: Optional[float] = None
    decode_steps_n: int = 0
    verify_steps_n: int = 0
    spec_accepted_n: int = 0
    #: cost-attribution key (observability/accounting.py): "-" = the
    #: untagged default; slo mirrors the router's class for the ledger
    tenant: str = "-"
    slo: str = "standard"
    #: True when this request's prefill (and first token) ran on another
    #: engine (try_import_prefill) — its prefill/first-token usage was
    #: attributed there, so _finish must not count them again
    imported: bool = False
    #: pro-rata KV page occupancy charged to this request so far, in
    #: integer page-microseconds (PageSecondsMeter)
    acct_page_us: int = 0
    #: weight epoch this request was admitted under (per-slot epoch pin):
    #: the request decodes against these weights until it finishes, even
    #: if the engine promotes a newer epoch mid-flight — the per-epoch
    #: greedy bit-equal contract rides on this
    epoch: int = 0


# ---------------------------------------------------------------------------
# host-side page accounting: free-list allocator + prefix registry
# ---------------------------------------------------------------------------


class PagePool:
    """Free-list page allocator with refcounts.

    Page ``TRASH_PAGE`` (0) is reserved and never allocated. A page is
    free iff its refcount is 0; ``alloc`` hands it out at refcount 1,
    sharing increfs, and the last ``decref`` returns it to the free
    list — so the invariant ``available() + pages_referenced == num_pages
    - 1`` holds at every step and a double-allocation is structurally
    impossible (allocated pages are not on the free list).
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (trash page + 1)")
        self.num_pages = int(num_pages)
        # pop() hands out low page ids first
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._ref = np.zeros(self.num_pages, np.int64)

    def available(self) -> int:
        return len(self._free)

    def refcount(self, page: int) -> int:
        return int(self._ref[page])

    def shared_pages(self) -> int:
        """Pages currently referenced by more than one owner."""
        return int((self._ref[1:] >= 2).sum())

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh pages at refcount 1, or None (never partial)."""
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._ref[p] = 1
        return pages

    def incref(self, page: int):
        if page == TRASH_PAGE or self._ref[page] <= 0:
            raise ValueError(f"incref of unallocated page {page}")
        self._ref[page] += 1

    def decref(self, page: int):
        if self._ref[page] <= 0:
            raise ValueError(f"decref of free page {page}")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)


class PrefixRegistry:
    """Bounded LRU of full prompt blocks: chain hash -> page id.

    Each registered page carries one registry reference, so pages stay
    resident (and shareable) after their request finishes until LRU
    capacity or an explicit ``evict_unused`` reclaims them. Entries whose
    page is still used by a running request can drop OUT of the registry
    (no longer discoverable) without freeing the page — the refcount
    keeps it alive until the request finishes.
    """

    def __init__(self, pool: PagePool, capacity: int):
        self.pool = pool
        self.capacity = int(capacity)
        self._lru: "OrderedDict[bytes, int]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def __len__(self):
        return len(self._lru)

    @staticmethod
    def block_keys(prompt: np.ndarray, page_size: int) -> List[bytes]:
        """Chain hashes of the prompt's FULL blocks: block j's key folds
        in its parent's key, so equal keys imply equal whole prefixes,
        not just equal blocks."""
        keys, parent = [], b"paddle_tpu/prefix"
        t0 = int(prompt.shape[0])
        for j in range(t0 // page_size):
            blk = np.ascontiguousarray(
                prompt[j * page_size:(j + 1) * page_size], dtype=np.int64)
            parent = hashlib.blake2b(
                parent + blk.tobytes(), digest_size=16).digest()
            keys.append(parent)
        return keys

    def lookup_chain(self, keys: List[bytes]) -> List[int]:
        """Pages for the longest registered prefix of `keys`, each
        increfed for the caller (release with pool.decref)."""
        pages = []
        for key in keys:
            page = self._lru.get(key)
            if page is None:
                self.misses += 1
                break
            self._lru.move_to_end(key)
            self.pool.incref(page)
            pages.append(page)
            self.hits += 1
        return pages

    def register(self, key: bytes, page: int):
        if key in self._lru:
            self._lru.move_to_end(key)
            return
        self.pool.incref(page)
        self._lru[key] = page
        while len(self._lru) > self.capacity:
            _, old = self._lru.popitem(last=False)
            self.pool.decref(old)

    def evict_unused(self, want: int) -> int:
        """Drop up to `want` LRU entries whose page only the registry
        still references (freeing the page); returns pages freed."""
        freed = 0
        for key in list(self._lru):
            if freed >= want:
                break
            page = self._lru[key]
            if self.pool.refcount(page) == 1:
                del self._lru[key]
                self.pool.decref(page)
                freed += 1
        return freed

    def clear(self):
        for page in self._lru.values():
            self.pool.decref(page)
        self._lru.clear()


# ---------------------------------------------------------------------------
# cache plumbing (pure jnp; traced inside the engine's compiled programs)
# ---------------------------------------------------------------------------


def _block_page_write(cache, scales, layer, kv, row, cached_len, true_len,
                      int8, page_size):
    """Write a prompt tail kv [1, TB, Hkv, D] (positions cached_len ...
    cached_len + TB - 1) into the pages ``row[cached_len//P + j]``.
    Pages holding padding only (entirely >= true_len) are redirected to
    the trash page so a padded tail bucket can never scribble past the
    request's allocation."""
    x = kv[0]  # [TB, Hkv, D]
    tb, hkv, d = x.shape
    p = page_size
    nb = -(-tb // p)
    if nb * p != tb:
        x = jnp.pad(x, ((0, nb * p - tb), (0, 0), (0, 0)))
    blk = jnp.swapaxes(x.reshape(nb, p, hkv, d), 1, 2)  # [nb, Hkv, P, D]
    mp = row.shape[0]
    g = cached_len // p + jnp.arange(nb)
    need = (true_len + p - 1) // p  # pages with any real prompt content
    idx = jnp.where(g < need, row[jnp.minimum(g, mp - 1)], TRASH_PAGE)
    if int8:
        q, scale = quantize_absmax(blk, axis=-1)  # scale [nb, Hkv, P, 1]
        cache = cache.at[layer, idx].set(q.astype(cache.dtype))
        scales = scales.at[layer, idx].set(scale[..., 0])
        return cache, scales
    cache = cache.at[layer, idx].set(blk.astype(cache.dtype))
    return cache, scales


def _token_page_write(cache, scales, layer, kv, tables, positions, int8,
                      page_size):
    """Write kv [S, T, Hkv, D] at absolute positions [S, T] through the
    page tables [S, MP] (decode T=1, verify T=k+1). Inactive slots carry
    zeroed table rows, so their writes land on the trash page."""
    pg = jnp.take_along_axis(tables, positions // page_size, axis=1)
    off = positions % page_size
    if int8:
        q, scale = quantize_absmax(kv, axis=-1)  # scale [S, T, Hkv, 1]
        cache = cache.at[layer, pg, :, off, :].set(q.astype(cache.dtype))
        scales = scales.at[layer, pg, :, off].set(scale[..., 0])
        return cache, scales
    cache = cache.at[layer, pg, :, off, :].set(kv.astype(cache.dtype))
    return cache, scales


def _layer_kv(cache, scales, layer, int8):
    """One layer's [N, Hkv, P, D] pool view, dequantized when int8."""
    lay = cache[layer]
    if int8:
        return dequantize_absmax(lay, scales[layer][..., None])
    return lay


def _pin_pool_shardings(kc, vc, ksc, vsc):
    """Trailing constraints pinning the RETURNED pools to the kv-head-
    sharded layout the engine committed them with, so the compiled
    program's output shardings match its input shardings and the
    cache-carry loop never flaps between layouts (a flap would recompile,
    breaking the buckets_used + 2 program-count gate). No-op without an
    active mp mesh."""
    from ..distributed import mesh as _mesh

    m = _mesh.get_global_mesh()
    if m is None or m.empty or _mesh.mesh_axis_size("mp", m) <= 1:
        return kc, vc, ksc, vsc
    kv = _mesh.P(None, None, "mp")  # [L, N, Hkv, ...]: shard kv heads
    kc = _mesh.sharding_constraint(kc, kv, m)
    vc = _mesh.sharding_constraint(vc, kv, m)
    if ksc is not None:
        ksc = _mesh.sharding_constraint(ksc, kv, m)
        vsc = _mesh.sharding_constraint(vsc, kv, m)
    return kc, vc, ksc, vsc


def _shard_kv_heads(kv):
    """Constraint hint sharding a fresh K/V projection [..., Hkv, D] over
    the mp axis on its head dim (axis -2), so the page-pool scatter that
    follows stays shard-local instead of gathering the pool. No-op
    without an active mp mesh or when mp doesn't divide Hkv."""
    from ..distributed import mesh as _mesh

    m = _mesh.get_global_mesh()
    if m is None or m.empty or _mesh.mesh_axis_size("mp", m) <= 1:
        return kv
    spec = [None] * kv.ndim
    spec[-2] = "mp"
    return _mesh.sharding_constraint(kv, _mesh.P(*spec), m)


def _replicate_out(x):
    """Constraint hint forcing a program output replicated (sampled
    tokens, logits) so the one-int32-per-slot host transfer reads the
    same bits on every shard. No-op without an active mesh."""
    from ..distributed import mesh as _mesh

    m = _mesh.get_global_mesh()
    if m is None or m.empty or _mesh.mesh_axis_size("mp", m) <= 1:
        return x
    return _mesh.sharding_constraint(x, _mesh.P(), m)


def _sample_tokens(logits, keys, temperature, top_k, top_p, greedy,
                   exact_argmax=None):
    """On-device sampling for N rows: logits [N, V] f32, keys [N, ks],
    temperature/top_p f32 [N], top_k i32 [N], greedy bool [N]. Per-row
    keys keep every request's sample stream independent of co-scheduling.
    top_k <= 0 and top_p >= 1.0 disable their filters. ``exact_argmax``
    [N] i32, when given, replaces the local argmax for greedy rows — the
    quantized logit wire passes the verify exchange's exact winner here
    so greedy output never sees quantization (docs/SERVING.md §5)."""
    v = logits.shape[-1]
    x = logits / temperature[:, None]
    sorted_x = jnp.sort(x, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        sorted_x, (jnp.clip(top_k, 1, v) - 1)[:, None], axis=-1)
    x = jnp.where((top_k[:, None] > 0) & (x < kth), -jnp.inf, x)
    probs = jax.nn.softmax(x, axis=-1)
    sp = jnp.sort(probs, axis=-1)[:, ::-1]
    keep = (jnp.cumsum(sp, axis=-1) - sp) < top_p[:, None]
    thr = jnp.min(jnp.where(keep, sp, jnp.inf), axis=-1, keepdims=True)
    x = jnp.where((top_p[:, None] < 1.0) & (probs < thr), -jnp.inf, x)
    sampled = jax.vmap(lambda xr, kr: jax.random.categorical(kr, xr))(x, keys)
    arg = (jnp.argmax(logits, axis=-1) if exact_argmax is None
           else exact_argmax)
    return jnp.where(greedy, arg, sampled).astype(jnp.int32)


class DecodeEngine:
    """Continuous-batching serving engine over a decoder-only LM.

    Usage::

        eng = DecodeEngine(model, num_slots=8, max_length=512,
                           speculate_k=4)
        rid = eng.submit(prompt_ids, max_new_tokens=64, eos_token_id=2)
        eng.run()                     # or step() from your own loop
        out = eng.result(rid)         # np.ndarray prompt + generated

    or the batch front end ``eng.generate_batch(ids, ...)`` which
    ``text.generation.generate`` rides on.
    """

    def __init__(self, model, config: Optional[EngineConfig] = None,
                 **overrides):
        self.config = config or EngineConfig(**overrides)
        cfg = self.config
        if cfg.kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {KV_DTYPES}, got {cfg.kv_dtype!r}")
        if cfg.page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {cfg.page_size}")
        self.model = model
        model.eval()
        self.adapter = model.decode_adapter()
        ad = self.adapter
        if cfg.max_length > ad.max_positions:
            raise ValueError(
                f"max_length={cfg.max_length} exceeds the model's "
                f"max_positions={ad.max_positions}")
        if cfg.speculate_k and not getattr(ad, "multi_token_positions",
                                           False):
            raise ValueError(
                "speculate_k > 0 needs an adapter accepting [S, T] "
                "positions (multi_token_positions=True)")
        self.buckets = cfg.resolved_buckets()
        self._int8 = cfg.kv_dtype == "int8"
        store = {"f32": jnp.float32, "bf16": jnp.bfloat16,
                 "int8": jnp.int8}[cfg.kv_dtype]
        self._mp = cfg.max_pages
        self._num_pages = cfg.resolved_num_pages()
        self._mesh = cfg.mesh
        self._mp_degree = 1
        if self._mesh is not None:
            from ..distributed.mesh import mesh_axis_size

            self._mp_degree = mesh_axis_size("mp", self._mesh)
            if (self._mp_degree > 1
                    and ad.num_kv_heads % self._mp_degree != 0):
                raise ValueError(
                    f"mp={self._mp_degree} must divide num_kv_heads="
                    f"{ad.num_kv_heads}: the KV pool shards by whole kv "
                    "heads (GQA groups stay intact per shard)")
        # resolve the logit-recombination wire (docs/SERVING.md §5): the
        # explicit config wins; None inherits the ambient mp_comm
        # activation wire. f32 keeps the exact all-gather byte-for-byte.
        from ..distributed import mp_comm as _mp_comm

        lw, self._logit_verify = cfg.logit_wire, True
        if lw is None:
            wcfg = _mp_comm.resolve_config()
            lw = wcfg.wire_dtype if wcfg.quantized else "f32"
            self._logit_verify = wcfg.logit_verify
        elif lw in ("off", "f32"):
            lw = "f32"
        elif lw not in ("bf16", "int8"):
            raise ValueError(
                f"logit_wire must be one of (None, 'off', 'f32', 'bf16', "
                f"'int8'), got {cfg.logit_wire!r}")
        if self._mp_degree <= 1:
            lw = "f32"
        self._logit_wire = lw
        # resolve the paged-attention kernel once — it shapes every
        # compiled program (and so belongs in the AOT cache key). The
        # fused Pallas kernel cannot express the mp GSPMD sharding, so a
        # sharded pool falls back to the einsum oracle and says so.
        self._attn_kernel = F.resolve_attn_kernel(cfg.attn_kernel)
        if self._attn_kernel == "pallas":
            from ..ops.pallas import paged_attention as _pa_kernel

            if self._mp_degree > 1 or not _pa_kernel.available():
                self._attn_kernel = "einsum"
                _obs.inc("attn_kernel_fallback_total")
        _obs.set_gauge("attn_kernel_active",
                       1.0 if self._attn_kernel == "pallas" else 0.0)
        # einsum + int8 materializes both dequantized [N, Hkv, P, D] f32
        # pools per layer per step; the fused path never does — account
        # the avoided traffic per decode/verify step
        self._fused_dequant_bytes_step = (
            2 * ad.num_layers * self._num_pages * ad.num_kv_heads
            * cfg.page_size * ad.head_dim * 4
            if self._attn_kernel == "pallas" and self._int8 else 0)
        try:  # price the choice in the auto-planner's cost model
            from ..distributed.auto_parallel.planner import plan_attn_kernel

            plan_attn_kernel(
                num_slots=cfg.num_slots, max_pages=self._mp,
                kv_heads=ad.num_kv_heads, query_heads=ad.num_heads,
                page_size=cfg.page_size, head_dim=ad.head_dim,
                layers=ad.num_layers, kv_dtype=cfg.kv_dtype,
                selected=self._attn_kernel)
        except Exception:  # noqa: BLE001 — pricing never gates serving
            pass
        shape = (ad.num_layers, self._num_pages, ad.num_kv_heads,
                 cfg.page_size, ad.head_dim)
        self._kc = jnp.zeros(shape, store)
        self._vc = jnp.zeros(shape, store)
        if self._int8:
            self._ksc = jnp.ones(shape[:-1], jnp.float32)
            self._vsc = jnp.ones(shape[:-1], jnp.float32)
        else:
            self._ksc = self._vsc = None
        if self._mesh is not None:
            # commit the pools kv-head-sharded and the model state
            # replicated ONCE — per-call device_put of the weights would
            # re-replicate them every step
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as _P

            kv_sh = NamedSharding(self._mesh, _P(None, None, "mp"))
            rep = NamedSharding(self._mesh, _P())
            self._kc = jax.device_put(self._kc, kv_sh)
            self._vc = jax.device_put(self._vc, kv_sh)
            if self._int8:
                self._ksc = jax.device_put(self._ksc, kv_sh)
                self._vsc = jax.device_put(self._vsc, kv_sh)
            self._replicated_sharding = rep
        self.pool = PagePool(self._num_pages)
        cap = (cfg.prefix_registry_blocks
               if cfg.prefix_registry_blocks is not None
               else self._num_pages)
        self.registry = (PrefixRegistry(self.pool, cap)
                         if cfg.prefix_cache else None)
        #: per-slot page tables, uploaded to every decode/verify step;
        #: freed slots are zeroed so their writes/gathers hit trash
        self._tables = np.zeros((cfg.num_slots, self._mp), np.int32)
        # stable state ordering for the compiled-call state swap (the
        # TracedLayer idiom): dedup'd params first, then buffers. Names
        # ride along (first name wins on dedup) so the online weight
        # plane can address leaves by name over the wire.
        self._state, self._state_names, seen = [], [], set()
        for name, p in model.named_parameters():
            if id(p) not in seen:
                seen.add(id(p))
                self._state.append(p)
                self._state_names.append(name)
        for name, b in model.named_buffers():
            if id(b) not in seen:
                seen.add(id(b))
                self._state.append(b)
                self._state_names.append(name)
        self._state_index = {n: i for i, n in enumerate(self._state_names)}
        #: versioned weight-epoch plane (serving/online.py): the live
        #: epoch, value snapshots pinned for in-flight old-epoch
        #: requests, and the double-buffered shadow set an in-progress
        #: wt stream stages into
        self._epoch = 0
        self._epoch_vals: Dict[int, List] = {}
        self._shadow: Optional[dict] = None
        if self._mesh is not None:
            for t in self._state:
                t._value = jax.device_put(t._value,
                                          self._replicated_sharding)
        donate = cfg.donate
        if donate is None:
            donate = jax.default_backend() in ("tpu", "gpu")
        self._donate = bool(donate)
        self._prefill_jit: Dict[int, object] = {}
        self._decode_jit = None
        self._verify_jit = None
        self._compiled = set()
        self._aot: Dict[str, object] = {}  # persistent-cache Compiled objects
        self.aot_cache_hits = 0
        self.compile_count = 0
        self.total_tokens = 0
        self.decode_steps = 0
        self.verify_steps = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self._t_decode_ema = None
        self._t_verify_ema = None
        self._tok_verify_ema = None
        self._steps_since_probe = 0
        self.prefix_hit_tokens = 0
        self.peak_pages_in_use = 0
        self.peak_running = 0
        self.admission_waits = 0
        self.admission_wait_s = 0.0
        #: untagged prompt tokens prefilled on THIS engine (the
        #: independent integer the per-tenant ledger reconciles against)
        self.prompt_tokens_total = 0
        #: per-tenant metering (observability/accounting.py), created
        #: lazily on the first submit with accounting enabled; the hot
        #: paths pay one None check when it is off
        self._acct = None
        self._pg_meter = None
        self._backoff_s = 0.0
        self._base_key = jax.random.PRNGKey(cfg.seed)
        self._zero_key = np.asarray(self._base_key)
        self._waiting: deque = deque()
        self._running: Dict[int, Request] = {}
        self._free = list(range(cfg.num_slots))[::-1]  # pop() -> slot 0
        self._requests: Dict[int, Request] = {}
        self._next_id = 0

    # -- scheduler ----------------------------------------------------------

    def accounting_ledger(self, create: bool = False):
        """This engine's per-tenant metering ledger (accounting.py), or
        None while accounting is disabled. ``create=True`` instantiates
        it when accounting is enabled (one env lookup — the µs-scale
        disabled-path contract)."""
        if self._acct is None and create:
            from ..observability import accounting as _acct

            if _acct.enabled():
                self._acct = _acct.TenantLedger()
                self._pg_meter = _acct.PageSecondsMeter(self._acct)
        return self._acct

    def _acct_tick(self, now: float):
        """Charge KV page occupancy since the last tick to the running
        set, shared pages split pro rata (accounting.PageSecondsMeter)."""
        self._pg_meter.tick(now, self._running.values(),
                            self.pool.refcount,
                            self._num_pages - 1 - self.pool.available())

    def _acct_wire_bytes(self, active, vocab: int, rows_per_slot: int):
        """Attribute one step's sharded-decode logit-recombination wire
        bytes per tenant. The compiled program all-gathers every slot's
        logit rows regardless of occupancy, so active requests get their
        rows and the padded remainder lands on the unattributed cell.
        Zero when the engine is not mp-sharded (single-device wire-free
        decode — the bench conservation gate covers this shape too)."""
        if self._mp_degree <= 1:
            return
        from ..observability import accounting as _acct

        itemsize = {"f32": 4, "bf16": 2, "int8": 1}[self._logit_wire]
        row_bytes = vocab * itemsize * rows_per_slot
        for _slot, req in active:
            self._acct.add(req.tenant, req.slo, wire_bytes=row_bytes)
        pad = self.config.num_slots - len(active)
        if pad > 0:
            self._acct.add(_acct.DEFAULT_TENANT, _acct.UNATTRIBUTED_SLO,
                           wire_bytes=row_bytes * pad)

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               *, trace: Optional[dict] = None, tenant: Optional[str] = None,
               slo: Optional[str] = None, **kw) -> int:
        """Queue one request; returns its id. `prompt` is a 1-D int array
        (Tensor/np/list); keyword args build a SamplingParams. ``trace``
        is the router's propagated span context (protocol.py ``trace``
        field): when given, the engine's prefill/decode/verify spans join
        that request tree. ``tenant``/``slo`` label the request for the
        per-tenant cost ledger (absent -> the "-" default)."""
        if params is None:
            params = SamplingParams(**kw)
        ids = np.asarray(raw(prompt), dtype=np.int32).reshape(-1)
        t0 = int(ids.shape[0])
        if t0 < 1:
            raise ValueError("empty prompt")
        if t0 > self.buckets[-1]:
            raise ValueError(
                f"prompt length {t0} exceeds the largest prompt bucket "
                f"{self.buckets[-1]}")
        if t0 + params.max_new_tokens > self.config.max_length:
            raise ValueError(
                f"prompt ({t0}) + max_new_tokens ({params.max_new_tokens}) "
                f"exceeds max_length={self.config.max_length}")
        total_pages = -(-(t0 + params.max_new_tokens)
                        // self.config.page_size)
        if total_pages > self._num_pages - 1:
            raise ValueError(
                f"request needs {total_pages} KV pages but the pool only "
                f"has {self._num_pages - 1}")
        rid = self._next_id
        self._next_id += 1
        if params.seed is not None:
            key = jax.random.PRNGKey(params.seed)
        else:
            key = jax.random.fold_in(self._base_key, rid)
        req = Request(req_id=rid, prompt=ids, params=params,
                      key_np=np.asarray(key),
                      submit_time=time.perf_counter())
        if trace:
            req.trace_id = trace.get("trace_id")
            req.trace_parent = trace.get("parent_id")
            req.resubmitted = int(trace.get("resubmits", 0) or 0) > 0
        if tenant is not None or slo is not None:
            from ..observability import accounting as _acct

            req.tenant = _acct.normalize_tenant(tenant)
            if slo:
                req.slo = str(slo)
        self.accounting_ledger(create=True)
        self._requests[rid] = req
        self._waiting.append(req)
        _obs.inc("serving_requests_total")
        _obs.set_gauge("serving_queue_depth", float(len(self._waiting)))
        return rid

    def step(self) -> bool:
        """Admit waiting requests into free slots (one compiled tail
        prefill each), then advance every occupied slot: ONE compiled
        decode step, or — when speculation is on and a prompt-lookup
        draft exists — ONE compiled verify step emitting up to
        ``speculate_k + 1`` tokens per slot. Returns False when the
        engine is fully idle."""
        self._admit()
        if not self._running:
            if self._waiting:
                self._admission_backoff()
            return bool(self._waiting)
        self._backoff_s = 0.0
        epochs = sorted({r.epoch for r in self._running.values()})
        if len(epochs) > 1:
            # mixed-epoch flip window: one masked decode per epoch group
            # (excluded slots' table rows are zeroed, so their KV writes
            # land on the trash page and their sampled tokens are
            # ignored). Speculation is skipped for the window — verify
            # and decode sample identical position-keyed streams, so
            # forcing plain decode costs throughput, never bits.
            for e in epochs:
                self._step_decode(epoch=e)
            return True
        k = self.config.speculate_k
        if k > 0 and self._spec_worthwhile(k):
            drafts, any_real = self._collect_drafts(k)
            if any_real and self._verify_headroom(k):
                self._step_verify(drafts, k, epoch=epochs[0])
                return True
        self._step_decode(epoch=epochs[0])
        return True

    def _admission_backoff(self):
        """Every waiting request is blocked on free KV pages (or slots
        pinned by an external holder) and no slot is decoding: sleep a
        bounded exponentially-growing backoff instead of hot-spinning —
        run() would otherwise busy-loop _admit at 100% CPU until another
        actor releases pages. Reset the moment any slot runs again."""
        self._backoff_s = min(max(self._backoff_s * 2, 1e-3), 0.05)
        self.admission_waits += 1
        self.admission_wait_s += self._backoff_s
        _obs.observe("serving_admission_wait_seconds", self._backoff_s)
        time.sleep(self._backoff_s)

    def _spec_worthwhile(self, k: int) -> bool:
        """Adaptive gate: speculate when the measured step-time and
        acceptance EMAs predict verify emits more tokens/s than decode
        (always True with spec_adaptive=False). With no verify estimate
        yet — or a stale one — probe."""
        if not self.config.spec_adaptive:
            return True
        if self._t_decode_ema is None:
            return False  # measure the decode baseline first
        if self._t_verify_ema is None:
            return True
        if self._steps_since_probe >= self.config.spec_probe_every:
            return True
        if self._tok_verify_ema is None:
            return True
        # measured tokens/s comparison: one decode step yields exactly 1
        # token per slot; a verify step yields what acceptance actually
        # delivered (fallback-draft slots and budget truncation included)
        return (self._tok_verify_ema * self._t_decode_ema
                > self._t_verify_ema)

    @staticmethod
    def _ema(prev, x, alpha=0.3):
        return x if prev is None else (1 - alpha) * prev + alpha * x

    def _step_decode(self, epoch: Optional[int] = None):
        cfg = self.config
        if self._acct is not None:
            self._acct_tick(time.perf_counter())
        if epoch is None:
            epoch = self._epoch
        active = [(slot, req) for slot, req in self._running.items()
                  if req.epoch == epoch]
        s = cfg.num_slots
        tokens = np.zeros(s, np.int32)
        positions = np.zeros(s, np.int32)
        temp = np.ones(s, np.float32)
        top_k = np.zeros(s, np.int32)
        top_p = np.ones(s, np.float32)
        greedy = np.ones(s, bool)
        keys = np.broadcast_to(self._zero_key, (s,) + self._zero_key.shape)
        keys = np.array(keys)
        for slot, req in active:
            tokens[slot] = req.tokens[-1]
            positions[slot] = len(req.prompt) + len(req.tokens) - 1
            t_, k_, p_, g_ = req.params.fields()
            temp[slot], top_k[slot], top_p[slot], greedy[slot] = t_, k_, p_, g_
            keys[slot] = req.key_np
        tables = self._tables
        excluded = [slot for slot, req in self._running.items()
                    if req.epoch != epoch]
        if excluded:
            # other epoch groups ride along this call as masked slots:
            # zeroed table rows route their KV writes to the trash page,
            # exactly the warmup mechanism — their real pages are
            # untouched and their tokens below are never applied
            tables = self._tables.copy()
            tables[excluded] = 0
        if self._decode_jit is None:
            self._decode_jit = self._build_decode()
        warm = "decode" in self._compiled
        t0 = time.perf_counter()
        out = self._run_counted(
            "decode", self._decode_jit,
            self._state_vals(epoch), self._kc, self._vc, self._ksc,
            self._vsc, jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(tables), jnp.asarray(keys),
            jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
            jnp.asarray(greedy))
        self._kc, self._vc, self._ksc, self._vsc, nxt, logits = out
        nxt_host = np.asarray(nxt)  # the per-token host transfer: [S] int32
        dt = time.perf_counter() - t0
        _obs.observe("serving_decode_step_seconds", dt)
        if self._fused_dequant_bytes_step:
            _obs.inc("attn_kernel_fused_dequant_bytes_total",
                     self._fused_dequant_bytes_step)
        if warm:  # a compile-laden first step would poison the estimate
            self._t_decode_ema = self._ema(self._t_decode_ema, dt)
        self._steps_since_probe += 1
        self.decode_steps += 1
        self._last_logits = logits
        if self._acct is not None:
            self._acct_wire_bytes(active, int(logits.shape[-1]), 1)
        for slot, req in active:
            if req.decode_t0 is None:
                req.decode_t0 = t0  # first batched step this request joined
            req.decode_steps_n += 1
            self.total_tokens += 1
            self._append_token(req, int(nxt_host[slot]))
        _obs.inc("serving_tokens_total", len(active))
        self._update_gauges()

    def _step_verify(self, drafts: Dict[int, np.ndarray], k: int,
                     epoch: Optional[int] = None):
        """One multi-token speculative step: score cur + k drafts in a
        single target pass; accept target tokens while the draft agrees
        (position-keyed streams, so acceptance never changes WHAT is
        sampled — only how many tokens one step emits). Only runs when
        every running slot shares ``epoch`` (step() forces plain decode
        during mixed-epoch flip windows)."""
        cfg = self.config
        if epoch is None:
            epoch = self._epoch
        if self._acct is not None:
            self._acct_tick(time.perf_counter())
        s, k1 = cfg.num_slots, k + 1
        tokens = np.zeros((s, k1), np.int32)
        positions = np.zeros(s, np.int32)
        temp = np.ones(s, np.float32)
        top_k = np.zeros(s, np.int32)
        top_p = np.ones(s, np.float32)
        greedy = np.ones(s, bool)
        keys = np.array(np.broadcast_to(
            self._zero_key, (s,) + self._zero_key.shape))
        for slot, req in self._running.items():
            tokens[slot, 0] = req.tokens[-1]
            tokens[slot, 1:] = drafts[slot]
            positions[slot] = len(req.prompt) + len(req.tokens) - 1
            t_, k_, p_, g_ = req.params.fields()
            temp[slot], top_k[slot], top_p[slot], greedy[slot] = t_, k_, p_, g_
            keys[slot] = req.key_np
        if self._verify_jit is None:
            self._verify_jit = self._build_verify(k1)
        warm = f"verify_k{k}" in self._compiled
        t0 = time.perf_counter()
        out = self._run_counted(
            f"verify_k{k}", self._verify_jit,
            self._state_vals(epoch), self._kc, self._vc, self._ksc,
            self._vsc, jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(self._tables), jnp.asarray(keys),
            jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
            jnp.asarray(greedy))
        self._kc, self._vc, self._ksc, self._vsc, targets, logits = out
        targets_host = np.asarray(targets)  # [S, k+1] int32
        dt = time.perf_counter() - t0
        _obs.observe("serving_decode_step_seconds", dt)
        if self._fused_dequant_bytes_step:
            _obs.inc("attn_kernel_fused_dequant_bytes_total",
                     self._fused_dequant_bytes_step)
        if warm:
            self._t_verify_ema = self._ema(self._t_verify_ema, dt)
        self._steps_since_probe = 0
        self.decode_steps += 1
        self.verify_steps += 1
        self._last_logits = logits
        emitted = 0
        active_slots = len(self._running)
        if self._acct is not None:
            self._acct_wire_bytes(list(self._running.items()),
                                  int(logits.shape[-1]), k1)
        for slot, req in list(self._running.items()):
            tgt = targets_host[slot]
            m = 0
            while m < k and int(drafts[slot][m]) == int(tgt[m]):
                m += 1
            self.spec_proposed += k
            self.spec_accepted += m
            if req.decode_t0 is None:
                req.decode_t0 = t0
            req.decode_steps_n += 1
            req.verify_steps_n += 1
            req.spec_accepted_n += m
            for tok in tgt[:m + 1]:
                if req.status != "running":
                    break  # budget/eos hit mid-emission
                self.total_tokens += 1
                emitted += 1
                self._append_token(req, int(tok))
        if active_slots:
            self._tok_verify_ema = self._ema(
                self._tok_verify_ema, emitted / active_slots)
        _obs.inc("serving_tokens_total", emitted)
        _obs.set_gauge("serving_spec_accept_ratio",
                       self.spec_accepted / max(self.spec_proposed, 1))
        self._update_gauges()

    def _collect_drafts(self, k: int):
        """Prompt-lookup drafts per running slot; slots with no n-gram
        recurrence fall back to repeating their last token (still a
        legitimate draft — acceptance decides)."""
        from ..text.generation import prompt_lookup_draft

        drafts: Dict[int, np.ndarray] = {}
        any_real = False
        for slot, req in self._running.items():
            ctx = np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)])
            d = prompt_lookup_draft(ctx, k, max_ngram=self.config.ngram)
            if d is not None:
                any_real = True
            else:
                d = np.full(k, req.tokens[-1], np.int32)
            drafts[slot] = d
        return drafts, any_real

    def _verify_headroom(self, k: int) -> bool:
        """The verify step writes KV at positions p .. p+k; require them
        all inside the cache for every running slot (else this round
        falls back to the single-token decode program)."""
        limit = self.config.max_length - 1
        return all(
            len(r.prompt) + len(r.tokens) - 1 + k <= limit
            for r in self._running.values())

    def run(self) -> Dict[int, np.ndarray]:
        """Drive step() until every submitted request finished; returns
        {req_id: prompt + generated} for requests completed in this
        drain."""
        t0 = time.perf_counter()
        before = self.total_tokens
        finished = [r.req_id for r in self._requests.values()
                    if r.status == "done"]
        seen_done = set(finished)
        while self._waiting or self._running:
            self.step()
        emitted = self.total_tokens - before
        dt = max(time.perf_counter() - t0, 1e-9)
        if emitted:
            _obs.set_gauge("serving_tokens_per_second", emitted / dt)
        return {rid: self.result(rid) for rid, r in self._requests.items()
                if r.status == "done" and rid not in seen_done}

    def result(self, rid: int) -> np.ndarray:
        req = self._requests[rid]
        if req.status != "done":
            raise RuntimeError(f"request {rid} is {req.status}, not done")
        return np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])

    def generate_batch(self, input_ids, max_new_tokens: int = 32,
                       do_sample: bool = False, top_k: int = 0,
                       top_p: float = 1.0, temperature: float = 1.0,
                       eos_token_id=None, pad_token_id=None, seed=None):
        """Batch front end with text.generation.generate semantics: every
        row becomes a request, rows that finish early are padded with
        pad_token_id (else eos, else 0). Returns a Tensor [B, T0 + n]."""
        ids = np.asarray(raw(input_ids))
        b, t0 = ids.shape
        rids = [
            self.submit(ids[i], SamplingParams(
                max_new_tokens=max_new_tokens, do_sample=do_sample,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_token_id=eos_token_id,
                seed=None if seed is None else seed * 1000003 + i))
            for i in range(b)
        ]
        self.run()
        reqs = [self._requests[r] for r in rids]
        width = max(len(r.tokens) for r in reqs)
        filler = pad_token_id if pad_token_id is not None else (
            eos_token_id if eos_token_id is not None else 0)
        out = np.full((b, t0 + width), filler, dtype=ids.dtype)
        out[:, :t0] = ids
        for i, r in enumerate(reqs):
            out[i, t0:t0 + len(r.tokens)] = r.tokens
        return Tensor(jnp.asarray(out))

    def release_prefix_cache(self):
        """Drop every registry reference (running requests keep theirs);
        afterwards a drained engine holds zero pages."""
        if self.registry is not None:
            self.registry.clear()
        self._update_gauges()

    def warmup(self) -> dict:
        """Pre-build every compiled program before traffic arrives: one
        prefill per prompt bucket, the single-token decode, and (when
        ``speculate_k > 0``) the verify program. Synthetic inputs use
        all-zero page tables, so every KV write lands on the inert trash
        page 0 — pool, scheduler, and prefix registry are untouched. With
        ``PADDLE_TPU_COMPILE_CACHE`` set, each build is served from the
        persistent AOT cache when fingerprints match; ``cache_hits`` in
        the returned dict counts those.

        Idempotent: programs already compiled by THIS engine (a prior
        warmup, or live traffic) are skipped and counted as cache hits
        instead of re-executed — so a warmup after a weight flip is a
        cheap no-op rather than a second full sweep."""
        cfg = self.config
        s = cfg.num_slots
        hits0, n0 = self.aot_cache_hits, self.compile_count
        row = np.zeros(self._mp, np.int32)
        for tb in self.buckets:
            if f"prefill_b{tb}" in self._compiled:
                self.aot_cache_hits += 1
                continue
            fn = self._prefill_jit.get(tb)
            if fn is None:
                fn = self._build_prefill(tb)
                self._prefill_jit[tb] = fn
            ids = np.full((1, tb), 1, np.int32)
            out = self._run_counted(
                f"prefill_b{tb}", fn,
                self._state_vals(), self._kc, self._vc, self._ksc,
                self._vsc, jnp.asarray(ids), np.int32(0), np.int32(tb),
                jnp.asarray(row), jnp.asarray(self._zero_key),
                np.float32(1.0), np.int32(0), np.float32(1.0),
                np.asarray(True))
            self._kc, self._vc, self._ksc, self._vsc = out[:4]
        positions = np.zeros(s, np.int32)
        temp = np.ones(s, np.float32)
        top_k = np.zeros(s, np.int32)
        top_p = np.ones(s, np.float32)
        greedy = np.ones(s, bool)
        keys = np.array(np.broadcast_to(
            self._zero_key, (s,) + self._zero_key.shape))
        if "decode" in self._compiled:
            self.aot_cache_hits += 1
        else:
            if self._decode_jit is None:
                self._decode_jit = self._build_decode()
            out = self._run_counted(
                "decode", self._decode_jit,
                self._state_vals(), self._kc, self._vc, self._ksc,
                self._vsc, jnp.asarray(np.zeros(s, np.int32)),
                jnp.asarray(positions),
                jnp.asarray(self._tables), jnp.asarray(keys),
                jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
                jnp.asarray(greedy))
            self._kc, self._vc, self._ksc, self._vsc = out[:4]
        verify = False
        k = cfg.speculate_k
        if k > 0:
            verify = True
            if f"verify_k{k}" in self._compiled:
                self.aot_cache_hits += 1
            else:
                if self._verify_jit is None:
                    self._verify_jit = self._build_verify(k + 1)
                out = self._run_counted(
                    f"verify_k{k}", self._verify_jit,
                    self._state_vals(), self._kc, self._vc, self._ksc,
                    self._vsc, jnp.asarray(np.zeros((s, k + 1), np.int32)),
                    jnp.asarray(positions), jnp.asarray(self._tables),
                    jnp.asarray(keys), jnp.asarray(temp),
                    jnp.asarray(top_k), jnp.asarray(top_p),
                    jnp.asarray(greedy))
                self._kc, self._vc, self._ksc, self._vsc = out[:4]
        return {"buckets": len(self.buckets), "decode": True,
                "verify": verify,
                "programs": self.compile_count - n0,
                "cache_hits": self.aot_cache_hits - hits0}

    def stats(self) -> dict:
        return {
            "compile_count": self.compile_count,
            "compile_cache_hits": self.aot_cache_hits,
            "compiled": sorted(self._compiled),
            "buckets": list(self.buckets),
            "decode_steps": self.decode_steps,
            "verify_steps": self.verify_steps,
            "total_tokens": self.total_tokens,
            "prompt_tokens_total": self.prompt_tokens_total,
            "running": len(self._running),
            "waiting": len(self._waiting),
            "page_size": self.config.page_size,
            "num_pages": self._num_pages,
            "pages_free": self.pool.available(),
            "pages_shared": self.pool.shared_pages(),
            "peak_pages_in_use": self.peak_pages_in_use,
            "peak_running": self.peak_running,
            "prefix_blocks_registered": (
                len(self.registry) if self.registry is not None else 0),
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "spec_proposed": self.spec_proposed,
            "spec_accepted": self.spec_accepted,
            "admission_waits": self.admission_waits,
            "admission_wait_s": self.admission_wait_s,
            "attn_kernel": self._attn_kernel,
            "weight_epoch": int(self._epoch),
            "pinned_epochs": sorted(self._epoch_vals),
        }

    def occupancy(self) -> dict:
        """Scheduler-load snapshot for the serving router: the numbers
        serving/worker.py publishes to the coordination store each poll
        (least-outstanding-tokens dispatch reads outstanding_tokens;
        slots_free/pages_free gate admission-side throttling)."""
        outstanding = sum(r.params.max_new_tokens - len(r.tokens)
                          for r in self._running.values())
        outstanding += sum(len(r.prompt) + r.params.max_new_tokens
                           for r in self._waiting)
        return {
            "outstanding_tokens": int(outstanding),
            "running": len(self._running),
            "waiting": len(self._waiting),
            "slots_free": len(self._free),
            "pages_free": self.pool.available(),
            "prefix_hit_tokens": int(self.prefix_hit_tokens),
            "decode_steps": int(self.decode_steps),
            "total_tokens": int(self.total_tokens),
            "compile_cache_hits": int(self.aot_cache_hits),
            "weight_epoch": int(self._epoch),
        }

    # -- disaggregated prefill: KV-page export / import ---------------------

    def prefill_export(self, prompt, params: Optional[SamplingParams] = None,
                       *, trace: Optional[dict] = None,
                       tenant: Optional[str] = None,
                       slo: Optional[str] = None, **kw):
        """Run one prompt's prefill HERE and hand its KV pages to a decode
        engine (disaggregated serving; serving/worker.py streams the
        result over transport.encode_kv).

        Only the ``ceil(t0 / page_size)`` content pages are exported — the
        decode side allocates its own generation pages — and the slabs are
        bit-equal to what a local prefill leaves in this pool (padding
        rows past ``true_len`` included), so a raw-wire import decodes
        bit-equal to a unified engine. Sampled streams additionally need
        an explicit ``params.seed`` (the router always sets one); without
        it the two engines derive different request keys and only greedy
        output matches.

        Returns ``None`` when no slot (or pages) are free right now — the
        caller retries next poll; ``{"done": prompt+tokens}`` when the
        request finished at prefill (1-token budget / instant EOS); else
        ``{"first_token", "true_len", "prefill_s", "pool_dtype", "k", "v"
        [, "ks", "vs"]}`` with k/v ``[L, n_pages, Hkv, P, D]`` host arrays
        (plus the int8 scale slabs when this pool is int8). Raises
        ValueError on the same bad-request conditions as ``submit``.
        """
        if params is None:
            params = SamplingParams(**kw)
        ids = np.asarray(raw(prompt), dtype=np.int32).reshape(-1)
        t0 = int(ids.shape[0])
        if t0 < 1:
            raise ValueError("empty prompt")
        if t0 > self.buckets[-1]:
            raise ValueError(
                f"prompt length {t0} exceeds the largest prompt bucket "
                f"{self.buckets[-1]}")
        if t0 + params.max_new_tokens > self.config.max_length:
            raise ValueError(
                f"prompt ({t0}) + max_new_tokens ({params.max_new_tokens}) "
                f"exceeds max_length={self.config.max_length}")
        p = self.config.page_size
        content_pages = -(-t0 // p)
        if content_pages > self._num_pages - 1:
            raise ValueError(
                f"prompt needs {content_pages} KV pages but the pool only "
                f"has {self._num_pages - 1}")
        if not self._free:
            return None
        slot = self._free[-1]
        keys: List[bytes] = []
        shared: List[int] = []
        if self.registry is not None:
            keys = PrefixRegistry.block_keys(ids, p)
            shareable = min(len(keys), (t0 - 1) // p)
            shared = self.registry.lookup_chain(keys[:shareable])
        need = content_pages - len(shared)
        if self.pool.available() < need and self.registry is not None:
            self.registry.evict_unused(need - self.pool.available())
        pages = self.pool.alloc(need)
        if pages is None:
            for pg in shared:
                self.pool.decref(pg)
            return None
        rid = self._next_id
        self._next_id += 1
        if params.seed is not None:
            key = jax.random.PRNGKey(params.seed)
        else:
            key = jax.random.fold_in(self._base_key, rid)
        cached_len = len(shared) * p
        row = np.zeros(self._mp, np.int32)
        row[:len(shared)] = shared
        row[len(shared):content_pages] = pages
        self._tables[slot] = row
        req = Request(req_id=rid, prompt=ids, params=params,
                      key_np=np.asarray(key),
                      submit_time=time.perf_counter())
        if trace:
            req.trace_id = trace.get("trace_id")
            req.trace_parent = trace.get("parent_id")
            req.resubmitted = int(trace.get("resubmits", 0) or 0) > 0
        if tenant is not None or slo is not None:
            from ..observability import accounting as _acct

            req.tenant = _acct.normalize_tenant(tenant)
            if slo:
                req.slo = str(slo)
        self.accounting_ledger(create=True)
        req.page_ids = shared + pages
        req.cached_len = cached_len
        self.prefix_hit_tokens += cached_len
        if cached_len:
            _obs.inc("serving_prefix_hit_tokens", cached_len)
        if self.registry is not None:
            for j in range(len(shared), t0 // p):
                self.registry.register(keys[j], int(row[j]))
        self._requests[rid] = req
        self._prefill(req, slot, row, cached_len)
        self._free.pop()  # _finish may have re-appended it; net correct
        if req.status == "done":
            return {"done": self.result(rid)}
        idx = jnp.asarray(row[:content_pages])
        out = {
            "first_token": int(req.tokens[0]),
            "true_len": t0,
            "prefill_s": float(req.prefill_s),
            "pool_dtype": self.config.kv_dtype,
            "k": np.asarray(jnp.take(self._kc, idx, axis=1)),
            "v": np.asarray(jnp.take(self._vc, idx, axis=1)),
        }
        if self._int8:
            out["ks"] = np.asarray(jnp.take(self._ksc, idx, axis=1))
            out["vs"] = np.asarray(jnp.take(self._vsc, idx, axis=1))
        if req.tenant != "-":
            # label the handoff so the decode engine's ledger keys match
            # (absent tenant adds zero wire bytes, like the trace dict)
            out["tenant"] = req.tenant
            out["slo"] = req.slo
        if self._acct is not None:
            # the prefill engine's half of the request: prompt + first
            # token here, KV-stream wire bytes to the decode engine; the
            # occupancy tail is charged while the pages are still held
            self._acct_tick(time.perf_counter())
            self._acct.add(
                req.tenant, req.slo, prefill_tokens=int(t0),
                decode_tokens=1,
                queue_seconds=max(req.prefill_t0 - req.submit_time, 0.0),
                wire_bytes=sum(int(out[kk].nbytes)
                               for kk in ("k", "v", "ks", "vs")
                               if kk in out))
        # detach: the decode engine owns the request from its first token
        # on. The registry's +1 refs keep this prompt's full blocks
        # resident for future prefix hits; the request's own refs drop.
        del self._running[slot]
        self._tables[slot] = 0
        self._free.append(slot)
        req.slot = -1
        for page in req.page_ids:
            self.pool.decref(page)
        req.page_ids = []
        req.status = "done"
        self._update_gauges()
        return out

    def try_import_prefill(self, prompt, params: SamplingParams, kv: dict,
                           *, trace: Optional[dict] = None,
                           tenant: Optional[str] = None,
                           slo: Optional[str] = None) -> Optional[int]:
        """Adopt a prefill computed on ANOTHER engine: write its exported
        content pages into this pool and seat the request directly in
        decode (no local prefill program runs). `kv` is a
        ``prefill_export`` payload (after any wire codec round trip).

        With a raw wire and matching ``kv_dtype`` the imported pages are
        bit-identical to a local prefill, so greedy decode matches a
        unified engine exactly; an int8 wire over a float pool dequantizes
        on import (trajectory-tolerance territory). Returns the new
        request id, or ``None`` when no slot or pages are free right now
        (the caller retries next poll). Raises ValueError on bad requests
        or a prompt/payload length mismatch.
        """
        ids = np.asarray(raw(prompt), dtype=np.int32).reshape(-1)
        t0 = int(ids.shape[0])
        if t0 < 1:
            raise ValueError("empty prompt")
        if int(kv["true_len"]) != t0:
            raise ValueError(
                f"KV payload prefilled {int(kv['true_len'])} tokens but the "
                f"prompt has {t0}")
        if t0 + params.max_new_tokens > self.config.max_length:
            raise ValueError(
                f"prompt ({t0}) + max_new_tokens ({params.max_new_tokens}) "
                f"exceeds max_length={self.config.max_length}")
        p = self.config.page_size
        total_pages = -(-(t0 + params.max_new_tokens) // p)
        if total_pages > self._num_pages - 1:
            raise ValueError(
                f"request needs {total_pages} KV pages but the pool only "
                f"has {self._num_pages - 1}")
        if not self._free:
            return None
        if self.pool.available() < total_pages and self.registry is not None:
            self.registry.evict_unused(total_pages - self.pool.available())
        pages = self.pool.alloc(total_pages)
        if pages is None:
            return None
        slot = self._free.pop()
        content_pages = -(-t0 // p)
        row = np.zeros(self._mp, np.int32)
        row[:total_pages] = pages
        self._tables[slot] = row
        idx = jnp.asarray(np.asarray(pages[:content_pages], np.int32))
        k_in, v_in = kv["k"], kv["v"]
        if self._int8 and "ks" in kv:
            # int8 source pool -> int8 pool: copy the quantized slabs and
            # their scales verbatim (bit-equal)
            self._kc = self._kc.at[:, idx].set(
                jnp.asarray(k_in, self._kc.dtype))
            self._vc = self._vc.at[:, idx].set(
                jnp.asarray(v_in, self._vc.dtype))
            self._ksc = self._ksc.at[:, idx].set(
                jnp.asarray(kv["ks"], jnp.float32))
            self._vsc = self._vsc.at[:, idx].set(
                jnp.asarray(kv["vs"], jnp.float32))
        elif self._int8:
            # float payload into an int8 pool: requantize at the same
            # per-[page, head, token] granularity _block_page_write uses
            qk, sk = quantize_absmax(jnp.asarray(k_in, jnp.float32), axis=-1)
            qv, sv = quantize_absmax(jnp.asarray(v_in, jnp.float32), axis=-1)
            self._kc = self._kc.at[:, idx].set(qk.astype(self._kc.dtype))
            self._vc = self._vc.at[:, idx].set(qv.astype(self._vc.dtype))
            self._ksc = self._ksc.at[:, idx].set(sk[..., 0])
            self._vsc = self._vsc.at[:, idx].set(sv[..., 0])
        else:
            if "ks" in kv:  # int8 source pool -> float pool
                k_in = dequantize_absmax(
                    jnp.asarray(k_in), jnp.asarray(kv["ks"])[..., None])
                v_in = dequantize_absmax(
                    jnp.asarray(v_in), jnp.asarray(kv["vs"])[..., None])
            self._kc = self._kc.at[:, idx].set(
                jnp.asarray(k_in, self._kc.dtype))
            self._vc = self._vc.at[:, idx].set(
                jnp.asarray(v_in, self._vc.dtype))
        rid = self._next_id
        self._next_id += 1
        if params.seed is not None:
            key = jax.random.PRNGKey(params.seed)
        else:
            key = jax.random.fold_in(self._base_key, rid)
        now = time.perf_counter()
        req = Request(req_id=rid, prompt=ids, params=params,
                      key_np=np.asarray(key), submit_time=now,
                      status="running", slot=slot, epoch=self._epoch)
        req.page_ids = list(pages)
        req.prefill_t0 = now
        req.prefill_s = float(kv.get("prefill_s", 0.0))
        req.first_token_time = now
        if trace:
            req.trace_id = trace.get("trace_id")
            req.trace_parent = trace.get("parent_id")
            req.resubmitted = int(trace.get("resubmits", 0) or 0) > 0
        req.imported = True
        tenant = tenant if tenant is not None else kv.get("tenant")
        slo = slo if slo is not None else kv.get("slo")
        if tenant is not None or slo is not None:
            from ..observability import accounting as _acct

            req.tenant = _acct.normalize_tenant(tenant)
            if slo:
                req.slo = str(slo)
        self.accounting_ledger(create=True)
        if self.registry is not None:
            keys = PrefixRegistry.block_keys(ids, p)
            for j in range(t0 // p):
                self.registry.register(keys[j], int(row[j]))
        self._requests[rid] = req
        self._running[slot] = req
        _obs.inc("serving_requests_total")
        # the first token was sampled (and counted) on the prefill engine;
        # _append_token handles the instant-EOS / 1-token budget edge
        self._append_token(req, int(kv["first_token"]))
        self._update_gauges()
        return rid

    # -- internals ----------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"no prompt bucket holds length {n}")

    def _state_vals(self, epoch: Optional[int] = None):
        """Weight/buffer value list for one compiled call. ``epoch``
        selects a pinned old-epoch snapshot during a mixed-epoch flip
        window; None (or the live epoch) reads the live tensors. The
        value list is jit argument #0 and excluded from the AOT cache
        key, which is exactly why an epoch flip never recompiles."""
        if epoch is None or epoch == self._epoch:
            return [t._value for t in self._state]
        return list(self._epoch_vals[epoch])

    # -- versioned weight epochs (serving/online.py) ------------------------

    @property
    def weight_epoch(self) -> int:
        """The epoch new admissions are pinned to."""
        return self._epoch

    def state_keys(self) -> List[str]:
        """Leaf names in compiled-call state order (dedup'd params then
        buffers; first name wins) — the wt-stream address space."""
        return list(self._state_names)

    def begin_weight_epoch(self, epoch: int) -> bool:
        """Open the shadow param set for ``epoch``: a copy-on-stage view
        of the live values that ``stage_weight`` overwrites leaf by leaf
        while decoding continues on the live set. False (no-op) when
        ``epoch`` is not newer than the live one — a replayed wt stream
        after crash recovery must not reopen a committed epoch."""
        epoch = int(epoch)
        if epoch <= self._epoch:
            return False
        self._shadow = {"epoch": epoch,
                        "vals": [t._value for t in self._state],
                        "staged": set()}
        return True

    def stage_weight(self, name: str, value) -> None:
        """Stage one leaf's new-epoch value into the shadow set (host or
        device array; cast to the live leaf's dtype, replicated onto the
        serving mesh). The live set — and every in-flight request — is
        untouched until ``promote_epoch``."""
        if self._shadow is None:
            raise RuntimeError("stage_weight with no open shadow epoch "
                               "(begin_weight_epoch first)")
        i = self._state_index[name]
        cur = self._state[i]._value
        val = jnp.asarray(value, jnp.asarray(cur).dtype)
        if tuple(val.shape) != tuple(cur.shape):
            raise ValueError(
                f"staged weight {name!r} shape {tuple(val.shape)} != "
                f"live {tuple(cur.shape)}")
        if self._mesh is not None:
            val = jax.device_put(val, self._replicated_sharding)
        self._shadow["vals"][i] = val
        self._shadow["staged"].add(name)

    def discard_shadow(self, epoch: Optional[int] = None) -> bool:
        """Drop an un-promoted shadow set (weight-transaction rollback).
        ``epoch`` narrows the discard to that epoch's shadow; None drops
        whatever is open. Idempotent."""
        if self._shadow is None:
            return False
        if epoch is not None and self._shadow["epoch"] != int(epoch):
            return False
        self._shadow = None
        return True

    def promote_epoch(self, epoch: int) -> bool:
        """Flip the live weights to the staged shadow set by pointer
        swap — the request-boundary epoch flip. No compiled program is
        touched (the AOT cache key carries only shapes/mesh), no slot is
        drained: in-flight requests keep decoding against their pinned
        epoch (the pre-swap values are snapshotted for them), new
        admissions read the promoted set. Exactly-once by construction:
        an ``epoch`` at/below the live one, or with no matching staged
        shadow, is a False no-op — crash recovery re-sends swap orders
        freely. This is the ONLY method that rebinds ``_state`` values
        (check_robustness.py rule 9 pins its callers to the journaled
        weight transaction)."""
        epoch = int(epoch)
        if epoch <= self._epoch:
            return False
        if self._shadow is None or self._shadow["epoch"] != epoch:
            return False
        if any(r.epoch == self._epoch for r in self._running.values()):
            # pin the outgoing epoch's values for its in-flight slots
            self._epoch_vals[self._epoch] = [t._value for t in self._state]
        for t, v in zip(self._state, self._shadow["vals"]):
            t._value = v
        self._epoch = epoch
        self._shadow = None
        # drop pins whose last request already finished
        live = {r.epoch for r in self._running.values()}
        for e in [e for e in self._epoch_vals if e not in live]:
            del self._epoch_vals[e]
        return True

    def _admit(self):
        while self._free and self._waiting:
            if not self._try_prefill(self._waiting[0], self._free[-1]):
                break  # head request can't get pages yet; keep FIFO order
            self._waiting.popleft()
            self._free.pop()
        _obs.set_gauge("serving_queue_depth", float(len(self._waiting)))
        self._update_gauges()

    def _try_prefill(self, req: Request, slot: int) -> bool:
        """Reserve pages (sharing registry hits), run the tail prefill,
        register the request's own full prompt blocks. False = not enough
        free pages even after evicting unused registry entries."""
        cfg = self.config
        p = cfg.page_size
        t0 = int(req.prompt.shape[0])
        total_pages = -(-(t0 + req.params.max_new_tokens) // p)
        keys: List[bytes] = []
        shared: List[int] = []
        if self.registry is not None:
            keys = PrefixRegistry.block_keys(req.prompt, p)
            # never share ALL of the prompt: the prefill needs >= 1 tail
            # token to produce the first logits (the last block is
            # recomputed instead — copy-on-write by recompute)
            shareable = min(len(keys), (t0 - 1) // p)
            shared = self.registry.lookup_chain(keys[:shareable])
        need = total_pages - len(shared)
        if self.pool.available() < need and self.registry is not None:
            self.registry.evict_unused(need - self.pool.available())
        pages = self.pool.alloc(need)
        if pages is None:
            for pg in shared:  # retry next round with a fresh lookup
                self.pool.decref(pg)
            return False
        cached_len = len(shared) * p
        row = np.zeros(self._mp, np.int32)
        row[:len(shared)] = shared
        row[len(shared):total_pages] = pages
        self._tables[slot] = row
        req.page_ids = shared + pages
        req.cached_len = cached_len
        self.prefix_hit_tokens += cached_len
        if cached_len:
            _obs.inc("serving_prefix_hit_tokens", cached_len)
        # register BEFORE the prefill runs: the prefill can finish the
        # request outright (1-token budget / instant EOS), and _finish
        # drops the request's page refs — the registry's +1 must already
        # be in place so the blocks survive. No reader can race ahead of
        # the KV write: the next admission only happens after this
        # prefill has executed.
        if self.registry is not None:
            for j in range(len(shared), t0 // p):
                self.registry.register(keys[j], int(row[j]))
        self._prefill(req, slot, row, cached_len)
        return True

    def _prefill(self, req: Request, slot: int, row: np.ndarray,
                 cached_len: int):
        t0 = int(req.prompt.shape[0])
        tail = req.prompt[cached_len:]
        tb = self._bucket_for(len(tail))
        fn = self._prefill_jit.get(tb)
        if fn is None:
            fn = self._build_prefill(tb)
            self._prefill_jit[tb] = fn
        ids = np.zeros((1, tb), np.int32)
        ids[0, :len(tail)] = tail
        t_, k_, p_, g_ = req.params.fields()
        tp0 = time.perf_counter()
        out = self._run_counted(
            f"prefill_b{tb}", fn,
            self._state_vals(), self._kc, self._vc, self._ksc, self._vsc,
            jnp.asarray(ids), np.int32(cached_len), np.int32(t0),
            jnp.asarray(row), jnp.asarray(req.key_np), np.float32(t_),
            np.int32(k_), np.float32(p_), np.asarray(g_))
        self._kc, self._vc, self._ksc, self._vsc, nxt, logits = out
        token = int(nxt)
        now = time.perf_counter()
        req.first_token_time = now
        req.prefill_t0 = tp0
        req.prefill_s = now - tp0
        _obs.observe("serving_ttft_seconds", now - req.submit_time)
        if req.trace_id is not None:
            _obs.record_span(
                "srv_prefill", trace_id=req.trace_id,
                parent_id=req.trace_parent, dur_s=req.prefill_s,
                rid=req.req_id, bucket=int(tb), cached_len=int(cached_len),
                kernel=self._attn_kernel)
        req.slot = slot
        req.status = "running"
        req.epoch = self._epoch  # admission pins the epoch it prefilled on
        self._running[slot] = req
        self.total_tokens += 1
        self.prompt_tokens_total += t0
        _obs.inc("serving_tokens_total")
        self._append_token(req, token)

    def _append_token(self, req: Request, token: int):
        req.tokens.append(token)
        p = req.params
        if len(req.tokens) >= p.max_new_tokens or (
                p.eos_token_id is not None and token == p.eos_token_id):
            self._finish(req)

    def _finish(self, req: Request):
        req.status = "done"
        if self._acct is not None:
            # charge the page-occupancy tail while the request still
            # holds its pages, then attribute its totals to the ledger
            self._acct_tick(time.perf_counter())
            self._acct_request(req)
        if req.slot >= 0:
            del self._running[req.slot]
            self._tables[req.slot] = 0
            self._free.append(req.slot)
            req.slot = -1
            if req.epoch in self._epoch_vals and not any(
                    r.epoch == req.epoch for r in self._running.values()):
                # last in-flight request of a retired epoch: release its
                # pinned weight snapshot
                del self._epoch_vals[req.epoch]
        for page in req.page_ids:
            self.pool.decref(page)
        req.page_ids = []
        ttft = (None if req.first_token_time is None
                else req.first_token_time - req.submit_time)
        now = time.perf_counter()
        queue_s = (None if req.prefill_t0 is None
                   else req.prefill_t0 - req.submit_time)
        decode_s = 0.0 if req.decode_t0 is None else now - req.decode_t0
        if req.trace_id is not None and req.decode_t0 is not None:
            did = _obs.record_span(
                "srv_decode", trace_id=req.trace_id,
                parent_id=req.trace_parent, dur_s=decode_s,
                rid=req.req_id, steps=req.decode_steps_n,
                tokens=len(req.tokens), kernel=self._attn_kernel)
            if req.verify_steps_n:
                # the speculative share of the decode window, parented to
                # the srv_decode span it partitions
                _obs.record_span(
                    "srv_verify", trace_id=req.trace_id, parent_id=did,
                    dur_s=decode_s * req.verify_steps_n
                    / max(req.decode_steps_n, 1),
                    steps=req.verify_steps_n, accepted=req.spec_accepted_n)
        _obs.event("serving_request_done", req_id=req.req_id,
                   prompt_tokens=int(len(req.prompt)),
                   generated_tokens=len(req.tokens), ttft_seconds=ttft,
                   queue_s=queue_s, prefill_s=round(req.prefill_s, 6),
                   decode_s=round(decode_s, 6),
                   spec_accepted=req.spec_accepted_n,
                   spec_wasted=max(
                       self.config.speculate_k * req.verify_steps_n
                       - req.spec_accepted_n, 0),
                   tenant=req.tenant, slo_class=req.slo,
                   imported=req.imported, kv_page_us=req.acct_page_us,
                   resubmitted=req.resubmitted)

    def _acct_request(self, req: Request):
        """Fold one finished request into the per-tenant ledger. Token
        fields mirror the untagged counters exactly: an imported request's
        prompt + first token were metered on the prefill engine
        (prefill_export), so only its remaining generated tokens count
        here — summed across disaggregated engines every token lands in
        exactly one cell."""
        wasted = max(self.config.speculate_k * req.verify_steps_n
                     - req.spec_accepted_n, 0)
        queue_s = (0.0 if req.prefill_t0 is None
                   else max(req.prefill_t0 - req.submit_time, 0.0))
        self._acct.add(
            req.tenant, req.slo, requests=1,
            prefill_tokens=0 if req.imported else int(len(req.prompt)),
            decode_tokens=len(req.tokens) - (1 if req.imported else 0),
            spec_accepted_tokens=req.spec_accepted_n,
            spec_wasted_tokens=wasted, queue_seconds=queue_s)

    def _update_gauges(self):
        used = sum(len(r.prompt) + len(r.tokens)
                   for r in self._running.values())
        in_use = self._num_pages - 1 - self.pool.available()
        self.peak_pages_in_use = max(self.peak_pages_in_use, in_use)
        self.peak_running = max(self.peak_running, len(self._running))
        _obs.set_gauge("serving_batch_occupancy",
                       len(self._running) / float(self.config.num_slots))
        _obs.set_gauge("serving_kv_cache_utilization",
                       used / float((self._num_pages - 1)
                                    * self.config.page_size))
        _obs.set_gauge("serving_kv_pages_free", float(self.pool.available()))
        _obs.set_gauge("serving_kv_pages_shared",
                       float(self.pool.shared_pages()))

    def _mesh_ctx(self):
        """Activate the engine's mesh for a compiled-program call, so the
        sharding-constraint hints inside F.paged_attention and the pure
        bodies see it at trace time (thread-local; restored after). Also
        forces the mp_comm activation wire OFF for the traced body:
        model-internal mp collectives must stay exact for the greedy
        bit-equality contract — only the logit recombination quantizes,
        explicitly, via ``_wire_logits``."""
        import contextlib

        if self._mesh is None:
            return contextlib.nullcontext()
        from ..distributed import mp_comm as _mp_comm
        from ..distributed.mesh import global_mesh

        stack = contextlib.ExitStack()
        stack.enter_context(global_mesh(self._mesh))
        stack.enter_context(_mp_comm.activation_wire_disabled())
        return stack

    def _run_counted(self, name, fn, *args):
        first = name not in self._compiled
        t0 = time.perf_counter() if first else 0.0
        cached = self._aot.get(name)
        if cached is not None:
            fn = cached
        hit = None
        if first and cached is None:
            aot = _compile_cache.resolve()
            if aot is not None:
                try:
                    with self._mesh_ctx():
                        lowered = fn.lower(*args)
                    key = aot.key_for(
                        lowered, config=self._aot_key_parts(name),
                        mesh=self._mesh)
                    compiled, hit = aot.load_or_compile(
                        lowered, key, where="decode_engine")
                    self._aot[name] = compiled
                    fn = compiled
                    if hit:
                        self.aot_cache_hits += 1
                except Exception:  # noqa: BLE001 — never break serving
                    hit = None
        with self._mesh_ctx():
            out = fn(*args)
        if first:
            jax.block_until_ready(out[-2])
            dt = time.perf_counter() - t0
            self._compiled.add(name)
            self.compile_count += 1
            _obs.inc("serving_engine_compile_total")
            _obs.record_compile("decode_engine", dt, signature=name,
                                cache_hit=hit)
        return out

    def _aot_key_parts(self, name: str) -> dict:
        """Semantic fingerprint for the persistent AOT compile cache:
        everything about the engine geometry that shapes the program
        (the lowered-module hash covers the model body itself)."""
        cfg = self.config
        return {
            "program": name,
            "num_slots": cfg.num_slots,
            "max_length": cfg.max_length,
            "kv_dtype": cfg.kv_dtype,
            "page_size": cfg.page_size,
            "max_pages": self._mp,
            "buckets": list(self.buckets),
            "speculate_k": cfg.speculate_k,
            "donate": self._donate,
            "adapter": type(self.adapter).__name__,
            "logit_wire": self._logit_wire,
            "logit_verify": self._logit_verify,
            "attn_kernel": self._attn_kernel,
        }

    # -- compiled programs --------------------------------------------------
    #
    # All programs take the model state EXPLICITLY (param/buffer values are
    # swapped into the live tensors around the traced body and restored —
    # the jit.TracedLayer idiom), so parameters stay jit arguments rather
    # than baked-in constants, and the paged KV pool flows through as
    # donated inputs/outputs. Page tables arrive as plain int32 arguments.

    def _wire_logits(self, logits):
        """Route mp-vocab-sharded logits [..., V] through the quantized
        recombination (docs/SERVING.md §5). Returns ``(logits_for_
        sampling, exact_argmax, replicated_out)``: with the f32 wire all
        three degrade to ``(logits, None, None)`` so callers trace
        exactly the historical program (mp_comm=off is byte-for-byte);
        quantized, sampling sees the dequantized wire payload while
        greedy rows take the exact verify winner."""
        if self._logit_wire == "f32":
            return logits, None, None
        from ..distributed import mp_comm as _mp_comm

        r = _mp_comm.quantized_logit_gather(logits, self._logit_wire,
                                            self._mesh)
        if r is None:
            return logits, None, None
        wl, exact = r
        rows = int(np.prod(logits.shape[:-1]))
        _, wire_b = _mp_comm.logit_wire_bytes(
            rows, int(logits.shape[-1]), self._mp_degree, self._logit_wire)
        _obs.set_gauge("serving_logit_wire_bytes", wire_b)
        if not self._logit_verify:
            exact = None
        return wl, exact, wl

    def _attend(self, q, kc, vc, ksc, vsc, l, tables, positions):
        """One layer of paged attention on the resolved kernel. The fused
        Pallas path hands the kernel the STORED pool slices — plus the
        absmax scale slabs when int8, so dequant happens against the
        VMEM-resident page inside the kernel; the einsum oracle
        dequantizes the layer's pool up front (``_layer_kv``). The
        kernel is pinned explicitly so an ambient PADDLE_TPU_ATTN_KERNEL
        cannot diverge a program from the engine's resolved (and
        AOT-cache-keyed) choice."""
        if self._attn_kernel == "pallas":
            return F.paged_attention(
                q, kc[l], vc[l], tables, positions,
                k_scales=None if ksc is None else ksc[l],
                v_scales=None if vsc is None else vsc[l],
                kernel="pallas")
        return F.paged_attention(
            q, _layer_kv(kc, ksc, l, self._int8),
            _layer_kv(vc, vsc, l, self._int8), tables, positions,
            kernel="einsum")

    def _build_prefill(self, tb: int):
        ad, state, int8 = self.adapter, self._state, self._int8
        layers = ad.num_layers
        psz = self.config.page_size

        def pure(state_vals, kc, vc, ksc, vsc, ids, cached_len, true_len,
                 row, key, temp, top_k, top_p, greedy):
            originals = [t._value for t in state]
            try:
                for t_, v_ in zip(state, state_vals):
                    t_._value = v_
                with no_grad():
                    positions = cached_len + jnp.arange(tb, dtype=jnp.int32)
                    start = jnp.reshape(cached_len, (1,)).astype(jnp.int32)
                    table = row[None]  # [1, MP]
                    x = ad.embed(Tensor(ids), positions)
                    for l in range(layers):
                        h = ad.pre_attn(l, x)
                        q, k, v = ad.qkv(l, h, positions)
                        kc, ksc = _block_page_write(
                            kc, ksc, l, _shard_kv_heads(raw(k)), row,
                            cached_len, true_len, int8, psz)
                        vc, vsc = _block_page_write(
                            vc, vsc, l, _shard_kv_heads(raw(v)), row,
                            cached_len, true_len, int8, psz)
                        o = self._attend(q, kc, vc, ksc, vsc, l, table,
                                         start)
                        x = x + ad.attn_out(l, o)
                        x = x + ad.mlp(l, x)
                    x = ad.final_norm(x)
                    # right-pad positions >= true_len are inert under the
                    # position mask; the real last-token logits sit at
                    # tail offset true_len - 1 - cached_len
                    last = jax.lax.dynamic_slice_in_dim(
                        raw(x), true_len - 1 - cached_len, 1, 1)
                    logits = raw(ad.logits(Tensor(last)))[:, 0].astype(
                        jnp.float32)
            finally:
                for t_, v_ in zip(state, originals):
                    t_._value = v_
            # sample stream keyed by DESTINATION position: token landing at
            # position true_len uses fold_in(key, true_len), matching what
            # the decode step would use — scheduling-invariant
            step_key = jax.random.fold_in(key, true_len)
            s_logits, exact_arg, wired = self._wire_logits(logits)
            nxt = _sample_tokens(s_logits, step_key[None], temp[None],
                                 top_k[None], top_p[None], greedy[None],
                                 exact_argmax=exact_arg)
            kc, vc, ksc, vsc = _pin_pool_shardings(kc, vc, ksc, vsc)
            out_logits = (_replicate_out(logits[0]) if wired is None
                          else wired[0])
            return (kc, vc, ksc, vsc, _replicate_out(nxt[0]), out_logits)

        donate = (1, 2, 3, 4) if self._donate else ()
        return jax.jit(pure, donate_argnums=donate)

    def _build_decode(self):
        ad, state, int8 = self.adapter, self._state, self._int8
        layers = ad.num_layers
        psz = self.config.page_size

        def pure(state_vals, kc, vc, ksc, vsc, tokens, positions, tables,
                 keys, temp, top_k, top_p, greedy):
            originals = [t._value for t in state]
            try:
                for t_, v_ in zip(state, state_vals):
                    t_._value = v_
                with no_grad():
                    pos2 = positions[:, None]  # [S, 1]
                    x = ad.embed(Tensor(tokens[:, None]), pos2)
                    for l in range(layers):
                        h = ad.pre_attn(l, x)
                        q, k, v = ad.qkv(l, h, pos2)
                        kc, ksc = _token_page_write(
                            kc, ksc, l, _shard_kv_heads(raw(k)), tables,
                            pos2, int8, psz)
                        vc, vsc = _token_page_write(
                            vc, vsc, l, _shard_kv_heads(raw(v)), tables,
                            pos2, int8, psz)
                        o = self._attend(q, kc, vc, ksc, vsc, l, tables,
                                         positions)
                        x = x + ad.attn_out(l, o)
                        x = x + ad.mlp(l, x)
                    x = ad.final_norm(x)
                    logits = raw(ad.logits(x))[:, 0].astype(jnp.float32)
            finally:
                for t_, v_ in zip(state, originals):
                    t_._value = v_
            step_keys = jax.vmap(jax.random.fold_in)(keys, positions + 1)
            s_logits, exact_arg, wired = self._wire_logits(logits)
            nxt = _sample_tokens(s_logits, step_keys, temp, top_k, top_p,
                                 greedy, exact_argmax=exact_arg)
            kc, vc, ksc, vsc = _pin_pool_shardings(kc, vc, ksc, vsc)
            out_logits = _replicate_out(logits) if wired is None else wired
            return (kc, vc, ksc, vsc, _replicate_out(nxt), out_logits)

        donate = (1, 2, 3, 4) if self._donate else ()
        return jax.jit(pure, donate_argnums=donate)

    def _build_verify(self, k1: int):
        """The speculative companion of the decode program: k1 = k + 1
        tokens per slot in one pass, per-position sampling on the SAME
        position-keyed streams."""
        ad, state, int8 = self.adapter, self._state, self._int8
        layers = ad.num_layers
        psz = self.config.page_size

        def pure(state_vals, kc, vc, ksc, vsc, tokens, positions, tables,
                 keys, temp, top_k, top_p, greedy):
            s = tokens.shape[0]
            originals = [t._value for t in state]
            try:
                for t_, v_ in zip(state, state_vals):
                    t_._value = v_
                with no_grad():
                    pos2 = positions[:, None] + jnp.arange(
                        k1, dtype=jnp.int32)[None, :]  # [S, k1]
                    x = ad.embed(Tensor(tokens), pos2)
                    for l in range(layers):
                        h = ad.pre_attn(l, x)
                        q, k, v = ad.qkv(l, h, pos2)
                        kc, ksc = _token_page_write(
                            kc, ksc, l, _shard_kv_heads(raw(k)), tables,
                            pos2, int8, psz)
                        vc, vsc = _token_page_write(
                            vc, vsc, l, _shard_kv_heads(raw(v)), tables,
                            pos2, int8, psz)
                        o = self._attend(q, kc, vc, ksc, vsc, l, tables,
                                         positions)
                        x = x + ad.attn_out(l, o)
                        x = x + ad.mlp(l, x)
                    x = ad.final_norm(x)
                    logits = raw(ad.logits(x)).astype(jnp.float32)  # [S,k1,V]
            finally:
                for t_, v_ in zip(state, originals):
                    t_._value = v_
            step_keys = jax.vmap(jax.vmap(
                jax.random.fold_in, in_axes=(None, 0)))(keys, pos2 + 1)
            s_logits, exact_arg, wired = self._wire_logits(logits)
            flat = s_logits.reshape(s * k1, -1)
            rep = lambda a: jnp.repeat(a, k1, axis=0)
            targets = _sample_tokens(
                flat, step_keys.reshape(s * k1, -1), rep(temp), rep(top_k),
                rep(top_p), rep(greedy),
                exact_argmax=(None if exact_arg is None
                              else exact_arg.reshape(s * k1))).reshape(s, k1)
            kc, vc, ksc, vsc = _pin_pool_shardings(kc, vc, ksc, vsc)
            out_logits = _replicate_out(logits) if wired is None else wired
            return (kc, vc, ksc, vsc, _replicate_out(targets), out_logits)

        donate = (1, 2, 3, 4) if self._donate else ()
        return jax.jit(pure, donate_argnums=donate)
