"""KV-cached decode engine with continuous batching — the serving path.

Reference capability: Paddle Inference's generation serving stack (fused
attention-with-cache kernels updating an in-place ``cache_kv`` per layer)
and PaddleNLP's ``llm/predictor.py`` batched serving loop. TPU-native
design (the static-shape serving discipline on XLA):

* **Static shapes only.** Two compiled program families serve every
  request mix: one prefill per power-of-two prompt bucket (batch 1,
  written into a slot) and ONE single-token decode step over all
  ``num_slots`` slots. Nothing recompiles per request, per length, or
  per step; a 3-bucket workload compiles <= 4 XLA programs
  (tests/test_decode_engine.py gates this).
* **Slot-indexed KV cache.** ``[L, S, Hkv, T_max, D]`` stacked buffers
  live on device and are donated back to XLA on every compiled step
  (TPU/GPU backends), so the cache updates in place instead of copying.
* **Continuous batching.** A pure-Python scheduler admits waiting
  requests into free slots and evicts finished ones BETWEEN compiled
  steps: short requests never wait for long ones and decode occupancy
  stays high. Slot reuse cannot leak a previous request's KV — decode
  attention masks positions > the slot's own ``cache_position``, and
  every position <= it has been freshly written by the current request.
* **On-device sampling.** greedy/temperature/top-k/top-p run inside the
  decode program via ``jax.random`` with per-slot keys folded by target
  position (so a request's sample stream does not depend on which other
  requests it was batched with); the per-token host transfer is one
  int32 per slot, never a logits matrix.
* **Optional int8 KV.** ``kv_dtype="int8"`` stores the cache at one byte
  per element with per-(layer, slot, head, position) absmax scales via
  grad_comm's quantize/dequantize helpers — the reduced-precision-with-
  absmax-scales discipline the gradient wire already uses, applied to
  the dominant serving memory consumer.

Models plug in through ``model.decode_adapter()`` (text/models/gpt.py,
llama.py): the engine owns the residual stream, the cache, and the
sampler; the adapter exposes embed / per-layer norm+qkv+out-proj+mlp /
final-norm / logits hooks plus cache geometry. See docs/SERVING.md.
"""
from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..distributed.grad_comm import dequantize_absmax, quantize_absmax
from ..framework.core import Tensor, no_grad
from ..framework.op import raw
from ..nn import functional as F

__all__ = [
    "DecodeEngine",
    "EngineConfig",
    "SamplingParams",
    "pow2_bucket",
]

KV_DTYPES = ("f32", "bf16", "int8")


def pow2_bucket(n: int, lo: int = 16, hi: Optional[int] = None) -> int:
    """Smallest power-of-two >= n (floored at `lo`, capped at `hi`)."""
    b = lo
    while b < n:
        b *= 2
    return min(b, hi) if hi is not None else b


@dataclass
class EngineConfig:
    """Engine geometry + cache policy (see docs/SERVING.md for tuning)."""

    num_slots: int = 8
    max_length: int = 512
    kv_dtype: str = "f32"  # f32 | bf16 | int8
    #: explicit prompt buckets; None = powers of two from min_bucket up to
    #: max_length. Only buckets a prompt actually lands in get compiled.
    prompt_buckets: Optional[Tuple[int, ...]] = None
    min_bucket: int = 16
    #: None = donate cache buffers on tpu/gpu only (CPU XLA cannot alias
    #: them and would warn on every step)
    donate: Optional[bool] = None
    #: base seed for requests that don't carry their own
    seed: int = 0

    def resolved_buckets(self) -> List[int]:
        if self.prompt_buckets:
            bs = sorted({min(int(b), self.max_length)
                         for b in self.prompt_buckets})
        else:
            bs, b = [], self.min_bucket
            while b < self.max_length:
                bs.append(b)
                b *= 2
            bs.append(min(b, self.max_length))
        return bs


@dataclass
class SamplingParams:
    max_new_tokens: int = 32
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: Optional[int] = None
    seed: Optional[int] = None

    def fields(self):
        """(temperature, top_k, top_p, greedy) in device form."""
        greedy = (not self.do_sample) or self.temperature <= 0.0
        return (max(float(self.temperature), 1e-6), int(self.top_k),
                float(self.top_p), bool(greedy))


@dataclass
class Request:
    req_id: int
    prompt: np.ndarray
    params: SamplingParams
    key_np: np.ndarray
    tokens: List[int] = field(default_factory=list)
    status: str = "waiting"  # waiting | running | done
    slot: int = -1
    submit_time: float = 0.0
    first_token_time: Optional[float] = None


# ---------------------------------------------------------------------------
# cache plumbing (pure jnp; traced inside the engine's compiled programs)
# ---------------------------------------------------------------------------


def _prefill_write(cache, scales, layer, slot, kv, int8):
    """Write a whole prompt block kv [1, TB, Hkv, D] into (layer, slot)."""
    blk = jnp.swapaxes(kv[0], 0, 1)  # [Hkv, TB, D]
    if int8:
        q, scale = quantize_absmax(blk, axis=-1)  # scale [Hkv, TB, 1]
        cache = jax.lax.dynamic_update_slice(
            cache, q[None, None], (layer, slot, 0, 0, 0))
        scales = jax.lax.dynamic_update_slice(
            scales, scale[..., 0][None, None], (layer, slot, 0, 0))
        return cache, scales
    cache = jax.lax.dynamic_update_slice(
        cache, blk[None, None].astype(cache.dtype), (layer, slot, 0, 0, 0))
    return cache, scales


def _decode_write(cache, scales, layer, kv, positions, int8):
    """Write one token kv [S, 1, Hkv, D] at per-slot `positions` [S]."""
    x = kv[:, 0]  # [S, Hkv, D]
    if int8:
        q, scale = quantize_absmax(x, axis=-1)  # q [S,Hkv,D], scale [S,Hkv,1]

        def put(c, qs, p):  # c [Hkv, T, D]
            return jax.lax.dynamic_update_slice(c, qs[:, None, :], (0, p, 0))

        def put_scale(c, ss, p):  # c [Hkv, T]
            return jax.lax.dynamic_update_slice(c, ss, (0, p))

        cache = cache.at[layer].set(jax.vmap(put)(cache[layer], q, positions))
        scales = scales.at[layer].set(
            jax.vmap(put_scale)(scales[layer], scale, positions))
        return cache, scales

    def put(c, xs, p):
        return jax.lax.dynamic_update_slice(
            c, xs[:, None, :].astype(c.dtype), (0, p, 0))

    cache = cache.at[layer].set(jax.vmap(put)(cache[layer], x, positions))
    return cache, scales


def _layer_kv(cache, scales, layer, int8):
    """One layer's [S, Hkv, T, D] view, dequantized when int8."""
    lay = cache[layer]
    if int8:
        return dequantize_absmax(lay, scales[layer][..., None])
    return lay


def _sample_tokens(logits, keys, temperature, top_k, top_p, greedy):
    """On-device sampling for N rows: logits [N, V] f32, keys [N, ks],
    temperature/top_p f32 [N], top_k i32 [N], greedy bool [N]. Per-row
    keys keep every request's sample stream independent of co-scheduling.
    top_k <= 0 and top_p >= 1.0 disable their filters."""
    v = logits.shape[-1]
    x = logits / temperature[:, None]
    sorted_x = jnp.sort(x, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        sorted_x, (jnp.clip(top_k, 1, v) - 1)[:, None], axis=-1)
    x = jnp.where((top_k[:, None] > 0) & (x < kth), -jnp.inf, x)
    probs = jax.nn.softmax(x, axis=-1)
    sp = jnp.sort(probs, axis=-1)[:, ::-1]
    keep = (jnp.cumsum(sp, axis=-1) - sp) < top_p[:, None]
    thr = jnp.min(jnp.where(keep, sp, jnp.inf), axis=-1, keepdims=True)
    x = jnp.where((top_p[:, None] < 1.0) & (probs < thr), -jnp.inf, x)
    sampled = jax.vmap(lambda xr, kr: jax.random.categorical(kr, xr))(x, keys)
    return jnp.where(greedy, jnp.argmax(logits, axis=-1),
                     sampled).astype(jnp.int32)


class DecodeEngine:
    """Continuous-batching serving engine over a decoder-only LM.

    Usage::

        eng = DecodeEngine(model, num_slots=8, max_length=512)
        rid = eng.submit(prompt_ids, max_new_tokens=64, eos_token_id=2)
        eng.run()                     # or step() from your own loop
        out = eng.result(rid)         # np.ndarray prompt + generated

    or the batch front end ``eng.generate_batch(ids, ...)`` which
    ``text.generation.generate`` rides on.
    """

    def __init__(self, model, config: Optional[EngineConfig] = None,
                 **overrides):
        self.config = config or EngineConfig(**overrides)
        cfg = self.config
        if cfg.kv_dtype not in KV_DTYPES:
            raise ValueError(
                f"kv_dtype must be one of {KV_DTYPES}, got {cfg.kv_dtype!r}")
        self.model = model
        model.eval()
        self.adapter = model.decode_adapter()
        ad = self.adapter
        if cfg.max_length > ad.max_positions:
            raise ValueError(
                f"max_length={cfg.max_length} exceeds the model's "
                f"max_positions={ad.max_positions}")
        self.buckets = cfg.resolved_buckets()
        self._int8 = cfg.kv_dtype == "int8"
        store = {"f32": jnp.float32, "bf16": jnp.bfloat16,
                 "int8": jnp.int8}[cfg.kv_dtype]
        shape = (ad.num_layers, cfg.num_slots, ad.num_kv_heads,
                 cfg.max_length, ad.head_dim)
        self._kc = jnp.zeros(shape, store)
        self._vc = jnp.zeros(shape, store)
        if self._int8:
            self._ksc = jnp.ones(shape[:-1], jnp.float32)
            self._vsc = jnp.ones(shape[:-1], jnp.float32)
        else:
            self._ksc = self._vsc = None
        # stable state ordering for the compiled-call state swap (the
        # TracedLayer idiom): dedup'd params first, then buffers
        self._state, seen = [], set()
        for _, p in model.named_parameters():
            if id(p) not in seen:
                seen.add(id(p))
                self._state.append(p)
        for _, b in model.named_buffers():
            if id(b) not in seen:
                seen.add(id(b))
                self._state.append(b)
        donate = cfg.donate
        if donate is None:
            donate = jax.default_backend() in ("tpu", "gpu")
        self._donate = bool(donate)
        self._prefill_jit: Dict[int, object] = {}
        self._decode_jit = None
        self._compiled = set()
        self.compile_count = 0
        self.total_tokens = 0
        self.decode_steps = 0
        self._base_key = jax.random.PRNGKey(cfg.seed)
        self._zero_key = np.asarray(self._base_key)
        self._waiting: deque = deque()
        self._running: Dict[int, Request] = {}
        self._free = list(range(cfg.num_slots))[::-1]  # pop() -> slot 0
        self._requests: Dict[int, Request] = {}
        self._next_id = 0

    # -- scheduler ----------------------------------------------------------

    def submit(self, prompt, params: Optional[SamplingParams] = None,
               **kw) -> int:
        """Queue one request; returns its id. `prompt` is a 1-D int array
        (Tensor/np/list); keyword args build a SamplingParams."""
        if params is None:
            params = SamplingParams(**kw)
        ids = np.asarray(raw(prompt), dtype=np.int32).reshape(-1)
        t0 = int(ids.shape[0])
        if t0 < 1:
            raise ValueError("empty prompt")
        if t0 > self.buckets[-1]:
            raise ValueError(
                f"prompt length {t0} exceeds the largest prompt bucket "
                f"{self.buckets[-1]}")
        if t0 + params.max_new_tokens > self.config.max_length:
            raise ValueError(
                f"prompt ({t0}) + max_new_tokens ({params.max_new_tokens}) "
                f"exceeds max_length={self.config.max_length}")
        rid = self._next_id
        self._next_id += 1
        if params.seed is not None:
            key = jax.random.PRNGKey(params.seed)
        else:
            key = jax.random.fold_in(self._base_key, rid)
        req = Request(req_id=rid, prompt=ids, params=params,
                      key_np=np.asarray(key),
                      submit_time=time.perf_counter())
        self._requests[rid] = req
        self._waiting.append(req)
        _obs.inc("serving_requests_total")
        _obs.set_gauge("serving_queue_depth", float(len(self._waiting)))
        return rid

    def step(self) -> bool:
        """Admit waiting requests into free slots (one compiled prefill
        each), then run ONE compiled decode step over every occupied slot.
        Returns False when the engine is fully idle."""
        self._admit()
        if not self._running:
            return bool(self._waiting)
        cfg = self.config
        s = cfg.num_slots
        tokens = np.zeros(s, np.int32)
        positions = np.zeros(s, np.int32)
        temp = np.ones(s, np.float32)
        top_k = np.zeros(s, np.int32)
        top_p = np.ones(s, np.float32)
        greedy = np.ones(s, bool)
        keys = np.broadcast_to(self._zero_key, (s,) + self._zero_key.shape)
        keys = np.array(keys)
        for slot, req in self._running.items():
            tokens[slot] = req.tokens[-1]
            positions[slot] = len(req.prompt) + len(req.tokens) - 1
            t_, k_, p_, g_ = req.params.fields()
            temp[slot], top_k[slot], top_p[slot], greedy[slot] = t_, k_, p_, g_
            keys[slot] = req.key_np
        if self._decode_jit is None:
            self._decode_jit = self._build_decode()
        t0 = time.perf_counter()
        out = self._run_counted(
            "decode", self._decode_jit,
            self._state_vals(), self._kc, self._vc, self._ksc, self._vsc,
            jnp.asarray(tokens), jnp.asarray(positions), jnp.asarray(keys),
            jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
            jnp.asarray(greedy))
        self._kc, self._vc, self._ksc, self._vsc, nxt, logits = out
        nxt_host = np.asarray(nxt)  # the per-token host transfer: [S] int32
        _obs.observe("serving_decode_step_seconds",
                     time.perf_counter() - t0)
        self.decode_steps += 1
        self._last_logits = logits
        active = list(self._running.items())
        for slot, req in active:
            self.total_tokens += 1
            self._append_token(req, int(nxt_host[slot]))
        _obs.inc("serving_tokens_total", len(active))
        self._update_gauges()
        return True

    def run(self) -> Dict[int, np.ndarray]:
        """Drive step() until every submitted request finished; returns
        {req_id: prompt + generated} for requests completed in this
        drain."""
        t0 = time.perf_counter()
        before = self.total_tokens
        finished = [r.req_id for r in self._requests.values()
                    if r.status == "done"]
        seen_done = set(finished)
        while self._waiting or self._running:
            self.step()
        emitted = self.total_tokens - before
        dt = max(time.perf_counter() - t0, 1e-9)
        if emitted:
            _obs.set_gauge("serving_tokens_per_second", emitted / dt)
        return {rid: self.result(rid) for rid, r in self._requests.items()
                if r.status == "done" and rid not in seen_done}

    def result(self, rid: int) -> np.ndarray:
        req = self._requests[rid]
        if req.status != "done":
            raise RuntimeError(f"request {rid} is {req.status}, not done")
        return np.concatenate(
            [req.prompt, np.asarray(req.tokens, np.int32)])

    def generate_batch(self, input_ids, max_new_tokens: int = 32,
                       do_sample: bool = False, top_k: int = 0,
                       top_p: float = 1.0, temperature: float = 1.0,
                       eos_token_id=None, pad_token_id=None, seed=None):
        """Batch front end with text.generation.generate semantics: every
        row becomes a request, rows that finish early are padded with
        pad_token_id (else eos, else 0). Returns a Tensor [B, T0 + n]."""
        ids = np.asarray(raw(input_ids))
        b, t0 = ids.shape
        rids = [
            self.submit(ids[i], SamplingParams(
                max_new_tokens=max_new_tokens, do_sample=do_sample,
                temperature=temperature, top_k=top_k, top_p=top_p,
                eos_token_id=eos_token_id,
                seed=None if seed is None else seed * 1000003 + i))
            for i in range(b)
        ]
        self.run()
        reqs = [self._requests[r] for r in rids]
        width = max(len(r.tokens) for r in reqs)
        filler = pad_token_id if pad_token_id is not None else (
            eos_token_id if eos_token_id is not None else 0)
        out = np.full((b, t0 + width), filler, dtype=ids.dtype)
        out[:, :t0] = ids
        for i, r in enumerate(reqs):
            out[i, t0:t0 + len(r.tokens)] = r.tokens
        return Tensor(jnp.asarray(out))

    def stats(self) -> dict:
        return {
            "compile_count": self.compile_count,
            "compiled": sorted(self._compiled),
            "buckets": list(self.buckets),
            "decode_steps": self.decode_steps,
            "total_tokens": self.total_tokens,
            "running": len(self._running),
            "waiting": len(self._waiting),
        }

    # -- internals ----------------------------------------------------------

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"no prompt bucket holds length {n}")

    def _state_vals(self):
        return [t._value for t in self._state]

    def _admit(self):
        while self._free and self._waiting:
            req = self._waiting.popleft()
            self._prefill(req, self._free.pop())
        _obs.set_gauge("serving_queue_depth", float(len(self._waiting)))
        self._update_gauges()

    def _prefill(self, req: Request, slot: int):
        tb = self._bucket_for(len(req.prompt))
        fn = self._prefill_jit.get(tb)
        if fn is None:
            fn = self._build_prefill(tb)
            self._prefill_jit[tb] = fn
        ids = np.zeros((1, tb), np.int32)
        ids[0, :len(req.prompt)] = req.prompt
        t_, k_, p_, g_ = req.params.fields()
        out = self._run_counted(
            f"prefill_b{tb}", fn,
            self._state_vals(), self._kc, self._vc, self._ksc, self._vsc,
            jnp.asarray(ids), np.int32(len(req.prompt)), np.int32(slot),
            jnp.asarray(req.key_np), np.float32(t_), np.int32(k_),
            np.float32(p_), np.asarray(g_))
        self._kc, self._vc, self._ksc, self._vsc, nxt, logits = out
        token = int(nxt)
        now = time.perf_counter()
        req.first_token_time = now
        _obs.observe("serving_ttft_seconds", now - req.submit_time)
        req.slot = slot
        req.status = "running"
        self._running[slot] = req
        self.total_tokens += 1
        _obs.inc("serving_tokens_total")
        self._append_token(req, token)

    def _append_token(self, req: Request, token: int):
        req.tokens.append(token)
        p = req.params
        if len(req.tokens) >= p.max_new_tokens or (
                p.eos_token_id is not None and token == p.eos_token_id):
            self._finish(req)

    def _finish(self, req: Request):
        req.status = "done"
        if req.slot >= 0:
            del self._running[req.slot]
            self._free.append(req.slot)
            req.slot = -1
        ttft = (None if req.first_token_time is None
                else req.first_token_time - req.submit_time)
        _obs.event("serving_request_done", req_id=req.req_id,
                   prompt_tokens=int(len(req.prompt)),
                   generated_tokens=len(req.tokens), ttft_seconds=ttft)

    def _update_gauges(self):
        cfg = self.config
        used = sum(len(r.prompt) + len(r.tokens)
                   for r in self._running.values())
        _obs.set_gauge("serving_batch_occupancy",
                       len(self._running) / float(cfg.num_slots))
        _obs.set_gauge("serving_kv_cache_utilization",
                       used / float(cfg.num_slots * cfg.max_length))

    def _run_counted(self, name, fn, *args):
        first = name not in self._compiled
        t0 = time.perf_counter() if first else 0.0
        out = fn(*args)
        if first:
            jax.block_until_ready(out[-2])
            dt = time.perf_counter() - t0
            self._compiled.add(name)
            self.compile_count += 1
            _obs.inc("serving_engine_compile_total")
            _obs.record_compile("decode_engine", dt, signature=name)
        return out

    # -- compiled programs --------------------------------------------------
    #
    # Both programs take the model state EXPLICITLY (param/buffer values are
    # swapped into the live tensors around the traced body and restored —
    # the jit.TracedLayer idiom), so parameters stay jit arguments rather
    # than baked-in constants, and the KV cache flows through as donated
    # inputs/outputs.

    def _build_prefill(self, tb: int):
        ad, state, int8 = self.adapter, self._state, self._int8
        layers = ad.num_layers
        group = ad.num_heads // ad.num_kv_heads

        def pure(state_vals, kc, vc, ksc, vsc, ids, true_len, slot, key,
                 temp, top_k, top_p, greedy):
            originals = [t._value for t in state]
            try:
                for t_, v_ in zip(state, state_vals):
                    t_._value = v_
                with no_grad():
                    positions = jnp.arange(tb, dtype=jnp.int32)
                    x = ad.embed(Tensor(ids), positions)
                    for l in range(layers):
                        h = ad.pre_attn(l, x)
                        q, k, v = ad.qkv(l, h, positions)
                        kc, ksc = _prefill_write(kc, ksc, l, slot, raw(k),
                                                 int8)
                        vc, vsc = _prefill_write(vc, vsc, l, slot, raw(v),
                                                 int8)
                        if group > 1:
                            k = Tensor(jnp.repeat(raw(k), group, axis=2))
                            v = Tensor(jnp.repeat(raw(v), group, axis=2))
                        o = F.scaled_dot_product_attention(
                            q, k, v, is_causal=True, training=False)
                        x = x + ad.attn_out(l, o)
                        x = x + ad.mlp(l, x)
                    x = ad.final_norm(x)
                    # right-pad positions >= true_len are inert under the
                    # causal mask; the real last-token logits sit at
                    # true_len - 1
                    last = jax.lax.dynamic_slice_in_dim(
                        raw(x), true_len - 1, 1, 1)
                    logits = raw(ad.logits(Tensor(last)))[:, 0].astype(
                        jnp.float32)
            finally:
                for t_, v_ in zip(state, originals):
                    t_._value = v_
            # sample stream keyed by DESTINATION position: token landing at
            # position true_len uses fold_in(key, true_len), matching what
            # the decode step would use — scheduling-invariant
            step_key = jax.random.fold_in(key, true_len)
            nxt = _sample_tokens(logits, step_key[None], temp[None],
                                 top_k[None], top_p[None], greedy[None])
            return kc, vc, ksc, vsc, nxt[0], logits[0]

        donate = (1, 2, 3, 4) if self._donate else ()
        return jax.jit(pure, donate_argnums=donate)

    def _build_decode(self):
        ad, state, int8 = self.adapter, self._state, self._int8
        layers = ad.num_layers

        def pure(state_vals, kc, vc, ksc, vsc, tokens, positions, keys,
                 temp, top_k, top_p, greedy):
            originals = [t._value for t in state]
            try:
                for t_, v_ in zip(state, state_vals):
                    t_._value = v_
                with no_grad():
                    pos2 = positions[:, None]  # [S, 1]
                    x = ad.embed(Tensor(tokens[:, None]), pos2)
                    for l in range(layers):
                        h = ad.pre_attn(l, x)
                        q, k, v = ad.qkv(l, h, pos2)
                        kc, ksc = _decode_write(kc, ksc, l, raw(k),
                                                positions, int8)
                        vc, vsc = _decode_write(vc, vsc, l, raw(v),
                                                positions, int8)
                        o = F.decode_attention(
                            q, _layer_kv(kc, ksc, l, int8),
                            _layer_kv(vc, vsc, l, int8), positions)
                        x = x + ad.attn_out(l, o)
                        x = x + ad.mlp(l, x)
                    x = ad.final_norm(x)
                    logits = raw(ad.logits(x))[:, 0].astype(jnp.float32)
            finally:
                for t_, v_ in zip(state, originals):
                    t_._value = v_
            step_keys = jax.vmap(jax.random.fold_in)(keys, positions + 1)
            nxt = _sample_tokens(logits, step_keys, temp, top_k, top_p,
                                 greedy)
            return kc, vc, ksc, vsc, nxt, logits

        donate = (1, 2, 3, 4) if self._donate else ()
        return jax.jit(pure, donate_argnums=donate)
