"""paddle.geometric parity — graph-learning message passing and segment ops.

Reference: ``python/paddle/geometric/`` (message_passing/send_recv.py,
math.py segment ops, sampling/neighbors.py — phi graph_send_recv /
segment_pool CUDA kernels). TPU-native design: message passing IS a
gather + segment-reduce, which XLA compiles to fused scatter-adds on
device — ``send_u_recv(x, src, dst)`` lowers to
``segment_reduce(x[src], dst)`` with no custom kernel needed. With a
static ``out_size`` everything traces under jit (the TPU-idiomatic form);
without it the output length is data-dependent (max(dst)+1), which is an
eager-only path by the same rule as nonzero/unique (manipulation.py).

Neighbor sampling is host-side by design: it is data-layout work
(CSC walks + RNG) that belongs on CPU feeding the device, exactly like
the DataLoader's role.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework.op import defop, raw

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv",
    "sample_neighbors", "reindex_graph",
]


def _num_segments(ids, out_size):
    if out_size is not None and int(out_size) > 0:
        return int(out_size)
    idv = raw(ids)
    try:
        return int(jnp.max(idv)) + 1
    except jax.errors.ConcretizationTypeError:
        raise ValueError(
            "geometric ops need a static output length under jit: pass "
            "out_size= explicitly (the data-dependent max(index)+1 default "
            "is eager-only, like nonzero/unique)") from None


def _segment_reduce(data, ids, pool, n):
    ids = jnp.asarray(ids)
    if pool == "sum":
        return jax.ops.segment_sum(data, ids, num_segments=n)
    if pool == "mean":
        s = jax.ops.segment_sum(data, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(ids, data.dtype), ids, num_segments=n)
        return s / jnp.maximum(cnt, 1).reshape((-1,) + (1,) * (data.ndim - 1))
    if pool == "max":
        out = jax.ops.segment_max(data, ids, num_segments=n)
        return jnp.where(jnp.isfinite(out), out, 0.0)  # empty segments -> 0 (paddle)
    if pool == "min":
        out = jax.ops.segment_min(data, ids, num_segments=n)
        return jnp.where(jnp.isfinite(out), out, 0.0)
    raise ValueError(f"unknown reduce op {pool!r}")


def _make_segment(pool):
    @defop(name=f"segment_{pool}_op")
    def seg(data, segment_ids, n):
        return _segment_reduce(data, segment_ids, pool, n)

    def op(data, segment_ids, name=None):
        return seg(data, segment_ids, n=_num_segments(segment_ids, None))

    op.__name__ = f"segment_{pool}"
    op.__doc__ = (
        f"paddle.geometric.segment_{pool}: {pool}-reduce rows of `data` by "
        "`segment_ids` (sorted or not). Output length = max(ids)+1 "
        "(eager; under jit use send_u_recv with out_size).")
    return op


segment_sum = _make_segment("sum")
segment_mean = _make_segment("mean")
segment_max = _make_segment("max")
segment_min = _make_segment("min")


@defop(name="send_u_recv_op")
def _send_u_recv(x, src, dst, pool, n):
    return _segment_reduce(jnp.take(x, jnp.asarray(src), axis=0),
                           jnp.asarray(dst), pool, n)


def send_u_recv(x, src_index, dst_index, reduce_op="sum",
                out_size: Optional[int] = None, name=None):
    """Gather node features along edges and reduce at destinations:
    out[d] = reduce over edges (s->d) of x[s]. The core message-passing
    primitive (reference: graph_send_recv)."""
    n = _num_segments(dst_index, out_size)
    return _send_u_recv(x, src_index, dst_index, pool=reduce_op, n=n)


@defop(name="send_ue_recv_op")
def _send_ue_recv(x, y, src, dst, msg, pool, n):
    h = jnp.take(x, jnp.asarray(src), axis=0)
    e = jnp.asarray(y)
    if e.ndim < h.ndim:
        e = e.reshape(e.shape + (1,) * (h.ndim - e.ndim))
    m = {"add": h + e, "sub": h - e, "mul": h * e, "div": h / e}[msg]
    return _segment_reduce(m, jnp.asarray(dst), pool, n)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size: Optional[int] = None, name=None):
    """Combine source-node features with per-edge features, reduce at
    destinations: out[d] = reduce over (s->d) of msg(x[s], y[edge])."""
    n = _num_segments(dst_index, out_size)
    return _send_ue_recv(x, y, src_index, dst_index, msg=message_op,
                         pool=reduce_op, n=n)


@defop(name="send_uv_op")
def _send_uv(x, y, src, dst, msg):
    h = jnp.take(x, jnp.asarray(src), axis=0)
    t = jnp.take(y, jnp.asarray(dst), axis=0)
    return {"add": h + t, "sub": h - t, "mul": h * t, "div": h / t}[msg]


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge messages msg(x[src], y[dst]) — no reduction (shape [E, ...])."""
    return _send_uv(x, y, src_index, dst_index, msg=message_op)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling on a CSC graph (reference:
    geometric/sampling/neighbors.py). Host-side numpy by design — this is
    data-pipeline work (per-node RNG walks over ragged adjacency), the
    same CPU-feeds-TPU split as the DataLoader.

    Returns (neighbors, counts) — and edge ids too when return_eids.
    """
    rowv = np.asarray(raw(row)).astype(np.int64)
    cptr = np.asarray(raw(colptr)).astype(np.int64)
    nodes = np.atleast_1d(np.asarray(raw(input_nodes))).astype(np.int64)
    ev = np.asarray(raw(eids)).astype(np.int64) if eids is not None else None
    rng = np.random.default_rng()
    outs, out_eids, counts = [], [], []
    for nd in nodes:
        lo, hi = int(cptr[nd]), int(cptr[nd + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(lo, hi)
        else:
            sel = lo + rng.choice(deg, size=sample_size, replace=False)
        outs.append(rowv[sel])
        if ev is not None:
            out_eids.append(ev[sel])
        counts.append(len(sel))
    neighbors = Tensor(jnp.asarray(np.concatenate(outs) if outs else
                                   np.zeros((0,), np.int64)))
    counts_t = Tensor(jnp.asarray(np.asarray(counts, np.int64)))
    if return_eids:
        if ev is None:
            raise ValueError("return_eids=True requires eids")
        return neighbors, counts_t, Tensor(jnp.asarray(np.concatenate(out_eids)))
    return neighbors, counts_t


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Renumber a sampled subgraph to contiguous ids (reference:
    geometric/reindex.py): x (center nodes) keep ids [0, len(x));
    first-seen neighbor order continues from there. Host-side numpy.

    Returns (reindexed_src, reindexed_dst, out_nodes).
    """
    xs = np.asarray(raw(x)).astype(np.int64)
    nb = np.asarray(raw(neighbors)).astype(np.int64)
    cnt = np.asarray(raw(count)).astype(np.int64)
    mapping = {int(v): i for i, v in enumerate(xs)}
    for v in nb:
        if int(v) not in mapping:
            mapping[int(v)] = len(mapping)
    src = np.asarray([mapping[int(v)] for v in nb], np.int64)
    dst = np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
    out_nodes = np.empty(len(mapping), np.int64)
    for v, i in mapping.items():
        out_nodes[i] = v
    return (Tensor(jnp.asarray(src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(out_nodes)))
