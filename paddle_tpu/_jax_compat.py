"""Version-compat shims over the installed jax.

The repo targets a range of jax releases: `shard_map` graduated from
`jax.experimental.shard_map` to a top-level `jax.shard_map`, renaming
kwargs on the way (`check_rep` -> `check_vma`; manual axes went from the
complement-form `auto=` to the direct `axis_names=`). Resolve whichever
this install provides and translate the kwargs, so kernel code is written
once against the modern surface. Keep every cross-version alias HERE —
scattering hasattr probes through kernel code is how silent API drift
creeps in.
"""
from __future__ import annotations

import jax

_NEW_SHARD_MAP = hasattr(jax, "shard_map")
if not _NEW_SHARD_MAP:
    from jax.experimental.shard_map import shard_map as _legacy_shard_map


def shard_map(f, mesh=None, in_specs=None, out_specs=None, *,
              axis_names=None, check_vma=None, check_rep=None, auto=None,
              **kwargs):
    """`jax.shard_map` with modern kwargs on every supported jax.

    `axis_names` (modern) and `auto` (legacy complement) are two spellings
    of the manual-axes set; `check_vma` (modern) and `check_rep` (legacy)
    are two names for the same replication check. Either spelling is
    accepted and translated to what the installed jax understands."""
    if _NEW_SHARD_MAP:
        if check_vma is None and check_rep is not None:
            check_vma = check_rep
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        if axis_names is None and auto is not None and mesh is not None:
            axis_names = frozenset(mesh.axis_names) - frozenset(auto)
        if axis_names is not None:
            kwargs["axis_names"] = frozenset(axis_names)
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)
    if check_rep is None and check_vma is not None:
        check_rep = check_vma
    if check_rep is not None:
        kwargs["check_rep"] = check_rep
    # Legacy jax lowers every axis FULLY manual, ignoring the requested
    # auto/axis_names split: its partial-manual path runs the body through
    # the SPMD partitioner, which rejects the partition_id that
    # `lax.axis_index` lowers to — and every shard_map body in this repo
    # (pipeline schedule, ring attention) takes its rank from axis_index.
    # Promoting auto axes to manual is semantics-preserving for those
    # bodies: in/out specs may only name manual axes so they stay valid,
    # and data along a promoted axis is simply replicated (the GSPMD hints
    # the body would have used for it are dropped by
    # `mesh.sharding_constraint` inside any manual region). Costs redundant
    # compute along the promoted axes on old jax, never wrong answers.
    return _legacy_shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kwargs)


def bound_axis_names() -> frozenset:
    """Axis names bound in the CURRENT trace (shard_map/pmap/vmap regions).

    Inside such a region these axes are MANUAL: data is already rank-local,
    so a GSPMD sharding hint naming them is at best moot and (on every jax
    we support) a lowering error. Callers use this to strip them from
    PartitionSpecs before `with_sharding_constraint`. Returns the empty set
    when the introspection hook is unavailable — the conservative answer."""
    try:
        from jax._src import core as _core

        return frozenset(_core.unsafe_get_axis_names())
    except Exception:
        return frozenset()


def axis_size(axis_name):
    """`lax.axis_size` for jax versions that predate it: a psum of the
    literal 1 constant-folds to the static mesh-axis extent inside any
    mapped region (the canonical pre-axis_size idiom)."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
