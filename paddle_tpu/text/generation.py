"""Decoding utilities for the causal LM families (GPT / Llama).

Reference capability: PaddleNLP's `GenerationMixin` (greedy/sampling/beam
over models with cache). TPU-native v1: an eager decode loop that re-runs
the compiled forward on the growing sequence — each length hits the jit
cache once, so a generation sweep compiles O(max_len) programs the first
time and replays them afterwards. A fixed-shape variant
(`generate_padded`) keeps ONE compiled program by right-padding to
max_length and masking, which is the TPU-friendly shape discipline for
serving loops.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.core import Tensor, no_grad
from ..framework.op import raw


def _without_grad(fn):
    """Decorator creating a FRESH no_grad context per call: the shared
    ContextDecorator instance stores its saved state on itself, which is
    not reentrant across nested/concurrent generate calls."""
    import functools

    @functools.wraps(fn)
    def wrapper(*a, **k):
        with no_grad():
            return fn(*a, **k)

    return wrapper


def _pow2_bucket(n: int, lo: int = 16) -> int:
    """Smallest power-of-two >= n (floored at `lo`) — the shared length-
    bucketing rule (inference.engine uses the same for prompt buckets)."""
    b = lo
    while b < n:
        b *= 2
    return b


def prompt_lookup_draft(context, k: int, max_ngram: int = 3):
    """Prompt-lookup decoding draft (model-free speculation): find the
    most recent earlier occurrence of the context's trailing n-gram
    (longest n <= `max_ngram` first) and propose the k tokens that
    followed it. Returns an int32 [k] array, or None when no n-gram of
    the context's tail recurs — the caller decides the fallback. Pure
    host-side numpy: drafting is control flow, only verification burns
    accelerator FLOPs (inference.engine's verify program).
    """
    ctx = np.asarray(context).reshape(-1)
    t = int(ctx.shape[0])
    for n in range(min(max_ngram, t - 1), 0, -1):
        tail = ctx[t - n:]
        # scan candidate starts right-to-left: the most recent match is
        # the best predictor of what follows
        for s in range(t - n - 1, -1, -1):
            if not np.array_equal(ctx[s:s + n], tail):
                continue
            follow = ctx[s + n:s + n + k]
            if follow.shape[0] == 0:
                continue
            draft = np.empty(k, np.int32)
            draft[:follow.shape[0]] = follow
            # short match: pad by repeating the last drafted token
            draft[follow.shape[0]:] = follow[-1]
            return draft
    return None


def _engine_for(model, use_engine, prompt_len: int, total_len: int):
    """The attached decode engine (inference.enable_decode_engine) when it
    can serve this call, else None. `use_engine=False` forces the legacy
    loop; `use_engine=None` auto-selects. A request the engine cannot hold
    (prompt beyond its largest bucket, or total length beyond its cache)
    silently falls back to the legacy loop rather than failing."""
    if use_engine is False:
        return None
    eng = getattr(model, "_decode_engine", None)
    if eng is None:
        return None
    if total_len > eng.config.max_length or prompt_len > eng.buckets[-1]:
        return None
    return eng


def _check_length(model, needed: int):
    """Out-of-range position embeddings clamp SILENTLY under XLA gather —
    raise up front instead of returning corrupted tokens."""
    cfg = getattr(model, "config", None)
    limit = getattr(cfg, "max_position_embeddings", None)
    if limit is not None and needed > limit:
        raise ValueError(
            f"generation needs {needed} positions but the model supports "
            f"max_position_embeddings={limit}"
        )


def _sample_next(logits_row, top_k, top_p, temperature, rng):
    """numpy sampling over one [V] logits row (host-side: decoding control
    flow is data-dependent by nature)."""
    x = np.asarray(logits_row, np.float64)
    if temperature is None:
        temperature = 1.0
    if temperature <= 0.0:
        return int(x.argmax())  # temperature -> 0 degenerates to greedy
    if temperature != 1.0:
        x = x / temperature
    if top_k and top_k > 0:
        k = min(int(top_k), len(x))  # clamp like the reference
        kth = np.partition(x, -k)[-k]
        x = np.where(x < kth, -np.inf, x)
    p = np.exp(x - x.max())
    p = p / p.sum()
    if top_p and top_p < 1.0:
        order = np.argsort(-p)
        csum = np.cumsum(p[order])
        # keep the smallest prefix whose cumulative prob REACHES top_p
        # (standard nucleus semantics: include the crossing token)
        cut = np.concatenate([[True], csum[:-1] < top_p])
        keep = order[cut]
        mask = np.zeros_like(p, bool)
        mask[keep] = True
        p = np.where(mask, p, 0.0)
        p = p / p.sum()
    return int(rng.choice(len(p), p=p))


def _next_tokens(last, do_sample, top_k, top_p, temperature, rng):
    """[B, V] logits -> [B] next token ids (shared by every decode loop)."""
    if do_sample:
        return np.array([
            _sample_next(last[i], top_k, top_p, temperature, rng)
            for i in range(last.shape[0])
        ])
    return last.argmax(-1)


@_without_grad
def generate(
    model,
    input_ids,
    max_new_tokens: int = 32,
    do_sample: bool = False,
    top_k: int = 0,
    top_p: float = 1.0,
    temperature: float = 1.0,
    eos_token_id: Optional[int] = None,
    pad_token_id: Optional[int] = None,
    seed: Optional[int] = None,
    use_engine: Optional[bool] = None,
):
    """Decode continuations for a batch of prompts.

    Args:
      model: a causal LM returning [B, T, V] logits when called without
        labels (GPTForCausalLM / LlamaForCausalLM or compatible).
      input_ids: [B, T0] prompt tokens (Tensor or array).
      do_sample: False = greedy; True = top-k / nucleus sampling.
      use_engine: None auto-routes through the KV-cached decode engine
        when one is attached (inference.enable_decode_engine, see
        docs/SERVING.md); False forces the legacy loop. Engine sampling
        runs on device with per-request streams, so sampled outputs for
        a given `seed` differ between the two paths (greedy is
        identical).
    Returns [B, T0 + n] token ids (numpy), n <= max_new_tokens (stops early
    when every sequence has emitted eos).

    The legacy fallback right-pads the growing sequence to power-of-two
    length buckets (padding is inert under the causal mask), so one call
    compiles O(log max_new_tokens) programs instead of one per emitted
    token.
    """
    was_training = getattr(model, "training", False)
    if hasattr(model, "eval"):
        model.eval()
    try:
        ids = np.asarray(raw(input_ids))
        b, t0 = ids.shape
        total = t0 + max_new_tokens
        eng = _engine_for(model, use_engine, t0, total)
        if eng is not None:
            out = eng.generate_batch(
                ids, max_new_tokens=max_new_tokens, do_sample=do_sample,
                top_k=top_k, top_p=top_p, temperature=temperature,
                eos_token_id=eos_token_id, pad_token_id=pad_token_id,
                seed=seed)
            return np.asarray(raw(out))
        rng = np.random.default_rng(seed)
        done = np.zeros(b, bool)
        filler = pad_token_id if pad_token_id is not None else eos_token_id
        _check_length(model, total)
        # any valid id works as bucket padding: padded positions sit to the
        # RIGHT of every position we read, and causal attention never looks
        # forward
        bucket_fill = filler if filler is not None else 0
        for _ in range(max_new_tokens):
            cur = ids.shape[1]
            tb = min(_pow2_bucket(cur), total)
            if tb > cur:
                pad = np.full((b, tb - cur), bucket_fill, ids.dtype)
                feed = np.concatenate([ids, pad], axis=1)
            else:
                feed = ids
            logits = model(Tensor(feed))
            last = np.asarray(raw(logits))[:, cur - 1, :]  # [B, V]
            nxt = _next_tokens(last, do_sample, top_k, top_p, temperature, rng)
            if eos_token_id is not None:
                nxt = np.where(done, filler, nxt)
                done |= nxt == eos_token_id
            ids = np.concatenate([ids, nxt[:, None].astype(ids.dtype)], axis=1)
            if eos_token_id is not None and done.all():
                break
        return ids
    finally:
        if was_training and hasattr(model, "train"):
            model.train()


@_without_grad
def generate_padded(
    model,
    input_ids,
    max_length: int,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    use_engine: Optional[bool] = None,
):
    """Greedy decode with ONE fixed shape: the sequence is right-padded to
    `max_length` so every step re-runs the same compiled program (the
    TPU serving discipline — no per-length recompilation). When a decode
    engine is attached (inference.enable_decode_engine) the call routes
    through its KV-cached continuous-batching loop instead — same greedy
    tokens, O(1) work per emitted token rather than a full forward."""
    was_training = getattr(model, "training", False)
    if hasattr(model, "eval"):
        model.eval()
    try:
        ids = np.asarray(raw(input_ids))
        b, t0 = ids.shape
        if t0 >= max_length:
            raise ValueError(
                f"prompt length {t0} already >= max_length {max_length}"
            )
        eng = _engine_for(model, use_engine, t0, max_length)
        if eng is not None:
            out = eng.generate_batch(
                ids, max_new_tokens=max_length - t0,
                eos_token_id=eos_token_id, pad_token_id=pad_token_id)
            return np.asarray(raw(out))
        _check_length(model, max_length)
        buf = np.full((b, max_length), pad_token_id, ids.dtype)
        buf[:, :t0] = ids
        done = np.zeros(b, bool)
        cur = t0
        while cur < max_length:
            logits = model(Tensor(buf))  # fixed [B, max_length, V]
            last = np.asarray(raw(logits))[:, cur - 1, :]
            nxt = last.argmax(-1).astype(ids.dtype)
            if eos_token_id is not None:
                nxt = np.where(done, pad_token_id, nxt)
                done |= nxt == eos_token_id
            buf[:, cur] = nxt
            cur += 1
            if eos_token_id is not None and done.all():
                break
        return buf[:, :cur]
    finally:
        if was_training and hasattr(model, "train"):
            model.train()


@_without_grad
def beam_search(
    model,
    input_ids,
    max_new_tokens: int = 32,
    num_beams: int = 4,
    length_penalty: float = 1.0,
    eos_token_id: Optional[int] = None,
):
    """Beam-search decode (PaddleNLP GenerationMixin beam semantics).

    Host-side beam bookkeeping over the jit-cached forward; scores are
    sum of log-probs, length-normalized by len**length_penalty at finish.
    Returns [B, T0 + n] best sequences.
    """
    was_training = getattr(model, "training", False)
    if hasattr(model, "eval"):
        model.eval()
    try:
        ids0 = np.asarray(raw(input_ids))
        b, t0 = ids0.shape
        _check_length(model, t0 + max_new_tokens)
        results = []
        for row in range(b):  # per-prompt beams (batch sizes here are small)
            beams = [(0.0, ids0[row])]  # (logprob_sum, tokens)
            finished = []
            for _ in range(max_new_tokens):
                batch = np.stack([t for _, t in beams])
                logits = model(Tensor(batch))
                last = np.asarray(raw(logits))[:, -1, :].astype(np.float64)
                logp = last - (
                    np.log(np.exp(last - last.max(-1, keepdims=True)).sum(-1, keepdims=True))
                    + last.max(-1, keepdims=True)
                )
                cand = []
                for bi, (score, toks) in enumerate(beams):
                    top = np.argsort(-logp[bi])[: num_beams]
                    for tok in top:
                        cand.append(
                            (score + float(logp[bi][tok]),
                             np.concatenate([toks, [tok]]).astype(toks.dtype))
                        )
                cand.sort(key=lambda x: -x[0])
                beams = []
                for score, toks in cand:
                    if eos_token_id is not None and toks[-1] == eos_token_id:
                        norm = score / (len(toks) - t0) ** length_penalty
                        finished.append((norm, toks))
                    else:
                        beams.append((score, toks))
                    if len(beams) == num_beams:
                        break
                if not beams:
                    break
            for score, toks in beams:  # unfinished beams compete too
                norm = score / max(len(toks) - t0, 1) ** length_penalty
                finished.append((norm, toks))
            finished.sort(key=lambda x: -x[0])
            results.append(finished[0][1])
        width = max(len(r) for r in results)
        pad = eos_token_id if eos_token_id is not None else 0
        out = np.full((b, width), pad, ids0.dtype)
        for i, r in enumerate(results):
            out[i, : len(r)] = r
        return out
    finally:
        if was_training and hasattr(model, "train"):
            model.train()


def alloc_kv_caches(num_layers: int, batch_size: int, max_length: int,
                    num_kv_heads: int, head_dim: int):
    """Per-layer zero KV caches [B, Tmax, Hkv, D] fp32 (shared by every
    cached model: one place owns layout/dtype)."""
    import jax.numpy as jnp

    return [
        {"k": Tensor(jnp.zeros(
            (batch_size, max_length, num_kv_heads, head_dim), jnp.float32)),
         "v": Tensor(jnp.zeros(
            (batch_size, max_length, num_kv_heads, head_dim), jnp.float32))}
        for _ in range(num_layers)
    ]


@_without_grad
def run_cached_generation(model, cached_forward, init_cache, logits_fn,
                          input_ids, max_new_tokens=32, do_sample=False,
                          top_k=0, top_p=1.0, temperature=1.0,
                          eos_token_id=None, pad_token_id=None, seed=None):
    """Shared prefill + one-token-decode loop for KV-cached models.

    cached_forward(ids_tensor, caches, pos_or_None) -> hidden;
    init_cache(batch, max_len) -> caches; logits_fn(hidden) -> [B, t, V].
    """
    was_training = getattr(model, "training", False)
    if hasattr(model, "eval"):
        model.eval()
    try:
        ids = np.asarray(raw(input_ids))
        b, t0 = ids.shape
        max_len = t0 + max_new_tokens
        _check_length(model, max_len)
        rng = np.random.default_rng(seed)
        caches = init_cache(b, max_len)
        hidden = cached_forward(Tensor(ids), caches, None)  # prefill
        done = np.zeros(b, bool)
        filler = pad_token_id if pad_token_id is not None else eos_token_id
        for step in range(max_new_tokens):
            # project ONLY the final position to vocab
            last = np.asarray(raw(logits_fn(hidden[:, -1:])))[:, -1, :]
            nxt = _next_tokens(last, do_sample, top_k, top_p, temperature, rng)
            if eos_token_id is not None:
                nxt = np.where(done, filler, nxt)
                done |= nxt == eos_token_id
            ids = np.concatenate([ids, nxt[:, None].astype(ids.dtype)], axis=1)
            if (eos_token_id is not None and done.all()) \
                    or step == max_new_tokens - 1:
                break
            hidden = cached_forward(Tensor(ids[:, -1:]), caches, t0 + step)
        return ids
    finally:
        if was_training and hasattr(model, "train"):
            model.train()
