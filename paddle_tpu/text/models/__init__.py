from .gpt import (  # noqa: F401
    GPTConfig,
    GPTForCausalLM,
    GPTForPretraining,
    GPTLMHeadModel,
    GPTModel,
    GPTPretrainingCriterion,
)
from .bert import (  # noqa: F401
    BertConfig,
    BertForMaskedLM,
    BertForSequenceClassification,
    BertModel,
    BertPretrainingCriterion,
)
from .ernie import (  # noqa: F401
    ErnieConfig,
    ErnieForMaskedLM,
    ErnieForSequenceClassification,
    ErnieForTokenClassification,
    ErnieModel,
)
from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaDecoderLayer,
    LlamaForCausalLM,
    LlamaModel,
)
