"""Llama family — modern decoder-only LM: RoPE + RMSNorm + SwiGLU + GQA.

Reference capability: the Paddle ecosystem's Llama lives in PaddleNLP
(`LlamaModel`/`LlamaForCausalLM` built from the same fleet mpu layers as
GPT, with fused rope and GQA via its flash-attention integration). Core
Paddle provides the building blocks (mpu layers, flash_attn kernels).

TPU-native design mirrors paddle_tpu's GPT: mpu layer classes as sharding
annotations, bf16-friendly [B, T, H, D] attention layout. Grouped-query
attention runs through the Pallas flash kernel's native GQA path
(ops/pallas/flash_attention.py — kv heads selected in the BlockSpec index
map, no head replication in HBM); rotary embeddings are applied on the
fly from a per-block cos/sin cache (a read-only buffer, so the decoder
stacks under SpmdPipeline including its buffers).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ... import nn
from ...distributed import mesh as _mesh
from ...distributed.fleet.layers.mpu import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    mark_activation,
)
from ...distributed.fleet.utils import recompute as _recompute
from ...framework.core import Tensor
from ...framework.op import defop, raw
from ...nn import functional as F
from ...nn import initializer as I


class LlamaConfig:
    def __init__(
        self,
        vocab_size: int = 32000,
        hidden_size: int = 768,
        intermediate_size: Optional[int] = None,
        num_hidden_layers: int = 12,
        num_attention_heads: int = 12,
        num_key_value_heads: Optional[int] = None,
        max_position_embeddings: int = 2048,
        rms_norm_eps: float = 1e-6,
        rope_theta: float = 10000.0,
        initializer_range: float = 0.02,
        tie_word_embeddings: bool = False,
        use_flash_attention: bool = True,
        use_recompute: bool = False,
        sequence_parallel: bool = False,
        fold_layers: bool = False,
        recompute_granularity: str = "full",
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        # Llama SwiGLU sizing: 8/3 * h rounded up to a multiple of 256
        self.intermediate_size = intermediate_size or (
            (int(8 * hidden_size / 3) + 255) // 256 * 256
        )
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = num_key_value_heads or num_attention_heads
        assert num_attention_heads % self.num_key_value_heads == 0
        self.max_position_embeddings = max_position_embeddings
        self.rms_norm_eps = rms_norm_eps
        self.rope_theta = rope_theta
        self.initializer_range = initializer_range
        self.tie_word_embeddings = tie_word_embeddings
        self.use_flash_attention = use_flash_attention
        self.use_recompute = use_recompute
        # see GPTConfig.recompute_granularity ("full" is required for the
        # folded/stacked layer forms; dots-saveable stacks across layers)
        self.recompute_granularity = recompute_granularity
        self.sequence_parallel = sequence_parallel
        # one lax.scan over layer-stacked params without pp: compile time
        # O(1) in depth (see GPTConfig.fold_layers; same scan machinery)
        self.fold_layers = fold_layers


def _rope_cache(max_t: int, dim: int, theta: float):
    inv = 1.0 / (theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    t = np.arange(max_t, dtype=np.float64)
    freqs = np.outer(t, inv)  # [T, dim/2]
    return (np.cos(freqs).astype(np.float32),
            np.sin(freqs).astype(np.float32))


@defop(name="apply_rope")
def _apply_rope(x, cos, sin, name=None):
    """x: [B, T, H, D]; cos/sin: [Tmax, D/2] → rotate pairs (interleaved
    halves, the Llama convention)."""
    import jax.numpy as jnp

    t = x.shape[1]
    d2 = x.shape[-1] // 2
    c = cos[:t][None, :, None, :]  # [1, T, 1, D/2]
    s = sin[:t][None, :, None, :]
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1).astype(
        x.dtype
    )


@defop(name="gqa_flash_attention")
def _gqa_attention(q, k, v, causal=True):
    """[B, T, H, D] x [B, T, Hkv, D] — Pallas flash kernel, native GQA."""
    from ...ops.pallas.flash_attention import flash_attention

    return flash_attention(q, k, v, causal=causal)


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = h // self.num_heads
        kv_h = self.num_kv_heads * self.head_dim
        self.q_proj = ColumnParallelLinear(h, h, has_bias=False, gather_output=False)
        self.k_proj = ColumnParallelLinear(h, kv_h, has_bias=False, gather_output=False)
        self.v_proj = ColumnParallelLinear(h, kv_h, has_bias=False, gather_output=False)
        self.o_proj = RowParallelLinear(h, h, has_bias=False, input_is_parallel=True)
        cos, sin = _rope_cache(
            config.max_position_embeddings, self.head_dim, config.rope_theta
        )
        import jax.numpy as jnp

        self.register_buffer("rope_cos", Tensor(jnp.asarray(cos)))
        self.register_buffer("rope_sin", Tensor(jnp.asarray(sin)))
        self.use_flash = config.use_flash_attention

    def forward(self, x):
        b, t, h = x.shape
        q = self.q_proj(x).reshape([b, t, self.num_heads, self.head_dim])
        k = self.k_proj(x).reshape([b, t, self.num_kv_heads, self.head_dim])
        v = self.v_proj(x).reshape([b, t, self.num_kv_heads, self.head_dim])
        q = _apply_rope(q, self.rope_cos, self.rope_sin)
        k = _apply_rope(k, self.rope_cos, self.rope_sin)
        if self.use_flash:
            o = _gqa_attention(q, k, v, causal=True)
        else:
            from ... import tensor as pt

            group = self.num_heads // self.num_kv_heads
            o = F.scaled_dot_product_attention(
                q,
                pt.repeat_interleave(k, group, axis=2),
                pt.repeat_interleave(v, group, axis=2),
                is_causal=True,
                training=self.training,
            )
        return self.o_proj(o.reshape([b, t, h]))


class LlamaMLP(nn.Layer):
    """SwiGLU: down(silu(gate(x)) * up(x))."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        self.gate_proj = ColumnParallelLinear(h, i, has_bias=False, gather_output=False)
        self.up_proj = ColumnParallelLinear(h, i, has_bias=False, gather_output=False)
        self.down_proj = RowParallelLinear(i, h, has_bias=False, input_is_parallel=True)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    """Pre-RMSNorm block — structurally uniform → SpmdPipeline-stackable
    (its rope caches stack as read-only buffers)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(
            config.hidden_size, epsilon=config.rms_norm_eps
        )
        self.mlp = LlamaMLP(config)
        self._use_recompute = config.use_recompute
        self._recompute_granularity = getattr(
            config, "recompute_granularity", "full")
        self._sequence_parallel = config.sequence_parallel

    def _block(self, x):
        x = x + self.self_attn(self.input_layernorm(x))
        x = x + self.mlp(self.post_attention_layernorm(x))
        if self._sequence_parallel:
            x = mark_activation(x, seq_mp=True)
        return x

    def forward(self, x):
        if self._use_recompute:
            return _recompute(self._block, x,
                              granularity=self._recompute_granularity)
        return self._block(x)


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size,
            weight_attr=nn.ParamAttr(initializer=I.Normal(std=config.initializer_range)),
        )
        blocks = [LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)]
        pp = _mesh.mesh_axis_size("pp")
        if pp > 1 and config.num_hidden_layers % pp == 0:
            from ...distributed.fleet.meta_parallel.pipeline_parallel import (
                SpmdPipeline,
            )

            self.layers = SpmdPipeline(
                blocks, num_stages=pp, recompute_block=config.use_recompute,
                recompute_granularity=getattr(
                    config, "recompute_granularity", "full"),
                # per-model overrides; None defers to DistributedStrategy
                # pipeline_configs / PADDLE_TPU_PP_SCHEDULE
                num_virtual_stages=getattr(config, "virtual_pp_degree", None),
                schedule=getattr(config, "pp_schedule", None),
            )
        else:
            if pp > 1:
                import warnings

                warnings.warn(
                    f"num_hidden_layers={config.num_hidden_layers} not "
                    f"divisible by pp_degree={pp}: Llama decoder runs "
                    "WITHOUT pipeline partitioning"
                )
            from ...distributed.fleet.meta_parallel.pipeline_parallel import (
                fold_or_list,
            )

            self.layers = fold_or_list(
                blocks, getattr(config, "fold_layers", False),
                recompute=config.use_recompute,
                recompute_granularity=getattr(
                    config, "recompute_granularity", "full"))
        self.norm = nn.RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids):
        from ...distributed.fleet.meta_parallel.pipeline_parallel import (
            run_stack,
        )

        x = self.embed_tokens(input_ids)
        x = run_stack(self.layers, x)
        return self.norm(x)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.llama = LlamaModel(config)
        self.config = config
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False,
                gather_output=False,
            )
        self.criterion = ParallelCrossEntropy(ignore_index=-100)

    def _logits(self, hidden):
        if self.config.tie_word_embeddings:
            w = self.llama.embed_tokens.weight
            logits = F.linear(hidden, w.t())
            return mark_activation(logits, last_mp=True)
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, loss_mask=None):
        hidden = self.llama(input_ids)
        logits = self._logits(hidden)
        if labels is not None:
            loss = self.criterion(logits, labels)
            if loss_mask is not None:
                lm = loss_mask.reshape(loss.shape)
                return (loss * lm).sum() / lm.sum().clip(min=1.0)
            # average over VALID tokens: ignore_index positions contribute
            # zero loss and must not deflate the mean (HF Llama semantics)
            valid = (labels.reshape(loss.shape) != -100).astype(loss.dtype)
            return loss.sum() / valid.sum().clip(min=1.0)
        return logits


# ---------------------------------------------------------------------------
# KV-cache incremental decoding (reference: PaddleNLP Llama `use_cache` path
# over fused attention with cache_kv). TPU shape discipline: caches are
# PREALLOCATED [B, Tmax, Hkv, D] buffers updated in place by position, so a
# jitted decode step has one fixed signature for the whole generation.
# ---------------------------------------------------------------------------

@defop(name="rope_at")
def _apply_rope_at(x, cos, sin, pos):
    """Rotate a single-step [B, 1, H, D] tensor at absolute position `pos`."""
    import jax
    import jax.numpy as jnp

    d2 = x.shape[-1] // 2
    c = jax.lax.dynamic_slice_in_dim(cos, pos, 1, 0)[None, :, None, :]
    s = jax.lax.dynamic_slice_in_dim(sin, pos, 1, 0)[None, :, None, :]
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
    ).astype(x.dtype)


@defop(name="rope_positions")
def _apply_rope_positions(x, cos, sin, positions):
    """Rotate [B, T, H, D] at explicit ABSOLUTE positions — the serving
    engine's form of rope: `positions` is an int array [T] (shared across
    the batch, prefill) or [B, T] (per-slot decode), gathered from the
    cos/sin cache instead of sliced, so per-slot decode positions stay a
    single compiled program."""
    import jax.numpy as jnp

    d2 = x.shape[-1] // 2
    pos = jnp.asarray(positions)
    c = jnp.take(cos, pos, axis=0)[..., None, :]  # [(B,) T, 1, D/2]
    s = jnp.take(sin, pos, axis=0)[..., None, :]
    if pos.ndim == 1:
        c, s = c[None], s[None]
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1
    ).astype(x.dtype)


@defop(name="cache_write")
def _cache_write(cache, kv, pos):
    """cache [B, Tmax, Hkv, D] <- kv [B, T, Hkv, D] at [pos : pos+T]."""
    import jax

    return jax.lax.dynamic_update_slice_in_dim(cache, kv.astype(cache.dtype), pos, 1)


def _decode_attention(q, ck, cv, pos):
    """One-step attention against the cache: q [B, 1, H, D] over
    ck/cv [B, Tmax, Hkv, D], positions > pos masked out.

    Thin adapter over ``F.decode_attention`` — the single decode-shape
    reference oracle (nn/functional/attention.py, GQA-native: no head
    replication): swap the cache to its [B, Hkv, Tmax, D] layout and
    broadcast the scalar position per slot. The head grouping (query
    head h -> kv head h // group) is identical on both sides."""
    import jax.numpy as jnp

    b = raw(q).shape[0]
    ckt = jnp.swapaxes(raw(ck), 1, 2)  # [B, Hkv, Tmax, D]
    cvt = jnp.swapaxes(raw(cv), 1, 2)
    return F.decode_attention(q, ckt, cvt, jnp.full((b,), pos, jnp.int32))


def _attn_prefill(attn: "LlamaAttention", x, cache):
    b, t, h = x.shape
    q = attn.q_proj(x).reshape([b, t, attn.num_heads, attn.head_dim])
    k = attn.k_proj(x).reshape([b, t, attn.num_kv_heads, attn.head_dim])
    v = attn.v_proj(x).reshape([b, t, attn.num_kv_heads, attn.head_dim])
    q = _apply_rope(q, attn.rope_cos, attn.rope_sin)
    k = _apply_rope(k, attn.rope_cos, attn.rope_sin)
    cache["k"] = _cache_write(cache["k"], k, 0)
    cache["v"] = _cache_write(cache["v"], v, 0)
    if attn.use_flash:
        o = _gqa_attention(q, k, v, causal=True)
    else:
        from ... import tensor as pt

        group = attn.num_heads // attn.num_kv_heads
        o = F.scaled_dot_product_attention(
            q, pt.repeat_interleave(k, group, axis=2),
            pt.repeat_interleave(v, group, axis=2), is_causal=True,
            training=False,
        )
    return attn.o_proj(o.reshape([b, t, h]))


def _attn_decode(attn: "LlamaAttention", x, cache, pos: int):
    b, t, h = x.shape  # t == 1
    q = attn.q_proj(x).reshape([b, t, attn.num_heads, attn.head_dim])
    k = attn.k_proj(x).reshape([b, t, attn.num_kv_heads, attn.head_dim])
    v = attn.v_proj(x).reshape([b, t, attn.num_kv_heads, attn.head_dim])
    q = _apply_rope_at(q, attn.rope_cos, attn.rope_sin, pos=pos)
    k = _apply_rope_at(k, attn.rope_cos, attn.rope_sin, pos=pos)
    cache["k"] = _cache_write(cache["k"], k, pos)
    cache["v"] = _cache_write(cache["v"], v, pos)
    o = _decode_attention(q, cache["k"], cache["v"], pos=pos)
    return attn.o_proj(o.reshape([b, t, h]))


def _layer_step(layer: "LlamaDecoderLayer", x, cache, pos: Optional[int]):
    h = layer.input_layernorm(x)
    if pos is None:
        a = _attn_prefill(layer.self_attn, h, cache)
    else:
        a = _attn_decode(layer.self_attn, h, cache, pos)
    x = x + a
    return x + layer.mlp(layer.post_attention_layernorm(x))


def _llama_cached_forward(self, input_ids, caches, pos: Optional[int]):
    if not isinstance(self.layers, nn.LayerList):
        raise NotImplementedError(
            "KV-cache decoding requires the non-pipelined decoder "
            "(pp_degree=1); pipelined serving uses generate_padded"
        )
    x = self.embed_tokens(input_ids)
    for blk, cache in zip(self.layers, caches):
        x = _layer_step(blk, x, cache, pos)
    return self.norm(x)


def _llama_init_cache(self, batch_size: int, max_length: int):
    from ..generation import alloc_kv_caches

    c = self.config
    return alloc_kv_caches(
        c.num_hidden_layers, batch_size, max_length, c.num_key_value_heads,
        c.hidden_size // c.num_attention_heads,
    )


def _llama_generate(self, input_ids, max_new_tokens: int = 32,
                    do_sample: bool = False, top_k: int = 0, top_p: float = 1.0,
                    temperature: float = 1.0, eos_token_id=None,
                    pad_token_id=None, seed=None):
    """KV-cached generation: one prefill over the prompt, then one-token
    decode steps against the preallocated caches (see
    text.generation.run_cached_generation for the shared loop)."""
    from ..generation import run_cached_generation

    return run_cached_generation(
        self,
        lambda ids, caches, pos: _llama_cached_forward(self.llama, ids, caches, pos),
        lambda b, n: _llama_init_cache(self.llama, b, n),
        self._logits,
        input_ids, max_new_tokens=max_new_tokens, do_sample=do_sample,
        top_k=top_k, top_p=top_p, temperature=temperature,
        eos_token_id=eos_token_id, pad_token_id=pad_token_id, seed=seed,
    )


LlamaForCausalLM.generate = _llama_generate


# ---------------------------------------------------------------------------
# Serving decode-engine adapter (inference/engine.py; see the GPT twin in
# gpt.py for the contract). Rope is applied inside qkv() at the engine's
# explicit positions so prefill buckets and per-slot decode share one code
# path.
# ---------------------------------------------------------------------------


class _LlamaDecodeAdapter:
    def __init__(self, lm: "LlamaForCausalLM"):
        if not isinstance(lm.llama.layers, nn.LayerList):
            raise NotImplementedError(
                "the decode engine requires the non-pipelined, unfolded "
                "decoder (pp_degree=1, fold_layers=False)"
            )
        cfg = lm.config
        self.lm = lm
        self.blocks = list(lm.llama.layers)
        self.num_layers = cfg.num_hidden_layers
        self.num_heads = cfg.num_attention_heads
        self.num_kv_heads = cfg.num_key_value_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.max_positions = cfg.max_position_embeddings
        # positions may arrive [B, T] with a DIFFERENT offset per row
        # (the engine's speculative verify step); both the learned
        # position table and rope gather per-element, so [B, T] is
        # first-class here
        self.multi_token_positions = True

    def embed(self, input_ids, positions):
        return self.lm.llama.embed_tokens(input_ids)

    def pre_attn(self, layer, x):
        return self.blocks[layer].input_layernorm(x)

    def qkv(self, layer, h, positions):
        attn = self.blocks[layer].self_attn
        b, t = h.shape[0], h.shape[1]
        q = attn.q_proj(h).reshape([b, t, attn.num_heads, attn.head_dim])
        k = attn.k_proj(h).reshape([b, t, attn.num_kv_heads, attn.head_dim])
        v = attn.v_proj(h).reshape([b, t, attn.num_kv_heads, attn.head_dim])
        q = _apply_rope_positions(q, attn.rope_cos, attn.rope_sin, positions)
        k = _apply_rope_positions(k, attn.rope_cos, attn.rope_sin, positions)
        return q, k, v

    def attn_out(self, layer, o):
        attn = self.blocks[layer].self_attn
        b, t = o.shape[0], o.shape[1]
        return attn.o_proj(
            o.reshape([b, t, attn.num_heads * attn.head_dim]))

    def mlp(self, layer, x):
        blk = self.blocks[layer]
        return blk.mlp(blk.post_attention_layernorm(x))

    def final_norm(self, x):
        return self.lm.llama.norm(x)

    def logits(self, hidden):
        return self.lm._logits(hidden)


def _llama_decode_adapter(self):
    return _LlamaDecodeAdapter(self)


LlamaForCausalLM.decode_adapter = _llama_decode_adapter
