"""GPT family — decoder-only LM, hybrid-parallel-ready (the flagship model).

Reference capability (SURVEY.md §6 workloads "GPT-3 1.3B (dp+mp)",
"GPT-3 6.7B (pp+sharding)"): the Paddle ecosystem's GPT lives in
PaddleNLP/fleetx (`GPTModel`, `GPTForPretraining`, `GPTPretrainingCriterion`)
built from fleet mpu layers (`VocabParallelEmbedding`,
`ColumnParallelLinear`/`RowParallelLinear`) with 1F1B pipeline and
sequence-parallel options.

TPU-native design: the same layer classes (they ARE sharding annotations
here), flash attention on the MXU-friendly [B, T, H, D] layout, bf16-first,
and the transformer body built as a list of identical blocks so
`SpmdPipeline` can stack them (layer-dim scan → one compiled block, or pp
circular schedule over the mesh). The causal mask is folded into attention
(no materialized [T,T] mask tensor in HBM).
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

from ... import nn
from ...nn import functional as F
from ...nn import initializer as I
from ...framework.core import Tensor
from ...distributed import mesh as _mesh
from ...distributed.fleet.layers.mpu import (
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    mark_activation,
    mp_wire_linear,
)
from ...distributed.fleet.utils import recompute as _recompute


class GPTConfig:
    """Static model hyperparameters (mirrors PaddleNLP GPTConfig fields)."""

    def __init__(
        self,
        vocab_size: int = 50304,
        hidden_size: int = 768,
        num_hidden_layers: int = 12,
        num_attention_heads: int = 12,
        intermediate_size: Optional[int] = None,
        hidden_act: str = "gelu",
        max_position_embeddings: int = 1024,
        hidden_dropout_prob: float = 0.1,
        attention_probs_dropout_prob: float = 0.1,
        initializer_range: float = 0.02,
        use_recompute: bool = False,
        use_flash_attention: bool = True,
        sequence_parallel: bool = False,
        tie_word_embeddings: bool = True,
        layer_norm_epsilon: float = 1e-5,
        fold_layers: bool = False,
        recompute_granularity: str = "full",
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.hidden_act = hidden_act
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.initializer_range = initializer_range
        self.use_recompute = use_recompute
        # recompute_granularity (reference GPT knob, same default): "full"
        # saves only block inputs — the OOM-safe choice, and REQUIRED for
        # folded/stacked layers where saved intermediates stack across the
        # lax.scan layer dim (see fleet/utils/recompute_helper.py);
        # "full_attn"/"core_attn" keep matmul outputs (dots_saveable) —
        # on an UNFOLDED stack with HBM headroom they trade memory for a
        # faster backward (no matmul re-execution) and are the better pick.
        self.recompute_granularity = recompute_granularity
        self.use_flash_attention = use_flash_attention
        self.sequence_parallel = sequence_parallel
        self.tie_word_embeddings = tie_word_embeddings
        self.layer_norm_epsilon = layer_norm_epsilon
        # fold_layers: build the decoder as ONE lax.scan over layer-stacked
        # parameters even without pipeline parallelism. XLA then compiles a
        # single block body instead of num_hidden_layers unrolled copies —
        # compile time drops from O(layers) to O(1) (the jax large-model
        # idiom; same mechanism SpmdPipeline uses per stage). Checkpoint
        # keys become the stacked `decoder.*__stacked` form.
        self.fold_layers = fold_layers

    # canonical sizes (PaddleNLP gpt configs / GPT-3 table)
    @staticmethod
    def gpt2_small(**kw):
        return GPTConfig(hidden_size=768, num_hidden_layers=12, num_attention_heads=12, **kw)

    @staticmethod
    def gpt3_1p3b(**kw):
        kw.setdefault("num_hidden_layers", 24)
        kw.setdefault("max_position_embeddings", 2048)
        return GPTConfig(hidden_size=2048, num_attention_heads=16, **kw)

    @staticmethod
    def gpt3_6p7b(**kw):
        kw.setdefault("num_hidden_layers", 32)
        return GPTConfig(hidden_size=4096, num_attention_heads=32,
                         max_position_embeddings=2048, **kw)


class GPTEmbeddings(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.word_embeddings = VocabParallelEmbedding(
            config.vocab_size, config.hidden_size,
            weight_attr=nn.ParamAttr(initializer=I.Normal(std=config.initializer_range)),
        )
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=nn.ParamAttr(initializer=I.Normal(std=config.initializer_range)),
        )
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, position_ids=None):
        from ... import tensor as pt

        if position_ids is None:
            seq = input_ids.shape[1]
            position_ids = pt.arange(0, seq, dtype="int64")
        emb = self.word_embeddings(input_ids) + self.position_embeddings(position_ids)
        return self.dropout(emb)


class GPTAttention(nn.Layer):
    """Causal self-attention: fused mp-sharded QKV projection + flash kernel."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, gather_output=False)
        self.out_proj = RowParallelLinear(h, h, input_is_parallel=True)
        self.dropout_p = config.attention_probs_dropout_prob
        self.use_flash = config.use_flash_attention

    def forward(self, x):
        b, t, h = x.shape
        qkv = self.qkv_proj(x)  # [b, t, 3h] (hidden mp-sharded)
        # head-major fused layout [H, 3, d]: an mp shard of the flat 3h dim
        # is a whole group of heads, so the reshape keeps the activation
        # sharded instead of forcing a GSPMD re-replication all-gather
        qkv = qkv.reshape([b, t, self.num_heads, 3, self.head_dim])
        q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]  # [b, t, H, d]
        out = F.scaled_dot_product_attention(
            q, k, v, dropout_p=self.dropout_p, is_causal=True, training=self.training
        )
        out = out.reshape([b, t, h])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.fc_in = ColumnParallelLinear(config.hidden_size, config.intermediate_size, gather_output=False)
        self.fc_out = RowParallelLinear(config.intermediate_size, config.hidden_size, input_is_parallel=True)
        self.act = F.gelu if config.hidden_act == "gelu" else getattr(F, config.hidden_act)

    def forward(self, x):
        return self.fc_out(self.act(self.fc_in(x)))


class GPTDecoderLayer(nn.Layer):
    """Pre-LN block. Structurally uniform across depth → SpmdPipeline-stackable."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.mlp = GPTMLP(config)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self._use_recompute = config.use_recompute
        self._recompute_granularity = getattr(
            config, "recompute_granularity", "full")
        self._sequence_parallel = config.sequence_parallel

    def _block(self, x):
        x = x + self.dropout(self.attn(self.ln_1(x)))
        x = x + self.dropout(self.mlp(self.ln_2(x)))
        if self._sequence_parallel:
            x = mark_activation(x, seq_mp=True)
        return x

    def forward(self, x):
        if self._use_recompute:
            return _recompute(self._block, x,
                              granularity=self._recompute_granularity)
        return self._block(x)


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        blocks = [GPTDecoderLayer(config) for _ in range(config.num_hidden_layers)]
        pp = _mesh.mesh_axis_size("pp")
        if pp > 1 and config.num_hidden_layers % pp == 0:
            from ...distributed.fleet.meta_parallel.pipeline_parallel import SpmdPipeline

            self.decoder = SpmdPipeline(
                blocks, num_stages=pp, recompute_block=config.use_recompute,
                recompute_granularity=getattr(
                    config, "recompute_granularity", "full"),
                # per-model overrides; None defers to DistributedStrategy
                # pipeline_configs / PADDLE_TPU_PP_SCHEDULE
                num_virtual_stages=getattr(config, "virtual_pp_degree", None),
                schedule=getattr(config, "pp_schedule", None),
            )
        else:
            from ...distributed.fleet.meta_parallel.pipeline_parallel import (
                fold_or_list,
            )

            self.decoder = fold_or_list(
                blocks, getattr(config, "fold_layers", False),
                recompute=config.use_recompute,
                recompute_granularity=getattr(
                    config, "recompute_granularity", "full"))
        self.final_layernorm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None):
        from ...distributed.fleet.meta_parallel.pipeline_parallel import (
            run_stack,
        )

        x = self.embeddings(input_ids, position_ids)
        x = run_stack(self.decoder, x)
        return self.final_layernorm(x)


class GPTPretrainingCriterion(nn.Layer):
    """Masked LM loss over mp-sharded logits (reference:
    GPTPretrainingCriterion with c_softmax_with_cross_entropy)."""

    def __init__(self, config: Optional[GPTConfig] = None):
        super().__init__()
        self.ce = ParallelCrossEntropy(ignore_index=-100)

    def forward(self, logits, labels, loss_mask=None):
        loss = self.ce(logits, labels)
        if loss_mask is not None:
            lm = loss_mask.reshape(loss.shape)
            return (loss * lm).sum() / lm.sum().clip(min=1.0)
        return loss.mean()


class GPTForCausalLM(nn.Layer):
    """GPT with a (tied) LM head — PaddleNLP GPTForCausalLM/GPTForPretraining."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)
        self.config = config
        if not config.tie_word_embeddings:
            self.lm_head = ColumnParallelLinear(
                config.hidden_size, config.vocab_size, has_bias=False, gather_output=False
            )
        self.criterion = GPTPretrainingCriterion(config)

    def _logits(self, hidden):
        if self.config.tie_word_embeddings:
            emb = self.gpt.embeddings.word_embeddings
            w = emb.weight  # [V, h], mp-sharded on V
            # column-form tied head: rides the quantized backward wire
            # when the mp_comm activation wire is on (exact F.linear off)
            logits = mp_wire_linear(hidden, w.t(), emb.world_size)
            return mark_activation(logits, last_mp=True)
        return self.lm_head(hidden)

    def forward(self, input_ids, position_ids=None, labels=None, loss_mask=None):
        hidden = self.gpt(input_ids, position_ids)
        logits = self._logits(hidden)
        if labels is not None:
            return self.criterion(logits, labels, loss_mask)
        return logits


GPTLMHeadModel = GPTForCausalLM
GPTForPretraining = GPTForCausalLM


# ---------------------------------------------------------------------------
# KV-cache incremental decoding (mirrors llama.py; shares the cache-write and
# cache-attention defops and the text.generation loop). llama never imports
# gpt, so this import is cycle-free.
# ---------------------------------------------------------------------------
from .llama import _cache_write, _decode_attention  # noqa: E402

def _gpt_qkv(attn: "GPTAttention", x):
    """The SAME projection+split GPTAttention.forward performs (one place)."""
    b, t, _ = x.shape
    qkv = attn.qkv_proj(x).reshape([b, t, attn.num_heads, 3, attn.head_dim])
    return qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]


def _gpt_attn_cached(attn: "GPTAttention", x, cache, pos):
    """Prefill (pos None) or one-step decode (pos int) against the cache."""
    b, t, h = x.shape
    q, k, v = _gpt_qkv(attn, x)
    cache["k"] = _cache_write(cache["k"], k, 0 if pos is None else pos)
    cache["v"] = _cache_write(cache["v"], v, 0 if pos is None else pos)
    if pos is None:
        o = F.scaled_dot_product_attention(
            q, k, v, is_causal=True, training=False
        )
    else:
        o = _decode_attention(q, cache["k"], cache["v"], pos=pos)
    return attn.out_proj(o.reshape([b, t, h]))


def _gpt_cached_forward(model: "GPTModel", input_ids, caches, pos):
    from ... import tensor as pt

    if not isinstance(model.decoder, nn.LayerList):
        raise NotImplementedError(
            "KV-cache decoding requires the non-pipelined decoder "
            "(pp_degree=1); pipelined serving uses generate_padded"
        )
    if pos is None:
        x = model.embeddings(input_ids)
    else:
        position_ids = pt.arange(pos, pos + 1, dtype="int64")
        x = model.embeddings(input_ids, position_ids)
    for blk, cache in zip(model.decoder, caches):
        x = x + _gpt_attn_cached(blk.attn, blk.ln_1(x), cache, pos)
        x = x + blk.mlp(blk.ln_2(x))
    return model.final_layernorm(x)


def _gpt_init_cache(model: "GPTModel", batch_size: int, max_length: int):
    from ..generation import alloc_kv_caches

    c = model.config
    return alloc_kv_caches(
        c.num_hidden_layers, batch_size, max_length, c.num_attention_heads,
        c.hidden_size // c.num_attention_heads,
    )


def _gpt_generate(self, input_ids, max_new_tokens: int = 32,
                  do_sample: bool = False, top_k: int = 0, top_p: float = 1.0,
                  temperature: float = 1.0, eos_token_id=None,
                  pad_token_id=None, seed=None):
    from ..generation import run_cached_generation

    return run_cached_generation(
        self,
        lambda ids, caches, pos: _gpt_cached_forward(self.gpt, ids, caches, pos),
        lambda b, n: _gpt_init_cache(self.gpt, b, n),
        self._logits,
        input_ids, max_new_tokens=max_new_tokens, do_sample=do_sample,
        top_k=top_k, top_p=top_p, temperature=temperature,
        eos_token_id=eos_token_id, pad_token_id=pad_token_id, seed=seed,
    )


GPTForCausalLM.generate = _gpt_generate


# ---------------------------------------------------------------------------
# Serving decode-engine adapter (inference/engine.py). The engine owns the
# residual stream and the slot-indexed KV cache; the adapter exposes the
# per-layer hooks (norm / qkv / out-proj / mlp) plus the geometry the engine
# needs to size its [L, S, Hkv, Tmax, D] cache. One engine loop then serves
# every decoder-only model family.
# ---------------------------------------------------------------------------


class _GPTDecodeAdapter:
    def __init__(self, lm: "GPTForCausalLM"):
        if not isinstance(lm.gpt.decoder, nn.LayerList):
            raise NotImplementedError(
                "the decode engine requires the non-pipelined, unfolded "
                "decoder (pp_degree=1, fold_layers=False)"
            )
        cfg = lm.config
        self.lm = lm
        self.blocks = list(lm.gpt.decoder)
        self.num_layers = cfg.num_hidden_layers
        self.num_heads = cfg.num_attention_heads
        self.num_kv_heads = cfg.num_attention_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.max_positions = cfg.max_position_embeddings
        # positions may arrive [B, T] with a DIFFERENT offset per row
        # (the engine's speculative verify step); both the learned
        # position table and rope gather per-element, so [B, T] is
        # first-class here
        self.multi_token_positions = True

    def embed(self, input_ids, positions):
        """input_ids Tensor [B, T]; positions int array [T] or [B, T]."""
        import jax.numpy as jnp

        return self.lm.gpt.embeddings(
            input_ids, Tensor(jnp.asarray(positions)))

    def pre_attn(self, layer, x):
        return self.blocks[layer].ln_1(x)

    def qkv(self, layer, h, positions):
        return _gpt_qkv(self.blocks[layer].attn, h)

    def attn_out(self, layer, o):
        attn = self.blocks[layer].attn
        b, t = o.shape[0], o.shape[1]
        return attn.out_proj(
            o.reshape([b, t, attn.num_heads * attn.head_dim]))

    def mlp(self, layer, x):
        blk = self.blocks[layer]
        return blk.mlp(blk.ln_2(x))

    def final_norm(self, x):
        return self.lm.gpt.final_layernorm(x)

    def logits(self, hidden):
        return self.lm._logits(hidden)


def _gpt_decode_adapter(self):
    return _GPTDecodeAdapter(self)


GPTForCausalLM.decode_adapter = _gpt_decode_adapter
