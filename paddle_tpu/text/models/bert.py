"""BERT family (encoder LM) — PaddleNLP BertModel parity, TPU-native.

Reference capability (SURVEY.md §6 workloads "BERT-base MLM (data-parallel)"):
PaddleNLP `BertModel` / `BertForMaskedLM` / `BertForSequenceClassification` /
`BertForPretraining` built on paddle.nn.TransformerEncoder.

TPU-native notes: encoder blocks use the same mp-shardable projections as GPT
(so mp/dp hybrid works out of the box), attention runs on the flash kernel
with an additive padding mask, and blocks are uniform for SpmdPipeline
stacking.
"""
from __future__ import annotations

from typing import Optional

from ... import nn
from ...nn import functional as F
from ...nn import initializer as I
from ...distributed.fleet.layers.mpu import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)


class BertConfig:
    def __init__(
        self,
        vocab_size: int = 30522,
        hidden_size: int = 768,
        num_hidden_layers: int = 12,
        num_attention_heads: int = 12,
        intermediate_size: int = 3072,
        hidden_act: str = "gelu",
        hidden_dropout_prob: float = 0.1,
        attention_probs_dropout_prob: float = 0.1,
        max_position_embeddings: int = 512,
        type_vocab_size: int = 2,
        initializer_range: float = 0.02,
        pad_token_id: int = 0,
        layer_norm_eps: float = 1e-12,
        use_flash_attention: bool = True,
        fold_layers: bool = False,
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.pad_token_id = pad_token_id
        self.layer_norm_eps = layer_norm_eps
        self.use_flash_attention = use_flash_attention
        # one lax.scan over layer-stacked params (see GPTConfig.fold_layers)
        self.fold_layers = fold_layers


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        init = nn.ParamAttr(initializer=I.Normal(std=config.initializer_range))
        self.word_embeddings = VocabParallelEmbedding(config.vocab_size, config.hidden_size, weight_attr=init)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings, config.hidden_size, weight_attr=init)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size, config.hidden_size, weight_attr=init)
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        from ... import tensor as pt

        seq = input_ids.shape[1]
        if position_ids is None:
            position_ids = pt.arange(0, seq, dtype="int64")
        if token_type_ids is None:
            token_type_ids = pt.zeros_like(input_ids)
        emb = (
            self.word_embeddings(input_ids)
            + self.position_embeddings(position_ids)
            + self.token_type_embeddings(token_type_ids)
        )
        return self.dropout(self.layer_norm(emb))


class BertSelfAttention(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        self.qkv_proj = ColumnParallelLinear(h, 3 * h, gather_output=False)
        self.out_proj = RowParallelLinear(h, h, input_is_parallel=True)
        self.dropout_p = config.attention_probs_dropout_prob

    def forward(self, x, attn_mask=None):
        b, t, h = x.shape
        # head-major fused layout [H, 3, d] — keeps the mp-sharded 3h dim
        # reshape shard-local (see GPTAttention.forward)
        qkv = self.qkv_proj(x).reshape([b, t, self.num_heads, 3, self.head_dim])
        q, k, v = qkv[:, :, :, 0], qkv[:, :, :, 1], qkv[:, :, :, 2]
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout_p,
            is_causal=False, training=self.training,
        )
        return self.out_proj(out.reshape([b, t, h]))


class BertLayer(nn.Layer):
    """Post-LN encoder block (BERT convention)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(config)
        self.ln_1 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.fc_in = ColumnParallelLinear(config.hidden_size, config.intermediate_size, gather_output=False)
        self.fc_out = RowParallelLinear(config.intermediate_size, config.hidden_size, input_is_parallel=True)
        self.ln_2 = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.act = F.gelu if config.hidden_act == "gelu" else getattr(F, config.hidden_act)

    def forward(self, x, attn_mask=None):
        x = self.ln_1(x + self.dropout(self.attention(x, attn_mask)))
        x = self.ln_2(x + self.dropout(self.fc_out(self.act(self.fc_in(x)))))
        return x


class BertPooler(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, hidden):
        return F.tanh(self.dense(hidden[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig, add_pooling_layer: bool = True):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        from ...distributed.fleet.meta_parallel.pipeline_parallel import (
            fold_or_list,
        )

        self.encoder = fold_or_list(
            [BertLayer(config) for _ in range(config.num_hidden_layers)],
            getattr(config, "fold_layers", False))
        self.pooler = BertPooler(config) if add_pooling_layer else None

    def forward(self, input_ids, token_type_ids=None, position_ids=None, attention_mask=None):
        mask = None
        if attention_mask is not None:
            # [b, t] (1 = keep) → additive [b, 1, 1, t] on logits
            from ...framework.op import raw
            import jax.numpy as jnp

            m = raw(attention_mask)
            mask = ((1.0 - m.astype(jnp.float32)) * -1e9)[:, None, None, :]
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        from ...distributed.fleet.meta_parallel.pipeline_parallel import (
            run_stack,
        )

        x = run_stack(self.encoder, x, *(() if mask is None else (mask,)))
        pooled = self.pooler(x) if self.pooler is not None else None
        return x, pooled


class BertLMPredictionHead(nn.Layer):
    def __init__(self, config: BertConfig, embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.act = F.gelu
        self._tied = embedding_weights
        if embedding_weights is None:
            self.decoder = nn.Linear(config.hidden_size, config.vocab_size)
        self.decoder_bias = self.create_parameter([config.vocab_size], is_bias=True)

    def forward(self, hidden):
        h = self.layer_norm(self.act(self.transform(hidden)))
        if self._tied is not None:
            return F.linear(h, self._tied.t()) + self.decoder_bias
        return self.decoder(h) + self.decoder_bias


class BertForMaskedLM(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config, add_pooling_layer=False)
        self.cls = BertLMPredictionHead(config, self.bert.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        hidden, _ = self.bert(input_ids, token_type_ids, attention_mask=attention_mask)
        logits = self.cls(hidden)
        if labels is not None:
            return F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]),
                ignore_index=-100,
            )
        return logits


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2, dropout=None):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = nn.Dropout(dropout if dropout is not None else config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels)
        return logits


class BertPretrainingCriterion(nn.Layer):
    def __init__(self, vocab_size: int):
        super().__init__()
        self.vocab_size = vocab_size

    def forward(self, prediction_scores, seq_relationship_score, masked_lm_labels, next_sentence_labels=None):
        mlm = F.cross_entropy(
            prediction_scores.reshape([-1, self.vocab_size]),
            masked_lm_labels.reshape([-1]),
            ignore_index=-100,
        )
        if next_sentence_labels is not None and seq_relationship_score is not None:
            nsp = F.cross_entropy(seq_relationship_score, next_sentence_labels.reshape([-1]))
            return mlm + nsp
        return mlm
