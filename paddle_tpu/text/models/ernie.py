"""ERNIE family (Baidu's BERT-style encoder with task-type embeddings).

Reference capability (SURVEY.md §6 "ERNIE-3.0-base fine-tune (dygraph)" —
the headline workload of BASELINE.json): PaddleNLP `ErnieModel` /
`ErnieForMaskedLM` / `ErnieForSequenceClassification`. Architecturally an
encoder transformer like BERT plus a `task_type` embedding table (ERNIE 3.0)
and relu/gelu FFN; we share the BERT blocks (same mp-shardable projections).
"""
from __future__ import annotations

from typing import Optional

from ... import nn
from ...nn import functional as F
from ...nn import initializer as I
from .bert import BertLayer, BertPooler


class ErnieConfig:
    def __init__(
        self,
        vocab_size: int = 40000,
        hidden_size: int = 768,
        num_hidden_layers: int = 12,
        num_attention_heads: int = 12,
        intermediate_size: int = 3072,
        hidden_act: str = "gelu",
        hidden_dropout_prob: float = 0.1,
        attention_probs_dropout_prob: float = 0.1,
        max_position_embeddings: int = 2048,
        type_vocab_size: int = 4,
        task_type_vocab_size: int = 3,
        use_task_id: bool = True,
        initializer_range: float = 0.02,
        pad_token_id: int = 0,
        layer_norm_eps: float = 1e-12,
        use_flash_attention: bool = True,
        fold_layers: bool = False,
    ):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.task_type_vocab_size = task_type_vocab_size
        self.use_task_id = use_task_id
        self.initializer_range = initializer_range
        self.pad_token_id = pad_token_id
        self.layer_norm_eps = layer_norm_eps
        self.use_flash_attention = use_flash_attention
        # one lax.scan over layer-stacked params (see GPTConfig.fold_layers)
        self.fold_layers = fold_layers

    @staticmethod
    def ernie3_base(**kw):
        return ErnieConfig(hidden_size=768, num_hidden_layers=12, num_attention_heads=12, **kw)


class ErnieEmbeddings(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        from ...distributed.fleet.layers.mpu import VocabParallelEmbedding

        init = nn.ParamAttr(initializer=I.Normal(std=config.initializer_range))
        self.word_embeddings = VocabParallelEmbedding(config.vocab_size, config.hidden_size, weight_attr=init)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings, config.hidden_size, weight_attr=init)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size, config.hidden_size, weight_attr=init)
        self.use_task_id = config.use_task_id
        if config.use_task_id:
            self.task_type_embeddings = nn.Embedding(config.task_type_vocab_size, config.hidden_size, weight_attr=init)
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None, task_type_ids=None):
        from ... import tensor as pt

        seq = input_ids.shape[1]
        if position_ids is None:
            position_ids = pt.arange(0, seq, dtype="int64")
        if token_type_ids is None:
            token_type_ids = pt.zeros_like(input_ids)
        emb = (
            self.word_embeddings(input_ids)
            + self.position_embeddings(position_ids)
            + self.token_type_embeddings(token_type_ids)
        )
        if self.use_task_id:
            if task_type_ids is None:
                task_type_ids = pt.zeros_like(input_ids)
            emb = emb + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(emb))


class _ErnieBlockConfig:
    """Adapter so BertLayer can consume ErnieConfig fields."""

    def __init__(self, c: ErnieConfig):
        self.hidden_size = c.hidden_size
        self.num_attention_heads = c.num_attention_heads
        self.intermediate_size = c.intermediate_size
        self.hidden_act = c.hidden_act
        self.hidden_dropout_prob = c.hidden_dropout_prob
        self.attention_probs_dropout_prob = c.attention_probs_dropout_prob
        self.layer_norm_eps = c.layer_norm_eps
        self.use_flash_attention = c.use_flash_attention


class ErnieModel(nn.Layer):
    def __init__(self, config: ErnieConfig, add_pooling_layer: bool = True):
        super().__init__()
        self.config = config
        bc = _ErnieBlockConfig(config)
        self.embeddings = ErnieEmbeddings(config)
        from ...distributed.fleet.meta_parallel.pipeline_parallel import (
            fold_or_list,
        )

        self.encoder = fold_or_list(
            [BertLayer(bc) for _ in range(config.num_hidden_layers)],
            getattr(config, "fold_layers", False))
        self.pooler = BertPooler(bc) if add_pooling_layer else None

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        mask = None
        if attention_mask is not None:
            from ...framework.op import raw
            import jax.numpy as jnp

            m = raw(attention_mask)
            mask = ((1.0 - m.astype(jnp.float32)) * -1e9)[:, None, None, :]
        x = self.embeddings(input_ids, token_type_ids, position_ids, task_type_ids)
        from ...distributed.fleet.meta_parallel.pipeline_parallel import (
            run_stack,
        )

        x = run_stack(self.encoder, x, *(() if mask is None else (mask,)))
        pooled = self.pooler(x) if self.pooler is not None else None
        return x, pooled


class ErnieForMaskedLM(nn.Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        from .bert import BertLMPredictionHead

        self.ernie = ErnieModel(config, add_pooling_layer=False)
        head_cfg = _ErnieBlockConfig(config)
        head_cfg.vocab_size = config.vocab_size
        self.cls = BertLMPredictionHead(head_cfg, self.ernie.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        hidden, _ = self.ernie(input_ids, token_type_ids, attention_mask=attention_mask)
        logits = self.cls(hidden)
        if labels is not None:
            return F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]),
                ignore_index=-100,
            )
        return logits


class ErnieForSequenceClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2, dropout=None):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = nn.Dropout(dropout if dropout is not None else config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        _, pooled = self.ernie(input_ids, token_type_ids, attention_mask=attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            return F.cross_entropy(logits, labels)
        return logits


class ErnieForTokenClassification(nn.Layer):
    def __init__(self, config: ErnieConfig, num_classes: int = 2, dropout=None):
        super().__init__()
        self.ernie = ErnieModel(config, add_pooling_layer=False)
        self.dropout = nn.Dropout(dropout if dropout is not None else config.hidden_dropout_prob)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, labels=None):
        hidden, _ = self.ernie(input_ids, token_type_ids, attention_mask=attention_mask)
        logits = self.classifier(self.dropout(hidden))
        if labels is not None:
            return F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1])
            )
        return logits
