"""paddle_tpu.text — text model zoo + dataset helpers.

Reference: `python/paddle/text/` (datasets) and the PaddleNLP model zoo the
BASELINE workloads are drawn from (SURVEY.md §6): BERT-base MLM, ERNIE-3.0
fine-tune, GPT-3 pretraining configs.
"""
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from .models import *  # noqa: F401,F403
from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
from .viterbi import ViterbiDecoder, viterbi_decode  # noqa: F401
from . import generation  # noqa: F401
from .generation import beam_search, generate, generate_padded  # noqa: F401
