"""paddle.text.viterbi_decode / ViterbiDecoder parity.

Reference: ``python/paddle/text/viterbi_decode.py`` (phi viterbi_decode
kernel). TPU-native: the DP recursion is a lax.scan over time — one compiled
program, batch-parallel on the MXU (the [B, N, N] score broadcast is a
batched matrix of adds, not a Python loop).
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..framework.core import Tensor
from ..framework.op import defop, raw


@defop(name="viterbi_decode_op")
def _viterbi(potentials, transition, lengths, include_bos_eos_tag):
    """potentials [B, T, N]; transition [N, N]; lengths [B] → (scores [B],
    paths [B, T])."""
    B, T, N = potentials.shape
    if include_bos_eos_tag:
        # reference convention: last two tags are BOS(start)/EOS(stop); the
        # BOS transition row scores starting in each tag
        bos = N - 2
        alpha0 = potentials[:, 0] + transition[bos][None, :]
    else:
        alpha0 = potentials[:, 0]

    def step(alpha, t):
        # score[b, i, j] = alpha[b, i] + trans[i, j] + emit[b, t, j]
        scores = alpha[:, :, None] + transition[None, :, :]
        best_prev = scores.argmax(axis=1)  # [B, N]
        best_score = scores.max(axis=1) + potentials[:, t]
        # positions past a sequence's length keep their alpha (masked)
        active = (t < lengths)[:, None]
        alpha_new = jnp.where(active, best_score, alpha)
        back = jnp.where(active, best_prev, jnp.arange(N)[None, :])
        return alpha_new, back

    alpha, backs = lax.scan(step, alpha0, jnp.arange(1, T))  # backs: [T-1, B, N]

    if include_bos_eos_tag:
        alpha = alpha + transition[:, N - 1][None, :]

    last_tag = alpha.argmax(axis=-1)  # [B]
    scores = alpha.max(axis=-1)

    def backtrack(carry, back_t):
        # carry = tag at time t+1; back_t[b, j] = best tag at t given j at t+1
        prev = jnp.take_along_axis(back_t, carry[:, None], axis=1)[:, 0]
        return prev, prev

    _, path_rev = lax.scan(backtrack, last_tag, backs, reverse=True)
    paths = jnp.concatenate(
        [path_rev, last_tag[None, :]], axis=0
    ).T  # [B, T]
    return scores, paths.astype(jnp.int32)


def viterbi_decode(potentials, transition_params, lengths, include_bos_eos_tag=True, name=None):
    scores, paths = _viterbi(
        potentials, transition_params, lengths,
        include_bos_eos_tag=bool(include_bos_eos_tag),
    )
    return scores, paths


class ViterbiDecoder:
    """paddle.text.ViterbiDecoder parity (callable layer-like)."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths):
        return viterbi_decode(
            potentials, self.transitions, lengths, self.include_bos_eos_tag
        )
