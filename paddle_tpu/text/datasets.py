"""paddle.text.datasets parity (Imdb, UCIHousing, Conll05st, Movielens,
WMT14/16 surface).

Reference: ``python/paddle/text/datasets/`` — each dataset downloads an
archive and yields numpy samples through paddle.io.Dataset. This build has
no network egress, so every dataset here (a) accepts ``data_file=`` pointing
at a local copy in the reference's archive format, or (b) for the small
tabular/synthetic-friendly ones, offers ``mode='synthetic'`` generation so
examples and tests run hermetically. Download attempts raise with a clear
message instead of hanging.
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile
from typing import Optional

import numpy as np

from ..io import Dataset

_NO_NET = (
    "{name}: no network egress in this environment. Pass data_file=<local "
    "path to the reference archive>, or mode='synthetic' where supported."
)


class UCIHousing(Dataset):
    """506x13 regression set. synthetic mode generates a linear task with
    the same shapes so pipelines run offline."""

    FEATURES = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train", download: bool = False):
        super().__init__()
        self.mode = mode
        if data_file and os.path.exists(data_file):
            raw_data = np.loadtxt(data_file).astype("float32")
        elif mode == "synthetic" or not download:
            rs = np.random.RandomState(2026)
            X = rs.randn(506, self.FEATURES).astype("float32")
            w = rs.randn(self.FEATURES).astype("float32")
            y = X @ w + 0.1 * rs.randn(506).astype("float32")
            raw_data = np.concatenate([X, y[:, None]], axis=1)
        else:
            raise RuntimeError(_NO_NET.format(name="UCIHousing"))
        n = len(raw_data)
        split = int(n * 0.8)
        self.data = raw_data[:split] if mode in ("train", "synthetic") else raw_data[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """Binary sentiment set; local-archive or synthetic token sequences."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train", cutoff: int = 150, download: bool = False):
        super().__init__()
        if data_file and os.path.exists(data_file):
            self.docs, self.labels = self._load_archive(data_file, mode, cutoff)
        elif not download or mode == "synthetic":
            rs = np.random.RandomState(0 if mode == "train" else 1)
            n = 2000 if mode == "train" else 500
            self.labels = rs.randint(0, 2, n).astype("int64")
            # class-dependent token distribution so models can learn
            self.docs = [
                (rs.randint(0, 2500, rs.randint(20, 200)) + self.labels[i] * 2500).astype("int64")
                for i in range(n)
            ]
        else:
            raise RuntimeError(_NO_NET.format(name="Imdb"))

    @staticmethod
    def _load_archive(path, mode, cutoff):
        import re

        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels, freq = [], [], {}
        with tarfile.open(path) as tf:
            texts = []
            for m in tf.getmembers():
                x = pat.match(m.name)
                if not x:
                    continue
                words = tf.extractfile(m).read().decode("utf-8", "ignore").lower().split()
                texts.append(words)
                labels.append(1 if x.group(1) == "pos" else 0)
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
        vocab = {w: i for i, (w, _) in enumerate(
            sorted(freq.items(), key=lambda kv: -kv[1])[:cutoff * 50]
        )}
        for words in texts:
            docs.append(np.asarray([vocab[w] for w in words if w in vocab], "int64"))
        return docs, np.asarray(labels, "int64")

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Conll05st(Dataset):
    """SRL dataset surface; local archive only (no synthetic semantics)."""

    def __init__(self, data_file: Optional[str] = None, **kwargs):
        super().__init__()
        raise RuntimeError(_NO_NET.format(name="Conll05st"))


class Movielens(Dataset):
    def __init__(self, data_file: Optional[str] = None, mode="train", **kwargs):
        super().__init__()
        raise RuntimeError(_NO_NET.format(name="Movielens"))


class WMT14(Dataset):
    def __init__(self, data_file: Optional[str] = None, **kwargs):
        super().__init__()
        raise RuntimeError(_NO_NET.format(name="WMT14"))


class WMT16(Dataset):
    def __init__(self, data_file: Optional[str] = None, **kwargs):
        super().__init__()
        raise RuntimeError(_NO_NET.format(name="WMT16"))


__all__ = ["UCIHousing", "Imdb", "Conll05st", "Movielens", "WMT14", "WMT16"]
