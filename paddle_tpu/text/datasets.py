"""paddle.text.datasets parity (Imdb, UCIHousing, Conll05st, Movielens,
WMT14/16 surface).

Reference: ``python/paddle/text/datasets/`` — each dataset downloads an
archive and yields numpy samples through paddle.io.Dataset. This build has
no network egress, so every dataset here (a) accepts ``data_file=`` pointing
at a local copy in the reference's archive format, or (b) for the small
tabular/synthetic-friendly ones, offers ``mode='synthetic'`` generation so
examples and tests run hermetically. Download attempts raise with a clear
message instead of hanging.
"""
from __future__ import annotations

import gzip
import os
import pickle
import tarfile
from typing import Optional

import numpy as np

from ..io import Dataset

_NO_NET = (
    "{name}: no network egress in this environment. Pass data_file=<local "
    "path to the reference archive>, or mode='synthetic' where supported."
)


class UCIHousing(Dataset):
    """506x13 regression set. synthetic mode generates a linear task with
    the same shapes so pipelines run offline."""

    FEATURES = 13

    def __init__(self, data_file: Optional[str] = None, mode: str = "train", download: bool = False):
        super().__init__()
        self.mode = mode
        if data_file and os.path.exists(data_file):
            raw_data = np.loadtxt(data_file).astype("float32")
        elif mode == "synthetic" or not download:
            rs = np.random.RandomState(2026)
            X = rs.randn(506, self.FEATURES).astype("float32")
            w = rs.randn(self.FEATURES).astype("float32")
            y = X @ w + 0.1 * rs.randn(506).astype("float32")
            raw_data = np.concatenate([X, y[:, None]], axis=1)
        else:
            raise RuntimeError(_NO_NET.format(name="UCIHousing"))
        n = len(raw_data)
        split = int(n * 0.8)
        self.data = raw_data[:split] if mode in ("train", "synthetic") else raw_data[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class Imdb(Dataset):
    """Binary sentiment set; local-archive or synthetic token sequences."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train", cutoff: int = 150, download: bool = False):
        super().__init__()
        if data_file and os.path.exists(data_file):
            self.docs, self.labels = self._load_archive(data_file, mode, cutoff)
        elif not download or mode == "synthetic":
            rs = np.random.RandomState(0 if mode == "train" else 1)
            n = 2000 if mode == "train" else 500
            self.labels = rs.randint(0, 2, n).astype("int64")
            # class-dependent token distribution so models can learn
            self.docs = [
                (rs.randint(0, 2500, rs.randint(20, 200)) + self.labels[i] * 2500).astype("int64")
                for i in range(n)
            ]
        else:
            raise RuntimeError(_NO_NET.format(name="Imdb"))

    @staticmethod
    def _load_archive(path, mode, cutoff):
        import re

        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        docs, labels, freq = [], [], {}
        with tarfile.open(path) as tf:
            texts = []
            for m in tf.getmembers():
                x = pat.match(m.name)
                if not x:
                    continue
                words = tf.extractfile(m).read().decode("utf-8", "ignore").lower().split()
                texts.append(words)
                labels.append(1 if x.group(1) == "pos" else 0)
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
        vocab = {w: i for i, (w, _) in enumerate(
            sorted(freq.items(), key=lambda kv: -kv[1])[:cutoff * 50]
        )}
        for words in texts:
            docs.append(np.asarray([vocab[w] for w in words if w in vocab], "int64"))
        return docs, np.asarray(labels, "int64")

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Conll05st(Dataset):
    """SRL dataset (CoNLL-2005 column format). Samples mirror the reference's
    tuple: (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_id, mark,
    label_ids). Local column files (``word<TAB>...<TAB>predicate<TAB>label``
    per token, blank line between sentences) or mode='synthetic'."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 word_dict: Optional[dict] = None,
                 label_dict: Optional[dict] = None,
                 download: bool = False, **kwargs):
        super().__init__()
        # expose the vocabularies so train/test splits can share ids
        # (reference ships fixed dict files; pass word_dict/label_dict from
        # the train split when constructing the test split)
        self.word_dict = {} if word_dict is None else word_dict
        self.label_dict = {} if label_dict is None else label_dict
        # only grow vocabularies we own; a supplied dict (from the train
        # split) stays frozen so test construction can't shift train ids
        grow = word_dict is None
        if data_file:
            if not os.path.exists(data_file):
                raise FileNotFoundError(f"Conll05st data_file: {data_file}")
            sents = self._parse_columns(
                data_file, self.word_dict, self.label_dict, grow)
        elif mode == "synthetic":
            rs = np.random.RandomState(7 if mode == "train" else 8)
            sents = []
            for _ in range(200 if mode == "train" else 50):
                n = rs.randint(5, 30)
                words = rs.randint(0, 5000, n).astype("int64")
                pred = int(rs.randint(0, n))
                labels = rs.randint(0, 67, n).astype("int64")
                sents.append((words, pred, labels))
        else:
            raise RuntimeError(_NO_NET.format(name="Conll05st"))
        self.samples = [self._featurize(w, p, l) for w, p, l in sents]

    @staticmethod
    def _parse_columns(path, vocab, labvoc, grow=True):
        def wid(w, voc):
            if grow:
                return voc.setdefault(w, len(voc))
            return voc.get(w, voc.get("<unk>", 0))

        opener = gzip.open if path.endswith(".gz") else open
        sents, words, preds, labels = [], [], [], []
        with opener(path, "rt") as f:
            for line in f:
                line = line.strip()
                if not line:
                    if words:
                        pred = preds.index(True) if True in preds else 0
                        sents.append((np.asarray(words, "int64"), pred,
                                      np.asarray(labels, "int64")))
                    words, preds, labels = [], [], []
                    continue
                cols = line.split()
                w, lab = cols[0].lower(), cols[-1]
                words.append(wid(w, vocab))
                preds.append(len(cols) > 2 and cols[-2] != "-")
                labels.append(wid(lab, labvoc))
        if words:
            pred = preds.index(True) if True in preds else 0
            sents.append((np.asarray(words, "int64"), pred,
                          np.asarray(labels, "int64")))
        return sents

    @staticmethod
    def _featurize(words, pred, labels):
        n = len(words)
        pad = lambda i: words[min(max(i, 0), n - 1)]
        ctx = [np.asarray([pad(i + d) for i in range(n)], "int64")
               for d in (-2, -1, 0, 1, 2)]
        mark = np.zeros(n, "int64")
        mark[pred] = 1
        pred_ids = np.full(n, words[pred], "int64")
        return (words, *ctx, pred_ids, mark, labels)

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Movielens(Dataset):
    """MovieLens-1M rating prediction. Samples mirror the reference:
    (user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
    rating). Parses a local ml-1m archive (zip/tar/directory with
    ``ratings.dat``/``users.dat``/``movies.dat``, ``::``-separated) or
    generates a synthetic set with the same field spaces."""

    AGES = [1, 18, 25, 35, 45, 50, 56]

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0,
                 download: bool = False, **kwargs):
        super().__init__()
        if data_file:
            if not os.path.exists(data_file):
                raise FileNotFoundError(f"Movielens data_file: {data_file}")
            users, movies, ratings = self._read_archive(data_file)
        elif mode == "synthetic":
            rs = np.random.RandomState(11)
            users = {u: (u % 2, u % len(self.AGES), u % 21)
                     for u in range(1, 301)}
            movies = {m: ([m % 18, (m * 7) % 18],
                          list(rs.randint(0, 5000, 1 + m % 8)))
                      for m in range(1, 201)}
            ratings = [(int(rs.randint(1, 301)), int(rs.randint(1, 201)),
                        float(rs.randint(1, 6))) for _ in range(4000)]
        else:
            raise RuntimeError(_NO_NET.format(name="Movielens"))
        rs = np.random.RandomState(rand_seed)
        keep_test = rs.rand(len(ratings)) < test_ratio
        self.samples = []
        for (u, m, r), is_test in zip(ratings, keep_test):
            if (mode == "test") != is_test and mode != "synthetic":
                continue
            if u not in users or m not in movies:
                continue
            g, a, j = users[u]
            cats, title = movies[m]
            self.samples.append((
                np.asarray([u], "int64"), np.asarray([g], "int64"),
                np.asarray([a], "int64"), np.asarray([j], "int64"),
                np.asarray([m], "int64"), np.asarray(cats, "int64"),
                np.asarray(title, "int64"), np.asarray([r], "float32"),
            ))

    @classmethod
    def _read_archive(cls, path):
        def read_members(get):
            users, movies, ratings = {}, {}, []
            cat_voc, title_voc = {}, {}
            for line in get("users.dat"):
                uid, gender, age, job = line.split("::")[:4]
                users[int(uid)] = (
                    0 if gender == "M" else 1,
                    cls.AGES.index(int(age)) if int(age) in cls.AGES else 0,
                    int(job),
                )
            for line in get("movies.dat"):
                mid, title, cats = line.split("::")[:3]
                cat_ids = [cat_voc.setdefault(c, len(cat_voc))
                           for c in cats.strip().split("|")]
                title_ids = [title_voc.setdefault(w, len(title_voc))
                             for w in title.lower().split()]
                movies[int(mid)] = (cat_ids, title_ids)
            for line in get("ratings.dat"):
                uid, mid, r = line.split("::")[:3]
                ratings.append((int(uid), int(mid), float(r)))
            return users, movies, ratings

        def decode(b):
            return b.decode("latin-1").strip()

        if os.path.isdir(path):
            def get(name):
                with open(os.path.join(path, name), encoding="latin-1") as f:
                    return [l.strip() for l in f if l.strip()]

            return read_members(get)
        if path.endswith(".zip"):
            import zipfile

            with zipfile.ZipFile(path) as zf:
                names = {os.path.basename(n): n for n in zf.namelist()}
                return read_members(lambda name: [
                    decode(l) for l in zf.read(names[name]).splitlines()
                    if l.strip()])
        with tarfile.open(path) as tf:
            names = {os.path.basename(m.name): m for m in tf.getmembers()}
            return read_members(lambda name: [
                decode(l) for l in tf.extractfile(names[name]).read().splitlines()
                if l.strip()])

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class _WMTBase(Dataset):
    """Shared machinery for the WMT parallel-corpus surfaces: local
    tab-separated ``source<TAB>target`` text (optionally .gz / inside a tar),
    or synthetic paired token sequences. Samples are
    (src_ids, trg_ids, trg_ids_next) like the reference.

    ``mode`` selects the member whose basename contains it when data_file is
    a tar of splits; a plain text/gz file IS one split, so mode is ignored
    there — point each split's Dataset at its own file. Pass the train
    split's ``src_dict``/``trg_dict`` into the test split so ids agree."""

    NAME = "WMT"

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 dict_size: int = 3000, src_dict: Optional[dict] = None,
                 trg_dict: Optional[dict] = None,
                 download: bool = False, **kwargs):
        super().__init__()
        self.dict_size = dict_size
        base = {"<s>": 0, "<e>": 1, "<unk>": 2}
        self.src_dict = dict(base) if src_dict is None else src_dict
        self.trg_dict = dict(base) if trg_dict is None else trg_dict
        # a supplied dict stays frozen (unseen words -> <unk>) so the test
        # split can't grow or shift the train split's vocabulary
        self._grow = src_dict is None
        if data_file:
            if not os.path.exists(data_file):
                raise FileNotFoundError(f"{self.NAME} data_file: {data_file}")
            pairs = self._parse(data_file, mode)
            if not pairs:
                raise ValueError(
                    f"{self.NAME}: no '{mode}' pairs found in {data_file} "
                    "(tar members are matched by basename substring; text "
                    "files need source<TAB>target lines)")
        elif mode == "synthetic":
            rs = np.random.RandomState(3 if mode == "train" else 4)
            pairs = []
            for _ in range(500 if mode == "train" else 100):
                n = rs.randint(4, 30)
                src = rs.randint(3, dict_size, n).astype("int64")
                trg = np.asarray(
                    [(t * 13 + 7) % dict_size for t in src][: max(3, n - 2)],
                    "int64",
                )
                pairs.append((src, trg))
        else:
            raise RuntimeError(_NO_NET.format(name=self.NAME))
        self.samples = []
        for src, trg in pairs:
            trg_in = np.concatenate([[0], trg]).astype("int64")   # <s> = 0
            trg_next = np.concatenate([trg, [1]]).astype("int64")  # <e> = 1
            self.samples.append((src, trg_in, trg_next))

    def _parse(self, path, mode):
        vocab_s, vocab_t = self.src_dict, self.trg_dict

        def to_ids(words, vocab):
            out = []
            for w in words:
                if self._grow and w not in vocab and len(vocab) < self.dict_size:
                    vocab[w] = len(vocab)
                out.append(vocab.get(w, 2))
            return np.asarray(out, "int64")

        def lines_of(fileobj):
            for raw_line in fileobj:
                line = raw_line.decode("utf-8", "ignore") if isinstance(raw_line, bytes) else raw_line
                if "\t" in line:
                    s, t = line.rstrip("\n").split("\t", 1)
                    if s.strip() and t.strip():
                        yield s.strip().lower().split(), t.strip().lower().split()

        pairs = []
        if tarfile.is_tarfile(path):
            with tarfile.open(path) as tf:
                for m in tf.getmembers():
                    if m.isfile() and mode in os.path.basename(m.name):
                        for s, t in lines_of(tf.extractfile(m)):
                            pairs.append((to_ids(s, vocab_s), to_ids(t, vocab_t)))
        else:
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rt", encoding="utf-8", errors="ignore") as f:
                for s, t in lines_of(f):
                    pairs.append((to_ids(s, vocab_s), to_ids(t, vocab_t)))
        return pairs

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class WMT14(_WMTBase):
    NAME = "WMT14"


class WMT16(_WMTBase):
    NAME = "WMT16"


__all__ = ["UCIHousing", "Imdb", "Conll05st", "Movielens", "WMT14", "WMT16"]


class Imikolov(Dataset):
    """PTB language-model n-grams (reference: text/datasets/imikolov.py;
    the Mikolov simple-examples archive). Local ``data_file`` may be the
    .tgz archive or a plain token text file; synthetic mode generates a
    Zipf token stream with the same interface."""

    def __init__(self, data_file: Optional[str] = None, data_type: str = "NGRAM",
                 window_size: int = 5, mode: str = "train",
                 min_word_freq: int = 50, download: bool = False):
        super().__init__()
        if data_type not in ("NGRAM", "SEQ"):
            raise ValueError("data_type must be NGRAM or SEQ")
        self.data_type, self.window_size = data_type, window_size
        split = "train" if mode in ("train", "synthetic") else "valid"
        if data_file and os.path.exists(data_file):
            words = self._read_words(data_file, split)
        elif mode == "synthetic" or not download:
            rs = np.random.RandomState(0 if split == "train" else 1)
            n = 20000 if split == "train" else 4000
            # Zipf-ish stream over 2000 types (realistic frequency decay)
            words = rs.zipf(1.3, n) % 2000
            words = [f"w{t}" for t in words]
        else:
            raise RuntimeError(_NO_NET.format(name="Imikolov"))
        freq = {}
        for w in words:
            freq[w] = freq.get(w, 0) + 1
        kept = {w for w, c in freq.items() if c >= min_word_freq}
        self.word_idx = {w: i for i, w in enumerate(sorted(kept))}
        unk = len(self.word_idx)
        ids = np.asarray([self.word_idx.get(w, unk) for w in words], "int64")
        self.vocab_size = unk + 1
        if data_type == "NGRAM":
            k = window_size
            self.data = [ids[i:i + k] for i in range(len(ids) - k + 1)]
        else:
            k = window_size if window_size > 0 else 20
            self.data = [
                (ids[i:i + k], ids[i + 1:i + k + 1])
                for i in range(0, len(ids) - k - 1, k)
            ]

    @staticmethod
    def _read_words(path, split):
        name = f"ptb.{split}.txt"
        if tarfile.is_tarfile(path):
            with tarfile.open(path) as tf:
                member = next(m for m in tf.getmembers() if m.name.endswith(name))
                text = tf.extractfile(member).read().decode("utf-8")
        else:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        return text.replace("\n", " <eos> ").split()

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)
