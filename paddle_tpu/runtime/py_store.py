"""Pure-Python TCPStore fallback (same semantics as the native store).

Used only when the native runtime can't be built (no toolchain) or when
``PADDLE_STORE_FORCE_PY=1`` / chaos store-fault injection forces the Python
path; keeps ``paddle_tpu.distributed.launch`` rendezvous working everywhere.
Protocol is line-oriented and private to this module (the native and Python
stores don't interoperate — a job uses one or the other on all ranks).

Robustness contract (docs/FAULT_TOLERANCE.md):

* every socket op runs under a DEADLINE — a dead or wedged server turns
  into a ``TimeoutError`` naming the op and key, never an indefinite hang
  inside ``socket.recv``;
* connect retries with exponential backoff + jitter up to the caller's
  timeout, so a client starting before the master's listener is up (the
  normal launch race) converges without hammering the host;
* idempotent ops (get/wait/check/set/del) transparently reconnect and
  re-issue once after a dropped connection; ``add`` never auto-retries (a
  replay would double-count a rank).

Env knobs (read lazily so tests can flip them per-case):

  PADDLE_STORE_OP_TIMEOUT   deadline for non-blocking ops (set/add/check/
                            del) and the connect phase default, seconds
                            (default 60)
  PADDLE_STORE_RPC_SLACK    extra client-side slack on top of a blocking
                            get/wait's server-side timeout, seconds
                            (default 15) — the window in which a live
                            server's "timed out" reply must arrive
  PADDLE_STORE_RETRY_BASE   initial reconnect backoff, seconds (default 0.05)
  PADDLE_STORE_RETRY_CAP    max per-attempt backoff, seconds (default 2.0)
"""
from __future__ import annotations

import os
import pickle
import random
import socket
import socketserver
import struct
import threading
import time

from .. import observability as _obs


def _knob(name: str, default: float) -> float:
    v = os.environ.get(name)
    try:
        return float(v) if v not in (None, "") else default
    except ValueError:
        return default


def op_timeout() -> float:
    return _knob("PADDLE_STORE_OP_TIMEOUT", 60.0)


def rpc_slack() -> float:
    return _knob("PADDLE_STORE_RPC_SLACK", 15.0)


def _chaos():
    """The chaos harness, or None when inert — the import itself is gated
    so the normal path never pays for (or depends on) the testing pkg."""
    if os.environ.get("PADDLE_CHAOS", "0") in ("0", ""):
        return None
    from ..testing import chaos

    return chaos if chaos.store_faults_enabled() else None


def _send_msg(sock, obj, deadline=None, what="store op"):
    data = pickle.dumps(obj)
    payload = struct.pack("<Q", len(data)) + data
    if deadline is not None:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TimeoutError(f"PyTCPStore: deadline expired sending {what}")
        sock.settimeout(remaining)
    try:
        sock.sendall(payload)
    except socket.timeout as e:
        raise TimeoutError(f"PyTCPStore: timed out sending {what}") from e


def _recv_msg(sock, deadline=None, what="store op"):
    """Receive one length-prefixed message, honoring `deadline`
    (monotonic). A dead server becomes TimeoutError naming the op instead
    of an unbounded blocking recv."""

    def _read(n):
        buf = b""
        while len(buf) < n:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"PyTCPStore: timed out waiting for reply to {what}")
                sock.settimeout(remaining)
            try:
                c = sock.recv(min(1 << 16, n - len(buf)))
            except socket.timeout as e:
                raise TimeoutError(
                    f"PyTCPStore: timed out waiting for reply to {what}") from e
            if not c:
                raise ConnectionError("store connection closed")
            buf += c
        return buf

    (n,) = struct.unpack("<Q", _read(8))
    return pickle.loads(_read(n))


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr):
        self.kv = {}
        self.cv = threading.Condition()
        super().__init__(addr, _Handler)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: _Server = self.server
        while True:
            try:
                cmd, key, arg = _recv_msg(self.request)
            except (ConnectionError, EOFError, OSError, TimeoutError):
                return
            # Responses are sent OUTSIDE srv.cv: a client with a full TCP
            # buffer would otherwise block sendall while holding the global
            # lock, stalling every other rank's store op.
            if cmd == "set":
                with srv.cv:
                    srv.kv[key] = arg
                    srv.cv.notify_all()
                resp = True
            elif cmd == "get":
                deadline = time.monotonic() + arg if arg > 0 else None
                with srv.cv:
                    while key not in srv.kv:
                        remaining = None if deadline is None else deadline - time.monotonic()
                        if remaining is not None and remaining <= 0:
                            break
                        srv.cv.wait(remaining)
                    resp = srv.kv.get(key)
            elif cmd == "add":
                with srv.cv:
                    cur = int.from_bytes(srv.kv.get(key, b"\0" * 8), "little", signed=True)
                    nv = cur + arg
                    srv.kv[key] = nv.to_bytes(8, "little", signed=True)
                    srv.cv.notify_all()
                resp = nv
            elif cmd == "check":
                with srv.cv:
                    resp = key in srv.kv
            elif cmd == "del":
                with srv.cv:
                    resp = srv.kv.pop(key, None) is not None
            else:
                return
            try:
                _send_msg(self.request, resp)
            except (ConnectionError, OSError, TimeoutError):
                return


def _connect_with_backoff(host, port, timeout, why="store"):
    """Dial with exponential backoff + jitter until `timeout` elapses.

    The first attempts race the master's listener coming up — that's the
    normal launch sequence, not an error — so retry quietly, but when the
    deadline passes, say exactly who we couldn't reach and for how long."""
    deadline = time.monotonic() + timeout
    delay = _knob("PADDLE_STORE_RETRY_BASE", 0.05)
    cap = _knob("PADDLE_STORE_RETRY_CAP", 2.0)
    attempt = 0
    last_err = None
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            _obs.event("store_connect_failed", host=host, port=port, why=why,
                       attempts=attempt - 1, timeout=timeout,
                       last_error=repr(last_err))
            raise ConnectionError(
                f"PyTCPStore: cannot reach {why} at {host}:{port} after "
                f"{attempt - 1} attempts over {timeout:.1f}s "
                f"(last error: {last_err!r}) — is the master rank up, and "
                "do PADDLE_MASTER/port match on every rank?")
        try:
            sock = socket.create_connection((host, port),
                                            timeout=min(remaining, max(delay, 1.0)))
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock
        except OSError as e:
            last_err = e
            _obs.inc("store_connect_attempts_total")
            # full jitter: sleep U(0, delay), then grow the ceiling
            time.sleep(min(random.uniform(0, delay), max(0.0, remaining)))
            delay = min(delay * 2, cap)


class PyTCPStore:
    #: ops safe to re-issue after a dropped connection (`add` is excluded:
    #: replaying an increment would double-count a rank)
    _IDEMPOTENT = frozenset({"get", "check", "del", "set"})

    def __init__(self, host="127.0.0.1", port=0, is_master=False, timeout=60.0):
        self._server = None
        self._host = host
        self.timeout = float(timeout)
        if is_master:
            # Bind the master address specifically (not 0.0.0.0): master
            # election depends on non-owners failing this bind.
            self._server = _Server((host, port))
            self.port = self._server.server_address[1]
            threading.Thread(target=self._server.serve_forever, daemon=True).start()
        else:
            self.port = port
        self._sock = _connect_with_backoff(host, self.port, self.timeout)
        self._lock = threading.Lock()

    def _reconnect(self):
        try:
            self._sock.close()
        except OSError:
            pass
        _obs.inc("store_reconnect_total")
        self._sock = _connect_with_backoff(self._host, self.port, self.timeout)

    def _rpc(self, cmd, key, arg=None, op_deadline=None):
        """One request/response, under a deadline. Idempotent ops survive a
        dropped connection by reconnecting (with backoff) and re-issuing
        ONCE — covers both injected drops and a master that restarted its
        listener between ops."""
        what = f"{cmd}({key!r})"
        if op_deadline is None:
            op_deadline = time.monotonic() + op_timeout()
        chaos = _chaos()
        t0 = time.perf_counter()
        with self._lock:
            if chaos is not None:
                chaos.store_latency()
                # drops only on ops the retry path may re-issue; severing
                # an `add` would poison the counter semantics by design
                if cmd in self._IDEMPOTENT and chaos.store_should_drop():
                    try:
                        self._sock.close()
                    except OSError:
                        pass
            for retry in (False, True):
                try:
                    _send_msg(self._sock, (cmd, key, arg), op_deadline, what)
                    resp = _recv_msg(self._sock, op_deadline, what)
                    _obs.observe("store_op_seconds",
                                 time.perf_counter() - t0, op=cmd)
                    return resp
                except (ConnectionError, OSError) as e:
                    if isinstance(e, TimeoutError):
                        raise
                    if retry or cmd not in self._IDEMPOTENT:
                        raise ConnectionError(
                            f"PyTCPStore: {what} failed ({e!r}) and is not "
                            "retryable") from e
                    _obs.inc("store_op_retry_total", op=cmd)
                    self._reconnect()

    def set(self, key, value):
        data = value.encode() if isinstance(value, str) else bytes(value)
        self._rpc("set", key, data)

    def get(self, key, timeout=60.0):
        # the server blocks up to `timeout` for the key; the client allows
        # that plus slack for the reply itself — so a DEAD server is
        # distinguished from a key that simply never arrived
        deadline = time.monotonic() + float(timeout) + rpc_slack()
        v = self._rpc("get", key, float(timeout), op_deadline=deadline)
        if v is None:
            raise TimeoutError(f"PyTCPStore.get({key!r}) timed out after "
                               f"{timeout}s (key never set)")
        return v

    def add(self, key, delta=1):
        return self._rpc("add", key, int(delta))

    def wait(self, key, timeout=60.0):
        self.get(key, timeout)

    def check(self, key):
        return self._rpc("check", key)

    def delete_key(self, key):
        return self._rpc("del", key)

    # barrier lives on the TCPStore facade (runtime/__init__.py), composed
    # from add/set/wait which already delegate here.

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass
        if self._server is not None:
            self._server.shutdown()
            self._server = None
