"""Pure-Python TCPStore fallback (same semantics as the native store).

Used only when the native runtime can't be built (no toolchain); keeps
``paddle_tpu.distributed.launch`` rendezvous working everywhere. Protocol is
line-oriented and private to this module (the native and Python stores don't
interoperate — a job uses one or the other on all ranks).
"""
from __future__ import annotations

import pickle
import socket
import socketserver
import struct
import threading
import time


def _send_msg(sock, obj):
    data = pickle.dumps(obj)
    sock.sendall(struct.pack("<Q", len(data)) + data)


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        c = sock.recv(8 - len(hdr))
        if not c:
            raise ConnectionError("store connection closed")
        hdr += c
    (n,) = struct.unpack("<Q", hdr)
    data = b""
    while len(data) < n:
        c = sock.recv(min(1 << 16, n - len(data)))
        if not c:
            raise ConnectionError("store connection closed")
        data += c
    return pickle.loads(data)


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, addr):
        self.kv = {}
        self.cv = threading.Condition()
        super().__init__(addr, _Handler)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        srv: _Server = self.server
        while True:
            try:
                cmd, key, arg = _recv_msg(self.request)
            except (ConnectionError, EOFError, OSError):
                return
            # Responses are sent OUTSIDE srv.cv: a client with a full TCP
            # buffer would otherwise block sendall while holding the global
            # lock, stalling every other rank's store op.
            if cmd == "set":
                with srv.cv:
                    srv.kv[key] = arg
                    srv.cv.notify_all()
                resp = True
            elif cmd == "get":
                deadline = time.monotonic() + arg if arg > 0 else None
                with srv.cv:
                    while key not in srv.kv:
                        remaining = None if deadline is None else deadline - time.monotonic()
                        if remaining is not None and remaining <= 0:
                            break
                        srv.cv.wait(remaining)
                    resp = srv.kv.get(key)
            elif cmd == "add":
                with srv.cv:
                    cur = int.from_bytes(srv.kv.get(key, b"\0" * 8), "little", signed=True)
                    nv = cur + arg
                    srv.kv[key] = nv.to_bytes(8, "little", signed=True)
                    srv.cv.notify_all()
                resp = nv
            elif cmd == "check":
                with srv.cv:
                    resp = key in srv.kv
            elif cmd == "del":
                with srv.cv:
                    resp = srv.kv.pop(key, None) is not None
            else:
                return
            _send_msg(self.request, resp)


class PyTCPStore:
    def __init__(self, host="127.0.0.1", port=0, is_master=False, timeout=60.0):
        self._server = None
        if is_master:
            # Bind the master address specifically (not 0.0.0.0): master
            # election depends on non-owners failing this bind.
            self._server = _Server((host, port))
            self.port = self._server.server_address[1]
            threading.Thread(target=self._server.serve_forever, daemon=True).start()
        else:
            self.port = port
        deadline = time.monotonic() + timeout
        while True:
            try:
                self._sock = socket.create_connection((host, self.port), timeout=timeout)
                self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise ConnectionError(f"PyTCPStore: cannot reach {host}:{self.port}")
                time.sleep(0.1)
        self._lock = threading.Lock()

    def _rpc(self, cmd, key, arg=None):
        with self._lock:
            _send_msg(self._sock, (cmd, key, arg))
            return _recv_msg(self._sock)

    def set(self, key, value):
        data = value.encode() if isinstance(value, str) else bytes(value)
        self._rpc("set", key, data)

    def get(self, key, timeout=60.0):
        v = self._rpc("get", key, float(timeout))
        if v is None:
            raise TimeoutError(f"PyTCPStore.get({key!r}) timed out")
        return v

    def add(self, key, delta=1):
        return self._rpc("add", key, int(delta))

    def wait(self, key, timeout=60.0):
        self.get(key, timeout)

    def check(self, key):
        return self._rpc("check", key)

    def delete_key(self, key):
        return self._rpc("del", key)

    # barrier lives on the TCPStore facade (runtime/__init__.py), composed
    # from add/set/wait which already delegate here.

    def close(self):
        try:
            self._sock.close()
        except Exception:
            pass
        if self._server is not None:
            self._server.shutdown()
            self._server = None
