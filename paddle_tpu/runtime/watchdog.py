"""Heartbeat-based worker watchdog for the multiprocess SPMD path.

A hung rank (deadlocked collective, wedged host callback, stuck input
pipeline) is worse than a dead one: the job burns accelerator time forever
with no error. Every rank runs a BEAT thread that bumps a per-rank counter
in the coordination store; the monitor rank (rank 0 by default) runs a
MONITOR thread that tracks when each peer's counter last changed and, once
a peer has been silent past the miss budget, fails the job loudly with a
diagnosis naming the stalled rank(s) — turning a silent hang into a
restartable crash the elastic layer can recover from.

Env knobs (wired by ``paddle_tpu.distributed.launch --heartbeat_interval``
and read by ``maybe_start_from_env``):

  PADDLE_HEARTBEAT_INTERVAL   seconds between beats (0/unset = disabled)
  PADDLE_HEARTBEAT_MISS       beats a peer may miss before it is declared
                              stalled (default 5; grace = interval * miss)
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Dict, Optional

from .. import observability as _obs


def _default_on_stall(stalled: Dict[int, float], grace: float) -> None:
    names = ", ".join(f"rank {r} (silent {age:.0f}s)"
                      for r, age in sorted(stalled.items()))
    print(
        f"[watchdog] FATAL: {names} missed the heartbeat budget "
        f"({grace:.0f}s) — the worker is hung (deadlocked collective or "
        "wedged host loop), not dead. Failing the job so the supervisor "
        "can relaunch from the last checkpoint.",
        file=sys.stderr, flush=True)
    # os._exit, not sys.exit: the monitor must take the process down even
    # if the main thread is the thing that's wedged
    os._exit(124)


class HeartbeatWatchdog:
    """Store-backed liveness monitor.

    Every participant calls ``start()``; the ``monitor_rank`` additionally
    watches all peers. ``stop()`` (or process exit — threads are daemons)
    ends participation. The store must outlive the watchdog (it is the
    launch rendezvous store, which the master rank owns)."""

    def __init__(self, store, rank: int, world_size: int,
                 interval: float = 5.0, miss: int = 5,
                 label: str = "default", monitor_rank: int = 0,
                 on_stall: Optional[Callable[[Dict[int, float], float], None]] = None):
        self.store = store
        self.rank = int(rank)
        self.world_size = int(world_size)
        self.interval = float(interval)
        self.miss = max(1, int(miss))
        self.label = label
        self.monitor_rank = int(monitor_rank)
        self.on_stall = on_stall or _default_on_stall
        self._stop = threading.Event()
        self._threads = []
        self._beats = 0

    # -- wire format --------------------------------------------------------
    def _key(self, rank: int) -> str:
        return f"__hb/{self.label}/{rank}"

    # -- beat side ----------------------------------------------------------
    def _beat_loop(self):
        last_beat = time.monotonic()
        while not self._stop.is_set():
            self._beats += 1
            try:
                self.store.set(self._key(self.rank), str(self._beats))
            except (ConnectionError, OSError, TimeoutError):
                # the store died with the master; the job is coming down
                # anyway — don't add a watchdog crash on top
                return
            now = time.monotonic()
            # self-observed age: every rank exports its own liveness series
            # (the monitor only sees PEERS, and only runs on one rank)
            _obs.inc("heartbeat_beats_total")
            _obs.set_gauge("heartbeat_age_seconds", now - last_beat,
                           rank=self.rank)
            _obs.observe("watchdog_poll_age_seconds", now - last_beat,
                         rank=self.rank)
            _obs.flush()  # keep the prom textfile live while training runs
            last_beat = now
            self._stop.wait(self.interval)

    # -- monitor side -------------------------------------------------------
    def _read_peer(self, rank: int) -> Optional[bytes]:
        try:
            if not self.store.check(self._key(rank)):
                return None
            return self.store.get(self._key(rank), timeout=self.interval)
        except (ConnectionError, OSError, TimeoutError):
            return None

    def _monitor_loop(self):
        grace = self.interval * self.miss
        last_value: Dict[int, Optional[bytes]] = {}
        last_change: Dict[int, float] = {}
        now = time.monotonic()
        for r in range(self.world_size):
            if r != self.rank:
                last_value[r] = None
                last_change[r] = now  # startup grace: clock starts now
        while not self._stop.is_set():
            self._stop.wait(self.interval)
            if self._stop.is_set():
                return
            now = time.monotonic()
            stalled: Dict[int, float] = {}
            for r in last_value:
                v = self._read_peer(r)
                if v is not None and v != last_value[r]:
                    last_value[r] = v
                    last_change[r] = now
                elif now - last_change[r] > grace:
                    stalled[r] = now - last_change[r]
                age = now - last_change[r]
                _obs.set_gauge("heartbeat_age_seconds", age, rank=r)
                _obs.observe("watchdog_poll_age_seconds", age, rank=r)
            if stalled:
                # diagnosis + final export BEFORE on_stall: the default
                # handler os._exit()s, which skips atexit hooks
                _obs.event("rank_stalled",
                           stalled={str(r): round(a, 3)
                                    for r, a in stalled.items()},
                           grace=grace, monitor_rank=self.rank)
                _obs.flush()
                self.on_stall(stalled, grace)
                return

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "HeartbeatWatchdog":
        _obs.event("watchdog_start", interval=self.interval, miss=self.miss,
                   world_size=self.world_size, label=self.label,
                   monitor=(self.rank == self.monitor_rank))
        t = threading.Thread(target=self._beat_loop, daemon=True,
                             name=f"hb-beat-{self.label}")
        t.start()
        self._threads.append(t)
        if self.rank == self.monitor_rank and self.world_size > 1:
            m = threading.Thread(target=self._monitor_loop, daemon=True,
                                 name=f"hb-monitor-{self.label}")
            m.start()
            self._threads.append(m)
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2 * self.interval)
        self._threads = []


_active: Optional[HeartbeatWatchdog] = None


def maybe_start_from_env() -> Optional[HeartbeatWatchdog]:
    """Start the watchdog when the launch CLI asked for one
    (PADDLE_HEARTBEAT_INTERVAL > 0). The heartbeat store lives on the
    rendezvous master's port + 2 (port + 1 is rank negotiation); the master
    rank hosts it, everyone connects. Safe to call more than once."""
    global _active
    if _active is not None:
        return _active
    try:
        interval = float(os.environ.get("PADDLE_HEARTBEAT_INTERVAL", "0"))
    except ValueError:
        return None
    if interval <= 0:
        return None
    master = os.environ.get("PADDLE_MASTER")
    world_size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if not master or world_size < 2:
        return None
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    miss = int(os.environ.get("PADDLE_HEARTBEAT_MISS", "5"))
    host, port = master.rsplit(":", 1)
    from . import TCPStore

    store = TCPStore(host, int(port) + 2, is_master=(rank == 0),
                     timeout=max(60.0, interval * miss))
    _active = HeartbeatWatchdog(store, rank, world_size,
                                interval=interval, miss=miss,
                                label="spmd").start()
    return _active


def stop_active():
    global _active
    if _active is not None:
        _active.stop()
        try:
            _active.store.close()
        except Exception:
            pass
        _active = None
