"""ctypes loader for the native runtime core (csrc/paddle_tpu_rt.cc).

The reference ships its runtime services (allocator, TCPStore, dataloader
workers, host profiler) as C++ linked into the wheel
(``paddle/fluid/memory/``, ``paddle/phi/core/distributed/store/``,
``paddle/fluid/platform/profiler/`` — SURVEY.md §2.1). Here the equivalent
library is built from ``csrc/`` on first use (g++ is part of the toolchain)
and loaded via ctypes; every caller in the Python layer degrades gracefully
when the toolchain is unavailable (``available() == False``).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_CSRC = os.path.join(_REPO_ROOT, "csrc")
_LIB_PATH = os.path.join(_CSRC, "build", "libpaddle_tpu_rt.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    src = os.path.join(_CSRC, "paddle_tpu_rt.cc")
    if not os.path.exists(src):
        return False
    # Serialize concurrent first imports (e.g. simultaneously launched
    # ranks) across processes: without the lock one process can dlopen a
    # half-written .so while another is still compiling it.
    import fcntl

    lock_path = os.path.join(_CSRC, ".build.lock")
    try:
        lock_fd = os.open(lock_path, os.O_CREAT | os.O_RDWR)
    except OSError:
        lock_fd = None
    try:
        if lock_fd is not None:
            fcntl.flock(lock_fd, fcntl.LOCK_EX)
        if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(src):
            return True
        try:
            subprocess.run(
                ["make", "-C", _CSRC],
                check=True,
                capture_output=True,
                timeout=120,
            )
            return os.path.exists(_LIB_PATH)
        except Exception:
            return False
    finally:
        if lock_fd is not None:
            fcntl.flock(lock_fd, fcntl.LOCK_UN)
            os.close(lock_fd)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u64, i64, f64 = ctypes.c_uint64, ctypes.c_int64, ctypes.c_double
    p, cp = ctypes.c_void_p, ctypes.c_char_p

    lib.pt_arena_create.argtypes = [u64]
    lib.pt_arena_create.restype = p
    lib.pt_arena_destroy.argtypes = [p]
    lib.pt_arena_alloc.argtypes = [p, u64]
    lib.pt_arena_alloc.restype = p
    lib.pt_arena_free.argtypes = [p, p]
    lib.pt_arena_stats.argtypes = [p, ctypes.POINTER(u64 * 4)]

    lib.pt_stack.argtypes = [p, ctypes.POINTER(p), i64, u64, ctypes.c_int]

    lib.pt_now_ns.restype = i64
    lib.pt_trace_record.argtypes = [cp, cp, i64, i64, i64]
    lib.pt_trace_export.argtypes = [p, i64]
    lib.pt_trace_export.restype = i64
    lib.pt_trace_count.restype = i64
    lib.pt_trace_enabled.restype = ctypes.c_int

    lib.pt_store_create.argtypes = [cp, ctypes.c_int, ctypes.c_int, f64]
    lib.pt_store_create.restype = p
    lib.pt_store_port.argtypes = [p]
    lib.pt_store_port.restype = ctypes.c_int
    lib.pt_store_destroy.argtypes = [p]
    lib.pt_store_set.argtypes = [p, cp, p, u64]
    lib.pt_store_set.restype = ctypes.c_int
    lib.pt_store_get.argtypes = [p, cp, p, i64, f64]
    lib.pt_store_get.restype = i64
    lib.pt_store_add.argtypes = [p, cp, i64]
    lib.pt_store_add.restype = i64
    lib.pt_store_wait.argtypes = [p, cp, f64]
    lib.pt_store_wait.restype = ctypes.c_int
    lib.pt_store_check.argtypes = [p, cp]
    lib.pt_store_check.restype = ctypes.c_int
    lib.pt_store_del.argtypes = [p, cp]
    lib.pt_store_del.restype = ctypes.c_int
    return lib


def get_lib():
    """The loaded native library, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("PADDLE_TPU_DISABLE_NATIVE"):
            return None
        if _build():
            try:
                _lib = _bind(ctypes.CDLL(_LIB_PATH))
            except OSError:
                _lib = None
    return _lib


def available() -> bool:
    return get_lib() is not None


def store_native_enabled() -> bool:
    """Whether TCPStore should use the native backend.

    False when the lib is unavailable, when ``PADDLE_STORE_FORCE_PY=1``
    (debugging / CI determinism), or when chaos store-fault injection is
    active — the fault hooks (latency, connection drops) live in the Python
    store, so chaos runs must exercise that path on every rank."""
    if os.environ.get("PADDLE_STORE_FORCE_PY", "0") not in ("0", ""):
        return False
    if os.environ.get("PADDLE_CHAOS", "0") not in ("0", ""):
        from ..testing import chaos

        if chaos.store_faults_enabled():
            return False
    return available()
