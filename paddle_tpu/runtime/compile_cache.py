"""Persistent AOT compile cache — serialized XLA executables keyed by a
config/topology/version fingerprint.

Every jit cache miss on the proxy costs 4.7–7 s of XLA compile
(MULTICHIP_SCALING.json ``compile_s``) and is paid again on every elastic
relaunch and every serving cold-start, because the in-process jit cache
dies with the process. This module makes the compiled artifact outlive the
process: a train-step or decode-engine program is lowered once
(``fn.lower(*args)``), compiled, serialized with
``jax.experimental.serialize_executable``, and written to a directory
keyed by a fingerprint of everything that could invalidate it —

  * jax / jaxlib versions (XLA serialization is not stable across them),
  * backend platform, device kind, device count, process count,
  * mesh axis names and sizes (the sharding topology),
  * the caller's semantic config (strategy knobs, engine geometry, …),
  * a hash of the lowered StableHLO module text itself.

The module-text hash means an under-specified ``config`` can never alias
two different programs onto one entry; the explicit parts exist so a
*different lowering of the same source* (changed strategy, topology,
jaxlib) misses instead of deserializing an executable built for another
world.

Strictly **opt-in**: nothing touches disk unless ``PADDLE_TPU_COMPILE_CACHE``
names a directory (or a cache is constructed explicitly). On this CPU
jaxlib some deserialized executables have been observed to abort on
re-execution (see tests/conftest.py on the removed global XLA cache), so
the default-off posture is load-bearing; tier-1 never enables it.

Failure posture: a cache entry that fails to read/deserialize is evicted,
counted (``compile_cache_corrupt_total``), logged as a
``compile_cache_corrupt`` event, and the caller gets a fresh compile —
never a crash. Write failures are equally non-fatal: the compile result
is simply not persisted.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Any, Dict, Optional, Tuple

from .. import observability as _obs

__all__ = ["CompileCache", "resolve", "ENV_VAR"]

#: Environment opt-in: a directory path enables the cache process-wide.
ENV_VAR = "PADDLE_TPU_COMPILE_CACHE"

#: Bump when the on-disk payload layout changes; part of every filename's
#: fingerprint so old entries simply miss instead of failing to parse.
_FORMAT = 1


def _canonical(obj: Any) -> str:
    """Deterministic JSON for fingerprinting (sorted keys, no whitespace)."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=str)


def _version_parts() -> Dict[str, str]:
    import jax
    import jaxlib
    return {
        "jax": getattr(jax, "__version__", "?"),
        "jaxlib": getattr(jaxlib, "__version__", "?"),
        "format": str(_FORMAT),
    }


def _topology_parts(mesh=None) -> Dict[str, Any]:
    import jax
    devs = jax.devices()
    parts: Dict[str, Any] = {
        "platform": devs[0].platform if devs else "none",
        "device_kind": getattr(devs[0], "device_kind", "?") if devs else "?",
        "n_devices": len(devs),
        "process_count": jax.process_count(),
    }
    if mesh is not None:
        try:
            parts["mesh"] = dict(mesh.shape)
        except Exception:
            parts["mesh"] = str(mesh)
    return parts


class CompileCache:
    """File-per-entry executable cache rooted at ``directory``.

    Entries are ``<key>.jex`` pickles of the
    ``serialize_executable.serialize`` 3-tuple plus a small metadata
    header. Writes are atomic (tmp + ``os.replace``) so a concurrent
    reader never sees a torn entry.
    """

    def __init__(self, directory: str):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)

    # -- keying -------------------------------------------------------------
    def key_for(self, lowered=None, *, config: Any = None, mesh=None,
                schedule: Any = None, stage: Any = None,
                extra: Any = None) -> str:
        """Fingerprint of (config, topology, schedule, stage, versions,
        module).

        ``lowered`` is a ``jax.stages.Lowered``; its StableHLO text is
        hashed into the key so distinct programs can never collide even
        when the explicit parts are under-specified.

        ``stage`` scopes the entry to ONE pipeline stage of an MPMD
        program set (stage id + that stage's layer slice and width). An
        MPMD resize rebuilds only the resized stage's programs, so every
        other stage's key — and its on-disk entry — survives untouched;
        a shared key would evict S-1 perfectly good executables on every
        width change.
        """
        parts: Dict[str, Any] = {
            "versions": _version_parts(),
            "topology": _topology_parts(mesh),
            "config": config,
            "schedule": schedule,
            "stage": stage,
            "extra": extra,
        }
        if lowered is not None:
            try:
                text = lowered.as_text()
            except Exception:
                text = repr(lowered)
            parts["module"] = hashlib.blake2b(
                text.encode(), digest_size=16).hexdigest()
        return hashlib.blake2b(
            _canonical(parts).encode(), digest_size=20).hexdigest()

    def path_for(self, key: str) -> str:
        return os.path.join(self.directory, f"{key}.jex")

    # -- read side ----------------------------------------------------------
    def load(self, key: str, where: str = "unknown"):
        """Deserialized executable for ``key``, or None (miss/corrupt).

        Any failure past "file exists" is treated as corruption: the
        entry is evicted, counted, and logged — the caller falls back to
        a fresh compile.
        """
        path = self.path_for(key)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                blob = pickle.load(f)
            if blob.get("format") != _FORMAT or blob.get("key") != key:
                raise ValueError("compile-cache header mismatch")
            from jax.experimental import serialize_executable as _se
            return _se.deserialize_and_load(*blob["payload"])
        except Exception as exc:  # noqa: BLE001 — corrupt entry, any shape
            _obs.inc("compile_cache_corrupt_total", where=where)
            _obs.event("compile_cache_corrupt", where=where, key=key,
                       error=f"{type(exc).__name__}: {exc}"[:240])
            try:
                os.remove(path)
            except OSError:
                pass
            return None

    # -- write side ---------------------------------------------------------
    def store(self, key: str, compiled, where: str = "unknown") -> bool:
        """Serialize ``compiled`` under ``key``; non-fatal on failure."""
        try:
            from jax.experimental import serialize_executable as _se
            payload = _se.serialize(compiled)
            blob = pickle.dumps({"format": _FORMAT, "key": key,
                                 "where": where, "payload": payload})
            tmp = self.path_for(key) + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, self.path_for(key))
            _obs.inc("compile_cache_bytes_total", len(blob))
            return True
        except Exception:  # noqa: BLE001 — never fail the step over the cache
            _obs.inc("compile_cache_store_errors_total", where=where)
            return False

    # -- the one-call fast path ---------------------------------------------
    def load_or_compile(self, lowered, key: str, *,
                        where: str = "unknown") -> Tuple[Any, bool]:
        """``(executable, hit)`` — cached load, else compile + persist.

        Compile errors propagate (they are the caller's bug, not the
        cache's); cache-layer errors never do.
        """
        t0 = time.perf_counter()
        compiled = self.load(key, where=where)
        if compiled is not None:
            _obs.inc("compile_cache_hits_total", where=where)
            _obs.observe("compile_cache_load_seconds",
                         time.perf_counter() - t0, where=where)
            return compiled, True
        _obs.inc("compile_cache_miss_total", where=where)
        compiled = lowered.compile()
        self.store(key, compiled, where=where)
        return compiled, False


def resolve(explicit: Optional[str] = None) -> Optional[CompileCache]:
    """The process's cache, or None when disabled.

    ``explicit`` (a directory) wins; otherwise ``PADDLE_TPU_COMPILE_CACHE``
    is consulted *per call* so tests and supervisors can flip it at
    runtime. Unset/empty → disabled (the tier-1 default).
    """
    d = explicit or os.environ.get(ENV_VAR, "")
    if not d:
        return None
    try:
        return CompileCache(d)
    except OSError:
        return None
