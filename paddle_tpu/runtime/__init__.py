"""paddle_tpu.runtime — native runtime services (C++ core + Python surface).

TPU-native equivalents of the reference's L1 runtime layer (SURVEY.md §1 L1,
§2.4): host staging allocator with stats, TCPStore coordination service,
parallel batch assembly, and the host trace buffer behind
``paddle_tpu.profiler``. Device (HBM) memory itself is owned by PJRT/XLA —
what remains framework-owned on TPU is the host side, which is what lives
here.
"""
from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

import numpy as np

from . import native
from . import compile_cache

__all__ = [
    "compile_cache",
    "native_available",
    "HostArena",
    "default_arena",
    "host_memory_stats",
    "stack_samples",
    "TCPStore",
    "trace_start",
    "trace_stop",
    "trace_record",
    "trace_export",
]


def native_available() -> bool:
    return native.available()


# ---------------------------------------------------------------------------
# Host arena allocator
# ---------------------------------------------------------------------------
class HostArena:
    """Auto-growth best-fit caching allocator for host staging buffers.

    Reference capability: ``AutoGrowthBestFitAllocator``
    (``paddle/fluid/memory/allocation/`` — SURVEY.md §2.1 "Memory"); here it
    backs input-pipeline batch buffers that feed ``jax.device_put``.
    """

    def __init__(self, chunk_bytes: int = 64 << 20):
        lib = native.get_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        self._h = lib.pt_arena_create(chunk_bytes)

    def alloc_array(self, shape, dtype):
        """Allocate arena-backed storage; returns ``(ndarray, ptr)``.

        The array views arena memory — keep it alive only while the arena
        lives, and release with ``free(ptr)`` when done.
        """
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        ptr = self._lib.pt_arena_alloc(self._h, max(nbytes, 1))
        if not ptr:
            raise MemoryError(f"arena alloc of {nbytes} bytes failed")
        buf = (ctypes.c_char * max(nbytes, 1)).from_address(ptr)
        arr = np.frombuffer(buf, dtype=dtype, count=int(np.prod(shape))).reshape(shape)
        arr.flags.writeable = True
        return arr, ptr

    def alloc(self, nbytes: int) -> int:
        ptr = self._lib.pt_arena_alloc(self._h, max(int(nbytes), 1))
        if not ptr:
            raise MemoryError(f"arena alloc of {nbytes} bytes failed")
        return ptr

    def free(self, ptr: int):
        self._lib.pt_arena_free(self._h, ptr)

    def stats(self) -> dict:
        out = (ctypes.c_uint64 * 4)()
        self._lib.pt_arena_stats(self._h, ctypes.byref(out))
        return {
            "allocated_bytes": int(out[0]),
            "reserved_bytes": int(out[1]),
            "peak_allocated_bytes": int(out[2]),
            "alloc_count": int(out[3]),
        }

    def __del__(self):
        try:
            self._lib.pt_arena_destroy(self._h)
        except Exception:
            pass


_default_arena: Optional[HostArena] = None
_arena_lock = threading.Lock()


def default_arena() -> Optional[HostArena]:
    global _default_arena
    if not native.available():
        return None
    with _arena_lock:
        if _default_arena is None:
            _default_arena = HostArena()
    return _default_arena


def host_memory_stats() -> dict:
    """paddle.device.cuda.memory_stats analogue for host staging memory."""
    a = default_arena()
    if a is None:
        return {
            "allocated_bytes": 0,
            "reserved_bytes": 0,
            "peak_allocated_bytes": 0,
            "alloc_count": 0,
        }
    return a.stats()


# ---------------------------------------------------------------------------
# Parallel batch assembly (DataLoader collate hot loop)
# ---------------------------------------------------------------------------
def stack_samples(samples, out: Optional[np.ndarray] = None) -> np.ndarray:
    """np.stack over equally-shaped sample arrays via the native thread pool.

    Falls back to np.stack when the native lib is missing or inputs are not
    contiguous same-shape arrays. Reference capability: C++ dataloader
    workers assembling batches into shared memory (SURVEY.md §2.2 "Data").
    """
    lib = native.get_lib()
    n = len(samples)
    if n == 0:
        raise ValueError("empty batch")
    first = samples[0]
    if (
        lib is None
        or not all(
            isinstance(s, np.ndarray)
            and s.shape == first.shape
            and s.dtype == first.dtype
            and s.flags.c_contiguous
            for s in samples
        )
    ):
        return np.stack([np.asarray(s) for s in samples])
    if out is None:
        out = np.empty((n,) + first.shape, dtype=first.dtype)
    ptrs = (ctypes.c_void_p * n)(*[s.ctypes.data for s in samples])
    lib.pt_stack(
        out.ctypes.data_as(ctypes.c_void_p), ptrs, n, first.nbytes, 0
    )
    return out


# ---------------------------------------------------------------------------
# TCPStore
# ---------------------------------------------------------------------------
class TCPStore:
    """Coordination KV store (reference:
    ``paddle/phi/core/distributed/store/tcp_store.cc`` — SURVEY.md §2.3
    "Rendezvous / store").

    The master process runs the server; every process (master included)
    talks to it through a client connection. Used by
    ``paddle_tpu.distributed.launch`` to negotiate the rank table before
    ``jax.distributed.initialize``, mirroring the reference's
    TCPStore + NCCL-unique-id exchange.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        is_master: bool = False,
        timeout: Optional[float] = None,
    ):
        if timeout is None:
            timeout = float(os.environ.get("PADDLE_STORE_TIMEOUT", "60"))
        self._py_fallback = None
        # PADDLE_STORE_FORCE_PY=1 and chaos store-fault injection force the
        # Python store (where the fault hooks live) even with the native
        # lib present
        if not native.store_native_enabled():
            from . import py_store

            self._py_fallback = py_store.PyTCPStore(host, port, is_master, timeout)
            self.port = self._py_fallback.port
            return
        lib = native.get_lib()
        self._lib = lib
        self._h = lib.pt_store_create(
            host.encode(), int(port), 1 if is_master else 0, float(timeout)
        )
        if not self._h:
            raise ConnectionError(f"TCPStore: could not bind/connect {host}:{port}")
        self.port = lib.pt_store_port(self._h) if is_master else int(port)

    def set(self, key: str, value) -> None:
        if self._py_fallback:
            return self._py_fallback.set(key, value)
        data = value.encode() if isinstance(value, str) else bytes(value)
        if self._lib.pt_store_set(self._h, key.encode(), data, len(data)) != 0:
            raise ConnectionError("TCPStore.set failed")

    def get(self, key: str, timeout: float = 60.0) -> bytes:
        if self._py_fallback:
            return self._py_fallback.get(key, timeout)
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.pt_store_get(self._h, key.encode(), buf, cap, float(timeout))
            if n == -1:
                raise TimeoutError(f"TCPStore.get({key!r}) timed out")
            if n < -1:
                raise ConnectionError("TCPStore.get failed")
            if n <= cap:
                return buf.raw[:n]
            cap = int(n)

    def add(self, key: str, delta: int = 1) -> int:
        if self._py_fallback:
            return self._py_fallback.add(key, delta)
        v = self._lib.pt_store_add(self._h, key.encode(), int(delta))
        if v == -(2**63):
            raise ConnectionError("TCPStore.add failed")
        return int(v)

    def wait(self, key: str, timeout: float = 60.0) -> None:
        if self._py_fallback:
            return self._py_fallback.wait(key, timeout)
        r = self._lib.pt_store_wait(self._h, key.encode(), float(timeout))
        if r != 1:
            raise TimeoutError(f"TCPStore.wait({key!r}) timed out")

    def check(self, key: str) -> bool:
        if self._py_fallback:
            return self._py_fallback.check(key)
        return self._lib.pt_store_check(self._h, key.encode()) == 1

    def delete_key(self, key: str) -> bool:
        if self._py_fallback:
            return self._py_fallback.delete_key(key)
        return self._lib.pt_store_del(self._h, key.encode()) == 1

    def asymmetric_handshake(
        self, ns: str, rank: int, world_size: int, timeout: float = 60.0
    ) -> None:
        """Rendezvous where the master (rank 0) provably finishes last.

        Clients end with an acknowledged ``set`` (no request left in
        flight); the master ends waiting for every client ack — so the
        master, whose exit tears down the store server, cannot close while
        any client still has an unanswered request. A symmetric counter
        barrier is racy here (the master may pass it and exit before a
        slow client's final wait reaches the server). Shared by the launch
        rank negotiation and ``paddle.distributed.rpc.shutdown``.
        """
        if rank == 0:
            for r in range(1, world_size):
                try:
                    self.wait(f"{ns}/arrived/{r}", timeout)
                except TimeoutError as e:
                    raise TimeoutError(
                        f"rendezvous '{ns}': rank {r} of {world_size} never "
                        f"arrived within {timeout}s — check that rank's "
                        "process is alive and PADDLE_MASTER matches") from e
            self.set(f"{ns}/go", b"1")
            for r in range(1, world_size):
                try:
                    self.wait(f"{ns}/ack/{r}", timeout)
                except TimeoutError as e:
                    raise TimeoutError(
                        f"rendezvous '{ns}': rank {r} arrived but never "
                        f"acknowledged within {timeout}s (it likely died "
                        "between handshake phases)") from e
        else:
            self.set(f"{ns}/arrived/{rank}", b"1")
            try:
                self.wait(f"{ns}/go", timeout)
            except TimeoutError as e:
                raise TimeoutError(
                    f"rendezvous '{ns}': rank {rank} waited {timeout}s for "
                    "the master's go signal — the master (rank 0) is down "
                    "or still waiting on another rank") from e
            self.set(f"{ns}/ack/{rank}", b"1")

    def barrier(self, name: str, world_size: int, timeout: float = 60.0) -> None:
        """All `world_size` participants rendezvous on `name`.

        Two-phase (arrive + ack) so no participant — in particular the
        master, whose exit tears down the store server — can leave the
        barrier until every participant has confirmed passing it.
        """
        n = self.add(f"__barrier/{name}/count", 1)
        if n == world_size:
            self.set(f"__barrier/{name}/done", b"1")
        self.wait(f"__barrier/{name}/done", timeout)
        m = self.add(f"__barrier/{name}/acks", 1)
        if m == world_size:
            self.set(f"__barrier/{name}/fin", b"1")
        self.wait(f"__barrier/{name}/fin", timeout)

    def close(self):
        if self._py_fallback:
            return self._py_fallback.close()
        if getattr(self, "_h", None):
            self._lib.pt_store_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# Host tracer
# ---------------------------------------------------------------------------
def trace_start():
    lib = native.get_lib()
    if lib is not None:
        lib.pt_trace_start()


def trace_stop():
    lib = native.get_lib()
    if lib is not None:
        lib.pt_trace_stop()


def trace_enabled() -> bool:
    lib = native.get_lib()
    return lib is not None and bool(lib.pt_trace_enabled())


def trace_record(name: str, ts_ns: int, dur_ns: int, cat: str = "op", tid: int = 0):
    lib = native.get_lib()
    if lib is not None:
        lib.pt_trace_record(name.encode(), cat.encode(), ts_ns, dur_ns, tid)


def trace_export() -> list:
    """Drain the native trace buffer as a list of chrome-trace event dicts."""
    import json

    lib = native.get_lib()
    if lib is None:
        return []
    # Events may land between the sizing call and the export; loop until the
    # buffer was large enough for what was actually written.
    cap = int(lib.pt_trace_export(None, 0))
    while True:
        buf = ctypes.create_string_buffer(max(cap, 2))
        n = int(lib.pt_trace_export(buf, max(cap, 2)))
        if n <= max(cap, 2):
            return json.loads(buf.raw[:n].decode())
        cap = n


def now_ns() -> int:
    lib = native.get_lib()
    if lib is not None:
        return lib.pt_now_ns()
    import time

    return time.perf_counter_ns()


class RecordEvent:
    """Low-level scoped host trace event feeding the native buffer directly.

    The user-facing scoped annotation is ``paddle_tpu.profiler.RecordEvent``
    (which also tags the XLA timeline and the summary table); this class is
    the primitive it builds on (reference: ``platform::RecordEvent`` —
    SURVEY.md §5 "Tracing/profiling")."""

    def __init__(self, name: str, cat: str = "op"):
        self.name = name
        self.cat = cat

    def __enter__(self):
        self._t0 = now_ns()
        return self

    def __exit__(self, *exc):
        lib = native.get_lib()
        if lib is not None and lib.pt_trace_enabled():
            t1 = now_ns()
            trace_record(self.name, self._t0, t1 - self._t0, self.cat, threading.get_ident() % (1 << 31))
        return False
