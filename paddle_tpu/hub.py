"""paddle.hub parity — model-hub entrypoint discovery and loading.

Reference: ``python/paddle/hapi/hub.py`` (list/help/load over a repo's
``hubconf.py``, sources github/gitee/local). This build is offline by
design: ``source='local'`` is fully supported (the common production path
— a checked-out model repo on disk); the network sources raise with a
clear offline note instead of pretending to download.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

HUB_CONF = "hubconf.py"


def _load_hubconf(repo_dir: str):
    path = os.path.join(os.path.abspath(repo_dir), HUB_CONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(
            f"hub: no {HUB_CONF} in {repo_dir!r} (a hub repo exposes its "
            "entrypoints there)")
    name = f"_paddle_tpu_hubconf_{abs(hash(path))}"
    if name in sys.modules:
        return sys.modules[name]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, os.path.dirname(path))
    try:
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
    finally:
        sys.path.pop(0)
    return mod


def _check_source(source: str):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f"unknown source {source!r}: expected github/gitee/local")
    if source != "local":
        raise RuntimeError(
            f"hub source {source!r} needs network access; this build is "
            "offline — clone the repo and use source='local' with its path")


def list(repo_dir, source="github", force_reload=False):  # noqa: A001
    """Entrypoint names exposed by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n, v in vars(mod).items()
            if callable(v) and not n.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A001
    """The entrypoint's docstring."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"hub: no entrypoint {model!r} in {repo_dir!r}")
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Call the entrypoint and return its model."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"hub: no entrypoint {model!r} in {repo_dir!r}")
    return getattr(mod, model)(**kwargs)
