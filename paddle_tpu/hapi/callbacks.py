"""Training callbacks (paddle.callbacks parity).

Reference: ``python/paddle/hapi/callbacks.py`` — ProgBarLogger,
ModelCheckpoint, EarlyStopping, LRScheduler, VisualDL (SURVEY.md §5).
"""
from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

from .. import observability as _obs


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_begin(self, mode, logs=None):
        getattr(self, f"on_{mode}_begin", lambda l=None: None)(logs)

    def on_end(self, mode, logs=None):
        getattr(self, f"on_{mode}_end", lambda l=None: None)(logs)

    def on_batch_begin(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_begin", lambda s, l=None: None)(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        getattr(self, f"on_{mode}_batch_end", lambda s, l=None: None)(step, logs)

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    # mode-specific no-ops (subclasses override what they need)
    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def __iter__(self):
        return iter(self.callbacks)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def on_begin(self, mode, logs=None):
        for c in self.callbacks:
            c.on_begin(mode, logs)

    def on_end(self, mode, logs=None):
        for c in self.callbacks:
            c.on_end(mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_begin(mode, step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            c.on_batch_end(mode, step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self._t0 = time.time()
        self.epoch = 0

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._steps = 0
        self._epoch_t0 = time.time()
        if self.verbose:
            total = self.params.get("epochs")
            print(f"Epoch {epoch + 1}/{total}")

    def on_train_batch_end(self, step, logs=None):
        self._steps += 1
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {v:.4f}" for k, v in (logs or {}).items() if isinstance(v, (int, float)) and k != "batch_size"
            )
            steps = self.params.get("steps")
            dt = (time.time() - self._epoch_t0) / max(self._steps, 1)
            print(f"step {step + 1}/{steps} - {items} - {dt * 1000:.0f}ms/step")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = " - ".join(
                f"{k}: {v:.4f}" for k, v in (logs or {}).items() if isinstance(v, (int, float)) and k != "batch_size"
            )
            print(f"Epoch {epoch + 1} done - {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model and self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = None
        self.wait = 0

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        self._check(logs)

    def on_epoch_end(self, epoch, logs=None):
        self._check(logs)

    def _check(self, logs):
        logs = logs or {}
        cur = logs.get(self.monitor, logs.get(f"eval_{self.monitor}"))
        if cur is None:
            return
        cur = cur[0] if isinstance(cur, (list, tuple)) else cur
        if self._better(cur):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience and self.model is not None:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        from ..optimizer.lr import LRScheduler as Sched

        if opt is not None and isinstance(opt._learning_rate, Sched):
            return opt._learning_rate
        return None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class VisualDL(Callback):
    """Scalar logger; writes TSV (VisualDL itself is external to the repo)."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self._step = 0

    def on_train_batch_end(self, step, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        with open(os.path.join(self.log_dir, "scalars.tsv"), "a") as f:
            for k, v in (logs or {}).items():
                if isinstance(v, (int, float)):
                    f.write(f"{self._step}\t{k}\t{v}\n")
        self._step += 1


class TelemetryLogger(Callback):
    """Feeds per-step training telemetry into ``paddle_tpu.observability``:
    tokens/sec and estimated MFU gauges plus one ``train_step`` JSONL event
    per batch. Auto-appended by ``config_callbacks`` and a no-op (one env
    lookup per batch) unless ``PADDLE_TPU_TELEMETRY_DIR`` is set.

    MFU uses ``logs["step_flops"]`` (XLA cost analysis, supplied by
    ``Model.fit``) against ``PADDLE_TPU_PEAK_FLOPS`` (the accelerator's
    peak FLOP/s); without the env var only the achieved-FLOP/s gauge is
    exported.
    """

    def __init__(self):
        super().__init__()
        self._t0 = None

    def on_train_begin(self, logs=None):
        _obs.event("train_run", phase="begin",
                   epochs=self.params.get("epochs"),
                   steps=self.params.get("steps"))

    def on_train_end(self, logs=None):
        _obs.event("train_run", phase="end")
        _obs.flush()

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter() if _obs.enabled() else None

    def on_train_batch_end(self, step, logs=None):
        if self._t0 is None:
            return
        dt = max(time.perf_counter() - self._t0, 1e-9)
        self._t0 = None
        logs = logs or {}
        fields = {"step": int(step), "seconds": round(dt, 6)}
        loss = logs.get("loss")
        if isinstance(loss, (list, tuple, np.ndarray)):
            loss = loss[0] if len(loss) else None
        try:
            fields["loss"] = float(loss)
        except (TypeError, ValueError):
            pass
        bs = logs.get("batch_size")
        if bs:
            tps = float(bs) / dt
            _obs.set_gauge("train_tokens_per_second", tps)
            fields["tokens_per_second"] = round(tps, 3)
        flops = logs.get("step_flops")
        if flops:
            fps = float(flops) / dt
            _obs.set_gauge("train_flops_per_second", fps)
            try:
                peak = float(os.environ.get("PADDLE_TPU_PEAK_FLOPS", "0") or 0)
            except ValueError:
                peak = 0.0
            if peak > 0:
                mfu = fps / peak
                _obs.set_gauge("train_mfu", mfu)
                fields["mfu"] = round(mfu, 6)
        _obs.event("train_step", **fields)


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = callbacks if isinstance(callbacks, (list, tuple)) else ([callbacks] if callbacks else [])
    cbks = list(cbks)
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    if not any(isinstance(c, TelemetryLogger) for c in cbks):
        cbks = cbks + [TelemetryLogger()]
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or [],
    })
    return lst


class ReduceLROnPlateau(Callback):
    """Drive an optimizer.lr.ReduceOnPlateau scheduler from a monitored
    metric at epoch end (paddle.callbacks.ReduceLROnPlateau)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, mode="auto",
                 min_delta=1e-4, cooldown=0, min_lr=0.0, verbose=1):
        super().__init__()
        self.monitor = monitor
        self._kw = dict(factor=factor, patience=patience,
                        threshold=min_delta, cooldown=cooldown, min_lr=min_lr,
                        mode="min" if mode in ("auto", "min") else "max")
        self._sched = None

    def on_epoch_end(self, epoch, logs=None):
        val = (logs or {}).get(self.monitor)
        if val is None:
            return
        if isinstance(val, (list, tuple)):
            val = val[0]
        opt = getattr(self.model, "_optimizer", None)
        if opt is None:
            return
        if self._sched is None:
            from ..optimizer.lr import ReduceOnPlateau

            lr = opt.get_lr()
            self._sched = ReduceOnPlateau(learning_rate=lr, **self._kw)
            opt._learning_rate = self._sched
        self._sched.step(float(val))


class WandbCallback(Callback):
    """paddle.callbacks.WandbCallback parity: logs train/eval metrics to a
    Weights & Biases run. The wandb client is an optional dependency in the
    reference too — constructing this without it installed raises with the
    same guidance."""

    def __init__(self, project=None, entity=None, name=None, dir=None,
                 mode=None, job_type=None, **kwargs):
        super().__init__()
        try:
            import wandb
        except ImportError as e:
            raise ImportError(
                "WandbCallback requires the `wandb` package, which is not "
                "installed in this environment (`pip install wandb`)") from e
        self.wandb = wandb
        self._run = wandb.init(
            project=project, entity=entity, name=name, dir=dir, mode=mode,
            job_type=job_type, **kwargs)

    def on_train_batch_end(self, step, logs=None):
        self._run.log({f"train/{k}": v for k, v in (logs or {}).items()})

    def on_eval_end(self, logs=None):
        self._run.log({f"eval/{k}": v for k, v in (logs or {}).items()})

    def on_train_end(self, logs=None):
        self._run.finish()
