"""paddle.summary / paddle.flops parity.

Reference: ``python/paddle/hapi/model_summary.py`` (per-layer table via
forward hooks) and ``hapi/dynamic_flops.py`` (per-op FLOP counters).
TPU-native twist for flops: the authoritative count comes from XLA's own
cost analysis of the compiled forward (`lowered.compile().cost_analysis()`),
which accounts for fusion — not a hand-maintained per-op table.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..framework.core import Tensor
from ..nn.layer import Layer


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print and return {'total_params', 'trainable_params'} with a per-layer
    table (layer name, output shape, #params) captured via forward hooks."""
    import jax.numpy as jnp

    rows = []
    hooks = []

    def mk_hook(name, layer):
        def hook(lyr, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) else outputs
            shape = list(out._value.shape) if isinstance(out, Tensor) else "-"
            n_params = sum(int(np.prod(p._value.shape)) for p in lyr.parameters(include_sublayers=False))
            rows.append((name or lyr.__class__.__name__, lyr.__class__.__name__, shape, n_params))

        return layer.register_forward_post_hook(hook)

    for name, sub in net.named_sublayers():
        hooks.append(mk_hook(name, sub))

    was_training = net.training
    net.eval()
    try:
        if input is not None:
            xs = input if isinstance(input, (tuple, list)) else (input,)
            net(*xs)
        else:
            if input_size is None:
                raise ValueError("summary needs input_size or input")
            sizes = input_size if isinstance(input_size[0], (tuple, list)) else [input_size]
            dts = dtypes or ["float32"] * len(sizes)
            xs = [
                Tensor(jnp.zeros([s if s is not None else 1 for s in size], dt))
                for size, dt in zip(sizes, dts)
            ]
            net(*xs)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p._value.shape)) for p in net.parameters())
    trainable = sum(
        int(np.prod(p._value.shape)) for p in net.parameters() if p.trainable
    )
    line = "-" * 78
    print(line)
    print(f"{'Layer (type)':<34}{'Output Shape':<26}{'Param #':>14}")
    print(line)
    for name, cls, shape, n in rows:
        print(f"{name + ' (' + cls + ')':<34}{str(shape):<26}{n:>14,}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}


def flops(net: Layer, input_size=None, inputs=None, custom_ops=None, print_detail=False):
    """FLOPs of one forward pass, from XLA's cost analysis of the compiled
    program (counts fused reality, not a per-op estimate). Returns an int."""
    import jax
    import jax.numpy as jnp

    if inputs is None:
        if input_size is None:
            raise ValueError("flops needs input_size or inputs")
        inputs = (Tensor(jnp.zeros([s if s is not None else 1 for s in input_size], jnp.float32)),)
    elif isinstance(inputs, Tensor):
        inputs = (inputs,)

    from ..framework.op import raw

    state = [p for _, p in net.named_parameters()] + [b for _, b in net.named_buffers()]
    was_training = net.training
    net.eval()

    def pure(state_vals, *in_vals):
        originals = [t._value for t in state]
        try:
            for t, v in zip(state, state_vals):
                t._value = v
            out = net(*[Tensor(v) for v in in_vals])
            return raw(out[0] if isinstance(out, (tuple, list)) else out)
        finally:
            for t, v in zip(state, originals):
                t._value = v

    try:
        lowered = jax.jit(pure).lower(
            [t._value for t in state], *[raw(i) for i in inputs]
        )
        cost = lowered.compile().cost_analysis()
    finally:
        if was_training:
            net.train()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    total = int(cost.get("flops", 0)) if cost else 0
    if print_detail:
        print(f"FLOPs (XLA cost analysis, one forward): {total:,}")
    return total
