"""hapi package: callbacks, progress bar (paddle.hapi parity)."""
from . import callbacks  # noqa: F401
