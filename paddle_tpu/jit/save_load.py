"""paddle.jit.save / paddle.jit.load — portable compiled-model serialization.

Reference: ``python/paddle/jit/api.py`` (jit.save serializes the dy2static
Program + params; jit.load returns a TranslatedLayer) and the inference flow
``save_inference_model`` → AnalysisPredictor (SURVEY.md §2.1 "Inference
engine", §2.4 item 14). TPU-native design: the portable artifact is a
**serialized StableHLO module** produced by ``jax.export`` — the exact program
XLA will compile — plus a separate params file. Loading re-hydrates a callable
that compiles once per shape signature and runs on any PJRT backend (TPU/CPU),
which is the reference's "save program + params, run with a predictor" workflow
without a custom protobuf IR.

Artifacts for prefix ``path``:
  - ``path.pdmodel``   — serialized StableHLO (jax.export bytes)
  - ``path.pdiparams`` — pickled {name: numpy array} state (params + buffers)
  - ``path.pdmeta``    — pickled metadata: input names/specs, output treedef
"""
from __future__ import annotations

import os
import pickle
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from ..framework import rng as _rng
from ..framework.core import Tensor
from ..nn.layer import Layer

_MODEL_SUFFIX = ".pdmodel"
_PARAMS_SUFFIX = ".pdiparams"
_META_SUFFIX = ".pdmeta"


def _input_specs_to_sds(input_spec, scope):
    """Convert paddle InputSpecs / example Tensors to jax.ShapeDtypeStruct,
    mapping unknown dims (None / -1) to shared symbolic dimensions so the
    exported module is batch-polymorphic."""
    from . import InputSpec

    sds, names = [], []
    for i, spec in enumerate(input_spec):
        if isinstance(spec, Tensor):
            sds.append(jax.ShapeDtypeStruct(spec._value.shape, spec._value.dtype))
            names.append(getattr(spec, "name", None) or f"x{i}")
            continue
        if not isinstance(spec, InputSpec):
            arr = jnp.asarray(spec)
            sds.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))
            names.append(f"x{i}")
            continue
        dims = []
        for j, d in enumerate(spec.shape):
            if d is None or (isinstance(d, int) and d < 0):
                sym = "batch" if j == 0 else f"d{i}_{j}"
                dims.append(jax_export.symbolic_shape(sym, scope=scope)[0])
            else:
                dims.append(d)
        sds.append(jax.ShapeDtypeStruct(tuple(dims), spec.dtype))
        names.append(spec.name or f"x{i}")
    return sds, names


def _lift_layer(layer: Layer):
    """Lift a stateful Layer into pure(state_vals, *input_vals) -> flat outputs.

    Same state-swap pattern as jit.TracedLayer; traced in eval mode with a
    fixed RNG key (inference is deterministic; dropout layers are no-ops in
    eval mode anyway).
    """
    state_names, state = [], []
    for n, p in layer.named_parameters():
        state_names.append(n)
        state.append(p)
    for n, b in layer.named_buffers():
        state_names.append(n)
        state.append(b)
    out_tree_box = [None]

    def pure(state_vals, *input_vals):
        originals = [t._value for t in state]
        with _rng.trace_key_scope(jax.random.PRNGKey(0)):
            try:
                for t, v in zip(state, state_vals):
                    t._value = v
                inputs = [Tensor(v) for v in input_vals]
                out = layer.forward(*inputs)
            finally:
                for t, v in zip(state, originals):
                    t._value = v
        leaves, tree = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor)
        )
        out_tree_box[0] = tree
        return tuple(
            leaf._value if isinstance(leaf, Tensor) else leaf for leaf in leaves
        )

    return pure, state, state_names, out_tree_box


def save(layer, path: str, input_spec: Optional[Sequence] = None, **configs):
    """paddle.jit.save parity: export ``layer`` (or a TracedLayer / function
    whose forward was wrapped by to_static) as StableHLO + params.

    input_spec: list of InputSpec / example Tensors. Required unless the layer
    was already called (in which case pass the example inputs here too — the
    export needs concrete avals).
    """
    from . import TracedLayer

    if isinstance(layer, TracedLayer):
        # Unwrap only the unambiguous case: a TracedLayer over one Layer's
        # bound forward. A traced free function touching several layers
        # can't be reduced to any single layer's forward — exporting one of
        # them would silently serialize the wrong computation.
        if len(layer._layers) != 1:
            raise ValueError(
                "jit.save of a traced function spanning "
                f"{len(layer._layers)} layers is ambiguous; wrap the "
                "computation in a single Layer and save that"
            )
        layer = layer._layers[0]
    if not isinstance(layer, Layer):
        raise TypeError(f"jit.save expects a Layer, got {type(layer)}")
    if input_spec is None:
        raise ValueError("jit.save requires input_spec=[InputSpec(...), ...]")

    was_training = layer.training
    layer.eval()
    try:
        pure, state, state_names, out_tree_box = _lift_layer(layer)
        scope = jax_export.SymbolicScope()
        in_sds, in_names = _input_specs_to_sds(input_spec, scope)
        state_sds = [
            jax.ShapeDtypeStruct(t._value.shape, t._value.dtype) for t in state
        ]
        exported = jax_export.export(jax.jit(pure))(state_sds, *in_sds)
    finally:
        if was_training:
            layer.train()

    dirname = os.path.dirname(path)
    if dirname:
        os.makedirs(dirname, exist_ok=True)
    with open(path + _MODEL_SUFFIX, "wb") as f:
        f.write(exported.serialize())
    params_np = {n: np.asarray(t._value) for n, t in zip(state_names, state)}
    with open(path + _PARAMS_SUFFIX, "wb") as f:
        pickle.dump(params_np, f, protocol=4)
    meta = {
        "state_names": state_names,
        "input_names": in_names,
        "out_tree": out_tree_box[0],
        "format": "stablehlo-v1",
    }
    with open(path + _META_SUFFIX, "wb") as f:
        pickle.dump(meta, f, protocol=4)
    return path


class TranslatedLayer(Layer):
    """paddle.jit.load product: a Layer whose forward runs the deserialized
    StableHLO module (compiled & cached per input-shape signature)."""

    def __init__(self, exported, params_np, meta):
        super().__init__()
        self._exported = exported
        self._meta = meta
        self._state_vals = [
            jnp.asarray(params_np[n]) for n in meta["state_names"]
        ]
        # params are frozen constants of the serving artifact; expose them as
        # buffers so state_dict round-trips but nothing is trainable.
        for n, v in zip(meta["state_names"], self._state_vals):
            self.register_buffer(n.replace(".", "__"), Tensor(v))
        self._call = jax.jit(exported.call)

    @property
    def input_names(self) -> List[str]:
        return list(self._meta["input_names"])

    def forward(self, *inputs):
        vals = [t._value if isinstance(t, Tensor) else jnp.asarray(t) for t in inputs]
        outs = self._call(self._state_vals, *vals)
        wrapped = [Tensor(o) for o in outs]
        tree = self._meta.get("out_tree")
        if tree is not None and tree.num_leaves == len(wrapped):
            return jax.tree_util.tree_unflatten(tree, wrapped)
        return wrapped[0] if len(wrapped) == 1 else tuple(wrapped)


def load(path: str, **configs) -> TranslatedLayer:
    """paddle.jit.load parity: returns a TranslatedLayer."""
    with open(path + _MODEL_SUFFIX, "rb") as f:
        exported = jax_export.deserialize(f.read())
    with open(path + _PARAMS_SUFFIX, "rb") as f:
        params_np = pickle.load(f)
    with open(path + _META_SUFFIX, "rb") as f:
        meta = pickle.load(f)
    return TranslatedLayer(exported, params_np, meta)
