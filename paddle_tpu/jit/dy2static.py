"""Dy2static: compile Python control flow on traced tensors.

Reference: ``python/paddle/jit/dy2static/`` — program_translator.py plus
~20 AST transformers rewrite ``if``/``while``/``for`` (and bool ops) into
static-graph control-flow ops, with runtime ``convert_*`` helpers that
dispatch on whether the condition is a tensor (SURVEY.md §2.2 "Dy2Static",
§7 hard-part #1).

TPU-native design: the same two-layer shape, retargeted at lax. An AST
pass rewrites the source of a ``to_static`` function so that

- ``if t:`` / ``elif`` → ``convert_if(...)`` → ``lax.cond`` when the
  predicate is traced, plain Python otherwise;
- ``while t:`` → ``convert_while(...)`` → ``lax.while_loop``;
- ``for i in range(t):`` → the while form with an explicit counter;
- ``a and b`` / ``or`` / ``not`` / ``a if c else b`` → short-circuit-
  preserving helpers that lower to ``logical_and``/``lax.cond`` on tensors;
- ``return`` inside a converted branch is folded into the conversion
  (the branch helper's return value IS the function return).

- ``break``/``continue`` in a convertible loop — bare, or as the sole
  body of a plain ``if`` — are rewritten away (the reference's
  break_continue_transformer): continues gate the rest of the body on
  the (possibly tensor) condition, breaks set a carried stop flag that
  also gates the loop test, so ``while True: ... if c: break`` compiles
  to ``lax.while_loop``.

The conversion is attempted lazily, the first time tracing a function hits
a host-sync point (``TraceHostSyncError``); anything the transformer cannot
prove safe (break/continue buried deeper than the supported shapes,
attribute stores inside branches, yield/global/nonlocal, returns inside
loops that must lower to lax) keeps the ORIGINAL statement, so the
behavior degrades to the existing guard: trace again, and if the untouched
statement still host-syncs, fall back to eager with a warning — exactly
the reference's dygraph fallback, but now a last resort instead of the
only answer.

Functions CALLED from a converted function are themselves converted:
every call site is rewritten to route through ``convert_call`` (the
reference's ``convert_call_func.py`` contract), which recursively converts
plain functions, bound methods, and user Layer forwards — cached per
function object, depth-bounded, with per-callee fallback to the original
when a callee's source can't convert.

Known limits (documented, reference has analogues unless noted):

- **Snapshot semantics**: closure variables and module globals are
  snapshotted at CONVERSION time (the first trace that hit a host sync).
  The reference resolves globals live at every call; here a converted
  function keeps reading the values its module/closure had when it was
  converted. Rebinding a global after conversion is NOT seen by the
  converted function (a guard test pins this divergence).
- **Attribute stores in converted branches**: ``self.x = v`` inside a
  tensor-``if`` branch keeps the whole ``if`` in Python — if the predicate
  is traced, the function degrades to the eager guard with the standard
  fallback warning rather than silently tracing a side effect into one
  branch.
- Functions defined INSIDE a converted function are not re-converted
  (their source lives in the transformed module, invisible to
  ``inspect.getsource``).
- Loop-carried variables must exist before a lax-lowered loop.
"""
from __future__ import annotations

import ast
import inspect
import textwrap
import types
from typing import Callable, Optional

__all__ = [
    "convert_to_static", "Dy2StaticUnsupported", "Dy2StaticError",
    "UNDEFINED", "conversion_log",
]

_JST = "__paddle_jst__"


class Dy2StaticUnsupported(Exception):
    """Raised (internally) when a function cannot be AST-converted; the
    caller falls back to the eager guard path."""


class Dy2StaticError(RuntimeError):
    """A converted program is structurally invalid for lax lowering (e.g. a
    variable defined in only one branch of a tensor ``if``)."""


class _UndefinedType:
    """Placeholder for a name not yet bound when entering converted control
    flow. Any use raises, naming the likely cause."""

    _err = (
        "a variable used here may be undefined on some path through "
        "converted (dy2static) control flow — define it before the "
        "if/while, or keep the branch in pure Python"
    )

    def __repr__(self):
        return "<paddle_tpu dy2static UNDEFINED>"

    def _raise(self, *a, **k):
        raise Dy2StaticError(self._err)

    __bool__ = __call__ = __iter__ = __len__ = _raise
    __add__ = __radd__ = __sub__ = __mul__ = __truediv__ = _raise
    __eq__ = __ne__ = __lt__ = __gt__ = __le__ = __ge__ = _raise

    def __getattr__(self, name):
        raise Dy2StaticError(self._err)


UNDEFINED = _UndefinedType()


# --------------------------------------------------------------------- #
# runtime dispatch helpers (the generated code calls these via __paddle_jst__)
# --------------------------------------------------------------------- #

def _raw(x):
    from ..framework.core import Tensor
    from ..framework.op import raw

    return raw(x) if isinstance(x, Tensor) else x


def _is_traced(x):
    from ..framework.core import is_tracer_value

    try:
        return is_tracer_value(_raw(x))
    except Exception:
        return False


def truthy(x) -> bool:
    import jax
    import numpy as np

    v = _raw(x)
    if isinstance(v, (jax.Array, np.ndarray)):
        return bool(np.asarray(v).reshape(()))
    return bool(v)


def inits(*thunks):
    """Current values of carried names; UNDEFINED for not-yet-bound ones."""
    out = []
    for t in thunks:
        try:
            out.append(t())
        except NameError:
            out.append(UNDEFINED)
    return tuple(out)


def _check_defined(init, what):
    if any(v is UNDEFINED for v in init):
        raise Dy2StaticError(
            f"{what}: a carried variable is undefined before the converted "
            "control flow; lax lowering needs every loop/branch variable "
            "bound (with its final shape/dtype) beforehand")


def _branch_args(init):
    """Fresh per-branch Tensor wrappers: both lax.cond branches trace over
    the same init objects, and a Tensor mutated in-place while tracing
    branch A must not leak its rebound value into branch B's trace."""
    from ..framework.core import Tensor

    return tuple(Tensor(v._value) if isinstance(v, Tensor) else v for v in init)


def convert_if(pred, t_fn, f_fn, init):
    """Statement-form if: branch helpers take and return the carried tuple."""
    p = _raw(pred)
    if not _is_traced(p):
        return tuple((t_fn if truthy(p) else f_fn)(*init))
    # UNDEFINED entries are fine when both branches bind them (or neither
    # reads them); lax.cond's structure check catches the one-sided case
    from ..static.nn import cond as st_cond

    try:
        out = st_cond(pred, lambda: tuple(t_fn(*_branch_args(init))),
                      lambda: tuple(f_fn(*_branch_args(init))))
    except TypeError as e:
        raise Dy2StaticError(
            "tensor `if`: both branches must produce every carried variable "
            f"with matching shape/dtype ({e})") from e
    return tuple(out)


def convert_if_ret(pred, t_fn, f_fn, init):
    """Return-form if: the taken branch's return value IS the function
    return value."""
    p = _raw(pred)
    if not _is_traced(p):
        return (t_fn if truthy(p) else f_fn)(*init)
    from ..static.nn import cond as st_cond

    try:
        return st_cond(pred, lambda: t_fn(*_branch_args(init)),
                       lambda: f_fn(*_branch_args(init)))
    except TypeError as e:
        raise Dy2StaticError(
            "tensor `if`: both return paths must produce matching "
            f"structure/shape/dtype ({e})") from e


def convert_while(test_fn, body_fn, init):
    vars_ = tuple(init)
    traced_state = any(_is_traced(v) for v in vars_ if v is not UNDEFINED)
    if not traced_state:
        # Python loop while everything stays concrete. The state (or the
        # test — e.g. a closure tensor enters the math) can BECOME traced
        # mid-loop; the iterations already run are plain value updates, so
        # the lax loop below continues soundly from the current state.
        c = test_fn(*vars_)
        while not _is_traced(c):
            if not truthy(c):
                return vars_
            vars_ = tuple(body_fn(*vars_))
            if any(_is_traced(v) for v in vars_ if v is not UNDEFINED):
                break
            c = test_fn(*vars_)
    _check_defined(vars_, "while")
    from ..static.nn import while_loop as st_while

    try:
        out = st_while(test_fn, lambda *vs: tuple(body_fn(*vs)), list(vars_))
    except TypeError as e:
        raise Dy2StaticError(
            "tensor `while`: the loop body must keep every carried "
            f"variable's shape/dtype fixed across iterations ({e})") from e
    return tuple(out)


def convert_cast(caster, x):
    """``float(x)`` / ``int(x)`` / ``bool(x)`` inside converted code
    (reference: dy2static convert_var_dtype): on a TRACED tensor the cast
    becomes a 0-d ``astype`` so the program keeps compiling — the result
    is a scalar tensor, which composes with arithmetic/comparisons like
    the Python scalar would. Non-traced values (and shadowed caster
    names) cast normally."""
    if caster in (float, int, bool) and _is_traced(x):
        import jax.numpy as jnp

        from ..framework.core import Tensor

        dt = {float: jnp.float32, int: jnp.int32, bool: jnp.bool_}[caster]
        # reshape(()) enforces size-1, exactly like the Python cast would
        return Tensor(jnp.reshape(jnp.asarray(_raw(x)), ()).astype(dt))
    return caster(x)


def range_cond(i, stop, step):
    """Continuation test for a converted ``for ... in range(...)``; honors
    the step sign on both the Python and tensor paths."""
    ri, rs, rp = _raw(i), _raw(stop), _raw(step)
    if any(_is_traced(v) for v in (ri, rs, rp)):
        import jax.numpy as jnp

        ri = jnp.asarray(ri)
        return ((rp > 0) & (ri < rs)) | ((rp < 0) & (ri > rs))
    return ri < rs if rp > 0 else ri > rs


def _bool_chain(jnp_op, short_circuit_on, first, rest):
    """Shared and_/or_ machinery: Python short-circuit semantics until a
    traced value appears, then an elementwise logical fold (bool dtype) of
    the remaining operands — the reference's convert_logical_* contract."""
    val = first
    for idx, thunk in enumerate(rest):
        if _is_traced(val):
            import jax.numpy as jnp

            out = jnp.asarray(_raw(val)).astype(bool)
            for t in rest[idx:]:
                out = jnp_op(out, jnp.asarray(_raw(t())).astype(bool))
            return out
        if truthy(val) is short_circuit_on:
            return val
        val = thunk()
    return val


def and_(first, *rest):
    import jax.numpy as jnp

    return _bool_chain(jnp.logical_and, False, first, rest)


def or_(first, *rest):
    import jax.numpy as jnp

    return _bool_chain(jnp.logical_or, True, first, rest)


def not_(x):
    if _is_traced(x):
        import jax.numpy as jnp

        return jnp.logical_not(jnp.asarray(_raw(x)).astype(bool))
    return not truthy(x)


def ifexp(pred, t_thunk, f_thunk):
    if not _is_traced(pred):
        return t_thunk() if truthy(pred) else f_thunk()
    from ..static.nn import cond as st_cond

    try:
        return st_cond(pred, t_thunk, f_thunk)
    except TypeError as e:
        raise Dy2StaticError(
            "tensor ternary: both arms must produce matching "
            f"structure/shape/dtype ({e})") from e


# --------------------------------------------------------------------- #
# convert_call: recursive callee conversion
# --------------------------------------------------------------------- #

# Roots whose functions are never converted: framework/numeric libraries
# are already tensor-safe (they use lax / raise host-sync intentionally),
# and converting them would only burn compile time.
_SKIP_ROOTS = frozenset({
    "builtins", "paddle_tpu", "jax", "jaxlib", "numpy", "flax", "optax",
    "chex", "einops", "torch", "math", "cmath", "functools", "itertools",
    "operator", "typing", "collections", "abc", "copy", "random", "re",
    "os", "sys", "warnings", "logging", "dataclasses", "scipy", "pandas",
    "PIL", "json", "pickle", "threading", "queue", "transformers",
})

# Bounds runaway conversion chains (mutually recursive helpers, deep call
# stacks): beyond this depth of nested CONVERTED frames, callees run
# unconverted (tensor control flow there degrades to the eager guard).
_MAX_CONVERT_DEPTH = 32
# Thread-local: concurrent to_static traces on different threads must not
# share the depth counter (one thread exhausting it would silently disable
# conversion on another).
import threading as _threading

_depth_state = _threading.local()


def _get_depth():
    return getattr(_depth_state, "depth", 0)

_ccall_cache: dict = {}  # id-keyed {raw_fn_id: (weakref, converted|False)}


def _depth_guard(converted):
    import functools

    @functools.wraps(converted)
    def run(*a, **k):
        _depth_state.depth = _get_depth() + 1
        try:
            return converted(*a, **k)
        finally:
            _depth_state.depth -= 1

    return run


def _convert_fn_cached(raw_fn):
    """Convert a plain function once per function OBJECT (closure cells are
    snapshotted per object); False caches a failed attempt."""
    import weakref

    key = id(raw_fn)
    hit = _ccall_cache.get(key)
    if hit is not None and hit[0]() is raw_fn:
        return hit[1] or None
    try:
        conv = _convert_raw(raw_fn)
        conv = _depth_guard(conv)
    except Dy2StaticUnsupported as e:
        _log_conversion(raw_fn, "fallback", reason=str(e))
        conv = None
    except (RecursionError, MemoryError):
        raise
    except Exception as e:
        _log_conversion(raw_fn, "fallback",
                        reason=f"{type(e).__name__}: {e}")
        conv = None
    try:
        ref = weakref.ref(
            raw_fn, lambda _r, _k=key, _c=_ccall_cache: _c.pop(_k, None))
        _ccall_cache[key] = (ref, conv if conv is not None else False)
    except TypeError:
        pass
    return conv


def _layer_forward_call(layer, fwd):
    """Invoke a converted forward through the Layer hook protocol (the one
    definition lives on Layer._run_with_hooks)."""

    def run(*inputs, **kwargs):
        return layer._run_with_hooks(fwd, inputs, kwargs)

    return run


def convert_call(f):
    """Reference ``dy2static/convert_call_func.py::convert_call`` parity:
    every call site inside a converted function routes its callee through
    here, so tensor-dependent control flow in a HELPER (function, bound
    method, or a user Layer's forward) compiles too — the whole reachable
    call graph converts, not just the entry.

    Returns the converted callable when ``f`` is a user-defined function /
    method / Layer whose source converts; otherwise returns ``f`` itself
    (per-callee fallback — an inconvertible callee degrades that callee,
    not the whole program). Conversions are cached per function object and
    bounded at ``_MAX_CONVERT_DEPTH`` nested converted frames.

    Not converted (documented): callables from framework/stdlib modules
    (``_SKIP_ROOTS``), classes (constructors), arbitrary callable objects,
    and functions defined INSIDE a converted function (their source lives
    in the transformed module and is unavailable to ``inspect``)."""
    if not callable(f) or isinstance(f, type):
        return f
    if _get_depth() >= _MAX_CONVERT_DEPTH:
        return f
    if isinstance(f, (types.BuiltinFunctionType, types.BuiltinMethodType)):
        return f
    import functools

    if isinstance(f, functools.partial):
        inner = convert_call(f.func)
        if inner is f.func:
            return f
        return functools.partial(inner, *f.args, **(f.keywords or {}))
    # a Layer instance: convert its forward, keep the hook protocol
    try:
        from ..nn.layer import Layer
    except Exception:
        Layer = None
    if Layer is not None and isinstance(f, Layer):
        fwd0 = f.forward  # capture once: attribute access rebinds each time
        fwd = convert_call(fwd0)
        if fwd is fwd0:
            return f
        return _layer_forward_call(f, fwd)
    if isinstance(f, types.MethodType):
        raw_fn, bound_self = f.__func__, f.__self__
    elif isinstance(f, types.FunctionType):
        raw_fn, bound_self = f, None
    else:
        return f
    if getattr(raw_fn, "__dy2static_original__", None) is not None:
        return f  # already converted
    mod_root = (getattr(raw_fn, "__module__", "") or "").split(".")[0]
    if mod_root in _SKIP_ROOTS:
        return f
    conv = _convert_fn_cached(raw_fn)
    if conv is None:
        return f
    return conv.__get__(bound_self) if bound_self is not None else conv


# --------------------------------------------------------------------- #
# static analysis
# --------------------------------------------------------------------- #

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


class _Facts(ast.NodeVisitor):
    """Names assigned / hazards inside a statement region (nested function
    scopes excluded — their bindings are their own)."""

    def __init__(self):
        self.assigned = set()
        self.attr_store = False
        self.hazard = False  # yield/await/global/nonlocal/del
        self.returns = 0
        self.raises = 0  # lax traces BOTH branches: a raise would fire always
        self.breaks_unbound = 0  # break/continue not bound to an inner loop
        self._loop_depth = 0

    # -- scope boundaries --
    def visit_FunctionDef(self, node):
        self.assigned.add(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.assigned.add(node.name)

    def visit_Lambda(self, node):
        pass

    # -- bindings --
    def _target(self, t):
        if isinstance(t, ast.Name):
            self.assigned.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e)
        elif isinstance(t, ast.Starred):
            self._target(t.value)
        elif isinstance(t, ast.Subscript):
            # x[i] = v rebinds x's value on the tape — treat as assigning x
            if isinstance(t.value, ast.Name):
                self.assigned.add(t.value.id)
            else:
                self.attr_store = True
        elif isinstance(t, ast.Attribute):
            self.attr_store = True

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t)
        self.visit(node.value)

    def visit_AugAssign(self, node):
        self._target(node.target)
        self.visit(node.value)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._target(node.target)
            self.visit(node.value)

    def visit_NamedExpr(self, node):
        self._target(node.target)
        self.visit(node.value)

    def visit_For(self, node):
        self._target(node.target)
        self.visit(node.iter)
        self._loop_depth += 1
        for s in node.body + node.orelse:
            self.visit(s)
        self._loop_depth -= 1

    def visit_While(self, node):
        self.visit(node.test)
        self._loop_depth += 1
        for s in node.body + node.orelse:
            self.visit(s)
        self._loop_depth -= 1

    def visit_withitem(self, node):
        if node.optional_vars is not None:
            self._target(node.optional_vars)
        self.visit(node.context_expr)

    def visit_ExceptHandler(self, node):
        if node.name:
            self.assigned.add(node.name)
        for s in node.body:
            self.visit(s)

    def visit_Import(self, node):
        for a in node.names:
            self.assigned.add((a.asname or a.name).split(".")[0])

    visit_ImportFrom = visit_Import

    # -- hazards --
    def visit_Return(self, node):
        self.returns += 1
        if node.value is not None:
            self.visit(node.value)

    def visit_Break(self, node):
        if self._loop_depth == 0:
            self.breaks_unbound += 1

    visit_Continue = visit_Break

    def visit_Raise(self, node):
        self.raises += 1

    def visit_Assert(self, node):
        self.raises += 1

    def visit_Global(self, node):
        self.hazard = True

    visit_Nonlocal = visit_Global

    def visit_Yield(self, node):
        self.hazard = True

    visit_YieldFrom = visit_Await = visit_Yield

    def visit_Delete(self, node):
        self.hazard = True


def _facts(stmts) -> _Facts:
    f = _Facts()
    for s in stmts if isinstance(stmts, list) else [stmts]:
        f.visit(s)
    return f


# Container-mutating method names (upstream dy2static's list_transformer
# scope): calling any of these inside a converted (lax) loop would mutate
# the Python object once at trace time instead of once per iteration.
# Deliberately EXCLUDES names that are also Tensor methods (add, clear,
# update, pop) — a false positive there would de-compile working loops.
_CONTAINER_MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "setdefault", "popitem",
    "discard",
})


def _has_container_mutation(stmts) -> bool:
    for s in stmts if isinstance(stmts, list) else [stmts]:
        for node in ast.walk(s):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _CONTAINER_MUTATORS):
                return True
    return False


def _is_range_for(st: "ast.For") -> bool:
    """The ONE definition of the convertible for-loop shape:
    ``for <name> in range(a[, b[, c]])`` with positional args only.
    Shared by _convert_for and _fold_ret_loop — widening the accepted
    forms in one place widens both paths."""
    return (
        isinstance(st.target, ast.Name)
        and isinstance(st.iter, ast.Call)
        and isinstance(st.iter.func, ast.Name)
        and st.iter.func.id == "range"
        and not st.iter.keywords
        and 1 <= len(st.iter.args) <= 3
    )


def _loaded_names(node) -> set:
    out = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            out.add(n.id)
    return out


class _ExprRewriter(ast.NodeTransformer):
    """``and``/``or``/``not``/ternary → runtime dispatch helpers (preserving
    Python short-circuiting via thunks), and every user call site
    ``f(args)`` → ``convert_call(f)(args)`` so callees are recursively
    converted at call time (the reference's convert_call_func.convert_call
    contract). Stops at nested function scopes."""

    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_Lambda = visit_ClassDef = visit_FunctionDef

    def visit_Call(self, node):
        self.generic_visit(node)
        # zero-arg super() is compiled magic (needs the caller frame's
        # __class__ cell) — routing it through convert_call would break it
        if isinstance(node.func, ast.Name) and node.func.id == "super":
            return node
        # cast transform (reference: convert_var_dtype): float(x)/int(x)/
        # bool(x) on a traced scalar becomes a 0-d astype instead of a
        # host sync. The NAME node is passed through, so a shadowed
        # `float` resolves to the user's binding and casts normally.
        if (isinstance(node.func, ast.Name)
                and node.func.id in ("float", "int", "bool")
                and len(node.args) == 1 and not node.keywords
                and not isinstance(node.args[0], ast.Starred)):
            return self._call("convert_cast", [node.func, node.args[0]])
        node.func = self._call("convert_call", [node.func])
        return node

    @staticmethod
    def _thunk(expr):
        return ast.Lambda(
            args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                               kw_defaults=[], defaults=[]),
            body=expr)

    def _call(self, name, args):
        return ast.Call(
            func=ast.Attribute(value=ast.Name(id=_JST, ctx=ast.Load()),
                               attr=name, ctx=ast.Load()),
            args=args, keywords=[])

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        head, rest = node.values[0], node.values[1:]
        name = "and_" if isinstance(node.op, ast.And) else "or_"
        return self._call(name, [head] + [self._thunk(v) for v in rest])

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return self._call("not_", [node.operand])
        return node

    def visit_IfExp(self, node):
        self.generic_visit(node)
        return self._call(
            "ifexp", [node.test, self._thunk(node.body), self._thunk(node.orelse)])


# --------------------------------------------------------------------- #
# the converter
# --------------------------------------------------------------------- #

def _jst_call(name: str, arg_src: str) -> str:
    return f"{_JST}.{name}({arg_src})"


def _parse_stmt(src: str) -> ast.stmt:
    return ast.parse(src).body[0]


class _FunctionConverter:
    def __init__(self, fndef: ast.FunctionDef):
        self.fndef = fndef
        self.counter = 0
        # function-wide positional name facts for while-carry pruning
        params = {a.arg for a in (
            fndef.args.posonlyargs + fndef.args.args + fndef.args.kwonlyargs)}
        if fndef.args.vararg:
            params.add(fndef.args.vararg.arg)
        if fndef.args.kwarg:
            params.add(fndef.args.kwarg.arg)
        self.params = params
        self.assign_lines: dict = {}
        self.load_lines: dict = {}
        # human-readable reasons for constructs left as Python / degraded
        # (surfaced via conversion_report)
        self.notes: list = []
        self._index_positions(fndef)

    def _index_positions(self, fndef):
        for n in ast.walk(fndef):
            if isinstance(n, ast.Name) and hasattr(n, "lineno"):
                book = (self.assign_lines
                        if isinstance(n.ctx, (ast.Store, ast.Del))
                        else self.load_lines)
                book.setdefault(n.id, []).append(n.lineno)

    def run(self) -> ast.FunctionDef:
        top = _facts(self.fndef.body)
        if top.hazard:
            raise Dy2StaticUnsupported("yield/global/nonlocal/del in function")
        self.fndef.body = self._block(self.fndef.body, fn_tail=True)
        return self.fndef

    # -- naming --
    def _fresh(self, kind):
        self.counter += 1
        return f"_pd_{kind}_{self.counter}"

    # -- emission --
    def _helper(self, name, carried, body):
        # template-parse the def so version-specific AST fields
        # (py3.12 type_params etc.) come out right
        tmpl = _parse_stmt(f"def {name}({', '.join(carried)}):\n    pass")
        tmpl.body = body
        return tmpl

    def _carried_return(self, carried):
        return _parse_stmt(
            "return (" + "".join(f"{c}, " for c in carried) + ")")

    def _inits_src(self, carried):
        lams = ", ".join(f"lambda: {c}" for c in carried)
        return f"{_JST}.inits({lams})"

    def _assign_call(self, call_src, test_expr):
        """``(a, b,) = __paddle_jst__.convert_*(<test>, ...)`` with the real
        test AST spliced over the __PDTEST__ placeholder."""
        st = _parse_stmt(call_src)
        if test_expr is not None:
            for n in ast.walk(st):
                for field, val in ast.iter_fields(n):
                    if isinstance(val, ast.Name) and val.id == "__PDTEST__":
                        setattr(n, field, test_expr)
                    elif isinstance(val, list):
                        for i, v in enumerate(val):
                            if isinstance(v, ast.Name) and v.id == "__PDTEST__":
                                val[i] = test_expr
        return st

    # -- block processing --
    def _block(self, stmts, fn_tail):
        """Process a statement block. fn_tail=True means falling off the end
        of this block ends the FUNCTION (so return-bearing ifs may be folded
        into convert_if_ret); inside loop/with/try bodies it is False and
        return-bearing ifs stay Python."""
        out = []
        for i, st in enumerate(stmts):
            if isinstance(st, ast.If):
                facts = _facts([st])
                if facts.returns and fn_tail and self._if_convertible(st):
                    out.extend(self._fold_ret_if(st, stmts[i + 1:]))
                    return out
                out.extend(self._convert_stmt(st, fn_tail))
            elif isinstance(st, (ast.While, ast.For)) and fn_tail \
                    and _facts(st.body).returns:
                folded = self._fold_ret_loop(st)
                if folded is None:
                    out.extend(self._convert_stmt(st, fn_tail))
                    continue
                loop_stmts, post = folded
                out.extend(loop_stmts)
                out.extend(self._block(post + stmts[i + 1:], fn_tail=True))
                return out
            else:
                out.extend(self._convert_stmt(st, fn_tail))
        return out

    def _ret_block(self, stmts, cont):
        """Block for a return-form helper: always ends in Return. ``cont``
        is the continuation (statements that run if this block falls
        through)."""
        out = []
        stmts = list(stmts)
        i = 0
        while True:
            if i >= len(stmts):
                if cont:
                    stmts, cont, i = list(cont), [], 0
                    continue
                out.append(ast.Return(value=None))
                return out
            st = stmts[i]
            if isinstance(st, ast.Return):
                out.append(self._expr_pass(st))
                return out
            if isinstance(st, ast.If) and _facts([st]).returns \
                    and self._if_convertible(st):
                out.extend(self._fold_ret_if(st, stmts[i + 1:] + cont))
                return out
            if isinstance(st, (ast.While, ast.For)) \
                    and _facts(st.body).returns:
                folded = self._fold_ret_loop(st)
                if folded is not None:
                    loop_stmts, post = folded
                    out.extend(loop_stmts)
                    out.extend(self._ret_block(
                        post + stmts[i + 1:], cont))
                    return out
            out.extend(self._convert_stmt(st, fn_tail=True))
            i += 1

    def _expr_pass(self, st):
        return ast.fix_missing_locations(_ExprRewriter().visit(st))

    # -- if --
    def _if_convertible(self, st: ast.If) -> bool:
        f = _facts(st.body + st.orelse)
        return not (f.hazard or f.attr_store or f.breaks_unbound or f.raises)

    def _convert_stmt(self, st, fn_tail):
        """Convert one statement (returns a list of replacement stmts)."""
        if isinstance(st, ast.If):
            facts = _facts([st])
            if facts.returns or not self._if_convertible(st):
                # stays Python; still convert nested blocks
                st.test = self._expr_value(st.test)
                st.body = self._block(st.body, fn_tail=False)
                st.orelse = self._block(st.orelse, fn_tail=False)
                return [ast.fix_missing_locations(st)]
            return self._convert_plain_if(st, fn_tail)
        if isinstance(st, ast.While):
            return self._convert_while(st, fn_tail)
        if isinstance(st, ast.For):
            return self._convert_for(st, fn_tail)
        if isinstance(st, ast.Assert):
            # asserts stay Python: a traced condition host-syncs at trace
            # time and the callable degrades to eager (XLA has no abort).
            # Recorded so conversion_report shows WHY a model fell back.
            self.notes.append(
                f"assert at line {st.lineno}: asserts run as Python — a "
                "tensor condition host-syncs and degrades the callable "
                "to eager (XLA programs cannot abort)")
            return [self._expr_pass(st)]
        if isinstance(st, (ast.With, ast.Try)):
            if isinstance(st, ast.Try):
                # documented fallback: XLA control flow cannot branch on
                # exceptions, so the try region executes as plain Python
                # during trace (handlers only see trace-time errors) and
                # return-form folding is disabled inside it
                self.notes.append(
                    f"try/except at line {st.lineno}: region runs as "
                    "Python during trace — lax cannot branch on "
                    "exceptions; handlers catch trace-time errors only")
            for field in ("body", "orelse", "finalbody"):
                blk = getattr(st, field, None)
                if blk:
                    setattr(st, field, self._block(blk, fn_tail=False))
            if isinstance(st, ast.Try):
                for h in st.handlers:
                    h.body = self._block(h.body, fn_tail=False)
            return [ast.fix_missing_locations(self._expr_pass(st))]
        return [self._expr_pass(st)]

    def _expr_value(self, expr):
        return ast.fix_missing_locations(_ExprRewriter().visit(expr))

    def _convert_plain_if(self, st, fn_tail):
        carried = sorted(_facts(st.body + st.orelse).assigned)
        t_name, f_name = self._fresh("ift"), self._fresh("iff")
        t_body = self._block(st.body, fn_tail=False) + [self._carried_return(carried)]
        f_body = self._block(st.orelse, fn_tail=False) + [self._carried_return(carried)]
        helpers = [self._helper(t_name, carried, t_body),
                   self._helper(f_name, carried, f_body)]
        if carried:
            targets = ", ".join(carried)
            call = (f"({targets},) = " + _jst_call(
                "convert_if",
                f"__PDTEST__, {t_name}, {f_name}, {self._inits_src(carried)}"))
        else:
            call = _jst_call(
                "convert_if", f"__PDTEST__, {t_name}, {f_name}, ()")
        stmt = self._assign_call(call, self._expr_value(st.test))
        return [ast.fix_missing_locations(h) for h in helpers] + \
            [ast.fix_missing_locations(stmt)]

    def _fold_ret_if(self, st, cont):
        """If with returns, in fn-tail position → return-form conversion."""
        t_name, f_name = self._fresh("rift"), self._fresh("riff")
        t_body = self._ret_block(st.body, cont)
        f_body = self._ret_block(st.orelse, cont)
        carried = sorted((_facts(st.body + st.orelse).assigned
                          | _facts(cont).assigned) if cont
                         else _facts(st.body + st.orelse).assigned)
        helpers = [self._helper(t_name, carried, t_body),
                   self._helper(f_name, carried, f_body)]
        call = "return " + _jst_call(
            "convert_if_ret",
            f"__PDTEST__, {t_name}, {f_name}, {self._inits_src(carried)}")
        stmt = self._assign_call(call, self._expr_value(st.test))
        return [ast.fix_missing_locations(h) for h in helpers] + \
            [ast.fix_missing_locations(stmt)]

    # -- early return in loops (reference: dy2static return_transformer) --
    def _returns_to_breaks(self, stmts):
        """Rewrite top-level ``return [expr]`` in a loop body — bare, or as
        the SOLE body of a plain ``if`` — into a carried boolean flag + a
        break. The return VALUE is not captured here: the loop exits at
        the flagged iteration, so the expr evaluates correctly from the
        post-loop state (which froze at the break). Returns
        (new_stmts, [(flag_name, expr_ast)]) or (None, None) for buried
        return forms."""
        out, rets = [], []
        for s in stmts:
            if isinstance(s, ast.Return):
                r = self._fresh("ret")
                rets.append((r, s.value))
                out.append(ast.copy_location(_parse_stmt(f"{r} = True"), s))
                out.append(ast.copy_location(ast.Break(), s))
                break  # statements after a bare return are dead
            if isinstance(s, ast.If) and not s.orelse and len(s.body) == 1 \
                    and isinstance(s.body[0], ast.Return):
                r = self._fresh("ret")
                rets.append((r, s.body[0].value))
                out.append(ast.copy_location(ast.Assign(
                    targets=[ast.Name(id=r, ctx=ast.Store())],
                    value=s.test), s))
                out.append(ast.copy_location(ast.If(
                    test=ast.Name(id=r, ctx=ast.Load()),
                    body=[ast.copy_location(ast.Break(), s)],
                    orelse=[]), s))
                continue
            if isinstance(s, (ast.For, ast.While)):
                if _facts([s]).returns:
                    return None, None  # return inside a NESTED loop
                out.append(s)
                continue
            if _facts([s]).returns:
                return None, None  # buried (else-branch, with, try, ...)
            out.append(s)
        return out, rets

    def _fold_ret_loop(self, st):
        """Loop with early returns, in fn-tail position: flags + breaks in
        the loop, then post-loop return-form ifs. Returns
        (converted_loop_stmts, post_stmts_to_process) or None."""
        if st.orelse:
            return None
        if isinstance(st, ast.For) and not _is_range_for(st):
            return None  # non-range for: python fallback handles it
        new_body, rets = self._returns_to_breaks(list(st.body))
        if not rets or new_body is None:
            return None
        cls = ast.While if isinstance(st, ast.While) else ast.For
        if cls is ast.While:
            loop = ast.copy_location(
                ast.While(test=st.test, body=new_body, orelse=[]), st)
        else:
            loop = ast.copy_location(
                ast.For(target=st.target, iter=st.iter, body=new_body,
                        orelse=[], type_comment=None), st)
        ast.fix_missing_locations(loop)
        # force-carry the flags and any return-expr name the body assigns:
        # both are read AFTER the loop by generated code the position books
        # cannot see
        body_assigned = _facts(new_body).assigned
        extra = {r for r, _ in rets}
        for _, e in rets:
            if e is not None:
                extra |= _loaded_names(e) & body_assigned
        pre = [ast.fix_missing_locations(ast.copy_location(
            _parse_stmt(f"{r} = False"), st)) for r, _ in rets]
        conv = (self._convert_while if cls is ast.While
                else self._convert_for)(
            loop, fn_tail=False, extra_carried=sorted(extra))
        post = []
        for r, e in rets:
            post.append(ast.fix_missing_locations(ast.copy_location(ast.If(
                test=ast.Name(id=r, ctx=ast.Load()),
                body=[ast.copy_location(ast.Return(value=e), st)],
                orelse=[]), st)))
        return pre + conv, post

    # -- while / for --
    def _carried_for_loop(self, node, body_assigned, test_loads):
        """Loop-carried names: assigned in the body AND live across
        iterations (read in the test, bound before the loop, or read after
        it). Iteration-local temps stay helper-local."""
        end = getattr(node, "end_lineno", node.lineno)
        carried = set()
        for n in body_assigned:
            if n in test_loads or n in self.params:
                carried.add(n)
                continue
            if any(l < node.lineno for l in self.assign_lines.get(n, [])):
                carried.add(n)
                continue
            if any(l > end for l in self.load_lines.get(n, [])):
                carried.add(n)
        return sorted(carried)

    def _loop_convertible(self, node) -> bool:
        f = _facts(node.body)
        if f.hazard or f.attr_store or f.returns or f.raises \
                or f.breaks_unbound or node.orelse:
            return False
        if _has_container_mutation(node.body):
            # tensor-array semantics (upstream list_transformer): a list
            # grown inside a lax loop would capture ONE traced element, not
            # one per iteration. The loop stays a Python loop instead:
            # static bounds UNROLL under trace (fully compiled, the
            # jax-idiomatic tensor-array form); a tensor-state `while`
            # cannot unroll and degrades to the eager guard.
            self.notes.append(
                f"loop at line {node.lineno}: list/container mutation "
                "(.append/.extend/...) in the body — kept as a Python "
                "loop (static bounds unroll compiled; tensor-bound loops "
                "fall back to eager)")
            return False
        return True

    # -- break / continue elimination (reference: dy2static
    #    break_continue_transformer) --
    def _rewrite_bc(self, stmts, brk, cnames):
        """Rewrite top-level ``break``/``continue`` in a loop-body statement
        list — bare, or as the SOLE body of a plain ``if`` — by gating the
        remainder of the body on the (possibly tensor) condition; breaks
        additionally set the carried ``brk`` flag. The generated condition
        temps are appended to ``cnames`` (the caller pre-initializes them
        at the top of the body so a gating ``if`` never carries an
        UNDEFINED out of one branch). Returns (new_stmts, uses_break) or
        None for unsupported forms. Nested loops own their breaks."""
        out, uses = [], False
        for i, s in enumerate(stmts):
            if isinstance(s, ast.Break):
                out.append(_parse_stmt(f"{brk} = True"))
                return out, True
            if isinstance(s, ast.Continue):
                return out, uses
            if isinstance(s, (ast.For, ast.While)):
                out.append(s)  # inner loop owns its breaks
                continue
            if isinstance(s, ast.If) and len(s.body) == 1 and not s.orelse \
                    and isinstance(s.body[0], (ast.Break, ast.Continue)):
                rest = self._rewrite_bc(stmts[i + 1:], brk, cnames)
                if rest is None:
                    return None
                rest_stmts, rest_uses = rest
                cname = self._fresh("bcc")
                cnames.append(cname)
                out.append(ast.Assign(
                    targets=[ast.Name(id=cname, ctx=ast.Store())],
                    value=s.test))
                is_break = isinstance(s.body[0], ast.Break)
                if is_break:
                    out.append(_parse_stmt(f"{brk} = {brk} or {cname}"))
                if rest_stmts:
                    out.append(ast.If(
                        test=ast.UnaryOp(
                            op=ast.Not(),
                            operand=ast.Name(id=cname, ctx=ast.Load())),
                        body=rest_stmts, orelse=[]))
                return out, (is_break or rest_uses)
            if _facts([s]).breaks_unbound:
                return None  # break/continue buried deeper: unsupported
            out.append(s)
        return out, False

    def _debreak_loop(self, st):
        """If the ONLY conversion blocker of a loop is eliminable
        break/continue, return (new_body, uses_break, brk_name); else
        None."""
        f = _facts(st.body)
        if not f.breaks_unbound or f.hazard or f.attr_store or f.returns \
                or f.raises or st.orelse:
            return None
        brk = self._fresh("brk")
        cnames: list = []
        res = self._rewrite_bc(list(st.body), brk, cnames)
        if res is None:
            return None
        new_body, uses_break = res
        inits = [_parse_stmt(f"{c} = False") for c in cnames]
        return inits + new_body, uses_break, brk

    def _convert_while(self, st, fn_tail, extra_carried=()):
        pre = []
        deb = self._debreak_loop(st)
        if deb is not None:
            new_body, uses_break, brk = deb
            test = st.test
            if uses_break:
                pre.append(ast.fix_missing_locations(
                    ast.copy_location(_parse_stmt(f"{brk} = False"), st)))
                test = ast.BoolOp(op=ast.And(), values=[
                    ast.UnaryOp(op=ast.Not(),
                                operand=ast.Name(id=brk, ctx=ast.Load())),
                    test])
            st = ast.copy_location(
                ast.While(test=test, body=new_body, orelse=[]), st)
            ast.fix_missing_locations(st)
        if not self._loop_convertible(st):
            st.test = self._expr_value(st.test)
            st.body = self._block(st.body, fn_tail=False)
            st.orelse = self._block(st.orelse, fn_tail=False)
            return pre + [ast.fix_missing_locations(st)]
        body_assigned = _facts(st.body).assigned
        carried = sorted(set(
            self._carried_for_loop(st, body_assigned,
                                   _loaded_names(st.test)))
            | set(extra_carried))
        t_name, b_name = self._fresh("wt"), self._fresh("wb")
        test_fn = self._helper(
            t_name, carried, [ast.Return(value=self._expr_value(st.test))])
        body_fn = self._helper(
            b_name, carried,
            self._block(st.body, fn_tail=False) + [self._carried_return(carried)])
        if carried:
            targets = ", ".join(carried)
            call = (f"({targets},) = " + _jst_call(
                "convert_while",
                f"{t_name}, {b_name}, {self._inits_src(carried)}"))
        else:
            call = _jst_call("convert_while", f"{t_name}, {b_name}, ()")
        stmt = self._assign_call(call, None)
        return pre + [ast.fix_missing_locations(x)
                      for x in (test_fn, body_fn, stmt)]

    def _convert_for(self, st, fn_tail, extra_carried=()):
        # only `for <name> in range(...)` converts; anything else stays
        # Python (a concrete iterable unrolls under trace, which is the
        # jax-idiomatic outcome for static trip counts anyway)
        pre_bc, brk, orig_st = [], None, st
        is_range_for = _is_range_for(st)
        if is_range_for:
            deb = self._debreak_loop(st)
            if deb is not None:
                new_body, uses_break, brk_name = deb
                st = ast.copy_location(ast.For(
                    target=st.target, iter=st.iter, body=new_body,
                    orelse=[], type_comment=None), st)
                ast.fix_missing_locations(st)
                if uses_break:
                    brk = brk_name
                    pre_bc.append(ast.fix_missing_locations(ast.copy_location(
                        _parse_stmt(f"{brk} = False"), st)))
        convertible = is_range_for and self._loop_convertible(st)
        if not convertible:
            # fall back with the ORIGINAL statement: a plain Python for of
            # the debroken body would not stop iterating on the brk flag
            st = orig_st
            st.iter = self._expr_value(st.iter)
            st.body = self._block(st.body, fn_tail=False)
            st.orelse = self._block(st.orelse, fn_tail=False)
            return [ast.fix_missing_locations(st)]
        var = st.target.id
        a = [self._expr_value(x) for x in st.iter.args]
        zero = ast.Constant(value=0)
        one = ast.Constant(value=1)
        if len(a) == 1:
            start, stop, step = zero, a[0], one
        elif len(a) == 2:
            start, stop, step = a[0], a[1], one
        else:
            start, stop, step = a
        # a dedicated counter drives the loop so the user's loop variable
        # keeps Python's post-loop value (last iterated, NOT the failing
        # bound). Known divergence: an empty range leaves `var` bound to
        # start where Python leaves it unbound.
        i_name = self._fresh("i")
        stop_name, step_name = self._fresh("stop"), self._fresh("step")
        pre = [
            ast.Assign(targets=[ast.Name(id=i_name, ctx=ast.Store())], value=start),
            ast.Assign(targets=[ast.Name(id=var, ctx=ast.Store())],
                       value=ast.Name(id=i_name, ctx=ast.Load())),
            ast.Assign(targets=[ast.Name(id=stop_name, ctx=ast.Store())], value=stop),
            ast.Assign(targets=[ast.Name(id=step_name, ctx=ast.Store())], value=step),
        ]
        body_assigned = _facts(st.body).assigned | {var, i_name}
        extra = {brk} if brk else set()
        carried = sorted(set(
            self._carried_for_loop(st, body_assigned, {i_name} | extra))
            | {var, i_name} | extra | set(extra_carried))
        t_name, b_name = self._fresh("ft"), self._fresh("fb")
        rc = _parse_stmt(
            f"{_JST}.range_cond({i_name}, {stop_name}, {step_name})").value
        if brk:
            rc = self._expr_value(ast.BoolOp(op=ast.And(), values=[
                ast.UnaryOp(op=ast.Not(),
                            operand=ast.Name(id=brk, ctx=ast.Load())),
                rc]))
        test_fn = self._helper(t_name, carried, [ast.Return(value=rc)])
        set_var = _parse_stmt(f"{var} = {i_name}")
        inc = _parse_stmt(f"{i_name} = {i_name} + {step_name}")
        body_fn = self._helper(
            b_name, carried,
            [set_var] + self._block(st.body, fn_tail=False)
            + [inc, self._carried_return(carried)])
        targets = ", ".join(carried)
        call = (f"({targets},) = " + _jst_call(
            "convert_while", f"{t_name}, {b_name}, {self._inits_src(carried)}"))
        stmt = self._assign_call(call, None)
        return [ast.fix_missing_locations(x)
                for x in pre_bc + pre + [test_fn, body_fn, stmt]]


# --------------------------------------------------------------------- #
# source-level plumbing
# --------------------------------------------------------------------- #

_cache: dict = {}


def _transformed_code(func):
    """Transform func's source once per CODE object; returns the compiled
    module code and the def's name. Per-function state (closure cells,
    defaults, globals) is bound by _convert_raw for each function object —
    two closures over one code object must not share snapshots."""
    key = func.__code__
    if key in _cache:
        return _cache[key]
    try:
        src = textwrap.dedent(inspect.getsource(func))
    except (OSError, TypeError) as e:
        raise Dy2StaticUnsupported(f"source unavailable: {e}") from e
    try:
        mod = ast.parse(src)
    except SyntaxError as e:  # e.g. source slice of a lambda
        raise Dy2StaticUnsupported(f"unparsable source: {e}") from e
    if not mod.body or not isinstance(mod.body[0], ast.FunctionDef):
        raise Dy2StaticUnsupported("not a plain function definition")
    fndef = mod.body[0]
    for dec in fndef.decorator_list:
        dec_src = ast.unparse(dec)
        if not any(tok in dec_src for tok in ("to_static", "jit", "dygraph_to_static")):
            raise Dy2StaticUnsupported(f"foreign decorator {dec_src!r}")
    fndef.decorator_list = []

    converter = _FunctionConverter(fndef)
    fndef = converter.run()
    notes = list(converter.notes)

    freevars = func.__code__.co_freevars
    if freevars:
        factory = _parse_stmt(
            f"def _pd_factory({', '.join(freevars)}):\n"
            f"    pass\n"
            f"    return {fndef.name}")
        factory.body = [fndef, factory.body[-1]]
        mod = ast.Module(body=[factory], type_ignores=[])
    else:
        mod = ast.Module(body=[fndef], type_ignores=[])
    ast.fix_missing_locations(mod)
    if get_code_level() > 0:
        print(f"# dy2static transformed code of {func.__qualname__}:\n"
              + ast.unparse(mod))
    code = compile(mod, filename=f"<dy2static {func.__qualname__}>", mode="exec")
    _cache[key] = (code, fndef.name, freevars, notes)
    return _cache[key]


# ---- conversion accounting (surfaced by StaticFunction.conversion_report;
# VERDICT r4 weak #6: a mostly-fallen-back model must be inspectable) ----
_conversion_log: dict = {}  # qualname -> {status, reason, notes}


def _log_conversion(fn, status, reason=None, notes=None):
    # Last writer wins, EXCEPT converted-over-converted merges in place to
    # keep accumulated notes. A later "fallback" deliberately REPLACES a
    # "converted" entry: TracedLayer's host-sync path relies on that to
    # flip the entry function to fallback when the converted form still
    # host-syncs at trace time (jit/__init__.py).
    q = getattr(fn, "__qualname__", None) or repr(fn)
    prev = _conversion_log.get(q)
    entry = {"status": status}
    if reason:
        entry["reason"] = reason
    if notes:
        entry["notes"] = list(notes)
    if prev and prev["status"] == "converted" and status == "converted":
        prev.update(entry)
    else:
        _conversion_log[q] = entry


def conversion_log() -> dict:
    """Snapshot of every convert_call / convert_to_static decision this
    process has made: qualname -> {status: converted|fallback,
    reason?, notes?}."""
    return {k: dict(v) for k, v in _conversion_log.items()}


# ---- debug verbosity (paddle.jit.set_code_level / set_verbosity parity) ----
_code_level = 0
_verbosity = 0


def set_code_level(level=100, also_to_stdout=False):
    """paddle.jit.set_code_level parity: level > 0 prints the dy2static-
    transformed source the next time a function is converted."""
    global _code_level
    _code_level = int(level)


def get_code_level():
    return _code_level


def set_verbosity(level=0, also_to_stdout=False):
    """paddle.jit.set_verbosity parity (conversion logging level)."""
    global _verbosity
    _verbosity = int(level)


def get_verbosity():
    return _verbosity


def _convert_raw(func):
    """Convert a plain (unbound) function; raises Dy2StaticUnsupported."""
    code, fname, freevars, notes = _transformed_code(func)
    _log_conversion(func, "converted", notes=notes)

    import paddle_tpu.jit.dy2static as _self

    # conversion-time snapshot of THIS function's globals (+ the runtime
    # helper module); the converted function resolves module globals
    # through this dict
    g = dict(func.__globals__)
    g[_JST] = _self
    ns: dict = {}
    exec(code, g, ns)
    if freevars:
        cells = [c.cell_contents for c in func.__closure__]
        converted = ns["_pd_factory"](*cells)
    else:
        converted = ns[fname]
    converted.__defaults__ = func.__defaults__
    converted.__kwdefaults__ = func.__kwdefaults__
    # a WEAK ref: a strong one would chain _ccall_cache -> converted ->
    # func and keep the cache's weakref eviction from ever firing for
    # dynamically created functions (the attribute is only used as an
    # is-converted marker)
    import weakref

    converted.__dy2static_original__ = weakref.ref(func)
    return converted


def convert_to_static(fn) -> Optional[Callable]:
    """AST-convert ``fn`` (function or bound method). Returns the converted
    callable, or None when conversion is unsupported (caller falls back to
    the eager guard)."""
    try:
        bound_self = getattr(fn, "__self__", None)
        raw_fn = fn.__func__ if bound_self is not None else fn
        if not isinstance(raw_fn, types.FunctionType):
            return None
        converted = _convert_raw(raw_fn)
        if bound_self is not None:
            return converted.__get__(bound_self)
        return converted
    except Dy2StaticUnsupported as e:
        _log_conversion(fn, "fallback", reason=str(e))
        return None
    except (RecursionError, MemoryError):
        raise
    except Exception as e:
        # conversion is best-effort; any surprise degrades to the guard
        _log_conversion(fn, "fallback", reason=f"{type(e).__name__}: {e}")
        return None
