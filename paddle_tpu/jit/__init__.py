"""paddle.jit parity: trace-and-compile stateful Layer programs.

Reference: ``python/paddle/jit/`` — dy2static rewrites Python AST into a
static Program, which the StandaloneExecutor runs (SURVEY.md §2.2 "Dy2Static",
§3.4). TPU-native design (SURVEY.md §7 "Design stance"): ``to_static`` LIFTS a
stateful Layer computation into a pure function of (params, buffers, args,
rng_key), traces it ONCE with jax, and caches the compiled XLA executable per
input signature — the "static graph mode" IS the jit cache. No AST rewriting:
data-dependent Python control flow simply triggers a retrace per branch taken
(guard semantics), and `.numpy()` inside a traced region raises with guidance.

``TrainStep`` is the training analogue: forward + backward + optimizer update
fused into ONE compiled program (the per-op dispatch loop of the reference's
DyGraph — §3.1 step 5 — disappears; XLA schedules the whole step).
"""
from __future__ import annotations

import functools
import time
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..framework import rng as _rng
from ..runtime import compile_cache as _compile_cache
from ..framework.core import Tensor, TraceHostSyncError, no_grad
from ..framework.op import raw
from ..nn.layer import Layer


class InputSpec:
    """paddle.static.InputSpec parity."""

    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        from ..framework.dtypes import convert_dtype

        self.shape = list(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def _is_tensor(x):
    return isinstance(x, Tensor)


def _collect_layers(obj) -> List[Layer]:
    if isinstance(obj, Layer):
        return [obj]
    self_obj = getattr(obj, "__self__", None)
    if isinstance(self_obj, Layer):
        return [self_obj]
    # function closures may reference layers
    layers = []
    closure = getattr(obj, "__closure__", None) or ()
    for cell in closure:
        try:
            v = cell.cell_contents
        except ValueError:
            continue
        if isinstance(v, Layer):
            layers.append(v)
    g = getattr(obj, "__globals__", None)
    return layers


class TracedLayer:
    """The product of ``to_static``: a signature-cached compiled callable."""

    def __init__(self, fn: Callable, layers: Optional[Sequence[Layer]] = None, full_graph=True):
        self._fn = fn
        self._orig_fn = fn
        self._layers = list(layers) if layers is not None else _collect_layers(fn)
        self._cache = {}
        self._last_out_tree = None
        self._eager_fallback = False
        self._tried_dy2static = False
        functools.update_wrapper(self, fn, updated=[])

    def _state_tensors(self):
        tensors, is_buffer = [], []
        seen = set()
        for layer in self._layers:
            for _, p in layer.named_parameters():
                if id(p) not in seen:
                    seen.add(id(p))
                    tensors.append(p)
                    is_buffer.append(False)
            for _, b in layer.named_buffers():
                if id(b) not in seen:
                    seen.add(id(b))
                    tensors.append(b)
                    is_buffer.append(True)
        return tensors, is_buffer

    def __call__(self, *args, **kwargs):
        from ..framework import op as _op

        if _op._capture_program is not None:
            # static Program capture is active: run eagerly so this
            # callable's ops are recorded (a jit trace would freeze its
            # output as a capture-time constant)
            return self._fn(*args, **kwargs)
        if self._eager_fallback or not _to_static_enabled:
            return self._fn(*args, **kwargs)
        from .dy2static import Dy2StaticError

        try:
            return self._traced_call(*args, **kwargs)
        except (TraceHostSyncError, Dy2StaticError):
            # dy2static (SURVEY.md §7 hard-part #1): the trace hit a host
            # sync (`if tensor:`, `while tensor:`, `.numpy()`). First try
            # the AST conversion (Python control flow -> lax.cond/
            # while_loop, mirroring the reference's program_translator);
            # only if the CONVERTED function still host-syncs (e.g. a
            # genuine `.numpy()` call) — or a LATER retrace of the
            # converted fn hits a structural Dy2StaticError — fall back to
            # eager like the reference's dygraph fallback.
            if not self._tried_dy2static:
                self._tried_dy2static = True
                from .dy2static import convert_to_static

                converted = convert_to_static(self._orig_fn)
                if converted is not None:
                    # drop executables compiled against the original fn
                    self._fn = converted
                    self._cache.clear()
                    try:
                        return self._traced_call(*args, **kwargs)
                    except (TraceHostSyncError, Dy2StaticError):
                        self._fn = self._orig_fn
                        self._cache.clear()
            else:
                # a later-signature retrace failed: revert to the original
                # for the eager fallback below
                self._fn = self._orig_fn
                self._cache.clear()
            import warnings

            warnings.warn(
                f"to_static({getattr(self._fn, '__name__', self._fn)!r}): a "
                "host sync point (.numpy()/float()/`if tensor:`) was hit "
                "during tracing and dy2static conversion could not compile "
                "it; falling back to EAGER execution for this callable. Use "
                "paddle_tpu.static.nn.cond/while_loop/switch_case to keep "
                "data-dependent control flow compiled.",
                stacklevel=2,
            )
            self._eager_fallback = True
            from .dy2static import _log_conversion

            _log_conversion(
                self._orig_fn, "fallback",
                reason="host sync survived dy2static conversion; whole "
                       "callable runs eagerly")
            return self._fn(*args, **kwargs)

    def conversion_report(self) -> dict:
        """Which callees compiled and which fell back (VERDICT r4 weak #6:
        a mostly-fallen-back model must be inspectable, not silent).

        Returns ``{"entry": qualname, "entry_mode": "compiled"|"eager",
        "n_converted": int, "n_fallback": int, "callees": {qualname:
        {status, reason?, notes?}}}``. ``callees`` is the process-wide
        convert_call/convert_to_static decision log — populated as traces
        run, so call it AFTER the first execution."""
        from .dy2static import conversion_log

        log = conversion_log()
        n_conv = sum(1 for v in log.values() if v["status"] == "converted")
        return {
            "entry": getattr(self._orig_fn, "__qualname__",
                             repr(self._orig_fn)),
            "entry_mode": "eager" if self._eager_fallback else "compiled",
            "n_converted": n_conv,
            "n_fallback": len(log) - n_conv,
            "callees": log,
        }

    def _traced_call(self, *args, **kwargs):
        state, is_buffer = self._state_tensors()
        state_vals = [t._value for t in state]
        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)
        arg_vals = [l._value if isinstance(l, Tensor) else l for l in leaves]
        # traced leaves: Tensors and ndarray-likes; python scalars stay static
        arr_idx = [
            i
            for i, (l, v) in enumerate(zip(leaves, arg_vals))
            if isinstance(l, Tensor) or isinstance(v, (np.ndarray, jax.Array))
        ]
        tensor_flags = tuple(isinstance(leaves[i], Tensor) for i in arr_idx)
        arr_vals = [jnp.asarray(arg_vals[i]) for i in arr_idx]
        static_part = tuple(
            (i, arg_vals[i]) for i in range(len(arg_vals)) if i not in set(arr_idx)
        )
        training = tuple(l.training for l in self._layers)
        key = (
            treedef,
            tuple((tuple(v.shape), str(v.dtype)) for v in arr_vals),
            static_part,
            training,
            len(state_vals),
        )
        entry = self._cache.get(key)
        miss_t0 = None
        if entry is None:
            # cache miss = an XLA (re)compile; the jit wrapper is lazy, so
            # the timer must span the first jitted call below too
            miss_t0 = time.perf_counter()
            entry = self._compile(treedef, arr_idx, tensor_flags, static_part, state, is_buffer)
            self._cache[key] = entry
        jitted, out_tree_box = entry
        rng_key = _rng.next_key()
        outs_flat, new_state = jitted(state_vals, arr_vals, rng_key)
        if miss_t0 is not None:
            _obs.record_compile(
                "to_static", time.perf_counter() - miss_t0,
                signature=f"{getattr(self._fn, '__qualname__', self._fn)} "
                          f"cache_size={len(self._cache)}")
        for t, v, buf in zip(state, new_state, is_buffer):
            t._value = v
        out_tree = out_tree_box[0]
        wrapped = [Tensor(o) if hasattr(o, "shape") else o for o in outs_flat]
        return jax.tree_util.tree_unflatten(out_tree, wrapped)

    def _compile(self, treedef, arr_idx, tensor_flags, static_part, state, is_buffer):
        fn = self._fn
        out_tree_box = [None]
        static_map = dict(static_part)

        def pure(state_vals, arr_vals, rng_key):
            vals = dict(static_map)
            for i, v, was_t in zip(arr_idx, arr_vals, tensor_flags):
                vals[i] = Tensor(v) if was_t else v
            rebuilt = [vals[i] for i in range(len(vals))]
            a, k = jax.tree_util.tree_unflatten(treedef, rebuilt)
            originals = [t._value for t in state]
            with _rng.trace_key_scope(rng_key):
                try:
                    for t, sv in zip(state, state_vals):
                        t._value = sv
                    out = fn(*a, **k)
                    new_state = [t._value for t in state]
                finally:
                    for t, ov in zip(state, originals):
                        t._value = ov
            out_leaves, out_tree = jax.tree_util.tree_flatten(
                out, is_leaf=_is_tensor
            )
            out_tree_box[0] = out_tree
            out_vals = [o._value if isinstance(o, Tensor) else o for o in out_leaves]
            return out_vals, new_state

        jitted = jax.jit(pure)
        return jitted, out_tree_box

    # introspection helpers (paddle parity-ish)
    @property
    def program_cache_size(self):
        return len(self._cache)


_to_static_enabled = True


def enable_to_static(enable: bool = True):
    """paddle.jit.enable_to_static parity: a global kill-switch for
    ``to_static`` (debugging aid — with it off, decorated functions run
    eagerly; already-built TracedLayers bypass their compiled cache)."""
    global _to_static_enabled
    _to_static_enabled = True if enable else False


def to_static(function=None, input_spec=None, build_strategy=None, full_graph=True, backend=None, **kwargs):
    """paddle.jit.to_static parity: decorator or direct call on Layer/function."""

    def deco(fn):
        if isinstance(fn, Layer):
            traced = TracedLayer(fn.forward, layers=[fn])
            fn.forward = traced
            return fn
        return TracedLayer(fn)

    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    return fn


def ignore_module(modules):
    pass


from .save_load import save, load, TranslatedLayer  # noqa: E402
from .dy2static import (  # noqa: E402,F401  (debug verbosity parity)
    get_code_level,
    get_verbosity,
    set_code_level,
    set_verbosity,
)


class TrainStep:
    """Fused, compiled train step: forward + grad + optimizer in one XLA program.

    TPU-native replacement for the reference's per-op DyGraph train loop
    (SURVEY.md §3.2). Under a device mesh, the same class compiles the SPMD
    program (sharded params in = sharded params out) — used by fleet.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer, donate=True):
        self._model = model
        self._loss_fn = loss_fn
        self._opt = optimizer
        self._cache = {}
        self._donate = donate
        # stable state ordering
        self._params = [p for p in optimizer._parameter_list]
        seen = {id(p) for p in self._params}
        self._buffers = [b for _, b in model.named_buffers() if id(b) not in seen]
        self._extra_params = [
            p for _, p in model.named_parameters() if id(p) not in seen
        ]

    def __call__(self, *batch):
        batch_vals = self._place_batch(
            [raw(b) if isinstance(b, Tensor) else jnp.asarray(b) for b in batch])
        key = tuple((tuple(v.shape), str(v.dtype)) for v in batch_vals)
        loss_val = self._dispatch(key, self._compile, batch_vals)
        return Tensor(loss_val)

    def _dispatch(self, key, build, batch_vals):
        """Shared plumbing for the single-step and multi-step paths: state
        extraction, cache get-or-compile, rng draw, and the write-back of
        params/buffers/optimizer states. Returns the jitted fn's first
        output (loss scalar or per-step losses)."""
        t0 = time.perf_counter()
        params = self._params
        buffers = self._buffers + self._extra_params
        p_vals = [p._value for p in params]
        b_vals = [b._value for b in buffers]
        opt_states = self._opt.functional_states()
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        rng_key = _rng.next_key()
        jitted = self._cache.get(key)
        miss = jitted is None
        aot_hit = None
        if miss:
            jitted = build()
            aot = _compile_cache.resolve()
            if aot is not None:
                try:
                    lowered = jitted.lower(
                        p_vals, b_vals, opt_states, batch_vals, lr, rng_key)
                    ckey = aot.key_for(lowered, config=self._aot_key_parts(),
                                       mesh=self._aot_mesh())
                    jitted, aot_hit = aot.load_or_compile(
                        lowered, ckey, where="train_step")
                except Exception:  # noqa: BLE001
                    # the cache must never break training — fall back to
                    # the plain jit path (first call compiles normally)
                    jitted, aot_hit = build(), None
            self._cache[key] = jitted
        out, new_p, new_b, new_st = jitted(
            p_vals, b_vals, opt_states, batch_vals, lr, rng_key)
        for p, v in zip(params, new_p):
            p._value = v
        for b, v in zip(buffers, new_b):
            b._value = v
        self._opt.load_functional_states(new_st)
        dt = time.perf_counter() - t0
        if miss:
            # compile steps are tracked separately so they don't pollute
            # the steady-state step-time distribution (record_compile also
            # emits the 'compile' span)
            _obs.record_compile("train_step", dt,
                                signature=f"{type(self).__name__} {key!r}",
                                cache_hit=aot_hit)
        else:
            _obs.observe("train_step_seconds", dt)
            _obs.record_span("train_step", dur_s=dt)
        return out

    def _place_batch(self, batch_vals):
        """Hook: distributed subclasses place the batch on the data mesh axes
        (fleet.DistTrainStep)."""
        return batch_vals

    def _aot_key_parts(self):
        """Semantic fingerprint parts for the persistent AOT compile cache
        (``runtime.compile_cache``). The lowered-module hash covers program
        structure; subclasses add strategy/topology knobs so a changed
        layout misses even before lowering diverges."""
        return {"step": type(self).__name__, "donate": bool(self._donate)}

    def _aot_mesh(self):
        """Hook: the mesh whose axis names/sizes key the AOT cache entry
        (fleet.DistTrainStep returns the global mesh)."""
        return None

    def _compiled_for(self, *batch):
        """Lower+compile the step for this batch signature (cached) and
        return the XLA Compiled object for introspection."""
        lowered, key = self._lower_for(*batch, _with_key=True)
        cache = self.__dict__.setdefault("_introspect_compiled", {})
        if key not in cache:
            cache[key] = lowered.compile()
        return cache[key]

    def _lower_for(self, *batch, _with_key=False):
        """The jax Lowered object (pre-optimization StableHLO) for this
        batch signature — program structure BEFORE XLA fusion/CSE.
        Lowerings and compiles are cached per signature: cost_analysis +
        memory_analysis + as_text on one step must not trigger repeated
        multi-second XLA compiles."""
        p_vals = [p._value for p in self._params]
        b_vals = [b._value for b in self._buffers + self._extra_params]
        opt_states = self._opt.functional_states()
        batch_vals = [raw(b) if isinstance(b, Tensor) else jnp.asarray(b) for b in batch]
        batch_vals = self._place_batch(batch_vals)
        lr = jnp.asarray(self._opt.get_lr(), jnp.float32)
        key = tuple((tuple(v.shape), str(v.dtype)) for v in batch_vals)
        jitted = self._cache.get(key)
        if jitted is None:
            jitted = self._compile()
            self._cache[key] = jitted
        elif not hasattr(jitted, "lower"):
            # the dispatch cache may hold an AOT Compiled (persistent
            # compile-cache path) — lower from a fresh traceable jit
            # without evicting the warm executable
            jitted = self._compile()
        rng_key = _rng.next_key()
        lcache = self.__dict__.setdefault("_introspect_lowered", {})
        if key not in lcache:
            lcache[key] = jitted.lower(
                p_vals, b_vals, opt_states, batch_vals, lr, rng_key)
        if _with_key:
            return lcache[key], key
        return lcache[key]

    def cost_analysis(self, *batch):
        """XLA cost analysis (flops, bytes accessed) of the compiled step for
        this batch signature. Feeds MFU reporting (bench.py); the reference
        has no per-program cost introspection — this rides XLA's
        ``compiled.cost_analysis()`` (same source as hapi.flops)."""
        cost = self._compiled_for(*batch).cost_analysis()
        # jax returns either a dict or a one-element list of dicts
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return dict(cost or {})

    def memory_analysis(self, *batch):
        """PER-DEVICE memory footprint of the compiled step, from XLA's
        CompiledMemoryStats: argument/output/temp/code bytes. Under a mesh
        the compiled program is the per-device SPMD program, so ZeRO
        sharding and rematerialization wins are directly measurable here
        (the quantitative counterpart of the reference's GroupSharded
        memory claims; `paddle.device.cuda.memory_*` report the live PJRT
        allocator numbers at runtime)."""
        m = self._compiled_for(*batch).memory_analysis()
        fields = (
            "argument_size_in_bytes", "output_size_in_bytes",
            "temp_size_in_bytes", "alias_size_in_bytes",
            "generated_code_size_in_bytes",
        )
        out = {f: int(getattr(m, f, 0)) for f in fields}
        out["live_size_in_bytes"] = (
            out["argument_size_in_bytes"] + out["output_size_in_bytes"]
            + out["temp_size_in_bytes"] - out["alias_size_in_bytes"]
        )
        return out

    # -- compiled multi-step loops (scan over steps) ------------------------
    def repeat(self, n, *batch):
        """Run ``n`` optimizer steps on the SAME batch inside ONE compiled
        program (lax.scan carrying params/buffers/opt-states); returns the
        per-step losses as a length-``n`` Tensor.

        This is the TPU-idiomatic training-loop shape (MaxText-style
        scan-over-steps): per-step host dispatch disappears — through the
        axon tunnel backend that is ~13ms/step, ~5% of an ERNIE-base step.
        The learning rate is held constant within the compiled window;
        step LR schedulers between windows. Per-step dropout keys are
        folded from one base key (jax.random.fold_in on the step index).
        """
        return self._run_multi(int(n), False, batch)

    def run_steps(self, *stacked_batch):
        """Like ``repeat`` but every batch argument carries a leading
        [n_steps, ...] axis: step i consumes slice i (scan over the data).
        Returns the per-step losses."""
        n = int(raw(stacked_batch[0]).shape[0])
        return self._run_multi(n, True, stacked_batch)

    def _run_multi(self, n, stacked, batch):
        batch_vals = [raw(b) if isinstance(b, Tensor) else jnp.asarray(b) for b in batch]
        if stacked:
            short = [i for i, v in enumerate(batch_vals)
                     if v.ndim == 0 or v.shape[0] != n]
            if short:
                raise ValueError(
                    f"run_steps: batch args {short} have leading axis "
                    f"{[batch_vals[i].shape[0] for i in short]} != {n} "
                    "(every arg must stack one slice per step — JAX's "
                    "clamping gather would otherwise silently repeat the "
                    "last slice)"
                )
            # placement of each per-step slice happens inside the scan body
        else:
            batch_vals = self._place_batch(batch_vals)
        key = ("multi", stacked, n,
               tuple((tuple(v.shape), str(v.dtype)) for v in batch_vals))
        losses = self._dispatch(
            key, lambda: self._jit(self._build_multi(n, stacked)),
            batch_vals)
        return Tensor(losses)

    def _build_multi(self, n, stacked):
        step = self._build_step()
        place = self._place_batch

        def multi(p_vals, b_vals, opt_states, batch_vals, lr, rng_key):
            def body(carry, i):
                p, b, st = carry
                bv = [v[i] for v in batch_vals] if stacked else batch_vals
                if stacked:
                    bv = place(bv)
                loss, p2, b2, st2 = step(
                    p, b, st, bv, lr, jax.random.fold_in(rng_key, i))
                return (p2, b2, st2), loss

            (p, b, st), losses = jax.lax.scan(
                body, (p_vals, b_vals, opt_states), jnp.arange(n))
            return losses, p, b, st

        return multi

    def _compile(self):
        return self._jit(self._build_step())

    def _make_loss_of(self, changed_cell=None):
        """The pure (train_vals, (b_vals, batch, key)) -> (loss, new_b)
        closure shared by every step builder. ``changed_cell`` (a list)
        receives, at trace time, one tuple of per-buffer "was mutated"
        flags — identity comparison during tracing is a static fact, and
        distributed builders use it to decide which buffers need a
        cross-replica mean without burning collectives on constants."""
        model, loss_fn = self._model, self._loss_fn
        params, buffers = self._params, self._buffers + self._extra_params

        def loss_of(train_vals, fixed):
            b_vals, batch_vals, rng_key = fixed
            orig_p = [p._value for p in params]
            orig_b = [b._value for b in buffers]
            with _rng.trace_key_scope(rng_key):
                try:
                    for p, v in zip(params, train_vals):
                        p._value = v
                    for b, v in zip(buffers, b_vals):
                        b._value = v
                    batch_t = [Tensor(v) for v in batch_vals]
                    loss = loss_fn(model, *batch_t)
                    loss_val = raw(loss)
                    new_b = [b._value for b in buffers]
                finally:
                    for p, v in zip(params, orig_p):
                        p._value = v
                    for b, v in zip(buffers, orig_b):
                        b._value = v
            if changed_cell is not None:
                changed_cell[:] = [tuple(
                    nv is not v for nv, v in zip(new_b, b_vals))]
            return loss_val, new_b

        return loss_of

    def _build_step(self):
        opt = self._opt
        trainable = [p.trainable for p in self._params]
        loss_of = self._make_loss_of()

        def step(p_vals, b_vals, opt_states, batch_vals, lr, rng_key):
            (loss_val, new_b), grads = jax.value_and_grad(loss_of, has_aux=True)(
                p_vals, (b_vals, batch_vals, rng_key)
            )
            grads = [g if t else None for g, t in zip(grads, trainable)]
            new_p, new_st = opt.functional_step(p_vals, grads, opt_states, lr)
            return loss_val, new_p, new_b, new_st

        return step

    def _jit(self, step):
        donate = (0, 2) if self._donate else ()
        return jax.jit(step, donate_argnums=donate)
