"""End-to-end distributed tracing acceptance (docs/OBSERVABILITY.md §8-§9).

Two real ``python -m paddle_tpu.serving.worker`` processes (telemetry
ranks 1 and 2) serve an in-process router (rank 0) with
``PADDLE_TPU_TELEMETRY_DIR`` set everywhere. The acceptance criteria:

* every admitted request is exactly ONE contiguous span tree spanning
  all three processes (router admit/queue/dispatch, worker
  transit/drain, engine prefill/decode) — cross-process propagation
  through the ``__srv`` wire record actually works;
* ``scripts/trace_report.py`` over the dir yields a valid Perfetto
  document (one track per rank) and a per-SLO-class attribution table
  whose phase shares partition 1.0;
* results stay BIT-EQUAL to an untraced single-engine reference —
  tracing must be invisible in the tokens.

Marked slow: boots 2 fresh interpreters that compile engine programs on
CPU; run with ``pytest tests/test_tracing_e2e.py --runslow``.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest
from conftest import free_port

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPORT = os.path.join(REPO, "scripts", "trace_report.py")
VOCAB = 61
MODEL_ARGS = ["--model-seed", "7", "--vocab", str(VOCAB), "--hidden", "32",
              "--layers", "2", "--heads", "4", "--max-positions", "128"]
ENGINE_ARGS = ["--slots", "2", "--max-length", "64", "--page-size", "16"]

#: the full request chain every done tree must cover (srv_verify only
#: appears for speculative decode, srv_retry only after failover)
CHAIN = {"srv_request", "srv_admit", "srv_queue", "srv_dispatch",
         "srv_store_transit", "srv_drain", "srv_prefill", "srv_decode"}


def _spawn_worker(master, rank, tdir):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "PADDLE_TPU_TELEMETRY_DIR": str(tdir),
        "PADDLE_TRAINER_ID": str(rank),
    })
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.serving.worker",
         "--master", master, "--poll-interval", "0.002",
         *MODEL_ARGS, *ENGINE_ARGS],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)


def _reference(requests):
    import paddle_tpu as paddle
    from paddle_tpu.inference.engine import DecodeEngine, EngineConfig
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    paddle.seed(7)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=128,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
    model.eval()
    eng = DecodeEngine(model, EngineConfig(num_slots=4, max_length=64,
                                           page_size=16, prefix_cache=True))
    rids = [eng.submit(p, params) for p, params in requests]
    eng.run()
    return [eng.result(r) for r in rids]


def test_trace_spans_three_processes_and_reports(tmp_path, monkeypatch):
    from paddle_tpu import observability as obs
    from paddle_tpu.observability import tracing
    from paddle_tpu.runtime import TCPStore
    from paddle_tpu.serving import Router

    tdir = tmp_path / "tele"
    tdir.mkdir()
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tdir))
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    obs.reset()

    port = free_port()
    store = TCPStore(host="127.0.0.1", port=port, is_master=True,
                     timeout=30.0)
    master = f"127.0.0.1:{port}"
    procs = [_spawn_worker(master, rank, tdir) for rank in (1, 2)]
    router = Router(store, queue_limit=32, engine_grace_s=120.0, seed=13,
                    deadlines={"interactive": 240.0, "standard": 240.0,
                               "batch": 600.0})
    try:
        deadline = time.monotonic() + 120.0
        while router._known_engines < 2:
            assert time.monotonic() < deadline, "workers never registered"
            for p in procs:
                assert p.poll() is None, p.stderr.read()[-2000:]
            router.pump()
            time.sleep(0.05)

        rng = np.random.default_rng(8)
        prompts = [rng.integers(1, VOCAB, size=n).astype(np.int64)
                   for n in (14, 23, 31, 11)]
        slos = ("interactive", "standard", "batch", "interactive")
        rids = [router.submit(p, slo=slo, max_new_tokens=8)
                for p, slo in zip(prompts, slos)]
        assert router.drain(timeout=240.0), router.stats()
        st = router.stats()
        assert st["done"] == len(rids) and st["shed"] == 0

        want = _reference([(p, router._requests[r].params)
                           for p, r in zip(prompts, rids)])
        for r, w in zip(rids, want):
            np.testing.assert_array_equal(router.result(r), w)
    finally:
        router.shutdown()
        for p in procs:
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=20)
        store.close()
        obs.reset()

    # --- span-file invariants: one contiguous tree per request, spanning
    # the router process (rank 0) and a worker process (rank 1 or 2)
    spans = tracing.load_spans(str(tdir))
    assert tracing.validate_trees(spans) == []
    roots = [s for s in spans if s["name"] == "srv_request"]
    assert len(roots) == 4
    assert {s["attrs"]["status"] for s in roots} == {"done"}
    for root in roots:
        tree = [s for s in spans if s["trace_id"] == root["trace_id"]]
        assert CHAIN <= {s["name"] for s in tree}
        ranks = {s["rank"] for s in tree}
        assert 0 in ranks and ranks & {1, 2}, ranks

    # --- the report CLI over the raw files
    proc = subprocess.run([sys.executable, REPORT, str(tdir)],
                          capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 tree problems" in proc.stdout

    doc = json.load(open(tdir / "trace.json"))
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(evs) == len(spans)
    assert all({"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
               for e in evs)
    assert {e["pid"] for e in evs} == {0, 1, 2}  # one track per rank

    summary = json.load(open(tdir / "fleet_trace_summary.json"))
    assert summary["requests"] == 4 and summary["unfinished"] == 0
    assert set(summary["classes"]) == {"interactive", "standard", "batch"}
    for cls in summary["classes"].values():
        total = sum(v["mean"] for v in cls["phase_share"].values())
        # shares are rounded to 6 decimals in the document
        assert total == pytest.approx(1.0, abs=1e-4)
