"""OpTest-analogue registry sweep (VERDICT r4 #5).

Reference pattern: test/legacy_test/op_test.py — every operator checked
against a numeric oracle (forward vs NumPy there; here, the eager tape's
analytic gradient vs central differences of the op's own forward, which
additionally exercises every registered vjp).

One classification sweep runs for the whole module (module-scope
fixture); the parametrized tests then assert each op's bucket. An op
that cannot be synthesized and is not in the explicit skip table FAILS —
the skip list can't silently grow.
"""
import pytest

from optest_utils import OP_REGISTRY, SKIP, classify_all

_ALL = sorted(OP_REGISTRY)


@pytest.fixture(scope="module")
def results():
    # classify exactly the collection-time snapshot (_ALL): other test
    # modules may register ad-hoc ops mid-session
    return classify_all(_ALL)


@pytest.mark.parametrize("name", _ALL)
def test_op_gradient(results, name):
    r = results[name]
    bucket = r.split(":")[0]
    if bucket == "skipped":
        pytest.skip(SKIP[name])
    assert bucket in ("checked", "non_float", "stochastic"), r


def test_coverage_at_least_80pct(results):
    """≥80% of float-valued registry ops must be gradient-checked; the
    denominator counts checked + explicitly-skipped (all skip-table
    entries are float-valued ops — integer ops classify as non_float)."""
    buckets = {}
    for name, r in results.items():
        buckets.setdefault(r.split(":")[0], []).append(name)
    checked = len(buckets.get("checked", ()))
    skipped = len(buckets.get("skipped", ()))
    stochastic = len(buckets.get("stochastic", ()))
    assert not buckets.get("SYNTH_FAIL"), buckets.get("SYNTH_FAIL")
    assert not buckets.get("GRAD_FAIL"), buckets.get("GRAD_FAIL")
    ratio = checked / max(checked + skipped + stochastic, 1)
    assert ratio >= 0.80, (
        f"gradient-checked {checked} of {checked + skipped + stochastic} "
        f"float ops ({ratio:.0%})")
