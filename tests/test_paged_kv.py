"""Paged KV cache: allocator, prefix sharing, speculation (docs/SERVING.md).

Gates the paged-serving promises on top of test_decode_engine.py's
contiguous-era guarantees: the free-list allocator never double-allocates
and never leaks (refcounts reach zero on eviction), copy-on-write prefix
sharing keeps shared pages immutable while requests diverge after the
shared blocks, greedy output is BIT-EQUAL with prefix caching and
speculative decode on or off, and the compiled-program count stays O(1)
in requests/lengths (prefill buckets + one decode + one verify).
"""
import numpy as np
import pytest

import paddle_tpu.inference as inference
from paddle_tpu.inference.engine import (DecodeEngine, EngineConfig,
                                         PagePool, PrefixRegistry,
                                         SamplingParams)
from paddle_tpu.text.generation import prompt_lookup_draft
from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

VOCAB = 61


@pytest.fixture(scope="module")
def model():
    import paddle_tpu as paddle
    from paddle_tpu.distributed import mesh as _mesh
    from paddle_tpu.distributed.fleet.topology import (
        get_hybrid_communicate_group, set_hybrid_communicate_group)

    prev = get_hybrid_communicate_group()
    prev_mesh = _mesh.get_global_mesh()
    set_hybrid_communicate_group(None)
    _mesh.set_global_mesh(None)
    try:
        paddle.seed(11)
        m = GPTForCausalLM(GPTConfig(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=128,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
        m.eval()
        yield m
        inference.disable_decode_engine(m)
    finally:
        set_hybrid_communicate_group(prev)
        _mesh.set_global_mesh(prev_mesh)


def _prompt(rng, n):
    return rng.integers(1, VOCAB, n, dtype=np.int64)


def _drain(eng, prompts, max_new=8, **kw):
    rids = [eng.submit(p, SamplingParams(max_new_tokens=max_new, **kw))
            for p in prompts]
    eng.run()
    return [eng.result(r) for r in rids]


def _pool_invariant(pool: PagePool):
    live = int((pool._ref[1:] > 0).sum())
    assert pool.available() + live == pool.num_pages - 1
    free_set = set(pool._free)
    assert len(free_set) == len(pool._free), "free list has duplicates"
    assert 0 not in free_set, "trash page on the free list"
    for p in free_set:
        assert pool.refcount(p) == 0, f"page {p} free but referenced"


# ---------------------------------------------------------------------------
# allocator unit tests
# ---------------------------------------------------------------------------


def test_pagepool_never_double_allocates():
    pool = PagePool(64)
    rng = np.random.default_rng(0)
    held = []  # list of allocations (lists of page ids)
    for _ in range(500):
        if held and rng.random() < 0.45:
            for p in held.pop(rng.integers(len(held))):
                pool.decref(p)
        else:
            got = pool.alloc(int(rng.integers(1, 6)))
            if got is not None:
                held.append(got)
        live = [p for pages in held for p in pages]
        assert len(live) == len(set(live)), "page handed out twice"
        _pool_invariant(pool)
    for pages in held:
        for p in pages:
            pool.decref(p)
    assert pool.available() == pool.num_pages - 1


def test_pagepool_refcount_discipline():
    pool = PagePool(8)
    (a,) = pool.alloc(1)
    pool.incref(a)
    pool.decref(a)
    assert pool.refcount(a) == 1 and a not in pool._free
    pool.decref(a)
    assert pool.refcount(a) == 0 and a in pool._free
    with pytest.raises(ValueError):
        pool.decref(a)  # already free
    with pytest.raises(ValueError):
        pool.incref(a)  # sharing can only extend a live allocation
    assert pool.alloc(100) is None  # never partial
    assert pool.available() == pool.num_pages - 1


def test_prefix_registry_lru_eviction_drops_refcounts():
    pool = PagePool(16)
    reg = PrefixRegistry(pool, capacity=2)
    pages = pool.alloc(3)
    keys = [bytes([i]) * 16 for i in range(3)]
    for k, p in zip(keys, pages):
        reg.register(k, p)
        pool.decref(p)  # registry reference keeps it alive
    # capacity 2: the oldest entry was evicted and its page freed
    assert len(reg) == 2
    assert pool.refcount(pages[0]) == 0 and pages[0] in pool._free
    assert reg.lookup_chain(keys[:1]) == []
    hit = reg.lookup_chain([keys[1]])
    assert hit == [pages[1]] and pool.refcount(pages[1]) == 2
    pool.decref(pages[1])
    reg.clear()
    assert pool.available() == pool.num_pages - 1
    _pool_invariant(pool)


def test_prefix_block_keys_chain():
    p = np.arange(48, dtype=np.int64)
    a = PrefixRegistry.block_keys(p, 16)
    b = PrefixRegistry.block_keys(p.copy(), 16)
    assert a == b and len(a) == 3
    q = p.copy()
    q[20] += 1  # mutate block 1: its key and every later key must change
    c = PrefixRegistry.block_keys(q, 16)
    assert c[0] == a[0] and c[1] != a[1] and c[2] != a[2]
    # chain hash: equal block contents at different depths don't collide
    r = np.concatenate([p[16:32], p[16:32], p[16:32]])
    d = PrefixRegistry.block_keys(r, 16)
    assert len(set(d)) == 3


def test_prompt_lookup_draft():
    ctx = np.array([5, 6, 7, 1, 2, 5, 6, 7, 9, 4, 5, 6, 7], np.int64)
    d = prompt_lookup_draft(ctx, 3)
    # most recent earlier [5, 6, 7] is at index 5 -> followed by 9, 4, 5
    assert d.tolist() == [9, 4, 5]
    assert prompt_lookup_draft(np.array([1, 2, 3, 4]), 3) is None
    short = prompt_lookup_draft(np.array([8, 1, 8]), 4)
    assert short.tolist() == [1, 8, 8, 8]  # padded with the last token


# ---------------------------------------------------------------------------
# engine-level guarantees
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_greedy_bitequal_prefix_cache_on_off(model):
    rng = np.random.default_rng(3)
    shared = _prompt(rng, 32)
    prompts = [np.concatenate([shared, _prompt(rng, 6)]) for _ in range(5)]
    off = DecodeEngine(model, EngineConfig(
        num_slots=2, max_length=64, page_size=8, prefix_cache=False))
    ref = _drain(off, prompts)
    on = DecodeEngine(model, EngineConfig(
        num_slots=2, max_length=64, page_size=8, prefix_cache=True))
    out = _drain(on, prompts)
    for a, b in zip(ref, out):
        np.testing.assert_array_equal(a, b)
    assert on.stats()["prefix_hit_tokens"] > 0
    assert off.stats()["prefix_hit_tokens"] == 0


@pytest.mark.slow
def test_greedy_bitequal_speculation_on_off(model):
    rng = np.random.default_rng(4)
    # repetitive prompts give the n-gram draft something to match
    motif = _prompt(rng, 5)
    prompts = [np.concatenate([np.tile(motif, 5), _prompt(rng, 3)])
               for _ in range(3)]
    off = DecodeEngine(model, EngineConfig(
        num_slots=3, max_length=96, page_size=8, speculate_k=0))
    ref = _drain(off, prompts, max_new=16)
    on = DecodeEngine(model, EngineConfig(
        num_slots=3, max_length=96, page_size=8, speculate_k=3,
        spec_adaptive=False))
    out = _drain(on, prompts, max_new=16)
    for a, b in zip(ref, out):
        np.testing.assert_array_equal(a, b)
    st = on.stats()
    assert st["verify_steps"] > 0 and st["spec_accepted"] > 0


def test_cow_divergence_after_shared_prefix(model):
    """Requests sharing full prompt blocks must diverge freely after the
    shared prefix without corrupting it for later readers."""
    rng = np.random.default_rng(5)
    shared = _prompt(rng, 16)  # exactly 2 full pages of 8
    tails = [_prompt(rng, 4) for _ in range(3)]
    prompts = [np.concatenate([shared, t]) for t in tails]
    ref_eng = DecodeEngine(model, EngineConfig(
        num_slots=1, max_length=64, page_size=8, prefix_cache=False))
    ref = _drain(ref_eng, prompts)
    eng = DecodeEngine(model, EngineConfig(
        num_slots=3, max_length=64, page_size=8, prefix_cache=True))
    # all three run CONCURRENTLY off the same shared pages
    out = _drain(eng, prompts)
    for a, b in zip(ref, out):
        np.testing.assert_array_equal(a, b)
    assert not np.array_equal(out[0][16:], out[1][16:]), (
        "distinct tails should diverge")
    # a late reader of the shared prefix still sees the original blocks
    # (decode writes of the finished requests never touched them)
    late = _drain(eng, [prompts[0]])
    np.testing.assert_array_equal(late[0], ref[0])
    assert eng.stats()["prefix_hit_tokens"] > 0


def test_shared_pages_counted_and_released(model):
    rng = np.random.default_rng(6)
    shared = _prompt(rng, 16)
    prompts = [np.concatenate([shared, _prompt(rng, 4)]) for _ in range(4)]
    eng = DecodeEngine(model, EngineConfig(
        num_slots=4, max_length=64, page_size=8, prefix_cache=True))
    rids = [eng.submit(p, SamplingParams(max_new_tokens=6))
            for p in prompts]
    eng.step()  # admit everyone
    assert eng.pool.shared_pages() == 2  # the two full prefix pages
    eng.run()
    for r in rids:
        eng.result(r)
    # registry still pins the prefix; dropping it frees every page
    eng.release_prefix_cache()
    assert eng.pool.available() == eng.pool.num_pages - 1
    _pool_invariant(eng.pool)


@pytest.mark.slow
def test_admission_waits_for_pages_then_recovers(model):
    """A pool too small for all slots at once must queue, not deadlock or
    double-book: every request still completes."""
    rng = np.random.default_rng(7)
    prompts = [_prompt(rng, 20) for _ in range(6)]
    # each request needs ceil((20 + 8) / 8) = 4 pages; 9 usable pages
    # -> at most 2 requests in flight although there are 4 slots
    eng = DecodeEngine(model, EngineConfig(
        num_slots=4, max_length=64, page_size=8, num_pages=10,
        prefix_cache=False))
    outs = _drain(eng, prompts)
    assert len(outs) == 6
    assert eng.stats()["peak_running"] <= 2
    assert eng.pool.available() == 9
    ref_eng = DecodeEngine(model, EngineConfig(
        num_slots=1, max_length=64, page_size=8, prefix_cache=False))
    ref = _drain(ref_eng, prompts)
    for a, b in zip(ref, outs):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_compile_count_o1_with_speculation(model):
    """Compiled programs: one per used prefill tail bucket + ONE decode +
    ONE verify — invariant in request count and request lengths."""
    rng = np.random.default_rng(8)
    motif = _prompt(rng, 4)
    eng = DecodeEngine(model, EngineConfig(
        num_slots=3, max_length=96, page_size=8, speculate_k=3,
        spec_adaptive=False, prefix_cache=True))
    prompts = ([np.concatenate([np.tile(motif, 4), _prompt(rng, 2)])
                for _ in range(4)]
               + [np.tile(motif, 7)[:26] for _ in range(3)])
    _drain(eng, prompts, max_new=12)
    st = eng.stats()
    buckets_used = sum(1 for name in st["compiled"]
                       if name.startswith("prefill_"))
    assert st["verify_steps"] > 0
    assert st["compile_count"] == buckets_used + 2, st["compiled"]
    before = st["compile_count"]
    # more work with the same shapes -> zero new programs
    _drain(eng, [np.concatenate([np.tile(motif, 4), _prompt(rng, 2)])
                 for _ in range(4)], max_new=12)
    assert eng.stats()["compile_count"] == before


@pytest.mark.slow
def test_quick_churn_no_leaked_pages(model):
    """Tier-1-sized churn: random lengths and budgets through a small
    pool; the free list must account for every page afterwards."""
    rng = np.random.default_rng(9)
    eng = DecodeEngine(model, EngineConfig(
        num_slots=3, max_length=64, page_size=8, prefix_cache=True,
        prefix_registry_blocks=6))
    shared = _prompt(rng, 24)
    for round_ in range(4):
        prompts = [
            np.concatenate([shared[:8 * rng.integers(0, 4)],
                            _prompt(rng, int(rng.integers(1, 12)))])
            for _ in range(5)
        ]
        _drain(eng, prompts, max_new=int(rng.integers(1, 8)))
        _pool_invariant(eng.pool)
        assert len(eng.registry) <= 6
    eng.release_prefix_cache()
    assert eng.pool.available() == eng.pool.num_pages - 1
    # freed slots must leave zeroed page-table rows (writes -> trash)
    assert (eng._tables == 0).all()


def test_transformer_paged_cache_matches_static():
    """nn-layer PagedCache (pool + identity page table) is bit-identical
    to the contiguous static cache — including an odd page size that
    does not divide max_length."""
    import jax.numpy as jnp

    from paddle_tpu.framework.core import Tensor
    from paddle_tpu.framework.op import raw
    from paddle_tpu.nn.layers.transformer import (TransformerDecoder,
                                                  TransformerDecoderLayer)

    import paddle_tpu as paddle

    paddle.seed(3)
    B, T, E, H = 2, 5, 16, 4
    dec = TransformerDecoder(
        TransformerDecoderLayer(E, H, 32, dropout=0.0), 2)
    dec.eval()
    rng = np.random.default_rng(0)
    x = Tensor(jnp.asarray(rng.standard_normal((B, T, E)), jnp.float32))
    mem = Tensor(jnp.asarray(rng.standard_normal((B, 3, E)), jnp.float32))
    static = dec.gen_cache(mem, max_length=8)
    paged = dec.gen_cache(mem, max_length=8, page_size=3)
    pool_k = raw(paged[0][0].k)
    assert pool_k.shape == (1 + B * 3, H, 3, E // H)  # trash page + 3/row
    for t in range(T):
        xt = Tensor(raw(x)[:, t:t + 1])
        os_, static = dec(xt, mem, cache=static, cache_position=t)
        op, paged = dec(xt, mem, cache=paged, cache_position=t)
        np.testing.assert_array_equal(np.asarray(raw(os_)),
                                      np.asarray(raw(op)))


@pytest.mark.slow
@pytest.mark.chaos
def test_chaos_churn_soak_no_leaks(model):
    """Long mixed soak: prefix sharing + speculation + tiny pool +
    registry eviction pressure, with the allocator invariant checked
    after every round and zero pages leaked at the end."""
    rng = np.random.default_rng(10)
    eng = DecodeEngine(model, EngineConfig(
        num_slots=4, max_length=96, page_size=8, num_pages=40,
        prefix_cache=True, prefix_registry_blocks=8, speculate_k=3,
        spec_adaptive=False))
    shared = _prompt(rng, 48)
    for round_ in range(12):
        prompts = []
        for _ in range(int(rng.integers(3, 8))):
            cut = 8 * int(rng.integers(0, 7))
            prompts.append(np.concatenate(
                [shared[:cut], _prompt(rng, int(rng.integers(1, 16)))]))
        _drain(eng, prompts, max_new=int(rng.integers(1, 12)),
               eos_token_id=int(rng.integers(1, VOCAB)))
        _pool_invariant(eng.pool)
        if round_ % 5 == 4:
            eng.release_prefix_cache()
            assert eng.pool.available() == eng.pool.num_pages - 1
    eng.release_prefix_cache()
    assert eng.pool.available() == eng.pool.num_pages - 1
    assert (eng._tables == 0).all()
