"""Kill -9 soak for the live-resize path: a worker training on a dp2xmp2
mesh shrinks itself to a 2-device dp mesh mid-run via
ElasticManager.live_resize; the chaos harness SIGKILLs it at a
mid-reshard leaf fence on the first attempt. The relaunched worker
(chaos disarmed) must resume from the newest VERIFIED checkpoint, redo
the resize cleanly and land on the reference run's exact final weights —
a fault mid-reshard never costs more than the uncheckpointed steps.

Marked slow+chaos (boots fresh interpreters):
    pytest tests/test_reshard_chaos.py --runslow
"""
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

TOTAL_STEPS = 12
RESHARD_STEP = 6

WORKER = textwrap.dedent("""
    import os, sys
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, os.environ["PT_REPO"])
    import _cpu_mesh_flags; _cpu_mesh_flags.apply(n_devices=8)
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.framework.op import raw
    from paddle_tpu.jit import TrainStep

    ckpt_dir, out_path, total = sys.argv[1], sys.argv[2], int(sys.argv[3])
    RESHARD = int(sys.argv[4])
    DEVS = np.array(jax.devices())
    MESH_A = Mesh(DEVS[:4].reshape(2, 2), ("dp", "mp"))
    MESH_B = Mesh(DEVS[:2].reshape(2), ("dp",))

    def build(mesh, wspec):
        paddle.seed(0)
        m = nn.Linear(16, 16)
        for _, p in m.named_parameters():
            v = raw(p)
            s = wspec if v.ndim == 2 else P(wspec[-1])
            p._rebind(jax.device_put(v, NamedSharding(mesh, s)))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=m.parameters())
        return m, opt

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((8, 16)).astype("float32"))
    y = paddle.to_tensor(rng.standard_normal((8, 16)).astype("float32"))
    loss_fn = lambda m, a, b: ((m(a) - b) ** 2).mean()

    model, opt = build(MESH_A, P("dp", "mp"))
    elastic = ElasticManager(ckpt_dir, save_interval=2, max_to_keep=2)
    start = elastic.resume(model, opt)
    # the kill fires at the RESHARD step before any save could outrun it,
    # so a relaunch always lands back in the phase-A range
    assert start <= RESHARD, f"resumed at {start}, past the resize point"
    step_fn = TrainStep(model, loss_fn, opt)
    for step in range(start, total):
        if step == RESHARD:
            # live shrink n=4 -> n=2: no disk in the happy path; chaos
            # fences fire inside reshard_state at every leaf barrier
            src = elastic.capture(model, opt)
            model, opt = build(MESH_B, P("dp"))
            nxt = elastic.live_resize(step - 1, src, model, opt)
            assert nxt == step, (nxt, step)
            step_fn = TrainStep(model, loss_fn, opt)
        float(step_fn(x, y))
        elastic.maybe_save(step, model, opt)
    elastic.flush()
    np.savez(out_path, **{k: np.asarray(v.numpy())
                          for k, v in model.state_dict().items()})
""")


def _run(tmp_path, tag, chaos_env=None):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    ckpt = tmp_path / f"ckpt_{tag}"
    out = tmp_path / f"final_{tag}.npz"
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("PADDLE_CHAOS")}
    env.update({
        "JAX_PLATFORMS": "cpu",
        "PT_REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    })
    env.update(chaos_env or {})
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--max_restarts", "3", "--restart_backoff", "0.1",
         str(worker), str(ckpt), str(out), str(TOTAL_STEPS),
         str(RESHARD_STEP)],
        env=env, capture_output=True, text=True, timeout=600,
        cwd=env["PT_REPO"])
    assert proc.returncode == 0, (
        f"launch rc={proc.returncode}\nstdout:\n{proc.stdout[-2000:]}"
        f"\nstderr:\n{proc.stderr[-4000:]}")
    return np.load(out), ckpt, proc


def _assert_bitwise_equal(got, want):
    assert sorted(got.files) == sorted(want.files)
    for k in want.files:
        a, b = got[k], want[k]
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes(), f"state {k} differs after resume"


@pytest.mark.parametrize("fence", [0, 2])
def test_kill_mid_reshard_recovers_bitwise(tmp_path, fence):
    ref, _, _ = _run(tmp_path, f"ref{fence}")
    got, ckpt, proc = _run(
        tmp_path, f"kill{fence}",
        chaos_env={
            "PADDLE_CHAOS": "1",
            "PADDLE_CHAOS_RESHARD_MODE": "kill",
            "PADDLE_CHAOS_RESHARD_AT": str(fence),
        })
    assert "SIGKILL" in proc.stderr  # the fault actually fired mid-reshard
    assert "relaunching" in proc.stderr
    _assert_bitwise_equal(got, ref)
    # nothing half-resharded was ever committed: every surviving
    # checkpoint verifies
    from paddle_tpu.distributed.checkpoint import manifest

    steps = [n for n in os.listdir(ckpt) if n.startswith("step_")]
    assert steps, "no checkpoint survived the kill"
    for name in steps:
        ok, why = manifest.verify(os.path.join(ckpt, name), deep=True)
        assert ok, f"{name} damaged but discoverable: {why}"


def test_reshard_latency_fault_is_survivable(tmp_path):
    """An injected mid-reshard stall shorter than the deadline only slows
    the resize down — the run completes on attempt 0, bitwise equal."""
    ref, _, _ = _run(tmp_path, "lat_ref")
    got, _, proc = _run(
        tmp_path, "lat",
        chaos_env={
            "PADDLE_CHAOS": "1",
            "PADDLE_CHAOS_RESHARD_MODE": "latency",
            "PADDLE_CHAOS_RESHARD_AT": "1",
            "PADDLE_CHAOS_RESHARD_LATENCY_MS": "300",
        })
    assert "SIGKILL" not in proc.stderr
    _assert_bitwise_equal(got, ref)
