"""Native runtime core tests: arena allocator, TCPStore, batch stacker,
host tracer (SURVEY.md §2.4 items 1/4/8/12 — the framework-owned host side).

Mirrors the reference's test strategy for its C++ runtime (gtest targets for
allocators and the store, §4): exercised here through the ctypes surface so
the same tests also guard the bindings.
"""
import os
import threading

import numpy as np
import pytest

from paddle_tpu import runtime
from paddle_tpu.runtime import native


def test_native_library_builds():
    # The build toolchain is part of the image; the native path must be live.
    assert runtime.native_available()


def test_arena_alloc_free_stats():
    a = runtime.HostArena(chunk_bytes=1 << 20)
    p1 = a.alloc(1000)
    p2 = a.alloc(2000)
    st = a.stats()
    assert st["allocated_bytes"] >= 3000
    assert st["reserved_bytes"] >= 1 << 20
    assert st["alloc_count"] == 2
    a.free(p1)
    a.free(p2)
    st = a.stats()
    assert st["allocated_bytes"] == 0
    assert st["peak_allocated_bytes"] >= 3000
    # free list reuse: same chunk should satisfy the next alloc
    p3 = a.alloc(2500)
    assert a.stats()["reserved_bytes"] == st["reserved_bytes"]
    a.free(p3)


def test_arena_coalescing_reuse():
    a = runtime.HostArena(chunk_bytes=1 << 16)
    ptrs = [a.alloc(4096) for _ in range(8)]
    for p in ptrs:
        a.free(p)
    # After freeing everything the chunk coalesces; a large alloc must fit
    # without growing.
    before = a.stats()["reserved_bytes"]
    big = a.alloc(8 * 4096)
    assert a.stats()["reserved_bytes"] == before
    a.free(big)


def test_arena_array_roundtrip():
    a = runtime.HostArena()
    arr, ptr = a.alloc_array((4, 8), np.float32)
    arr[:] = np.arange(32, dtype=np.float32).reshape(4, 8)
    assert arr.sum() == np.arange(32).sum()
    a.free(ptr)


def test_stack_samples_matches_numpy():
    samples = [np.random.rand(16, 16).astype(np.float32) for _ in range(32)]
    out = runtime.stack_samples(samples)
    np.testing.assert_array_equal(out, np.stack(samples))
    # large path (exercises the thread pool branch)
    big = [np.random.rand(256, 256).astype(np.float32) for _ in range(64)]
    np.testing.assert_array_equal(runtime.stack_samples(big), np.stack(big))


def test_stack_samples_fallback_mixed_shapes():
    with pytest.raises(ValueError):
        runtime.stack_samples([])
    out = runtime.stack_samples([np.ones((2,)), np.ones((3,))][:1])
    assert out.shape == (1, 2)


def test_tcp_store_set_get_add():
    master = runtime.TCPStore(is_master=True)
    client = runtime.TCPStore(port=master.port)
    master.set("k", b"hello")
    assert client.get("k") == b"hello"
    assert client.add("ctr", 5) == 5
    assert master.add("ctr", 2) == 7
    assert client.check("k")
    assert not client.check("missing")
    assert client.delete_key("k")
    assert not master.check("k")
    with pytest.raises(TimeoutError):
        client.get("missing", timeout=0.2)
    client.close()
    master.close()


def test_tcp_store_wait_blocks_until_set():
    master = runtime.TCPStore(is_master=True)
    client = runtime.TCPStore(port=master.port)
    got = []

    def waiter():
        client.wait("late", timeout=10.0)
        got.append(client.get("late"))

    t = threading.Thread(target=waiter)
    t.start()
    import time

    time.sleep(0.2)
    master.set("late", b"v")
    t.join(timeout=5)
    assert got == [b"v"]
    client.close()
    master.close()


def test_tcp_store_barrier():
    master = runtime.TCPStore(is_master=True)
    clients = [runtime.TCPStore(port=master.port) for _ in range(3)]
    done = []

    def run(s, i):
        s.barrier("b0", 4, timeout=10.0)
        done.append(i)

    threads = [threading.Thread(target=run, args=(s, i)) for i, s in enumerate(clients)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.2)
    assert done == []  # blocked until the 4th participant arrives
    run(master, 99)
    for t in threads:
        t.join(timeout=5)
    assert sorted(done) == [0, 1, 2, 99]
    for s in clients:
        s.close()
    master.close()


def test_py_store_fallback():
    from paddle_tpu.runtime.py_store import PyTCPStore

    master = PyTCPStore(is_master=True)
    client = PyTCPStore(port=master.port)
    master.set("a", b"1")
    assert client.get("a") == b"1"
    assert client.add("n", 3) == 3
    client.close()
    master.close()


def test_tracer_records_and_exports():
    runtime.trace_start()
    with runtime.RecordEvent("step", cat="train"):
        with runtime.RecordEvent("forward"):
            pass
    runtime.trace_stop()
    events = runtime.trace_export()
    names = {e["name"] for e in events}
    assert {"step", "forward"} <= names
    for e in events:
        assert e["ph"] == "X"
        assert e["dur"] >= 0


def test_host_memory_stats_surface():
    st = runtime.host_memory_stats()
    assert set(st) == {
        "allocated_bytes",
        "reserved_bytes",
        "peak_allocated_bytes",
        "alloc_count",
    }


# ---------------------------------------------------------------------------
# C++ test binary + sanitizer matrix (SURVEY.md §5 "Race detection/
# sanitizers" — the reference's SANITIZER_TYPE CMake option). The plain
# binary runs in the default suite; ASAN/TSAN/UBSAN builds are slow-marked.
# ---------------------------------------------------------------------------
import shutil
import subprocess

pytestmark = pytest.mark.fast  # whole-module smoke: cheap on 1 core

_CSRC = os.path.join(os.path.dirname(__file__), "..", "csrc")


def _make(target, timeout=600):
    return subprocess.run(
        ["make", "-C", _CSRC, target],
        capture_output=True, text=True, timeout=timeout,
    )


@pytest.mark.skipif(shutil.which("make") is None, reason="no make")
def test_cpp_rt_test_binary():
    r = _make("test")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RT_TEST PASS" in r.stdout


@pytest.mark.slow
@pytest.mark.parametrize("sanitizer", ["asan", "tsan", "ubsan"])
def test_cpp_sanitizers(sanitizer):
    r = _make(sanitizer)
    if r.returncode != 0 and ("cannot find" in r.stderr or "not found" in r.stderr):
        pytest.skip(f"toolchain lacks {sanitizer} runtime")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "RT_TEST PASS" in r.stdout
