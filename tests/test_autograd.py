"""Eager autograd tests — gradients checked against jax.grad on the same
pure function (the reference checks analytic grads against numeric ones;
jax.grad is our independent oracle)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer, grad as pgrad

pytestmark = pytest.mark.fast  # whole-module smoke: cheap on 1 core

rng = np.random.RandomState(1)


def test_simple_chain():
    a = rng.rand(3, 3).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    y = (x * x + 2 * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * a + 2, rtol=1e-5)


def test_matches_jax_grad():
    a = rng.rand(4, 4).astype(np.float32)
    b = rng.rand(4, 4).astype(np.float32)

    def f(x, y):
        return jnp.sum(jnp.tanh(x @ y) * jnp.exp(y * 0.1))

    gx_ref, gy_ref = jax.grad(f, argnums=(0, 1))(a, b)

    x = paddle.to_tensor(a, stop_gradient=False)
    y = paddle.to_tensor(b, stop_gradient=False)
    loss = (paddle.tanh(paddle.matmul(x, y)) * paddle.exp(y * 0.1)).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), gx_ref, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(y.grad.numpy(), gy_ref, rtol=1e-4, atol=1e-5)


def test_grad_accumulation_multi_use():
    a = rng.rand(3).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    y = (x * x + x * 3).sum()  # x used in two branches
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * a + 3, rtol=1e-5)


def test_stop_gradient_blocks():
    x = paddle.to_tensor(rng.rand(3).astype(np.float32), stop_gradient=False)
    y = paddle.to_tensor(rng.rand(3).astype(np.float32))  # stop_gradient=True
    loss = (x * y).sum()
    loss.backward()
    assert x.grad is not None
    assert y.grad is None


def test_detach():
    a = rng.rand(3).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    y = x * 2
    z = y.detach()
    assert z.stop_gradient
    loss = (x * 2 + z).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full(3, 2.0), rtol=1e-6)


def test_no_grad_context():
    x = paddle.to_tensor(rng.rand(3).astype(np.float32), stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._node is None


def test_backward_twice_raises():
    x = paddle.to_tensor(rng.rand(3).astype(np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_retain_graph():
    a = rng.rand(3).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 4 * a, rtol=1e-5)  # accumulated


def test_paddle_grad_api():
    a = rng.rand(3).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    y = (x**3).sum()
    (g,) = pgrad([y], [x])
    np.testing.assert_allclose(g.numpy(), 3 * a**2, rtol=1e-4)
    assert x.grad is None  # functional grad must not pollute .grad


def test_grad_through_getitem_concat():
    a = rng.rand(4, 4).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    y = paddle.concat([x[:2], x[2:] * 2], axis=0).sum()
    y.backward()
    expected = np.ones((4, 4), np.float32)
    expected[2:] = 2
    np.testing.assert_allclose(x.grad.numpy(), expected)


def test_multi_output_op_grad():
    a = rng.rand(5).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    vals, idx = paddle.topk(x, 2)
    vals.sum().backward()
    expected = np.zeros(5, np.float32)
    expected[np.argsort(-a)[:2]] = 1
    np.testing.assert_allclose(x.grad.numpy(), expected)


def test_gradient_hook():
    x = paddle.to_tensor(rng.rand(3).astype(np.float32), stop_gradient=False)
    h = x.register_hook(lambda g: g * 2)
    (x * 1.0).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full(3, 2.0))
    h.remove()


def test_pylayer():
    class Square(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x

        @staticmethod
        def backward(ctx, gy):
            (x,) = ctx.saved_tensor()
            return gy * x * 2

    a = rng.rand(3).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    y = Square.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), 2 * a, rtol=1e-6)


def test_backward_with_grad_tensor():
    a = rng.rand(3).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    y = x * 2
    y.backward(paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32)))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 4.0, 6.0])
