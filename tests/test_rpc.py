"""paddle.distributed.rpc tests — localhost multi-process, TestDistBase
style (SURVEY.md §4). Covers sync/async round-trips in both directions,
worker-info queries, remote-exception propagation, and the shutdown
barrier (reference: ``python/paddle/distributed/rpc/``)."""
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import sys
import paddle_tpu.distributed.rpc as rpc

master, rank, world = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
rpc.init_rpc(name=f"worker{rank}", rank=rank, world_size=world,
             master_endpoint=master)

me = rpc.get_current_worker_info()
assert me.name == f"worker{rank}" and me.rank == rank
infos = rpc.get_all_worker_infos()
assert [w.rank for w in infos] == list(range(world))
assert rpc.get_worker_info("worker0").rank == 0

# every worker calls every OTHER worker, sync and async
import operator
peers = [w.name for w in infos if w.rank != rank]
for peer in peers:
    assert rpc.rpc_sync(peer, operator.add, args=(rank, 100)) == rank + 100
futs = [rpc.rpc_async(p, pow, args=(2, rank + 3)) for p in peers]
for f in futs:
    assert f.wait() == 2 ** (rank + 3)

# remote exceptions re-raise at the caller with the original type
if peers:
    try:
        rpc.rpc_sync(peers[0], operator.truediv, args=(1, 0))
    except ZeroDivisionError:
        pass
    else:
        raise AssertionError("remote ZeroDivisionError did not propagate")

rpc.shutdown()
print(f"RPC_OK={rank}")
"""


from conftest import free_port as _free_port


@pytest.mark.parametrize(
    "world", [pytest.param(2, marks=pytest.mark.fast),
              pytest.param(3, marks=pytest.mark.slow)])
def test_rpc_roundtrip_subprocesses(world):
    master = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", WORKER, master, str(rank), str(world)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True,
        )
        for rank in range(world)
    ]
    oks = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, err
            oks += [int(l.split("=")[1]) for l in out.splitlines()
                    if l.startswith("RPC_OK=")]
    finally:
        for p in procs:  # a hung/failed worker must not orphan the rest
            if p.poll() is None:
                p.kill()
    assert sorted(oks) == list(range(world))


@pytest.mark.fast
def test_rpc_requires_init():
    import paddle_tpu.distributed.rpc as rpc

    with pytest.raises(RuntimeError, match="not initialized"):
        rpc.rpc_sync("worker0", max, args=(1, 2))
    with pytest.raises(RuntimeError, match="not initialized"):
        rpc.get_current_worker_info()
    rpc.shutdown()  # no-op when never initialized
