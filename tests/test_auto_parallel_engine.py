"""auto_parallel Engine + shard/reshard API tests on the 8-device CPU mesh."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import auto_parallel as auto
from paddle_tpu.distributed import fleet
import pytest

pytestmark = pytest.mark.fast  # whole-module smoke: cheap on 1 core


def _np(t):
    return np.asarray(t._value)


def test_engine_fit_evaluate_predict(tmp_path):
    s = fleet.DistributedStrategy()
    s.hybrid_configs["dp_degree"] = 8
    fleet.init(is_collective=True, strategy=s)
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = paddle.optimizer.Adam(learning_rate=3e-2, parameters=model.parameters())
    engine = auto.Engine(model, loss=nn.MSELoss(), optimizer=opt)

    rs = np.random.RandomState(0)
    X = rs.randn(64, 8).astype("float32")
    Y = (X @ rs.randn(8, 1)).astype("float32")
    hist = engine.fit((X, Y), epochs=20, batch_size=32, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0] * 0.5
    ev = engine.evaluate((X, Y), batch_size=32)
    assert ev["loss"] is not None and np.isfinite(ev["loss"])
    preds = engine.predict((X,), batch_size=32)
    assert len(preds) == 2 and _np(preds[0]).shape == (32, 1)
    engine.save(str(tmp_path / "ckpt"))
    engine.load(str(tmp_path / "ckpt"))


def test_shard_tensor_and_reshard():
    s = fleet.DistributedStrategy()
    s.hybrid_configs["dp_degree"] = 4
    s.hybrid_configs["mp_degree"] = 2
    fleet.init(is_collective=True, strategy=s)
    mesh = auto.get_mesh()
    assert mesh is not None and "dp" in mesh.dim_names

    x = paddle.to_tensor(np.arange(32, dtype="float32").reshape(8, 4))
    dp_axis = mesh.dim_names.index("dp")
    placements = [auto.Replicate()] * mesh.ndim
    placements[dp_axis] = auto.Shard(0)
    xs = auto.shard_tensor(x, mesh, placements)
    assert "dp" in str(xs._value.sharding.spec)
    np.testing.assert_allclose(_np(xs), _np(x))
    # reshard to replicated
    xr = auto.reshard(xs, mesh, [auto.Replicate()] * mesh.ndim)
    np.testing.assert_allclose(_np(xr), _np(x))
