"""framework.rng PRNG auto-selection matrix (subprocess-isolated: the
decision runs at import time from env vars only — see rng.py docstring)."""
import os
import subprocess
import sys

import pytest

# NOT in the fast tier: six subprocess jax imports cost ~18s on this box;
# the selection contract still runs in the full suite.

_CODE = """
import os, jax
jax.config.update("jax_platforms", "cpu")
import paddle_tpu
print("IMPL=" + jax.config.jax_default_prng_impl)
"""


def _impl_for(env_overrides):
    env = dict(os.environ)
    for var in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS", "JAX_DEFAULT_PRNG_IMPL",
                "PADDLE_TPU_PRNG_IMPL", "TPU_SKIP_MDS_QUERY", "TPU_NAME",
                "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID"):
        env.pop(var, None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env.update(env_overrides)
    p = subprocess.run([sys.executable, "-c", _CODE], env=env,
                       capture_output=True, text=True, timeout=180)
    for line in p.stdout.splitlines():
        if line.startswith("IMPL="):
            return line[5:]
    raise AssertionError(f"no IMPL line (rc={p.returncode}): {p.stderr[-300:]}")


def test_cpu_pinned_keeps_threefry():
    assert _impl_for({"JAX_PLATFORMS": "cpu"}) == "threefry2x32"


def test_tpu_primary_selects_rbg():
    # cpu as FALLBACK (second entry) must not disable the TPU default
    assert _impl_for({"JAX_PLATFORMS": "tpu,cpu"}) == "rbg"


def test_axon_env_marker_selects_rbg():
    assert _impl_for({"PALLAS_AXON_POOL_IPS": "203.0.113.1"}) == "rbg"


def test_app_env_config_defers():
    assert _impl_for({"JAX_PLATFORMS": "tpu",
                      "JAX_DEFAULT_PRNG_IMPL": "threefry2x32"}) == "threefry2x32"


def test_explicit_opt_out_wins():
    assert _impl_for({"JAX_PLATFORMS": "tpu",
                      "PADDLE_TPU_PRNG_IMPL": "threefry"}) == "threefry2x32"


def test_explicit_override_selects():
    assert _impl_for({"JAX_PLATFORMS": "cpu",
                      "PADDLE_TPU_PRNG_IMPL": "unsafe_rbg"}) == "unsafe_rbg"
