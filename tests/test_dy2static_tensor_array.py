"""Tensor-array (list-in-loop) handling + conversion report (VERDICT r4
missing #3 / weak #6; reference: upstream dy2static's list transformer in
python/paddle/jit/dy2static/ and program_translator reporting).

TPU-native stance: a Python list cannot grow inside a lax loop (XLA needs
static structure), so loops that mutate containers stay PYTHON loops —
static bounds unroll into fully compiled programs (the jax-idiomatic
tensor-array form); tensor-bound loops degrade to the eager guard with a
recorded reason. conversion_report() exposes every decision."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.jit import to_static

pytestmark = pytest.mark.fast


def _ones(shape=(2, 2)):
    return paddle.to_tensor(np.ones(shape, np.float32))


def test_append_in_static_loop_compiles():
    """Appends in a static-bounds loop with a tensor `if` inside: the loop
    unrolls, the `if` converts, NO eager fallback."""

    @to_static
    def f(x):
        outs = []
        for i in range(3):
            if (x.sum() > 0):
                x = x * 2
            else:
                x = x - 1
            outs.append(x)
        return paddle.stack(outs).sum()

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # an eager-fallback warning FAILS
        r = f(_ones())
    assert float(r) == (2 + 4 + 8) * 4
    assert not f._eager_fallback
    rep = f.conversion_report()
    assert rep["entry_mode"] == "compiled"


def test_extend_and_insert_in_static_loop_compile():
    @to_static
    def g(x):
        acc = []
        for i in range(2):
            if (x.sum() > 0):
                x = x + 1
            acc.extend([x, x * 2])
        head: list = []
        for i in range(2):
            head.insert(0, x + i)
        return paddle.stack(acc).sum() + paddle.stack(head).sum()

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        r = g(_ones())
    # x: 1+1=2, append [2,4]; x=3, append [3,6] -> acc sums (2+4+3+6)*4
    # head: [3+1, 3+0] -> (4+3)*4... insert(0,..) order irrelevant to sum
    assert float(r) == (2 + 4 + 3 + 6) * 4 + (3 + 4) * 4
    assert not g._eager_fallback


def test_append_in_tensor_while_falls_back_with_reason():
    """A tensor-condition while that appends cannot compile (dynamic
    length); it must fall back to eager WITH a recorded reason — and still
    compute correctly."""

    @to_static
    def h(x):
        outs = []
        while (x.sum() < 20):
            x = x * 2
            outs.append(x)
        return paddle.stack(outs).sum()

    with pytest.warns(UserWarning, match="EAGER"):
        r = h(_ones())
    # 1->2 (sum 8), ->4 (16), ->8 (32>=20 stop): outs [2,4,8] -> 14*4
    assert float(r) == (2 + 4 + 8) * 4
    rep = h.conversion_report()
    assert rep["entry_mode"] == "eager"
    assert any(v["status"] == "fallback" for v in rep["callees"].values())


def test_try_except_converts_with_note():
    @to_static
    def t(x):
        try:
            y = x * 2
        except ValueError:
            y = x
        if (y.sum() > 0):
            y = y + 1
        return y.sum()

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        r = t(_ones())
    assert float(r) == 2 * 4 + 4
    rep = t.conversion_report()
    entry = rep["callees"].get(t.__wrapped__.__qualname__
                               if hasattr(t, "__wrapped__")
                               else rep["entry"])
    assert entry is not None and entry["status"] == "converted"
    assert any("try/except" in n for n in entry.get("notes", ())), entry


def test_conversion_report_counts_callees():
    def helper_ok(x):
        if (x.sum() > 0):
            return x * 2
        return x

    def helper_bad(x):
        lst = [1]
        while (x.sum() < 9):  # tensor while + append: inconvertible body
            x = x * 2
            lst.append(1)
        return x

    @to_static
    def main(x):
        y = helper_ok(x)
        return y.sum()

    r = main(_ones())
    assert float(r) == 8.0
    rep = main.conversion_report()
    assert rep["n_converted"] >= 1
    assert isinstance(rep["callees"], dict)


def test_cast_transform_compiles():
    """float()/int()/bool() on traced scalars become 0-d astypes
    (reference: convert_var_dtype) instead of host syncs."""

    @to_static
    def f(x):
        s = x.sum()
        a = float(s) * 2.0
        b = int(s)
        c = bool(s > 0)
        if (c):
            return a + b
        return a - b

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        r = f(_ones())
    # s=4.0: a=8.0, b=4, c True -> 12
    assert float(r) == 12.0
    assert not f._eager_fallback


def test_cast_shadowed_name_untouched():
    @to_static
    def g(x):
        float = lambda v: v * 10  # noqa: E731 — deliberate shadow
        return float(x).sum()

    r = g(_ones())
    assert float(r) == 40.0


def test_assert_records_note():
    @to_static
    def h(x):
        assert x is not None
        if (x.sum() > 0):
            return x * 2
        return x

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        r = h(_ones())
    assert float(r.sum()) == 8.0
    rep = h.conversion_report()
    entry = rep["callees"].get(rep["entry"])
    assert entry and any("assert" in n for n in entry.get("notes", ())), entry
