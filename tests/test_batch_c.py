"""Tests for autograd Jacobian/Hessian/jvp/vjp, summary/flops, audio
features (vs librosa-style formulas / scipy), quantization, fused layers."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import audio, nn, quantization
from paddle_tpu.autograd import Hessian, Jacobian, jvp, vjp


def _np(t):
    return np.asarray(t._value)


# ---------------------------------------------------------------------------
# functional autodiff
# ---------------------------------------------------------------------------
@pytest.mark.fast
def test_vjp_jvp():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], "float32"))

    def f(t):
        return t * t

    out, g = vjp(f, x, paddle.to_tensor(np.ones(3, "float32")))
    np.testing.assert_allclose(_np(out), [1, 4, 9], rtol=1e-6)
    np.testing.assert_allclose(_np(g), [2, 4, 6], rtol=1e-6)
    out, jv = jvp(f, x, paddle.to_tensor(np.array([1.0, 0.0, 0.0], "float32")))
    np.testing.assert_allclose(_np(jv), [2, 0, 0], rtol=1e-6)


def test_jacobian_matrix():
    x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))

    def f(t):
        import paddle_tpu.tensor as T

        return T.concat([t * t, (t[0] * t[1]).reshape([1])])

    J = Jacobian(f, x)
    expect = np.array([[2.0, 0.0], [0.0, 4.0], [2.0, 1.0]], "float32")
    np.testing.assert_allclose(_np(J.matrix), expect, rtol=1e-5)
    assert J.shape == [3, 2]
    np.testing.assert_allclose(_np(J[0]), expect[0], rtol=1e-5)


def test_batched_jacobian_and_hessian():
    x = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3) + 1)
    J = Jacobian(lambda t: t * t, x, is_batched=True)
    m = _np(J.matrix)
    assert m.shape == (2, 3, 3)  # per-sample blocks, no cross-batch columns
    np.testing.assert_allclose(m[0], np.diag([2.0, 4.0, 6.0]), rtol=1e-5)
    np.testing.assert_allclose(m[1], np.diag([8.0, 10.0, 12.0]), rtol=1e-5)

    H = Hessian(lambda t: (t * t).sum(), x, is_batched=True)
    hm = _np(H.matrix)
    assert hm.shape == (2, 3, 3)
    np.testing.assert_allclose(hm[0], 2 * np.eye(3), rtol=1e-5)


def test_hessian_quadratic():
    A = np.array([[2.0, 1.0], [1.0, 3.0]], "float32")
    x = paddle.to_tensor(np.array([0.5, -1.0], "float32"))

    def f(t):
        import paddle_tpu.tensor as T

        return (t * (paddle.to_tensor(A) @ t)).sum() * 0.5

    H = Hessian(f, x)
    np.testing.assert_allclose(_np(H.matrix), (A + A.T) / 2 * 1.0, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# summary / flops
# ---------------------------------------------------------------------------
def test_summary_and_flops(capsys):
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    info = paddle.summary(net, (1, 8))
    assert info["total_params"] == 8 * 16 + 16 + 16 * 2 + 2
    assert "Total params" in capsys.readouterr().out
    n_flops = paddle.flops(net, input_size=(1, 8))
    # at least the two matmuls: 2*(1*8*16 + 1*16*2)
    assert n_flops >= 2 * (8 * 16 + 16 * 2)


# ---------------------------------------------------------------------------
# audio
# ---------------------------------------------------------------------------
def test_mel_scale_roundtrip_and_fbank():
    from paddle_tpu.audio import functional as AF

    freqs = np.array([100.0, 440.0, 4000.0], "float32")
    back = AF.mel_to_hz(AF.hz_to_mel(freqs))
    np.testing.assert_allclose(np.asarray(back), freqs, rtol=1e-4)
    fb = _np(AF.compute_fbank_matrix(16000, 512, n_mels=40))
    assert fb.shape == (40, 257)
    assert (fb >= 0).all() and fb.sum() > 0


def test_spectrogram_and_mfcc_shapes():
    sr, n_fft, hop = 16000, 256, 128
    wave = paddle.to_tensor(
        np.sin(2 * np.pi * 440 * np.arange(sr // 4) / sr).astype("float32")[None]
    )
    spec = audio.features.Spectrogram(n_fft=n_fft, hop_length=hop)(wave)
    assert _np(spec).shape[1] == n_fft // 2 + 1
    # 440 Hz peak lands in the right bin
    bin_hz = sr / n_fft
    peak = _np(spec)[0].mean(-1).argmax()
    assert abs(peak * bin_hz - 440) < bin_hz * 1.5
    mfcc = audio.features.MFCC(sr=sr, n_mfcc=13, n_fft=n_fft, hop_length=hop, n_mels=40)(wave)
    assert _np(mfcc).shape[1] == 13


def test_audio_features_gradient_flows_to_input():
    # adversarial-audio / vocoder-loss use case: d(mel)/d(wave) must exist
    wave = paddle.to_tensor(
        np.sin(np.linspace(0, 20, 512)).astype("float32")[None], stop_gradient=False
    )
    mel = audio.features.LogMelSpectrogram(sr=8000, n_fft=128, hop_length=64, n_mels=16, f_min=20.0)(wave)
    loss = (mel * mel).mean()
    loss.backward()
    g = wave.grad
    assert g is not None and np.abs(_np(g)).max() > 0


def test_jacobian_multi_output_and_multi_input():
    x = paddle.to_tensor(np.array([1.0, 2.0], "float32"))
    y = paddle.to_tensor(np.array([3.0], "float32"))

    # two outputs: rows stack [d(2a); d(3a)]
    J = Jacobian(lambda a: (a * 2, a * 3), x)
    np.testing.assert_allclose(
        _np(J.matrix),
        np.vstack([2 * np.eye(2), 3 * np.eye(2)]).astype("float32"),
        rtol=1e-6,
    )
    # two inputs: cols concat [d/da, d/db] of a*b0
    J2 = Jacobian(lambda a, b: a * b[0], [x, y])
    np.testing.assert_allclose(
        _np(J2.matrix), np.array([[3, 0, 1], [0, 3, 2]], "float32"), rtol=1e-6
    )


@pytest.mark.fast
def test_window_matches_scipy():
    import scipy.signal as ss

    from paddle_tpu.audio.functional import get_window

    for w in ("hann", "hamming", "blackman"):
        np.testing.assert_allclose(
            _np(get_window(w, 64)), ss.get_window(w, 64), rtol=1e-5, atol=1e-6
        )


# ---------------------------------------------------------------------------
# quantization
# ---------------------------------------------------------------------------
def test_qat_trains_and_quantizes():
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    q = quantization.QAT(quantization.QuantConfig())
    net = q.quantize(net)
    # quantizable layers got wrapped
    kinds = [type(s).__name__ for _, s in net.named_sublayers()]
    assert kinds.count("QuantedWrapper") == 2
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.randn(32, 8).astype("float32"))
    y = paddle.to_tensor(rs.randn(32, 1).astype("float32"))
    mse = nn.MSELoss()
    first_w_before = _np(net.parameters()[0]).copy()
    losses = []
    for _ in range(10):
        loss = mse(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < losses[0]  # STE lets grads flow
    # the FIRST layer must train too — catches the fake-quant op detaching
    # the tape for everything upstream of it
    assert np.abs(_np(net.parameters()[0]) - first_w_before).max() > 1e-6
    q.convert(net)
    out = _np(net(x))
    assert np.isfinite(out).all()


def test_ptq_calibration_and_convert():
    paddle.seed(1)
    net = nn.Sequential(nn.Linear(4, 4))
    ptq = quantization.PTQ()
    net = ptq.quantize(net)
    x = paddle.to_tensor(np.random.RandomState(0).randn(16, 4).astype("float32") * 3)
    ref = _np(net(x))  # observers pass through unchanged
    ptq.convert(net)
    got = _np(net(x))
    # int8 fake-quant error is small but nonzero
    err = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert 0 < err < 0.1


# ---------------------------------------------------------------------------
# fused layers
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_fused_transformer_encoder_layer():
    paddle.seed(0)
    from paddle_tpu.incubate.nn import FusedTransformerEncoderLayer

    layer = FusedTransformerEncoderLayer(32, 4, 64, dropout_rate=0.0)
    layer.eval()
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 6, 32).astype("float32"))
    out = layer(x)
    assert _np(out).shape == (2, 6, 32)
    assert np.isfinite(_np(out)).all()
    # trains: EVERY parameter (incl. qkv fused weight) must receive gradient
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=layer.parameters())
    layer.train()
    y = paddle.to_tensor(np.random.RandomState(1).randn(2, 6, 32).astype("float32"))
    mse = nn.MSELoss()
    before = [_np(p).copy() for p in layer.parameters()]
    losses = []
    for _ in range(5):
        loss = mse(layer(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < losses[0]
    after = [_np(p) for p in layer.parameters()]
    for b, a in zip(before, after):
        assert np.abs(a - b).max() > 0, "a parameter received no gradient"


@pytest.mark.fast
def test_trainstep_repeat_matches_sequential():
    """repeat(n) — one compiled scan-over-steps program — must produce the
    exact per-step loss trajectory of n sequential step() calls (dropout 0,
    so the RNG keying difference is immaterial)."""
    import numpy as np

    from paddle_tpu.jit import TrainStep

    def build():
        paddle.seed(7)
        m = paddle.nn.Sequential(
            paddle.nn.Linear(6, 8), paddle.nn.Tanh(), paddle.nn.Linear(8, 2))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=m.parameters())
        return TrainStep(m, lambda mm, x, y: ((mm(x) - y) ** 2).mean(), opt)

    rng = np.random.default_rng(0)
    x = paddle.to_tensor(rng.standard_normal((10, 6)).astype("float32"))
    y = paddle.to_tensor(rng.standard_normal((10, 2)).astype("float32"))

    s1 = build()
    seq_losses = [float(s1(x, y)) for _ in range(4)]
    s2 = build()
    rep_losses = np.asarray(s2.repeat(4, x, y)._value)
    np.testing.assert_allclose(rep_losses, seq_losses, rtol=1e-5, atol=1e-6)
    # final weights identical too
    for p1, p2 in zip(s1._params, s2._params):
        np.testing.assert_allclose(
            np.asarray(p1._value), np.asarray(p2._value), rtol=1e-5, atol=1e-6)


@pytest.mark.fast
def test_trainstep_run_steps_scans_data():
    """run_steps consumes a leading [n_steps] axis per batch arg; the loss
    trajectory equals sequential calls on the slices."""
    import numpy as np

    from paddle_tpu.jit import TrainStep

    def build():
        paddle.seed(3)
        m = paddle.nn.Linear(5, 3)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=m.parameters())
        return TrainStep(m, lambda mm, x, y: ((mm(x) - y) ** 2).mean(), opt)

    rng = np.random.default_rng(1)
    xs = rng.standard_normal((3, 8, 5)).astype("float32")
    ys = rng.standard_normal((3, 8, 3)).astype("float32")

    s1 = build()
    seq = [float(s1(paddle.to_tensor(xs[i]), paddle.to_tensor(ys[i])))
           for i in range(3)]
    s2 = build()
    got = np.asarray(s2.run_steps(paddle.to_tensor(xs), paddle.to_tensor(ys))._value)
    np.testing.assert_allclose(got, seq, rtol=1e-5, atol=1e-6)
