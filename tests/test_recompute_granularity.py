"""recompute_granularity plumbing (reference GPT knob:
``recompute_granularity`` on GPT-class model configs, upstream
`fleet/utils/recompute.py` + GPT model kwargs).

Covers the round-5 folded-stack OOM fix end-to-end:
  - policy mapping + fail-fast validation (helper, SpmdPipeline ctor,
    bare recompute() call);
  - every granularity reproduces the no-recompute loss trajectory
    EXACTLY on folded, unfolded and pp-scheduled GPT stacks (remat is
    semantics-preserving by construction — any drift is a bug);
  - the nested-recompute suppression in SpmdPipeline._apply_block: a
    block whose own forward calls recompute() must NOT double-wrap when
    the stack checkpoint wraps it, and the caller-owned flag must be
    restored after the apply (never permanently mutated).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
    SpmdPipeline,
)
from paddle_tpu.distributed.fleet.utils.recompute_helper import (
    policy_for_granularity,
    recompute,
)

import jax


def _init(pp=1):
    s = fleet.DistributedStrategy()
    s.hybrid_configs["dp_degree"] = 8 // pp
    s.hybrid_configs["pp_degree"] = pp
    fleet.init(is_collective=True, strategy=s)


# --------------------------------------------------------------------------
# mapping + validation
# --------------------------------------------------------------------------
@pytest.mark.fast
def test_policy_mapping():
    assert policy_for_granularity("full") is None
    assert policy_for_granularity(None) is None
    for g in ("full_attn", "core_attn", "dots"):
        assert policy_for_granularity(g) is jax.checkpoint_policies.dots_saveable
    with pytest.raises(ValueError, match="recompute_granularity"):
        policy_for_granularity("selective")


@pytest.mark.fast
def test_ctor_fails_fast_on_typo():
    _init()
    blocks = [nn.Linear(8, 8) for _ in range(2)]
    with pytest.raises(ValueError, match="recompute_granularity"):
        SpmdPipeline(blocks, num_stages=1, recompute_block=True,
                     recompute_granularity="ful")


@pytest.mark.fast
def test_bare_recompute_rejects_typo():
    lin = nn.Linear(4, 4)
    x = paddle.to_tensor(np.ones((2, 4), "float32"))
    with pytest.raises(ValueError, match="recompute_granularity"):
        recompute(lin, x, granularity="fulll")


# --------------------------------------------------------------------------
# trajectory equivalence: remat must not change the math
# --------------------------------------------------------------------------
def _gpt_losses(fold, use_recompute, granularity, steps=4):
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    _init()
    paddle.seed(0)
    cfg = GPTConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=4,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=64, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0, fold_layers=fold,
        use_recompute=use_recompute, recompute_granularity=granularity)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    step = TrainStep(model, lambda m, ids, lbl: m(ids, labels=lbl), opt)
    toks = np.random.RandomState(0).randint(0, 128, (2, 17)).astype("int32")
    ids = paddle.to_tensor(toks[:, :-1])
    lbl = paddle.to_tensor(toks[:, 1:])
    return [float(step(ids, lbl)) for _ in range(steps)]


@pytest.mark.slow
@pytest.mark.parametrize("fold", [False, True], ids=["unfolded", "folded"])
def test_granularity_trajectory_parity(fold):
    base = _gpt_losses(fold, use_recompute=False, granularity="full")
    # "full" remat re-emits the identical forward MATH, but wrapping the
    # region in jax.checkpoint changes XLA's fusion boundaries on this
    # jaxlib, so the last float ulp can differ and the AdamW trajectory
    # accumulates it (observed: step 3 of 4 off by ~1e-7 relative on the
    # unfolded variant). Bitwise equality over an optimizer trajectory is
    # not a guaranteed invariant — pin with the same tight allclose the
    # other-granularity check uses (tracking note in ROADMAP.md).
    np.testing.assert_allclose(_gpt_losses(fold, True, "full"), base,
                               rtol=2e-6)
    # a different save policy changes XLA fusion boundaries, so rounding
    # may differ at the last float digit — tight allclose, not equality
    np.testing.assert_allclose(_gpt_losses(fold, True, "core_attn"), base,
                               rtol=2e-6)


@pytest.mark.slow
def test_pp_schedule_granularity_parity():
    """recompute_block under the pp2 micro-batch schedule: both
    granularities match the schedule's own no-recompute trajectory."""
    def run(recompute_block, gran):
        _init(pp=2)
        blocks = []
        paddle.seed(3)
        for _ in range(4):
            blocks.append(nn.Sequential(nn.Linear(16, 32), nn.GELU(),
                                        nn.Linear(32, 16)))
        pipe = SpmdPipeline(blocks, num_stages=2, num_microbatches=2,
                            recompute_block=recompute_block,
                            recompute_granularity=gran)
        head = nn.Linear(16, 1)
        opt = paddle.optimizer.Adam(
            learning_rate=1e-2,
            parameters=pipe.parameters() + head.parameters())
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.randn(8, 16).astype("float32"))
        y = paddle.to_tensor(rs.randn(8, 1).astype("float32"))
        out = []
        for _ in range(3):
            loss = ((head(pipe(x)) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            out.append(float(loss))
        return out

    base = run(False, "full")
    assert run(True, "full") == base
    assert run(True, "core_attn") == base


# --------------------------------------------------------------------------
# nested-recompute suppression + flag restoration
# --------------------------------------------------------------------------
class _SelfRecomputingBlock(nn.Layer):
    """Mimics GPTDecoderLayer: forward() consults _use_recompute and wraps
    its body in recompute() when set. Records the flag value each forward
    observes so the suppression is directly assertable."""

    seen = []  # class-level: survives the template/holder indirection

    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(8, 8)
        self._use_recompute = True

    def _body(self, x):
        return self.lin(x).tanh()

    def forward(self, x):
        _SelfRecomputingBlock.seen.append(self._use_recompute)
        if self._use_recompute:
            return recompute(self._body, x, _param_owners=[self])
        return self._body(x)


@pytest.mark.fast
def test_nested_recompute_suppressed_and_flag_restored():
    _init()
    _SelfRecomputingBlock.seen = []
    blocks = [_SelfRecomputingBlock() for _ in range(2)]
    pipe = SpmdPipeline(blocks, num_stages=1, recompute_block=True)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype("float32"))
    pipe(x)
    # the stack's jax.checkpoint wraps the apply; the block's own inner
    # recompute must have been OFF during every traced forward
    assert _SelfRecomputingBlock.seen, "template forward never ran"
    assert not any(_SelfRecomputingBlock.seen), _SelfRecomputingBlock.seen
    # and the caller-owned template flag is restored afterwards
    tmpl = pipe._template_holder[0]
    assert tmpl._use_recompute is True
    # sanity: without recompute_block the inner flag is honored untouched
    _SelfRecomputingBlock.seen = []
    pipe2 = SpmdPipeline([_SelfRecomputingBlock() for _ in range(2)],
                         num_stages=1, recompute_block=False)
    pipe2(x)
    assert all(_SelfRecomputingBlock.seen), _SelfRecomputingBlock.seen
