"""Test fixture: 8 virtual CPU devices, axon TPU plugin disabled.

Mirrors the reference's hardware-free distributed test strategy
(SURVEY.md §4): where Paddle simulates a cluster with localhost
subprocesses + Gloo, we simulate an 8-chip slice with
--xla_force_host_platform_device_count on the CPU PJRT backend.
"""
import os
import sys

# Must happen before any jax backend initialization.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _cpu_mesh_flags  # noqa: E402  (jax-free; shared flag defaults)

_cpu_mesh_flags.apply()

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

# NO persistent XLA compile cache, deliberately. It was tried (the suite
# is compile-bound here) and is a process-killer on this jaxlib: a
# DESERIALIZED CPU executable for some programs (observed: the ZeRO-stage-3
# resharded train step) runs once and then SIGABRTs the whole pytest
# process on its SECOND execution — a C++ CHECK, uncatchable, and
# undetectable at cache-write time short of executing the deserialized
# executable twice (side effects forbid that). A warm cache thus turns one
# mid-suite test into a run-ending crash nondeterministically; a cold run
# merely recompiles. Separately, jax's LRUCache.put is a bare write_bytes
# with no overwrite-on-exists, so a kill -9 mid-write (CI timeout, chaos
# soak) poisons the entry permanently. Revisit only on a jaxlib whose
# deserialized executables are re-execution-safe.

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run tests marked slow (full vision-zoo compile sweep)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: XLA-compile-heavy tests skipped by default "
        "(run with --runslow)")
    config.addinivalue_line(
        "markers", "fast: quick smoke subset (`pytest -m fast`)")
    config.addinivalue_line(
        "markers", "chaos: fault-injection soak tests (kill -9 /torn-write "
        "runs via paddle_tpu.testing.chaos; slow — excluded from tier-1)")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="compile-heavy; use --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


def free_port():
    """An OS-assigned free TCP port (shared by the multi-process
    rendezvous/rpc tests; keep retry/SO_REUSEADDR tweaks in one place)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
