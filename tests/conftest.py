"""Test fixture: 8 virtual CPU devices, axon TPU plugin disabled.

Mirrors the reference's hardware-free distributed test strategy
(SURVEY.md §4): where Paddle simulates a cluster with localhost
subprocesses + Gloo, we simulate an 8-chip slice with
--xla_force_host_platform_device_count on the CPU PJRT backend.
"""
import os
import sys

# Must happen before any jax backend initialization.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _cpu_mesh_flags  # noqa: E402  (jax-free; shared flag defaults)

_cpu_mesh_flags.apply()

import jax  # noqa: E402

try:
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

try:
    # persistent XLA compile cache: the suite is compile-bound on this box
    # and most programs are identical run-over-run (CI reuse; cold run pays
    # once). NOTE: the env var JAX_COMPILATION_CACHE_DIR alone is ignored
    # by this jax version — the config update is load-bearing.
    import tempfile

    # per-user dir (same rationale as utils/cpp_extension.py: a fixed
    # world-shared /tmp path breaks multi-user boxes and invites poisoning)
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get(
                          "JAX_COMPILATION_CACHE_DIR",
                          os.path.join(tempfile.gettempdir(),
                                       f"paddle_tpu_test_jaxcache_{os.getuid()}")))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:
    pass

import pytest  # noqa: E402


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="run tests marked slow (full vision-zoo compile sweep)",
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: XLA-compile-heavy tests skipped by default "
        "(run with --runslow)")
    config.addinivalue_line(
        "markers", "fast: quick smoke subset (`pytest -m fast`)")


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="compile-heavy; use --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


def free_port():
    """An OS-assigned free TCP port (shared by the multi-process
    rendezvous/rpc tests; keep retry/SO_REUSEADDR tweaks in one place)."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port
