"""The static robustness gate (scripts/check_robustness.py) — both that
the live tree is clean and that the checker actually catches what it
claims to catch."""
import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.fast

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "check_robustness.py")

sys.path.insert(0, os.path.join(REPO, "scripts"))
import check_robustness  # noqa: E402


def test_live_tree_is_clean():
    proc = subprocess.run([sys.executable, SCRIPT], capture_output=True,
                          text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def _violations(tmp_path, src):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(src))
    return list(check_robustness.check_file(str(f)))


def test_bare_except_rejected(tmp_path):
    v = _violations(tmp_path, """
        try:
            x = 1
        except:
            pass
    """)
    assert len(v) == 1 and "bare" in v[0][1]


def test_typed_except_allowed(tmp_path):
    assert not _violations(tmp_path, """
        try:
            x = 1
        except (OSError, ValueError):
            pass
    """)


def test_unbounded_recv_rejected(tmp_path):
    v = _violations(tmp_path, """
        def f(sock):
            return sock.recv(4096)
    """)
    assert len(v) == 1 and "recv" in v[0][1]


def test_recv_with_deadline_allowed(tmp_path):
    assert not _violations(tmp_path, """
        def f(sock):
            sock.settimeout(5.0)
            return sock.recv(4096)
    """)


# -- rule 3: collectives in the reshard path run under deadline_guard -------
def _guard_violations(tmp_path, src):
    f = tmp_path / "reshard_mod.py"
    f.write_text(textwrap.dedent(src))
    return list(check_robustness.check_guarded_collectives(str(f)))


def test_unguarded_collective_rejected(tmp_path):
    v = _guard_violations(tmp_path, """
        import jax

        def move(arr, sh):
            return jax.device_put(arr, sh)
    """)
    assert len(v) == 1 and "deadline_guard" in v[0][1]


def test_guarded_collective_allowed(tmp_path):
    assert not _guard_violations(tmp_path, """
        import jax

        def move(arr, sh, deadline_guard):
            with deadline_guard("move"):
                return jax.device_put(arr, sh)
    """)


def test_collective_helper_definition_allowed(tmp_path):
    # the guarded helper's own body is where the call legitimately lives
    assert not _guard_violations(tmp_path, """
        def _constrain(arr, sharding):
            return _cached(sharding)(arr)
    """)


def test_live_reshard_module_is_guarded():
    target = os.path.join(REPO, "paddle_tpu", "distributed", "reshard.py")
    assert not list(check_robustness.check_guarded_collectives(target))


# -- rule 4: serving store ops run under deadline_guard ---------------------
def _store_violations(tmp_path, src):
    f = tmp_path / "serving_mod.py"
    f.write_text(textwrap.dedent(src))
    return list(check_robustness.check_guarded_store_ops(str(f)))


def test_unguarded_store_op_rejected(tmp_path):
    v = _store_violations(tmp_path, """
        def f(store, key):
            return store.get(key)
    """)
    assert len(v) == 1 and "deadline_guard" in v[0][1]


def test_unguarded_attr_store_op_rejected(tmp_path):
    # self._store.<op> counts: the receiver dereferences a store name
    v = _store_violations(tmp_path, """
        class W:
            def f(self, key):
                self._store.set(key, b"x")
                return self._store.add(key, 1)
    """)
    assert len(v) == 2


def test_guarded_store_op_allowed(tmp_path):
    assert not _store_violations(tmp_path, """
        from paddle_tpu.serving.protocol import deadline_guard

        def f(store, key):
            with deadline_guard("read"):
                return store.get(key)
    """)


def test_non_store_receiver_ignored(tmp_path):
    # dict/cache methods that happen to share op names are not store ops
    assert not _store_violations(tmp_path, """
        def f(cache, key):
            return cache.get(key)
    """)


def test_live_serving_modules_are_guarded():
    for rel in check_robustness.GUARDED_STORE_FILES:
        target = os.path.join(REPO, rel)
        assert os.path.isfile(target), rel
        assert not list(check_robustness.check_guarded_store_ops(target)), rel


def test_front_tier_files_are_enrolled():
    # PR 19: the federated front tier and the replay harness both talk
    # to the store in hot loops — dropping them from the guarded list
    # would silently un-police every one of those ops
    rels = {os.path.basename(p) for p in check_robustness.GUARDED_STORE_FILES}
    assert "frontier.py" in rels
    assert "replay.py" in rels


# -- rule 5: transport socket ops run under deadline_guard -------------------
def _socket_violations(tmp_path, src):
    f = tmp_path / "transport_mod.py"
    f.write_text(textwrap.dedent(src))
    return list(check_robustness.check_guarded_socket_ops(str(f)))


def test_unguarded_socket_op_rejected(tmp_path):
    v = _socket_violations(tmp_path, """
        def f(raw_sock, data):
            raw_sock.sendall(data)
            return raw_sock.recv(4096)
    """)
    assert len(v) == 2 and all("deadline_guard" in m for _, m in v)


def test_unguarded_attr_socket_op_rejected(tmp_path):
    # self._listen_sock.<op> counts: the receiver dereferences a *sock* name
    v = _socket_violations(tmp_path, """
        class S:
            def f(self):
                return self._listen_sock.accept()
    """)
    assert len(v) == 1


def test_unguarded_select_poll_rejected(tmp_path):
    # select.select blocks too when given a nonzero timeout
    v = _socket_violations(tmp_path, """
        import select

        def f(raw_sock):
            return select.select([raw_sock], [], [], 1.0)
    """)
    assert len(v) == 1 and ".select" in v[0][1]


def test_guarded_socket_op_allowed(tmp_path):
    assert not _socket_violations(tmp_path, """
        from paddle_tpu.serving.protocol import deadline_guard

        def f(raw_sock, data):
            with deadline_guard("send frame"):
                raw_sock.sendall(data)
    """)


def test_non_socket_receiver_ignored(tmp_path):
    # a queue/channel that happens to share op names is not a socket
    assert not _socket_violations(tmp_path, """
        def f(chan, data):
            chan.send(data)
            return chan.recv()
    """)


def test_live_transport_module_is_guarded():
    for rel in check_robustness.GUARDED_SOCKET_FILES:
        target = os.path.join(REPO, rel)
        assert os.path.isfile(target), rel
        assert not list(
            check_robustness.check_guarded_socket_ops(target)), rel


# -- rule 6: MPMD boundary channel ops run under deadline_guard -------------
def _chan_violations(tmp_path, src):
    f = tmp_path / "mpmd_mod.py"
    f.write_text(textwrap.dedent(src))
    return list(check_robustness.check_guarded_chan_ops(str(f)))


def test_unguarded_chan_send_rejected(tmp_path):
    v = _chan_violations(tmp_path, """
        def f(chan, frame):
            chan.send(frame)
    """)
    assert len(v) == 1 and "deadline_guard" in v[0][1]


def test_unguarded_attr_chan_poll_rejected(tmp_path):
    # self._chan.<op> counts: the receiver dereferences a *chan* name
    v = _chan_violations(tmp_path, """
        class E:
            def pump(self):
                for fr in self._chan.poll():
                    yield fr
    """)
    assert len(v) == 1


def test_guarded_chan_op_allowed(tmp_path):
    assert not _chan_violations(tmp_path, """
        from paddle_tpu.serving.protocol import deadline_guard

        def f(chan, frame):
            with deadline_guard("boundary send"):
                chan.send(frame)
    """)


def test_non_chan_receiver_ignored(tmp_path):
    # a socket/queue that doesn't mention chan is rule 5's business
    assert not _chan_violations(tmp_path, """
        def f(pipe_end, frame):
            pipe_end.send(frame)
            return pipe_end.recv()
    """)


def test_live_mpmd_module_is_guarded():
    for rel in check_robustness.GUARDED_CHAN_FILES:
        target = os.path.join(REPO, rel)
        assert os.path.isfile(target), rel
        assert not list(check_robustness.check_guarded_chan_ops(target)), rel


def _pallas_violations(tmp_path, src):
    f = tmp_path / "mod.py"
    f.write_text(textwrap.dedent(src))
    return list(check_robustness.check_pallas_interpret(str(f)))


def test_pallas_call_without_interpret_rejected(tmp_path):
    v = _pallas_violations(tmp_path, """
        import jax
        from jax.experimental import pallas as pl
        def run(kernel, x):
            return pl.pallas_call(kernel, out_shape=x)(x)
    """)
    assert len(v) == 1 and "interpret" in v[0][1]


def test_pallas_call_with_interpret_allowed(tmp_path):
    assert not _pallas_violations(tmp_path, """
        import jax
        from jax.experimental import pallas as pl
        def run(kernel, x, interpret):
            return pl.pallas_call(kernel, out_shape=x,
                                  interpret=interpret)(x)
    """)


def test_pallas_kwargs_splat_not_sufficient(tmp_path):
    # the fallback must be VISIBLE at the call site, not hidden in **kw
    v = _pallas_violations(tmp_path, """
        from jax.experimental import pallas as pl
        def run(kernel, x, **kw):
            return pl.pallas_call(kernel, out_shape=x, **kw)(x)
    """)
    assert len(v) == 1


def test_live_pallas_plane_declares_interpret():
    files = list(check_robustness._pallas_files(REPO))
    assert files, "kernel plane missing"
    for path in files:
        assert not list(check_robustness.check_pallas_interpret(path)), path


# -- rule 8: supervisor store ops guarded + journal writes atomic -----------
def _atomic_violations(tmp_path, src):
    f = tmp_path / "supervisor_mod.py"
    f.write_text(textwrap.dedent(src))
    return list(check_robustness.check_atomic_journal_writes(str(f)))


def test_stray_write_open_rejected(tmp_path):
    v = _atomic_violations(tmp_path, """
        import json

        def save(path, doc):
            with open(path, "w") as f:
                json.dump(doc, f)
    """)
    assert len(v) == 1 and "_atomic_write_json" in v[0][1]


def test_append_and_plus_modes_rejected(tmp_path):
    v = _atomic_violations(tmp_path, """
        def log(path, line):
            with open(path, "a") as f:
                f.write(line)
        def touch(path):
            open(path, "r+").close()
    """)
    assert len(v) == 2


def test_nonliteral_open_mode_rejected(tmp_path):
    # an open() whose mode is not visible at the call site counts as a
    # write — the reviewer cannot prove it is read-only
    v = _atomic_violations(tmp_path, """
        def save(path, mode):
            return open(path, mode)
    """)
    assert len(v) == 1


def test_read_open_allowed(tmp_path):
    assert not _atomic_violations(tmp_path, """
        import json

        def load(path):
            with open(path) as f:
                return json.load(f)
        def load_rb(path):
            with open(path, "rb") as f:
                return f.read()
    """)


def test_write_inside_atomic_chokepoint_allowed(tmp_path):
    assert not _atomic_violations(tmp_path, """
        import json, os

        def _atomic_write_json(path, doc):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
    """)


def test_atomic_fn_without_os_replace_rejected(tmp_path):
    # a "chokepoint" that writes in place is not a chokepoint at all
    v = _atomic_violations(tmp_path, """
        import json

        def _atomic_write_json(path, doc):
            with open(path, "w") as f:
                json.dump(doc, f)
    """)
    assert len(v) == 1 and "os.replace" in v[0][1]


def test_live_supervisor_module_is_durable():
    for rel in check_robustness.GUARDED_SUPERVISOR_FILES:
        target = os.path.join(REPO, rel)
        assert os.path.isfile(target), rel
        assert not list(check_robustness.check_guarded_store_ops(target)), rel
        assert not list(
            check_robustness.check_atomic_journal_writes(target)), rel
