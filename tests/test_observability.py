"""Unified telemetry (docs/OBSERVABILITY.md): the metrics registry, the
env-gated facade + exporters, fleet snapshot merging, instrumented hot
paths (jit dispatch, checkpoints, watchdog, chaos, hapi callbacks), and
the profiler satellites (scheduler step-0 state, summary sorting/units,
load_profiler_result, worker-named exports).

The 2-process end-to-end acceptance run lives in
tests/test_telemetry_fleet.py; this file is in-process."""
import json
import os
import threading
import time

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.observability.metrics import MetricsRegistry, labelkey_str
from paddle_tpu.observability.fleet import merge_snapshots


# ---------------------------------------------------------------------------
# helpers / fixtures
# ---------------------------------------------------------------------------
@pytest.fixture
def tdir(tmp_path, monkeypatch):
    """Telemetry enabled into a fresh dir, registry reset around the test."""
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
    monkeypatch.delenv("PADDLE_TRAINER_ID", raising=False)
    obs.reset()
    yield tmp_path
    obs.reset()


def _events(tdir, rank=0):
    p = tdir / f"events_rank{rank}.jsonl"
    if not p.exists():
        return []
    return [json.loads(l) for l in p.read_text().splitlines() if l.strip()]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------
def test_counter_inc_and_labels():
    reg = MetricsRegistry()
    c = reg.counter("reqs_total")
    c.inc()
    c.inc(2, op="get")
    c.inc(3, op="get")
    assert c.value() == 1
    assert c.value(op="get") == 5
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_last_write_wins():
    reg = MetricsRegistry()
    g = reg.gauge("depth")
    g.set(3)
    g.set(1.5)
    g.inc(0.5)
    assert g.value() == 2.0
    assert g.value(rank=9) is None


def test_histogram_bounded_reservoir_and_percentiles():
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", reservoir=8)
    for v in range(100):
        h.observe(float(v))
    assert h.count() == 100
    s = h.snapshot()["series"][""]
    assert s["count"] == 100 and s["sum"] == sum(range(100))
    assert s["min"] == 0.0 and s["max"] == 99.0 and s["mean"] == 49.5
    # reservoir keeps only the newest 8 observations (92..99)
    assert s["values"] == [float(v) for v in range(92, 100)]
    assert 92.0 <= s["p50"] <= s["p90"] <= s["p99"] <= 99.0


def test_metric_name_convention_enforced():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        reg.counter("BadName")


def test_kind_conflicts_raise():
    reg = MetricsRegistry()
    reg.counter("x_total")
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    # catalog pins the declared kind (and supplies default help)
    reg2 = MetricsRegistry(catalog={"y_total": ("counter", "y help")})
    with pytest.raises(ValueError):
        reg2.gauge("y_total")
    assert reg2.counter("y_total").help == "y help"


def test_labelkey_is_order_independent():
    reg = MetricsRegistry()
    c = reg.counter("k_total")
    c.inc(1, b="2", a="1")
    c.inc(1, a="1", b="2")
    assert c.value(a="1", b="2") == 2
    snap = c.snapshot()
    assert list(snap["values"]) == [labelkey_str((("a", "1"), ("b", "2")))]


def test_prometheus_exposition():
    reg = MetricsRegistry()
    reg.counter("foo_total", "total foos").inc(2, op="get")
    reg.gauge("bar").set(1.5)
    h = reg.histogram("baz_seconds")
    h.observe(0.5)
    h.observe(1.5)
    text = reg.to_prometheus()
    assert "# HELP paddle_tpu_foo_total total foos" in text
    assert 'paddle_tpu_foo_total{op="get"} 2' in text
    assert "paddle_tpu_bar 1.5" in text
    assert "# TYPE paddle_tpu_baz_seconds summary" in text
    assert "paddle_tpu_baz_seconds_count 2" in text
    assert "paddle_tpu_baz_seconds_sum 2" in text
    assert 'paddle_tpu_baz_seconds{quantile="0.50"}' in text
    assert "paddle_tpu_baz_seconds_min 0.5" in text
    assert "paddle_tpu_baz_seconds_max 1.5" in text


# ---------------------------------------------------------------------------
# env-gated facade + exporters
# ---------------------------------------------------------------------------
def test_enabled_records_exports_and_logs_events(tdir):
    obs.inc("store_reconnect_total")
    obs.set_gauge("heartbeat_age_seconds", 0.25, rank=0)
    obs.observe("store_op_seconds", 0.01, op="get")
    obs.event("watchdog_start", interval=1.0)
    with obs.timed("checkpoint_save_seconds") as t:
        pass
    assert t.seconds is not None and t.seconds >= 0
    obs.record_compile("train_step", 0.5, signature="sig " * 200)

    path = obs.flush()
    text = open(path).read()
    assert path == str(tdir / "metrics_rank0.prom")
    assert "paddle_tpu_store_reconnect_total 1" in text
    assert 'paddle_tpu_heartbeat_age_seconds{rank="0"} 0.25' in text
    assert 'paddle_tpu_store_op_seconds_count{op="get"} 1' in text

    evs = _events(tdir)
    kinds = [e["kind"] for e in evs]
    assert "watchdog_start" in kinds and "xla_compile" in kinds
    for e in evs:  # every record carries the envelope
        assert {"ts", "kind", "rank", "pid"} <= set(e)
    compile_ev = next(e for e in evs if e["kind"] == "xla_compile")
    assert compile_ev["where"] == "train_step"
    assert len(compile_ev["signature"]) <= 240  # truncated, not unbounded

    assert obs.registry().get("xla_compile_total").value(
        where="train_step") == 1
    snap = obs.snapshot()
    assert snap["rank"] == 0 and "store_op_seconds" in snap["metrics"]


def test_concurrent_flush_is_safe(tdir):
    """The watchdog beat thread and the main thread (fleet_sync / atexit)
    flush in the same process; a pid-only tmp name let the loser of the
    write->rename race hit FileNotFoundError and kill the worker."""
    obs.inc("store_reconnect_total")
    errors = []

    def spin():
        try:
            for _ in range(60):
                obs.flush()
        except Exception as e:  # pragma: no cover - the regression itself
            errors.append(e)

    threads = [threading.Thread(target=spin) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    prom = (tdir / "metrics_rank0.prom").read_text()
    assert "paddle_tpu_store_reconnect_total" in prom
    assert not [p for p in tdir.iterdir() if ".tmp." in p.name]


def test_disabled_is_inert(tmp_path, monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_TELEMETRY_DIR", raising=False)
    obs.reset()
    obs.inc("store_reconnect_total")
    obs.observe("store_op_seconds", 0.01, op="get")
    obs.event("watchdog_start", interval=1.0)
    with obs.timed("checkpoint_save_seconds") as t:
        pass
    assert t.seconds is None
    assert obs.flush() is None
    assert obs.registry().get("store_reconnect_total") is None
    assert not any(tmp_path.iterdir())


def test_disabled_adds_no_measurable_overhead(monkeypatch):
    """Acceptance guard: with telemetry off, a recording call must stay a
    single env lookup — no locks, registry writes, or file I/O. 20us/call
    is ~40x the observed cost, loose enough for a loaded CI box while still
    catching any accidental I/O on the disabled path."""
    monkeypatch.delenv("PADDLE_TPU_TELEMETRY_DIR", raising=False)
    obs.reset()
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        obs.observe("train_step_seconds", 0.01)
        obs.inc("xla_compile_total")
    per_call = (time.perf_counter() - t0) / (2 * n)
    assert per_call < 20e-6, \
        f"disabled telemetry costs {per_call * 1e6:.2f}us per call"
    assert obs.registry().get("train_step_seconds") is None


# ---------------------------------------------------------------------------
# fleet merge + single-process sync
# ---------------------------------------------------------------------------
def _snap(rank, step_mean, count=4):
    series = {"count": count, "sum": count * step_mean, "min": step_mean,
              "max": step_mean, "mean": step_mean, "p50": step_mean,
              "p90": step_mean, "p99": step_mean,
              "values": [step_mean] * min(count, 4)}
    return {"rank": rank, "ts": 0.0, "metrics": {
        "train_step_seconds": {"type": "histogram", "help": "",
                               "series": {"": series}},
        "xla_compile_total": {"type": "counter", "help": "",
                              "values": {"where=train_step": 1 + rank}},
        "heartbeat_age_seconds": {"type": "gauge", "help": "",
                                  "values": {f"rank={rank}": 0.1}},
    }}


def test_merge_snapshots_aggregates_and_flags_stragglers():
    doc = merge_snapshots({0: _snap(0, 0.01), 1: _snap(1, 0.02)},
                          world_size=3)
    assert doc["schema"] == 1 and doc["world_size"] == 3
    assert doc["missing_ranks"] == [2]

    agg = doc["aggregate"]["train_step_seconds"][""]
    assert agg["per_rank"] == {"0": 0.01, "1": 0.02}
    assert agg["min"] == 0.01 and agg["max"] == 0.02
    assert agg["min_rank"] == 0 and agg["max_rank"] == 1
    assert abs(agg["mean"] - 0.015) < 1e-12

    cnt = doc["aggregate"]["xla_compile_total"]["where=train_step"]
    assert cnt["per_rank"] == {"0": 1, "1": 2}

    # rank 1 runs 2x the fleet-mean step time -> straggler
    assert len(doc["stragglers"]) == 1
    s = doc["stragglers"][0]
    assert s["rank"] == 1 and s["metric"] == "train_step_seconds"
    assert s["slowdown"] > 1.3
    assert set(doc["ranks"]) == {"0", "1"}


def test_merge_snapshots_no_false_stragglers():
    doc = merge_snapshots({0: _snap(0, 0.01), 1: _snap(1, 0.011)},
                          world_size=2)
    assert doc["stragglers"] == [] and doc["missing_ranks"] == []


def test_merge_weights_straggler_mean_by_sample_count():
    """The straggler fleet mean is weighted by each rank's histogram
    sample count: a nearly-idle rank (2 fast steps against 100-step
    peers) must not drag the mean down and flag healthy ranks."""
    doc = merge_snapshots({0: _snap(0, 0.1, count=100),
                           1: _snap(1, 0.1, count=100),
                           2: _snap(2, 0.01, count=2)}, world_size=3)
    slot = doc["aggregate"]["train_step_seconds"][""]
    # unweighted mean-of-means would be 0.07 and flag ranks 0+1 at the
    # default 1.2x; the sample-weighted mean is the true per-step mean
    want = (100 * 0.1 + 100 * 0.1 + 2 * 0.01) / 202
    assert slot["weighted_mean"] == pytest.approx(want)
    assert doc["stragglers"] == []


def test_merge_skewed_counts_still_flag_real_straggler():
    # a genuine 2x straggler with equal weight stays flagged, and the
    # record carries its sample count + the weighted fleet mean
    doc = merge_snapshots({0: _snap(0, 0.1, count=100),
                           1: _snap(1, 0.1, count=100),
                           2: _snap(2, 0.2, count=100)}, world_size=3)
    assert [s["rank"] for s in doc["stragglers"]] == [2]
    s = doc["stragglers"][0]
    want = (100 * 0.1 + 100 * 0.1 + 100 * 0.2) / 300
    assert s["fleet_mean_seconds"] == pytest.approx(want)
    assert s["samples"] == 100
    assert s["slowdown"] == pytest.approx(0.2 / want)


def test_merge_zero_sample_counts_fall_back_unweighted():
    # snapshots whose series carry no counts (all zero) keep the old
    # unweighted mean instead of dividing by zero
    doc = merge_snapshots({0: _snap(0, 0.01, count=0),
                           1: _snap(1, 0.02, count=0)}, world_size=2)
    slot = doc["aggregate"]["train_step_seconds"][""]
    assert slot["weighted_mean"] == pytest.approx(0.015)
    assert [s["rank"] for s in doc["stragglers"]] == [1]


def test_straggler_threshold_env_override(monkeypatch, capsys):
    from paddle_tpu.observability.fleet import straggler_threshold

    monkeypatch.delenv("PADDLE_TPU_STRAGGLER_FACTOR", raising=False)
    assert straggler_threshold() == 1.2
    monkeypatch.setenv("PADDLE_TPU_STRAGGLER_FACTOR", "1.5")
    assert straggler_threshold() == 1.5
    # <= 1.0 would flag every rank; unparseable is operator error — both
    # diagnose to stderr and fall back rather than poison the merge
    for bad in ("0.5", "1.0", "abc"):
        monkeypatch.setenv("PADDLE_TPU_STRAGGLER_FACTOR", bad)
        assert straggler_threshold() == 1.2
        assert "invalid PADDLE_TPU_STRAGGLER_FACTOR" in capsys.readouterr().err


def test_merge_snapshots_honors_straggler_factor(monkeypatch):
    # rank 1 at 2x fleet mean: flagged at the default 1.2, ignored at 4x
    monkeypatch.setenv("PADDLE_TPU_STRAGGLER_FACTOR", "4.0")
    doc = merge_snapshots({0: _snap(0, 0.01), 1: _snap(1, 0.02)},
                          world_size=2)
    assert doc["stragglers"] == []
    monkeypatch.delenv("PADDLE_TPU_STRAGGLER_FACTOR")
    doc = merge_snapshots({0: _snap(0, 0.01), 1: _snap(1, 0.02)},
                          world_size=2)
    assert [s["rank"] for s in doc["stragglers"]] == [1]


def test_fleet_sync_single_process_writes_locally(tdir, monkeypatch):
    monkeypatch.delenv("PADDLE_TRAINERS_NUM", raising=False)
    obs.observe("train_step_seconds", 0.01)
    path = obs.fleet_sync()
    assert path == str(tdir / "fleet_metrics.json")
    doc = json.load(open(path))
    assert doc["world_size"] == 1 and doc["missing_ranks"] == []
    assert "train_step_seconds" in doc["aggregate"]
    # the per-rank prom textfile rides along with every sync
    assert (tdir / "metrics_rank0.prom").exists()


# ---------------------------------------------------------------------------
# instrumented hot paths (in-process)
# ---------------------------------------------------------------------------
def test_train_step_dispatch_instrumentation(tdir):
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    model = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    step = TrainStep(model, lambda m, a, b: ((m(a) - b) ** 2).mean(), opt)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    y = paddle.to_tensor(np.zeros((2, 4), np.float32))
    for _ in range(3):
        float(step(x, y))

    reg = obs.registry()
    # 1 compile (the miss), 2 recorded hot steps — the miss step is billed
    # to xla_compile_seconds, never double-counted in train_step_seconds
    assert reg.get("xla_compile_total").value(where="train_step") == 1
    assert reg.get("train_step_seconds").count() == 2
    ev = [e for e in _events(tdir) if e["kind"] == "xla_compile"]
    assert len(ev) == 1 and ev[0]["where"] == "train_step"
    assert ev[0]["seconds"] > 0


def test_checkpoint_save_restore_instrumentation(tdir):
    import paddle_tpu as paddle
    from paddle_tpu.distributed import checkpoint

    path = str(tdir / "ckpt" / "step_1")
    state = {"w": paddle.to_tensor(np.arange(8, dtype=np.float32))}
    checkpoint.save_state_dict(state, path)
    checkpoint.load_state_dict(path, state)

    reg = obs.registry()
    assert reg.get("checkpoint_save_seconds").count() == 1
    assert reg.get("checkpoint_save_bytes_total").value() > 0
    assert reg.get("checkpoint_restore_seconds").count() == 1
    kinds = [e["kind"] for e in _events(tdir)]
    assert "checkpoint_save" in kinds and "checkpoint_restore" in kinds
    save_ev = next(e for e in _events(tdir) if e["kind"] == "checkpoint_save")
    assert save_ev["path"] == path and save_ev["bytes"] > 0


class _DictStore:
    """In-memory stand-in for the heartbeat TCPStore."""

    def __init__(self):
        self.d = {}

    def set(self, k, v):
        self.d[k] = v if isinstance(v, bytes) else str(v).encode()

    def get(self, k, timeout=None):
        return self.d[k]

    def check(self, k):
        return k in self.d


def test_watchdog_stall_telemetry(tdir):
    """S4: the beat loop exports this rank's own heartbeat-age gauge and a
    stalled peer produces a rank_stalled JSONL diagnosis BEFORE on_stall
    (the default handler os._exit()s, skipping atexit)."""
    from paddle_tpu.runtime.watchdog import HeartbeatWatchdog

    stalled_seen = {}
    done = threading.Event()

    def on_stall(stalled, grace):
        stalled_seen.update(stalled)
        done.set()

    wd = HeartbeatWatchdog(_DictStore(), rank=0, world_size=2,
                           interval=0.05, miss=2, on_stall=on_stall).start()
    try:
        assert done.wait(10), "monitor never declared the silent peer stalled"
    finally:
        wd.stop()
    assert 1 in stalled_seen

    reg = obs.registry()
    assert reg.get("heartbeat_age_seconds").value(rank=0) is not None  # self
    assert reg.get("heartbeat_age_seconds").value(rank=1) is not None  # peer
    assert reg.get("heartbeat_beats_total").value() >= 1
    assert reg.get("watchdog_poll_age_seconds").count(rank=1) >= 1

    evs = _events(tdir)
    assert any(e["kind"] == "watchdog_start" for e in evs)
    st = [e for e in evs if e["kind"] == "rank_stalled"]
    assert st and "1" in st[-1]["stalled"] and st[-1]["monitor_rank"] == 0
    # the beat loop flushes, so the prom file is live mid-run
    assert (tdir / "metrics_rank0.prom").exists()


def test_chaos_fault_records_telemetry(tdir, monkeypatch):
    from paddle_tpu.testing import chaos

    monkeypatch.setenv("PADDLE_CHAOS", "1")
    monkeypatch.setenv("PADDLE_CHAOS_STORE_DROP", "1.0")
    monkeypatch.delenv("PADDLE_RESTART_COUNT", raising=False)
    chaos.reset()
    try:
        assert chaos.store_should_drop()
    finally:
        chaos.reset()
    assert obs.registry().get("chaos_fault_total").value(
        fault="store_drop") == 1
    ev = [e for e in _events(tdir) if e["kind"] == "chaos_fault"]
    assert ev and ev[0]["fault"] == "store_drop" and ev[0]["attempt"] == 0


def test_telemetry_logger_callback(tdir, monkeypatch):
    from paddle_tpu.hapi import callbacks as C

    monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "1e9")
    tl = C.TelemetryLogger()
    tl.set_params({"epochs": 1, "steps": 1})
    tl.on_train_begin()
    tl.on_train_batch_begin(0)
    time.sleep(0.005)
    tl.on_train_batch_end(0, {"loss": 0.5, "batch_size": 16,
                              "step_flops": 2.0e6})
    tl.on_train_end()

    reg = obs.registry()
    assert reg.get("train_tokens_per_second").value() > 0
    assert reg.get("train_flops_per_second").value() > 0
    assert reg.get("train_mfu").value() > 0

    evs = _events(tdir)
    runs = [e for e in evs if e["kind"] == "train_run"]
    assert [e["phase"] for e in runs] == ["begin", "end"]
    step_ev = next(e for e in evs if e["kind"] == "train_step")
    assert step_ev["loss"] == 0.5
    assert step_ev["tokens_per_second"] > 0 and step_ev["mfu"] > 0
    assert (tdir / "metrics_rank0.prom").exists()  # on_train_end flushes


def test_config_callbacks_auto_appends_telemetry_logger():
    from paddle_tpu.hapi import callbacks as C

    lst = C.config_callbacks(verbose=0)
    assert sum(isinstance(c, C.TelemetryLogger) for c in lst.callbacks) == 1
    # an explicit instance is not duplicated
    mine = C.TelemetryLogger()
    lst2 = C.config_callbacks(callbacks=[mine], verbose=0)
    tls = [c for c in lst2.callbacks if isinstance(c, C.TelemetryLogger)]
    assert tls == [mine]


# ---------------------------------------------------------------------------
# profiler satellites (S1-S3)
# ---------------------------------------------------------------------------
def _stubbed(prof):
    prof._start_trace = lambda: setattr(prof, "_tracing", True)
    prof._stop_trace = lambda: setattr(prof, "_tracing", False)
    return prof


def test_profiler_applies_step0_scheduler_state():
    """The step-0 state is applied at start() — with skip_first=1 the first
    step must run CLOSED (pre-fix it silently recorded)."""
    from paddle_tpu import profiler as P

    sched = P.make_scheduler(record=1, skip_first=1)
    prof = _stubbed(P.Profiler(scheduler=sched))
    prof.start()
    for _ in range(3):
        prof.step()
    prof.stop()
    assert prof._state_history == [
        P.ProfilerState.CLOSED,
        P.ProfilerState.RECORD_AND_RETURN,
        P.ProfilerState.RECORD_AND_RETURN,
        P.ProfilerState.RECORD_AND_RETURN,
    ]


def test_profiler_state_sequence_matches_scheduler():
    from paddle_tpu import profiler as P

    sched = P.make_scheduler(closed=1, ready=1, record=2, repeat=1)
    prof = _stubbed(P.Profiler(scheduler=sched))
    prof.start()
    assert not prof._tracing  # step 0 is CLOSED, not silently recording
    for _ in range(5):
        prof.step()
    prof.stop()
    S = P.ProfilerState
    assert prof._state_history == [
        S.CLOSED, S.READY, S.RECORD, S.RECORD_AND_RETURN,
        S.CLOSED, S.CLOSED,
    ]


def test_summary_sorted_by_and_time_unit(capsys):
    from paddle_tpu import profiler as P

    P.reset_host_events()
    try:
        for _ in range(3):
            with P.RecordEvent("aa_fast"):
                pass
        with P.RecordEvent("bb_slow"):
            time.sleep(0.02)

        prof = P.Profiler(timer_only=True)
        prof.start()
        prof.step()
        prof.stop()

        by_total = prof.summary(sorted_by="total")
        assert by_total.index("bb_slow") < by_total.index("aa_fast")
        by_calls = prof.summary(sorted_by=P.SortedKeys.Calls)
        assert by_calls.index("aa_fast") < by_calls.index("bb_slow")
        by_name = prof.summary(sorted_by="name")
        assert by_name.index("aa_fast") < by_name.index("bb_slow")
        by_avg = prof.summary(sorted_by="avg")
        assert by_avg.index("bb_slow") < by_avg.index("aa_fast")

        assert "total us" in prof.summary(time_unit="us")
        assert "total s" in prof.summary(time_unit="s")
        with pytest.raises(ValueError):
            prof.summary(sorted_by="bogus")
        with pytest.raises(ValueError):
            prof.summary(time_unit="minutes")

        P.reset_host_events()
        assert "aa_fast" not in prof.summary()
    finally:
        P.reset_host_events()
        capsys.readouterr()


def test_load_profiler_result(tmp_path):
    from paddle_tpu import profiler as P

    doc = {"traceEvents": [
        {"name": "op_a", "ph": "X", "ts": 10, "dur": 5},
        {"name": "op_a", "ph": "X", "ts": 20, "dur": 7},
        {"name": "op_b", "ph": "X", "ts": 30, "dur": 2},
    ]}
    (tmp_path / "host_trace.json").write_text(json.dumps(doc))

    for target in (str(tmp_path), str(tmp_path / "host_trace.json")):
        res = P.load_profiler_result(target)
        assert len(res) == 3
        assert res.names() == ["op_a", "op_b"]
        assert res.count("op_a") == 2
        assert res.total_duration("op_a") == 12.0
        assert res.time_range() == (10, 32)

    named = tmp_path / "named"
    named.mkdir()
    (named / "w3_host_trace.json").write_text(json.dumps(doc))
    assert P.load_profiler_result(str(named)).count("op_b") == 1

    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(FileNotFoundError):
        P.load_profiler_result(str(empty))


def test_export_chrome_tracing_worker_name(tmp_path, monkeypatch):
    from paddle_tpu import profiler as P

    handler = P.export_chrome_tracing(str(tmp_path), worker_name="w7")
    prof = P.Profiler(on_trace_ready=handler)
    # the config is live from construction (the host trace is written in
    # _stop_trace, BEFORE on_trace_ready fires)
    assert prof._export_dir == str(tmp_path)
    assert prof._worker_name == "w7"

    monkeypatch.setattr(P._runtime, "trace_stop", lambda: None)
    monkeypatch.setattr(
        P._runtime, "trace_export",
        lambda: [{"name": "x", "ph": "X", "ts": 0, "dur": 1}])
    prof._stop_trace()
    res = P.load_profiler_result(str(tmp_path))
    assert res.path.endswith("w7_host_trace.json")
    assert res.count("x") == 1
