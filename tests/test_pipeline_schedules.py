"""Scheduled pipeline execution (1F1B / zero-bubble) and backward-overlapped
gradient collectives (docs/PIPELINE.md): numeric equivalence with the gpipe
schedule over optimizer steps, schedule-knob resolution (strategy + env
grammar), the analytic/measured bubble model, and a compiled-HLO regression
that the bucketed gradient exchange is scheduled INSIDE the backward chain.
"""
import re
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.observability as _obs
from paddle_tpu import nn
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import grad_comm as gc
from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
    PpScheduleConfig,
    SpmdPipeline,
    _choose_microbatches,
    resolve_pp_schedule,
)


def _np(t):
    return np.asarray(t._value)


def _init(pp):
    s = fleet.DistributedStrategy()
    s.hybrid_configs["dp_degree"] = 8 // pp
    s.hybrid_configs["pp_degree"] = pp
    fleet.init(is_collective=True, strategy=s)


def _blocks(n, d=16, seed=0):
    paddle.seed(seed)
    return [nn.Sequential(nn.Linear(d, d), nn.Tanh()) for _ in range(n)]


def _train_losses(sched, V, pp=4, steps=3, seed=0):
    """3 AdamW steps of an 8-block toy stack under one schedule; the loss
    trajectory (not just one forward) is the equivalence witness — it sees
    forward, backward, and the optimizer update."""
    pipe = SpmdPipeline(_blocks(8, seed=seed), num_stages=pp,
                        num_microbatches=4, num_virtual_stages=V,
                        schedule=sched)
    paddle.seed(seed + 100)
    head = nn.Linear(16, 1)
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-2, parameters=pipe.parameters() + head.parameters())
    x = paddle.to_tensor(
        np.random.RandomState(seed).randn(8, 16).astype("float32"))
    losses = []
    for _ in range(steps):
        loss = (head(pipe(x)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    return losses


# =================================================== schedule equivalence ==
def test_1f1b_matches_gpipe_dp_pp_mesh(monkeypatch, tmp_path):
    """Tier-1 representative: interleaved 1F1B (V=2, explicitly scheduled
    backward) reproduces the gpipe loss trajectory on a dp2 x pp4 mesh, and
    its compiled schedule table has the smaller measured bubble."""
    # pp_* gauges are env-gated like all telemetry
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
    _init(pp=4)
    ref = _train_losses("gpipe", 1)
    bubble_gpipe = _obs.gauge("pp_bubble_fraction").value()
    got = _train_losses("1f1b", 2)
    bubble_1f1b = _obs.gauge("pp_bubble_fraction").value()
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=0)
    assert bubble_gpipe is not None and bubble_1f1b is not None
    assert bubble_1f1b < bubble_gpipe
    assert _obs.gauge("pp_schedule_ticks").value() > 0


@pytest.mark.slow
def test_zero_bubble_matches_gpipe_dp_pp_mesh():
    _init(pp=4)
    ref = _train_losses("gpipe", 1, seed=1)
    got = _train_losses("zero_bubble", 2, seed=1)
    np.testing.assert_allclose(got, ref, atol=1e-5, rtol=0)


@pytest.mark.slow
def test_schedules_match_on_pp_only_mesh():
    _init(pp=8)  # no data axis: pure pipeline, S=8, one block per stage
    ref = _train_losses("gpipe", 1, pp=8, seed=2)
    for sched in ("1f1b", "zero_bubble"):
        got = _train_losses(sched, 1, pp=8, seed=2)
        np.testing.assert_allclose(got, ref, atol=1e-5, rtol=0, err_msg=sched)


# ================================================== knob resolution ========
def test_resolve_pp_schedule_env_grammar(monkeypatch):
    s = fleet.DistributedStrategy()
    monkeypatch.delenv("PADDLE_TPU_PP_SCHEDULE", raising=False)
    assert resolve_pp_schedule(s) == PpScheduleConfig()
    monkeypatch.setenv("PADDLE_TPU_PP_SCHEDULE", "1f1b")
    assert resolve_pp_schedule(s).schedule == "1f1b"
    monkeypatch.setenv("PADDLE_TPU_PP_SCHEDULE", "zero_bubble,virtual=2")
    assert resolve_pp_schedule(s) == PpScheduleConfig("zero_bubble", 2)
    monkeypatch.setenv("PADDLE_TPU_PP_SCHEDULE", "schedule=1f1b,vpp=3")
    assert resolve_pp_schedule(s) == PpScheduleConfig("1f1b", 3)
    for bad in ("frobnicate", "schedule=bogus", "weird=1"):
        monkeypatch.setenv("PADDLE_TPU_PP_SCHEDULE", bad)
        with pytest.raises(ValueError):
            resolve_pp_schedule(s)


def test_resolve_pp_schedule_reads_strategy(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_PP_SCHEDULE", raising=False)
    s = fleet.DistributedStrategy()
    s.pipeline_configs.update(schedule="1f1b", virtual_pp_degree=2)
    assert resolve_pp_schedule(s) == PpScheduleConfig("1f1b", 2)
    # env overrides strategy, key by key
    monkeypatch.setenv("PADDLE_TPU_PP_SCHEDULE", "zero_bubble")
    assert resolve_pp_schedule(s) == PpScheduleConfig("zero_bubble", 2)
    s.pipeline_configs["schedule"] = "bogus"
    monkeypatch.delenv("PADDLE_TPU_PP_SCHEDULE", raising=False)
    with pytest.raises(ValueError):
        resolve_pp_schedule(s)


def test_grad_comm_overlap_knob(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_GRAD_COMM", raising=False)
    assert gc.resolve_config().overlap  # overlap on by default
    monkeypatch.setenv("PADDLE_TPU_GRAD_COMM", "on,overlap=0")
    assert not gc.resolve_config().overlap
    monkeypatch.setenv("PADDLE_TPU_GRAD_COMM", "on,overlap=1")
    assert gc.resolve_config().overlap


# ================================================== bubble accounting ======
def test_schedule_info_bubble_model():
    """Analytic model (docs/PIPELINE.md §3) and table-measured bubble:
    interleaving shrinks both; zero_bubble's deferred weight-grad fills the
    drain entirely once M >= 2(S-1)/V."""
    _init(pp=4)
    pipe1 = SpmdPipeline(_blocks(8, seed=7), num_stages=4, num_microbatches=4)
    pipe2 = SpmdPipeline(_blocks(8, seed=7), num_stages=4, num_microbatches=4,
                         num_virtual_stages=2)
    ig = pipe1.schedule_info(8, schedule="gpipe")
    iv = pipe2.schedule_info(8, schedule="1f1b")
    izb = pipe2.schedule_info(8, schedule="zero_bubble")
    assert ig["schedule"] == "gpipe" and iv["schedule"] == "1f1b"
    assert iv["analytic_bubble_fraction"] < ig["analytic_bubble_fraction"]
    assert iv["measured_bubble_fraction"] < ig["measured_bubble_fraction"]
    assert izb["analytic_bubble_fraction"] <= iv["analytic_bubble_fraction"]
    # S=4, V=2, M=4: 2(S-1)/V = 3 <= M -> the drain is completely filled
    assert izb["analytic_bubble_fraction"] == 0.0
    # gpipe, V=1, M=S=4: classic (S-1)/(M+S-1) fwd+bwd bubble = 3/7
    assert abs(ig["analytic_bubble_fraction"] - 3 / 7) < 1e-9
    assert abs(ig["measured_bubble_fraction"] - 3 / 7) < 1e-9


def test_choose_microbatches_warning_text_and_silence():
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert _choose_microbatches(6, 4) == 3
    msgs = [str(x.message) for x in w]
    assert any("num_microbatches=4 does not divide batch=6" in m
               and "using 3 micro-batches" in m for m in msgs), msgs
    # schedule_info and other probes must stay silent on the same input
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert _choose_microbatches(6, 4, warn=False) == 3
    assert not w


# =============================================== backward-overlapped comm ==
_VOCAB = 32


class _Net(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.emb = paddle.nn.Embedding(_VOCAB, 16)
        self.l1 = paddle.nn.Linear(16, 24)
        self.l2 = paddle.nn.Linear(24, 16)
        self.head = paddle.nn.Linear(16, _VOCAB)

    def forward(self, ids):
        h = self.emb(ids)
        h = paddle.nn.functional.gelu(self.l1(h))
        h = self.l2(h)
        return self.head(h)


@pytest.mark.slow
def test_overlap_schedules_exchange_inside_backward(monkeypatch):
    """With tiny buckets and overlap on (default), each tail bucket's
    all-reduce is a data dependency of the backward chain, so the compiled
    module's (topologically ordered) text must show at least one non-scalar
    dp all-reduce BEFORE the last dot — the monolithic path can only issue
    the exchange after every gradient exists."""
    monkeypatch.setenv("PADDLE_TPU_GRAD_COMM", "on,bucket_mb=0.001")
    s = fleet.DistributedStrategy()
    s.hybrid_configs.update(dp_degree=8, mp_degree=1, pp_degree=1,
                            sharding_degree=1)
    fleet.init(is_collective=True, strategy=s)
    paddle.seed(0)
    model = _Net()
    opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=model.parameters())
    fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(opt)

    def loss_fn(m, ids, lbl):
        return paddle.nn.functional.cross_entropy(
            m(ids).reshape([-1, _VOCAB]), lbl.reshape([-1]))

    step = fleet.DistTrainStep(model, loss_fn, opt)
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, _VOCAB, (16, 4)).astype(np.int32))
    assert np.isfinite(float(step(ids, ids)))
    plan = step._grad_comm_plan
    assert plan is not None and plan.overlap_tail and plan.n_buckets >= 2

    lines = step._compiled_for(ids, ids).as_text().splitlines()
    # non-scalar f32 all-reduces = the bucket exchanges (the loss reduction
    # is f32[]); dots = the matmuls of forward + backward
    ar = [i for i, l in enumerate(lines)
          if re.search(r"= f32\[\d[^\]]*\][^ ]* all-reduce", l)]
    dots = [i for i, l in enumerate(lines) if " dot(" in l]
    assert len(ar) >= 2, "expected split bucket all-reduces"
    assert dots, "expected dot ops in the compiled module"
    assert min(ar) < max(dots), (
        "no gradient all-reduce scheduled before the last dot: the "
        "exchange is not overlapped with backward compute")
