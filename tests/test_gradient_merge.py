"""strategy.gradient_merge: k accumulated micro-steps == one update on the
full batch (exact, both the compiled functional path and eager)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet

pytestmark = pytest.mark.fast


def _np(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


def _model_and_data():
    paddle.seed(3)
    m = nn.Linear(8, 4)
    rs = np.random.RandomState(0)
    x = rs.randn(8, 8).astype("float32")
    y = rs.randn(8, 4).astype("float32")
    return m, x, y


def test_gradient_merge_functional_matches_full_batch():
    from paddle_tpu.jit import TrainStep

    m, x, y = _model_and_data()
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                  parameters=m.parameters()), strat)
    step = TrainStep(m, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt)
    # two half-batches through the merged optimizer
    step(paddle.to_tensor(x[:4]), paddle.to_tensor(y[:4]))
    step(paddle.to_tensor(x[4:]), paddle.to_tensor(y[4:]))
    w_merged = _np(m.weight).copy()

    # reference: ONE step on the full batch with a plain optimizer
    m2, _, _ = _model_and_data()
    opt2 = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                     parameters=m2.parameters())
    step2 = TrainStep(m2, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt2)
    step2(paddle.to_tensor(x), paddle.to_tensor(y))
    np.testing.assert_allclose(w_merged, _np(m2.weight), rtol=1e-5, atol=1e-6)


def test_gradient_merge_skip_steps_leave_params():
    from paddle_tpu.jit import TrainStep

    m, x, y = _model_and_data()
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 3, "avg": True}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters()),
        strat)
    step = TrainStep(m, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt)
    w0 = _np(m.weight).copy()
    step(paddle.to_tensor(x[:4]), paddle.to_tensor(y[:4]))
    np.testing.assert_array_equal(_np(m.weight), w0)  # step 1/3: no update
    step(paddle.to_tensor(x[:4]), paddle.to_tensor(y[:4]))
    np.testing.assert_array_equal(_np(m.weight), w0)  # step 2/3: no update
    step(paddle.to_tensor(x[:4]), paddle.to_tensor(y[:4]))
    assert np.abs(_np(m.weight) - w0).max() > 1e-7  # boundary applied


def test_gradient_merge_checkpoint_roundtrip():
    """state_dict must carry the inner moments AND the mid-cycle merge
    accumulator so a restored run continues the same trajectory."""
    from paddle_tpu.jit import TrainStep

    m, x, y = _model_and_data()
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                  parameters=m.parameters()), strat)
    step = TrainStep(m, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt)
    # 3 micro-steps: one boundary applied + one mid-cycle accumulation
    for lo, hi in ((0, 4), (4, 8), (0, 4)):
        step(paddle.to_tensor(x[lo:hi]), paddle.to_tensor(y[lo:hi]))
    sd = opt.state_dict()
    keys = "".join(sd.keys())
    assert "gm_acc" in keys and "inner_velocity" in keys, sorted(sd)

    # restore into a fresh run at the same params; step 4 must match
    w_snapshot = _np(m.weight).copy()
    b_snapshot = _np(m.bias).copy()
    step(paddle.to_tensor(x[4:]), paddle.to_tensor(y[4:]))
    w_after = _np(m.weight).copy()

    jnp_ = __import__("jax").numpy
    m2, _, _ = _model_and_data()
    m2.weight._rebind(jnp_.asarray(w_snapshot))
    m2.bias._rebind(jnp_.asarray(b_snapshot))
    opt2 = fleet.distributed_optimizer(
        paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                  parameters=m2.parameters()), strat)
    opt2.set_state_dict(sd)
    step2 = TrainStep(m2, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt2)
    step2(paddle.to_tensor(x[4:]), paddle.to_tensor(y[4:]))
    np.testing.assert_allclose(_np(m2.weight), w_after, rtol=1e-5, atol=1e-6)


def test_gradient_merge_eager_matches_full_batch():
    m, x, y = _model_and_data()
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters()),
        strat)
    for lo, hi in ((0, 4), (4, 8)):
        loss = paddle.mean((m(paddle.to_tensor(x[lo:hi]))
                            - paddle.to_tensor(y[lo:hi])) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
    w_merged = _np(m.weight).copy()

    m2, _, _ = _model_and_data()
    opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=m2.parameters())
    loss = paddle.mean((m2(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2)
    loss.backward()
    opt2.step()
    np.testing.assert_allclose(w_merged, _np(m2.weight), rtol=1e-5, atol=1e-6)


def test_gradient_merge_ctr_advances_without_grad():
    """gm_ctr is cycle state: a param whose grad is None for a micro-step
    must still see its counter advance, or varying grad-liveness desyncs
    its accumulator from the merge boundary."""
    import jax.numpy as jnp

    from paddle_tpu.distributed.fleet import GradientMergeOptimizer

    m, _, _ = _model_and_data()
    gm = GradientMergeOptimizer(
        paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters()),
        k_steps=2, avg=True)
    states = gm.functional_states()
    p_vals = [p._value for p in m.parameters()]
    # micro-step 1: only param 0 has a grad
    grads = [jnp.ones_like(p_vals[0]), None]
    p_vals, states = gm.functional_step(p_vals, grads, states, 0.1)
    assert int(states[0]["gm_ctr"]) == 1
    assert int(states[1]["gm_ctr"]) == 1  # advanced despite grad=None
    # micro-step 2: both live — boundary applies for BOTH in sync
    grads = [jnp.ones_like(v) for v in p_vals]
    p_vals, states = gm.functional_step(p_vals, grads, states, 0.1)
    assert int(states[0]["gm_ctr"]) == 2 and int(states[1]["gm_ctr"]) == 2
    assert float(jnp.abs(states[1]["gm_acc"]).max()) == 0.0  # zeroed at boundary


def test_gradient_merge_nonlive_at_boundary_applies_accumulated():
    """A param live mid-cycle but grad-less AT the boundary must have its
    accumulated gradient applied at that boundary (and its accumulator
    zeroed), not leak it into the next cycle's average."""
    import jax.numpy as jnp

    from paddle_tpu.distributed.fleet import GradientMergeOptimizer

    m, _, _ = _model_and_data()
    gm = GradientMergeOptimizer(
        paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters()),
        k_steps=2, avg=True)
    states = gm.functional_states()
    p_vals = [p._value for p in m.parameters()]
    b0 = np.asarray(p_vals[1]).copy()
    # micro-step 1: both live
    grads = [jnp.ones_like(v) for v in p_vals]
    p_vals, states = gm.functional_step(p_vals, grads, states, 0.1)
    # micro-step 2 (boundary): param 1's grad is None
    grads = [jnp.ones_like(p_vals[0]), None]
    p_vals, states = gm.functional_step(p_vals, grads, states, 0.1)
    # param 1's step-1 grad (1.0), averaged over k=2, applied: -0.1 * 0.5
    np.testing.assert_allclose(np.asarray(p_vals[1]), b0 - 0.05,
                               rtol=1e-6, atol=1e-7)
    assert float(jnp.abs(states[1]["gm_acc"]).max()) == 0.0  # no leak
    # a never-grad trainable param is untouched at the boundary
    states2 = gm.functional_states()
    v0 = np.asarray(p_vals[1]).copy()
    pv = list(p_vals)
    pv, states2 = gm.functional_step(
        pv, [jnp.ones_like(pv[0]), None], states2, 0.1)
    pv, states2 = gm.functional_step(
        pv, [jnp.ones_like(pv[0]), None], states2, 0.1)
    np.testing.assert_array_equal(np.asarray(pv[1]), v0)


def test_gradient_merge_exact_zero_grad_still_updates_at_boundary():
    """A param that received an EXACTLY-ZERO grad mid-cycle (then None at
    the boundary) did see a gradient — weight decay must still apply at
    the boundary (gm_saw flag, not acc!=0 inference)."""
    import jax.numpy as jnp

    from paddle_tpu.distributed.fleet import GradientMergeOptimizer

    m, _, _ = _model_and_data()
    gm = GradientMergeOptimizer(
        paddle.optimizer.AdamW(learning_rate=0.1, weight_decay=0.1,
                               parameters=m.parameters()),
        k_steps=2, avg=True)
    states = gm.functional_states()
    p_vals = [p._value for p in m.parameters()]
    w0 = np.asarray(p_vals[0]).copy()  # weight init is nonzero (decay visible)
    # micro-step 1: param 0 live with an exactly-zero grad
    pv, states = gm.functional_step(
        p_vals, [jnp.zeros_like(p_vals[0]), jnp.ones_like(p_vals[1])],
        states, 0.1)
    # boundary: param 0's grad is None — decay must still land
    pv, states = gm.functional_step(
        pv, [None, jnp.ones_like(pv[1])], states, 0.1)
    assert np.abs(np.asarray(pv[0]) - w0).max() > 1e-8, \
        "weight decay skipped for zero-grad param at boundary"


def test_gradient_merge_eager_midcycle_checkpoint():
    """An EAGER-mode checkpoint taken between merge boundaries must carry
    the accumulated micro-step gradients and cycle counter — resuming and
    finishing the cycle matches the uninterrupted run exactly."""
    m, x, y = _model_and_data()
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}

    def _opt_for(model):
        return fleet.distributed_optimizer(
            paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=model.parameters()), strat)

    def _micro(model, opt_, lo, hi):
        loss = paddle.mean((model(paddle.to_tensor(x[lo:hi]))
                            - paddle.to_tensor(y[lo:hi])) ** 2)
        loss.backward()
        opt_.step()
        opt_.clear_grad()

    # uninterrupted run: both micro-steps, boundary applies at step 2
    opt = _opt_for(m)
    _micro(m, opt, 0, 4)
    sd = opt.state_dict()  # mid-cycle checkpoint (1 of 2 accumulated)
    assert any("gm_eager" in str(k) for k in sd), sorted(sd)
    _micro(m, opt, 4, 8)
    w_full = _np(m.weight).copy()

    # resumed run: fresh optimizer, restore mid-cycle state, finish cycle
    m2, _, _ = _model_and_data()
    opt2 = _opt_for(m2)
    opt2.set_state_dict(sd)
    _micro(m2, opt2, 4, 8)
    np.testing.assert_allclose(_np(m2.weight), w_full, rtol=1e-5, atol=1e-6)


def test_gradient_merge_with_global_norm_clip():
    """Clip must apply to the MERGED gradient at the boundary (one clip per
    k steps, inner optimizer semantics), matching a full-batch clipped step."""
    from paddle_tpu.jit import TrainStep

    m, x, y = _model_and_data()
    clip = paddle.nn.ClipGradByGlobalNorm(0.01)
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1, grad_clip=clip,
                             parameters=m.parameters()), strat)
    step = TrainStep(m, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt)
    step(paddle.to_tensor(x[:4]), paddle.to_tensor(y[:4]))
    step(paddle.to_tensor(x[4:]), paddle.to_tensor(y[4:]))
    w_merged = _np(m.weight).copy()

    m2, _, _ = _model_and_data()
    opt2 = paddle.optimizer.SGD(
        learning_rate=0.1, grad_clip=paddle.nn.ClipGradByGlobalNorm(0.01),
        parameters=m2.parameters())
    step2 = TrainStep(m2, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt2)
    step2(paddle.to_tensor(x), paddle.to_tensor(y))
    np.testing.assert_allclose(w_merged, _np(m2.weight), rtol=1e-5, atol=1e-6)
