"""strategy.gradient_merge: k accumulated micro-steps == one update on the
full batch (exact, both the compiled functional path and eager)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import fleet

pytestmark = pytest.mark.fast


def _np(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


def _model_and_data():
    paddle.seed(3)
    m = nn.Linear(8, 4)
    rs = np.random.RandomState(0)
    x = rs.randn(8, 8).astype("float32")
    y = rs.randn(8, 4).astype("float32")
    return m, x, y


def test_gradient_merge_functional_matches_full_batch():
    from paddle_tpu.jit import TrainStep

    m, x, y = _model_and_data()
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                  parameters=m.parameters()), strat)
    step = TrainStep(m, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt)
    # two half-batches through the merged optimizer
    step(paddle.to_tensor(x[:4]), paddle.to_tensor(y[:4]))
    step(paddle.to_tensor(x[4:]), paddle.to_tensor(y[4:]))
    w_merged = _np(m.weight).copy()

    # reference: ONE step on the full batch with a plain optimizer
    m2, _, _ = _model_and_data()
    opt2 = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                     parameters=m2.parameters())
    step2 = TrainStep(m2, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt2)
    step2(paddle.to_tensor(x), paddle.to_tensor(y))
    np.testing.assert_allclose(w_merged, _np(m2.weight), rtol=1e-5, atol=1e-6)


def test_gradient_merge_skip_steps_leave_params():
    from paddle_tpu.jit import TrainStep

    m, x, y = _model_and_data()
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 3, "avg": True}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters()),
        strat)
    step = TrainStep(m, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt)
    w0 = _np(m.weight).copy()
    step(paddle.to_tensor(x[:4]), paddle.to_tensor(y[:4]))
    np.testing.assert_array_equal(_np(m.weight), w0)  # step 1/3: no update
    step(paddle.to_tensor(x[:4]), paddle.to_tensor(y[:4]))
    np.testing.assert_array_equal(_np(m.weight), w0)  # step 2/3: no update
    step(paddle.to_tensor(x[:4]), paddle.to_tensor(y[:4]))
    assert np.abs(_np(m.weight) - w0).max() > 1e-7  # boundary applied


def test_gradient_merge_checkpoint_roundtrip():
    """state_dict must carry the inner moments AND the mid-cycle merge
    accumulator so a restored run continues the same trajectory."""
    from paddle_tpu.jit import TrainStep

    m, x, y = _model_and_data()
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                  parameters=m.parameters()), strat)
    step = TrainStep(m, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt)
    # 3 micro-steps: one boundary applied + one mid-cycle accumulation
    for lo, hi in ((0, 4), (4, 8), (0, 4)):
        step(paddle.to_tensor(x[lo:hi]), paddle.to_tensor(y[lo:hi]))
    sd = opt.state_dict()
    keys = "".join(sd.keys())
    assert "gm_acc" in keys and "inner_velocity" in keys, sorted(sd)

    # restore into a fresh run at the same params; step 4 must match
    w_snapshot = _np(m.weight).copy()
    b_snapshot = _np(m.bias).copy()
    step(paddle.to_tensor(x[4:]), paddle.to_tensor(y[4:]))
    w_after = _np(m.weight).copy()

    jnp_ = __import__("jax").numpy
    m2, _, _ = _model_and_data()
    m2.weight._rebind(jnp_.asarray(w_snapshot))
    m2.bias._rebind(jnp_.asarray(b_snapshot))
    opt2 = fleet.distributed_optimizer(
        paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                  parameters=m2.parameters()), strat)
    opt2.set_state_dict(sd)
    step2 = TrainStep(m2, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt2)
    step2(paddle.to_tensor(x[4:]), paddle.to_tensor(y[4:]))
    np.testing.assert_allclose(_np(m2.weight), w_after, rtol=1e-5, atol=1e-6)


def test_gradient_merge_eager_matches_full_batch():
    m, x, y = _model_and_data()
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters()),
        strat)
    for lo, hi in ((0, 4), (4, 8)):
        loss = paddle.mean((m(paddle.to_tensor(x[lo:hi]))
                            - paddle.to_tensor(y[lo:hi])) ** 2)
        loss.backward()
        opt.step()
        opt.clear_grad()
    w_merged = _np(m.weight).copy()

    m2, _, _ = _model_and_data()
    opt2 = paddle.optimizer.SGD(learning_rate=0.1,
                                parameters=m2.parameters())
    loss = paddle.mean((m2(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2)
    loss.backward()
    opt2.step()
    np.testing.assert_allclose(w_merged, _np(m2.weight), rtol=1e-5, atol=1e-6)


def test_gradient_merge_with_global_norm_clip():
    """Clip must apply to the MERGED gradient at the boundary (one clip per
    k steps, inner optimizer semantics), matching a full-batch clipped step."""
    from paddle_tpu.jit import TrainStep

    m, x, y = _model_and_data()
    clip = paddle.nn.ClipGradByGlobalNorm(0.01)
    strat = fleet.DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    opt = fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1, grad_clip=clip,
                             parameters=m.parameters()), strat)
    step = TrainStep(m, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt)
    step(paddle.to_tensor(x[:4]), paddle.to_tensor(y[:4]))
    step(paddle.to_tensor(x[4:]), paddle.to_tensor(y[4:]))
    w_merged = _np(m.weight).copy()

    m2, _, _ = _model_and_data()
    opt2 = paddle.optimizer.SGD(
        learning_rate=0.1, grad_clip=paddle.nn.ClipGradByGlobalNorm(0.01),
        parameters=m2.parameters())
    step2 = TrainStep(m2, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt2)
    step2(paddle.to_tensor(x), paddle.to_tensor(y))
    np.testing.assert_allclose(w_merged, _np(m2.weight), rtol=1e-5, atol=1e-6)
