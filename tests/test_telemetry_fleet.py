"""2-process telemetry acceptance run (docs/OBSERVABILITY.md §5).

Two OS processes go through the real launch CLI (rank negotiation, JAX
coordination service, heartbeat watchdog) with PADDLE_TPU_TELEMETRY_DIR
set. The run must leave behind, per rank, a JSONL event log and a
Prometheus textfile, plus rank 0's merged fleet_metrics.json carrying
step-time, compile-count, checkpoint-duration, and heartbeat-age series
for BOTH ranks.
"""
import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "telemetry_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_run_exports_fleet_telemetry(tmp_path):
    tdir = tmp_path / "telemetry"
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["PADDLE_TPU_TELEMETRY_DIR"] = str(tdir)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--nnodes", "2", "--master", f"127.0.0.1:{port}",
           "--heartbeat_interval", "0.2",
           WORKER, str(tmp_path / "ckpt")]
    procs = [subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, cwd=REPO)
             for _ in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for rc, out, err in outs:
        assert rc == 0, f"rc={rc}\nstdout:{out[-800:]}\nstderr:{err[-2500:]}"
    assert any('{"ok": true}' in out for _, out, _ in outs)

    # -- per-rank exports ---------------------------------------------------
    for r in (0, 1):
        lines = (tdir / f"events_rank{r}.jsonl").read_text().splitlines()
        evs = [json.loads(l) for l in lines if l.strip()]
        assert all(e["rank"] == r for e in evs if e["kind"] != "fleet_aggregate")
        kinds = {e["kind"] for e in evs}
        assert {"init_parallel_env", "watchdog_start", "xla_compile",
                "checkpoint_save"} <= kinds, (r, sorted(kinds))

        prom = (tdir / f"metrics_rank{r}.prom").read_text()
        assert "paddle_tpu_train_step_seconds_count" in prom
        assert "paddle_tpu_xla_compile_total" in prom
        assert "paddle_tpu_checkpoint_save_seconds_count" in prom
        assert "paddle_tpu_heartbeat_age_seconds" in prom

    rank0_kinds = {e["kind"] for e in map(
        json.loads, (tdir / "events_rank0.jsonl").read_text().splitlines())}
    assert "fleet_aggregate" in rank0_kinds

    # -- the merged fleet document ------------------------------------------
    doc = json.loads((tdir / "fleet_metrics.json").read_text())
    assert doc["schema"] == 1
    assert doc["world_size"] == 2
    assert doc["missing_ranks"] == []
    assert set(doc["ranks"]) == {"0", "1"}

    agg = doc["aggregate"]
    for r in ("0", "1"):
        assert r in agg["train_step_seconds"][""]["per_rank"]
        assert r in agg["xla_compile_total"]["where=train_step"]["per_rank"]
        assert r in agg["checkpoint_save_seconds"][""]["per_rank"]
    # every rank self-reports its own heartbeat-age series
    for r in (0, 1):
        assert str(r) in agg["heartbeat_age_seconds"][f"rank={r}"]["per_rank"]
    # cross-rank stats materialized once >1 rank reported
    slot = agg["train_step_seconds"][""]
    assert {"min", "max", "mean", "min_rank", "max_rank"} <= set(slot)

    # per-rank histogram series keep the raw bounded reservoir
    h = doc["ranks"]["1"]["metrics"]["train_step_seconds"]["series"][""]
    assert h["count"] >= 1 and len(h["values"]) == h["count"] <= 256
