"""Dy2static AST conversion: Python control flow on traced tensors compiles
to lax.cond/while_loop instead of falling back to eager.

Reference test model: ``test/dygraph_to_static/`` (program_translator tests
run the same function in dygraph and to_static modes and assert parity;
transform tests check if/while/for/bool-op conversion). VERDICT r2 #3's
done-criterion: a data-dependent branchy model runs with NO fallback
warning and matches eager outputs.
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _assert_no_fallback(record):
    msgs = [str(w.message) for w in record if "EAGER" in str(w.message)]
    assert not msgs, f"dy2static fell back to eager: {msgs}"


def _run_static(fn, *argsets):
    """to_static(fn), run every argset, assert no fallback warning; returns
    outputs + the traced callable."""
    sfn = paddle.jit.to_static(fn)
    outs = []
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        for args in argsets:
            outs.append(sfn(*args))
    _assert_no_fallback(rec)
    return outs, sfn


@pytest.mark.fast
def test_if_on_tensor_compiles_both_branches():
    def f(x):
        if x.sum() > 0:
            y = x * 2
        else:
            y = x - 3
        return y + 1

    pos = paddle.to_tensor(np.ones((2, 3), "float32"))
    neg = paddle.to_tensor(-np.ones((2, 3), "float32"))
    (got_pos, got_neg), sfn = _run_static(f, (pos,), (neg,))
    np.testing.assert_allclose(got_pos.numpy(), f(pos).numpy(), rtol=1e-6)
    np.testing.assert_allclose(got_neg.numpy(), f(neg).numpy(), rtol=1e-6)
    # ONE compiled program serves both branch directions (lax.cond inside)
    assert sfn.program_cache_size == 1


@pytest.mark.fast
def test_early_return_in_branch():
    def f(x):
        if x.mean() > 10:
            return x / 10
        z = x + 5
        return z * 2

    lo = paddle.to_tensor(np.full((4,), 1.0, "float32"))
    hi = paddle.to_tensor(np.full((4,), 100.0, "float32"))
    (g_lo, g_hi), sfn = _run_static(f, (lo,), (hi,))
    np.testing.assert_allclose(g_lo.numpy(), f(lo).numpy(), rtol=1e-6)
    np.testing.assert_allclose(g_hi.numpy(), f(hi).numpy(), rtol=1e-6)
    assert sfn.program_cache_size == 1


def test_elif_chain():
    def f(x):
        s = x.sum()
        if s > 100:
            out = x * 1
        elif s > 0:
            out = x * 2
        else:
            out = x * 3
        return out

    xs = [paddle.to_tensor(np.full((3,), v, "float32")) for v in (50.0, 1.0, -5.0)]
    outs, sfn = _run_static(f, *[(x,) for x in xs])
    for x, got in zip(xs, outs):
        np.testing.assert_allclose(got.numpy(), f(x).numpy(), rtol=1e-6)
    assert sfn.program_cache_size == 1


@pytest.mark.fast
def test_while_on_tensor():
    def f(x):
        s = x
        while s.sum() < 100:
            s = s * 2
        return s

    x = paddle.to_tensor(np.ones((2, 2), "float32"))
    (got,), _ = _run_static(f, (x,))
    np.testing.assert_allclose(got.numpy(), f(x).numpy(), rtol=1e-6)


def test_while_loop_carried_python_counter():
    def f(x):
        i = 0
        s = x
        while s.max() < 50:
            s = s * 3
            i = i + 1
        return s, i

    x = paddle.to_tensor(np.ones((2,), "float32"))
    (got,), _ = _run_static(f, (x,))
    ref = f(x)
    np.testing.assert_allclose(got[0].numpy(), ref[0].numpy(), rtol=1e-6)
    assert int(got[1]) == int(ref[1])


def test_while_state_becomes_traced_mid_loop():
    """Loop state starts as a Python float and becomes a tensor inside the
    body; the converted loop must carry on (lax continues from the current
    state) instead of crashing on a tracer truth test."""

    def f(x):
        s = 0.0
        while s < 10:
            s = s + x.sum()
        return s

    x = paddle.to_tensor(np.full((2,), 3.0, "float32"))
    (got,), _ = _run_static(f, (x,))
    np.testing.assert_allclose(float(got), float(f(x)), rtol=1e-6)


def test_for_range_tensor_bound():
    def f(x, n):
        out = x
        for _i in range(n):
            out = out + 2
        return out

    x = paddle.to_tensor(np.zeros((3,), "float32"))
    n = paddle.to_tensor(np.asarray(4, "int32"))
    (got,), _ = _run_static(f, (x, n))
    np.testing.assert_allclose(got.numpy(), f(x, 4).numpy(), rtol=1e-6)


@pytest.mark.fast
def test_bool_ops_and_ternary():
    def f(x, flag):
        big = (x.sum() > 0) and (x.max() > 2)
        y = x * 5 if big else x * -1
        if flag and not big:
            y = y + 100
        return y

    a = paddle.to_tensor(np.full((3,), 3.0, "float32"))
    b = paddle.to_tensor(np.full((3,), -1.0, "float32"))
    outs, _ = _run_static(f, (a, True), (b, True), (b, False))
    for args, got in zip([(a, True), (b, True), (b, False)], outs):
        np.testing.assert_allclose(got.numpy(), f(*args).numpy(), rtol=1e-6)


def test_branchy_layer_model():
    """The VERDICT done-criterion: a branchy MODEL under to_static, no
    fallback, eager parity across inputs taking different paths."""

    class Gate(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc_hot = nn.Linear(4, 4)
            self.fc_cold = nn.Linear(4, 4)

        def forward(self, x):
            h = x
            # data-dependent routing + a data-dependent refinement loop
            if h.abs().mean() > 1:
                h = self.fc_hot(h)
            else:
                h = self.fc_cold(h)
            while h.abs().max() < 3:
                h = h * 2
            return h

    paddle.seed(0)
    m = Gate()
    m.eval()
    hot = paddle.to_tensor(np.full((2, 4), 5.0, "float32"))
    cold = paddle.to_tensor(np.full((2, 4), 0.1, "float32"))
    ref_hot, ref_cold = m(hot).numpy(), m(cold).numpy()

    paddle.seed(0)
    sm = Gate()  # fresh params seeded identically for the static copy
    paddle.jit.to_static(sm)
    sm.eval()
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        got_hot = sm.forward(hot).numpy()
        got_cold = sm.forward(cold).numpy()
    _assert_no_fallback(rec)
    np.testing.assert_allclose(got_hot, ref_hot, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_cold, ref_cold, rtol=1e-5, atol=1e-6)


@pytest.mark.fast
def test_numpy_sync_still_falls_back():
    def f(x):
        v = float(x.sum().numpy())  # genuine host sync, unconvertible
        return x + v

    x = paddle.to_tensor(np.ones((2,), "float32"))
    sf = paddle.jit.to_static(f)
    with pytest.warns(UserWarning, match="EAGER"):
        got = sf(x)
    np.testing.assert_allclose(got.numpy(), f(x).numpy(), rtol=1e-6)


def test_python_condition_stays_python():
    def f(x, mode):
        if mode == "double":  # plain python condition: no conversion needed
            return x * 2
        return x / 2

    x = paddle.to_tensor(np.ones((2,), "float32"))
    outs, _ = _run_static(f, (x, "double"), (x, "half"))
    np.testing.assert_allclose(outs[0].numpy(), (x * 2).numpy())
    np.testing.assert_allclose(outs[1].numpy(), (x / 2).numpy())


def test_raise_in_branch_not_converted():
    """lax.cond traces BOTH branches, so a `raise` inside one must keep the
    whole if in Python (eager fallback) rather than firing unconditionally."""

    def f(x):
        if x.min() < 0:
            raise ValueError("negative input")
        return x * 2

    ok = paddle.to_tensor(np.ones((3,), "float32"))
    sf = paddle.jit.to_static(f)
    with pytest.warns(UserWarning, match="EAGER"):
        got = sf(ok)
    np.testing.assert_allclose(got.numpy(), (ok * 2).numpy())
    with pytest.raises(ValueError, match="negative"):
        sf(paddle.to_tensor(-np.ones((3,), "float32")))


def test_for_loop_var_keeps_python_post_value():
    def f(x):
        if x.sum() > 1e9:  # tensor cond forces whole-function conversion
            x = x + 0
        for i in range(10):
            x = x + 1
        return x * i  # python leaves i == 9 after the loop

    x = paddle.to_tensor(np.zeros((2,), "float32"))
    (got,), _ = _run_static(f, (x,))
    np.testing.assert_allclose(got.numpy(), np.full((2,), 90.0, "float32"))


def test_distinct_closures_not_aliased():
    """Two closures sharing one code object must keep their own cells."""

    def make(scale):
        def g(x):
            if x.sum() > 0:
                return x * scale
            return x - scale

        return g

    g2, g5 = make(2.0), make(5.0)
    x = paddle.to_tensor(np.ones((2,), "float32"))
    (got2,), _ = _run_static(g2, (x,))
    (got5,), _ = _run_static(g5, (x,))
    np.testing.assert_allclose(got2.numpy(), np.full((2,), 2.0, "float32"))
    np.testing.assert_allclose(got5.numpy(), np.full((2,), 5.0, "float32"))


def test_variable_defined_in_one_branch_raises_clearly():
    from paddle_tpu.jit.dy2static import Dy2StaticError  # noqa: F401

    def f(x):
        if x.sum() > 0:
            y = x * 2
        # y undefined on the false path
        return y  # noqa: F821

    x = paddle.to_tensor(-np.ones((2,), "float32"))
    sf = paddle.jit.to_static(f)
    # conversion is attempted, the structural error is detected, and the
    # guard degrades to eager — where the same bug surfaces as the natural
    # Python error for the taken path
    with pytest.warns(UserWarning, match="EAGER"):
        with pytest.raises(Exception):
            sf(x)
