"""Analytic scaling model from compiled HLO (VERDICT r4 weak #5 / #8).

The design claim under test is the reference's CommunicateTopology
comm-locality ordering (`fleet/base/topology.py`): in a multi-slice
deployment, ONLY dp-axis gradient reduction may cross the slice boundary
(DCN); mp/sep/pp traffic stays inside a slice (ICI). Here that claim is
checked against the actual compiled program, not the intent."""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import comm_analysis
from paddle_tpu.distributed import mesh as _mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------- unit tests: HLO parsing ----------------
@pytest.mark.fast
def test_parse_iota_replica_groups():
    line = ("%ar = f32[4,16]{1,0} all-reduce(%x), channel_id=5, "
            "replica_groups=[2,4]<=[8], use_global_device_ids=true")
    g = comm_analysis._parse_groups(line)
    assert g == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert comm_analysis._line_payload_bytes(line, "all-reduce") == 4 * 16 * 4


@pytest.mark.fast
def test_parse_transposed_iota_groups():
    line = "... replica_groups=[4,2]<=[2,4]T(1,0), ..."
    g = comm_analysis._parse_groups(line)
    # iota(8)->[2,4], T(1,0) -> [[0,4],[1,5],[2,6],[3,7]]
    assert g == [[0, 4], [1, 5], [2, 6], [3, 7]]


@pytest.mark.fast
def test_parse_explicit_groups():
    line = "... replica_groups={{0,2},{1,3}}, ..."
    assert comm_analysis._parse_groups(line) == [[0, 2], [1, 3]]


# ---------------- integration: compiled-program claims ----------------
def _tiny_step(degrees, env=None):
    from paddle_tpu.distributed import fleet
    from paddle_tpu.text.models import GPTConfig, GPTForCausalLM

    s = fleet.DistributedStrategy()
    s.hybrid_configs.update(degrees)
    fleet.init(is_collective=True, strategy=s)
    paddle.seed(0)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=16, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(opt)
    step = fleet.DistTrainStep(
        model, lambda m, ids, lbl: m(ids, labels=lbl), opt)
    ids = paddle.to_tensor(
        np.random.default_rng(0).integers(0, 64, (8, 16)).astype(np.int32))
    return step, ids


def test_two_slice_dcn_traffic_is_dp_gradient_only(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_NUM_SLICES", "2")
    step, ids = _tiny_step({"dp_degree": 2, "mp_degree": 4})
    hlo = step._compiled_for(ids, ids).as_text()
    mesh = _mesh.get_global_mesh()

    devs = list(mesh.devices.flat)
    slices = _mesh._device_slice_ids(devs, 2)
    slice_of = {d.id: s for d, s in zip(devs, slices)}
    crossing = comm_analysis.slice_crossing_traffic(hlo, mesh, slice_of)

    assert crossing, "expected at least the dp gradient all-reduce"
    for c in crossing:
        assert c["axes"] == ("dp",), (
            f"non-dp traffic crosses the slice boundary (DCN): {c}")
        assert c["kind"] == "all-reduce", c

    # and mp traffic exists but stays intra-slice
    colls = comm_analysis.collective_traffic(hlo, mesh)
    per_axis = comm_analysis.axis_traffic_summary(colls)
    assert per_axis.get("mp", 0) > 0
    assert per_axis.get("dp", 0) > 0


def test_pure_dp_emits_single_gradient_allreduce_axis():
    step, ids = _tiny_step({"dp_degree": 8})
    hlo = step._compiled_for(ids, ids).as_text()
    mesh = _mesh.get_global_mesh()
    colls = comm_analysis.collective_traffic(hlo, mesh)
    per_axis = comm_analysis.axis_traffic_summary(colls)
    assert set(per_axis) <= {"dp", "self"}, per_axis
    assert per_axis.get("dp", 0) > 0


@pytest.mark.fast
def test_scaling_model_artifact_committed():
    path = os.path.join(REPO, "SCALING_MODEL.json")
    assert os.path.exists(path), "run scripts/scaling_model.py"
    doc = json.load(open(path))
    assert "assumptions" in doc["meta"]
    for name in ("dp8", "mp8", "dp2_mp4", "sharding8_z1", "dp2_pp2_mp2",
                 "2slice_dp2_mp4", "dp2_mp4_int8"):
        cfg = doc["configs"][name]
        assert "per_axis_wire_bytes_per_device" in cfg, name
        assert "projection" in cfg, name
    # committed artifact must itself satisfy the DCN design claim
    cross = doc["configs"]["2slice_dp2_mp4"]["cross_slice"]
    assert cross and all(c["axes"] == ["dp"] for c in cross)
    # quantized-wire A/B: the int8 activation wire (mp_comm) must move
    # strictly fewer mp-axis bytes than the f32 row of the same mesh,
    # and the wire-dtype census must show the s8 payload
    f32_mp = doc["configs"]["dp2_mp4"]["per_axis_wire"]["mp"]
    int8_mp = doc["configs"]["dp2_mp4_int8"]["per_axis_wire"]["mp"]
    assert int8_mp["wire_bytes_per_device"] < f32_mp["wire_bytes_per_device"]
    assert "s8" in int8_mp["wire_dtypes"]
    assert int8_mp["quantized_fraction"] > 0.5
    # mp traffic per device must be degree-invariant in the projection
    proj = doc["configs"]["mp8"]["projection"]
    assert proj["8"]["ici_bytes_per_chip"] == proj["256"]["ici_bytes_per_chip"]
