"""Multi-process SPMD worker for test_multiprocess_spmd.py.

Launched twice (2 OS processes x 4 virtual CPU devices each) by the
launch CLI; trains the loss-parity tiny GPT over the resulting 8-device
global mesh and prints the loss trajectory as one JSON line from
process 0. Mirrors the reference's `test_dist_base.py` worker half
(same-seeded model + data on every rank).
"""
import json
import os
import sys

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
kept = [t for t in os.environ.get("XLA_FLAGS", "").split()
        if not t.startswith("--xla_force_host_platform_device_count")]
os.environ["XLA_FLAGS"] = " ".join(
    kept + ["--xla_force_host_platform_device_count=4"])

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.distributed as dist  # noqa: E402
from paddle_tpu.distributed import fleet  # noqa: E402
from paddle_tpu.text.models import GPTConfig, GPTForCausalLM  # noqa: E402

STEPS, BATCH, SEQ, VOCAB = 5, 8, 16, 64


def main():
    dist.init_parallel_env()  # bootstraps jax.distributed from PADDLE_* env
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    s = fleet.DistributedStrategy()
    s.hybrid_configs.update(dp_degree=2, mp_degree=4, pp_degree=1)
    fleet.init(is_collective=True, strategy=s)
    paddle.seed(1234)
    model = GPTForCausalLM(GPTConfig(
        vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=SEQ, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0))
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    fleet.distributed_model(model)
    opt = fleet.distributed_optimizer(opt)
    step = fleet.DistTrainStep(model, lambda m, ids, lbl: m(ids, labels=lbl),
                               opt)
    rng = np.random.default_rng(42)
    losses = []
    for _ in range(STEPS):
        ids = paddle.to_tensor(
            rng.integers(0, VOCAB, (BATCH, SEQ)).astype(np.int32))
        losses.append(float(step(ids, ids)))
    if jax.process_index() == 0:
        print(json.dumps({"losses": losses}), flush=True)


if __name__ == "__main__":
    main()
