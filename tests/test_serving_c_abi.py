"""C ABI serving entry (csrc/paddle_tpu_serve.cc): one inference through
the native path — load a jit.save'd StableHLO artifact and run a batch
from C, no Python written by the caller.

Reference capability: ``paddle_inference_api.h`` C++ AnalysisPredictor
(VERDICT r3 #9 / missing #6). Not in the fast tier: the test builds the
shared library and the embedded interpreter imports jax (~1 min cold).
"""
import os
import shutil
import subprocess
import sysconfig

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.static import InputSpec

_CSRC = os.path.join(os.path.dirname(__file__), "..", "csrc")
_REPO = os.path.abspath(os.path.join(_CSRC, ".."))


@pytest.mark.skipif(shutil.which("make") is None, reason="no make")
def test_one_inference_through_c_path(tmp_path):
    r = subprocess.run(["make", "-C", _CSRC, "serve_test"],
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stdout + r.stderr

    paddle.seed(5)
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 3))
    prefix = str(tmp_path / "toy")
    paddle.jit.save(net, prefix,
                    input_spec=[InputSpec([2, 4], "float32", "x")])

    # the exact input serve_test generates: ramp 0.01*i over [2, 4]
    x = (0.01 * np.arange(8, dtype=np.float32)).reshape(2, 4)
    from paddle_tpu import inference

    pred = inference.create_predictor(inference.Config(prefix))
    expected = pred.run([x])[0]

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # the embedded interpreter starts from the BASE prefix's sys.path:
    # point it at the repo and this interpreter's site-packages
    site = sysconfig.get_paths()["purelib"]
    env["PYTHONPATH"] = os.pathsep.join(
        [_REPO, site, env.get("PYTHONPATH", "")]).rstrip(os.pathsep)
    r = subprocess.run(
        [os.path.join(_CSRC, "build", "serve_test"), prefix, "2", "4"],
        capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    line = [l for l in r.stdout.splitlines() if l.startswith("OK ")][0]
    # OK n=6 rank=2 shape=[2,3] sum=<float>
    parts = dict(p.split("=", 1) for p in line[3:].split() if "=" in p)
    assert int(parts["n"]) == expected.size
    assert parts["shape"] == "[" + ",".join(str(d) for d in expected.shape) + "]"
    np.testing.assert_allclose(float(parts["sum"]), float(expected.sum()),
                               rtol=1e-4, atol=1e-5)
