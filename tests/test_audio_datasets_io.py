"""Round-3 surface closures: WAV codec, text dataset parsers, onnx non-goal.

Reference test models: ``test/legacy_test/test_audio_backend.py`` (load/save
roundtrip across encodings), ``python/paddle/text/datasets/`` dataset tests
(sample tuple shapes), SURVEY.md §4 op-vs-numpy pattern.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import audio
from paddle_tpu.text.datasets import WMT14, Conll05st, Movielens


@pytest.mark.fast
@pytest.mark.parametrize("encoding,tol", [
    ("PCM_U8", 1 / 100.0),
    ("PCM_16", 1e-4),
    ("PCM_24", 1e-6),
    ("PCM_32", 1e-8),
    ("PCM_F32", 1e-7),
])
def test_wav_roundtrip(tmp_path, encoding, tol):
    rs = np.random.RandomState(0)
    wav = np.clip(rs.randn(2, 4000).astype("float32") * 0.3, -1, 1)
    path = str(tmp_path / f"x_{encoding}.wav")
    audio.save(path, wav, 16000, channels_first=True, encoding=encoding)
    out, sr = audio.load(path, channels_first=True)
    assert sr == 16000
    got = out.numpy()
    assert got.shape == wav.shape
    np.testing.assert_allclose(got, wav, atol=tol)
    meta = audio.info(path)
    assert meta.num_channels == 2 and meta.num_frames == 4000
    assert meta.encoding == encoding


@pytest.mark.fast
def test_wav_slicing_and_mono(tmp_path):
    t = np.arange(8000, dtype="float32") / 8000.0
    wav = (0.5 * np.sin(2 * np.pi * 440 * t)).astype("float32")
    path = str(tmp_path / "mono.wav")
    audio.save(path, wav, 8000, encoding="PCM_16")
    full, _ = audio.load(path)
    assert full.numpy().shape == (1, 8000)
    part, _ = audio.load(path, frame_offset=1000, num_frames=500)
    np.testing.assert_allclose(
        part.numpy()[0], full.numpy()[0, 1000:1500], atol=1e-7)
    # unnormalized load returns integer PCM values
    raw, _ = audio.load(path, normalize=False)
    assert raw.numpy().dtype == np.int16


@pytest.mark.fast
def test_wav_feeds_feature_layers(tmp_path):
    rs = np.random.RandomState(1)
    path = str(tmp_path / "f.wav")
    audio.save(path, rs.randn(1600).astype("float32") * 0.1, 16000)
    wav, sr = audio.load(path)
    spec = audio.MelSpectrogram(sr=sr, n_fft=256, n_mels=32)(paddle.to_tensor(wav.numpy()))
    assert spec.shape[1] == 32 and np.isfinite(spec.numpy()).all()


@pytest.mark.fast
def test_movielens_synthetic_and_archive(tmp_path):
    ds = Movielens(mode="synthetic")
    assert len(ds) > 100
    u, g, a, j, m, cats, title, r = ds[0]
    assert u.dtype == np.int64 and r.dtype == np.float32
    assert cats.ndim == 1 and title.ndim == 1

    # ml-1m directory layout with ::-separated files
    d = tmp_path / "ml-1m"
    d.mkdir()
    (d / "users.dat").write_text(
        "1::M::25::10::48067\n2::F::35::3::55117\n")
    (d / "movies.dat").write_text(
        "1::Toy Story (1995)::Animation|Children's|Comedy\n"
        "2::Jumanji (1995)::Adventure|Children's|Fantasy\n")
    (d / "ratings.dat").write_text(
        "1::1::5::978300760\n1::2::3::978302109\n2::1::4::978301968\n")
    ds = Movielens(data_file=str(d), mode="train", test_ratio=0.0)
    assert len(ds) == 3
    u, g, a, j, m, cats, title, r = ds[0]
    assert int(u[0]) == 1 and int(g[0]) == 0 and float(r[0]) == 5.0
    assert len(cats) == 3 and len(title) == 3  # "toy story (1995)"


@pytest.mark.fast
def test_conll05_and_wmt_synthetic():
    srl = Conll05st(mode="synthetic")
    sample = srl[0]
    assert len(sample) == 9
    n = len(sample[0])
    assert all(len(f) == n for f in sample[:8])
    assert sample[7].sum() == 1  # exactly one predicate mark

    wmt = WMT14(mode="synthetic")
    src, trg_in, trg_next = wmt[0]
    assert trg_in[0] == 0 and trg_next[-1] == 1
    np.testing.assert_array_equal(trg_in[1:], trg_next[:-1])


@pytest.mark.fast
def test_wmt_local_tsv(tmp_path):
    p = tmp_path / "wmt.train.tsv"
    p.write_text("the cat sat\tle chat assis\nhello world\tbonjour monde\n")
    ds = WMT14(data_file=str(p), mode="train")
    assert len(ds) == 2
    src, trg_in, trg_next = ds[0]
    assert len(src) == 3 and len(trg_in) == 4


@pytest.mark.fast
def test_conll05_column_file(tmp_path):
    p = tmp_path / "srl.txt"
    p.write_text(
        "the\t-\tB-A0\ncat\t-\tI-A0\nsat\tsit\tB-V\n\n"
        "dogs\t-\tB-A0\nbark\tbark\tB-V\n\n")
    ds = Conll05st(data_file=str(p))
    assert len(ds) == 2
    words, *_ctx, pred_ids, mark, labels = ds[0]
    assert len(words) == 3 and mark[2] == 1


@pytest.mark.fast
def test_onnx_export_is_honest_nongoal():
    from paddle_tpu import onnx

    with pytest.raises(NotImplementedError, match="non-goal"):
        onnx.export(None, "/tmp/x.onnx")


@pytest.mark.fast
def test_flowers_local_dir(tmp_path):
    from PIL import Image
    from scipy.io import savemat

    from paddle_tpu.vision.datasets import Flowers

    d = tmp_path / "jpg"
    d.mkdir()
    for i in range(1, 4):
        Image.fromarray(
            np.full((8, 8, 3), i * 40, np.uint8)).save(d / f"image_{i:05d}.jpg")
    savemat(tmp_path / "imagelabels.mat",
            {"labels": np.asarray([[1, 2, 1]], np.uint8)})
    savemat(tmp_path / "setid.mat",
            {"trnid": np.asarray([[1, 3]]), "valid": np.asarray([[2]]),
             "tstid": np.asarray([[2]])})
    ds = Flowers(data_file=str(d), label_file=str(tmp_path / "imagelabels.mat"),
                 setid_file=str(tmp_path / "setid.mat"), mode="train")
    assert len(ds) == 2
    img, label = ds[0]
    assert img.shape == (8, 8, 3) and int(label) == 0  # labels are 0-based
    val = Flowers(data_file=str(d), label_file=str(tmp_path / "imagelabels.mat"),
                  setid_file=str(tmp_path / "setid.mat"), mode="valid")
    assert len(val) == 1 and int(val[0][1]) == 1


@pytest.mark.fast
def test_voc2012_local_dir(tmp_path):
    from PIL import Image

    from paddle_tpu.vision.datasets import VOC2012

    root = tmp_path / "VOCdevkit" / "VOC2012"
    for sub in ("JPEGImages", "SegmentationClass", "ImageSets/Segmentation"):
        (root / sub).mkdir(parents=True)
    for i, name in enumerate(["2007_000001", "2007_000002"]):
        Image.fromarray(np.full((6, 5, 3), 100 + i, np.uint8)).save(
            root / "JPEGImages" / f"{name}.jpg")
        mask = Image.fromarray(np.full((6, 5), i, np.uint8), mode="P")
        mask.save(root / "SegmentationClass" / f"{name}.png")
    (root / "ImageSets/Segmentation/train.txt").write_text(
        "2007_000001\n2007_000002\n")
    (root / "ImageSets/Segmentation/val.txt").write_text("2007_000002\n")
    ds = VOC2012(data_file=str(tmp_path), mode="train")
    assert len(ds) == 2
    img, mask = ds[1]
    assert img.shape == (6, 5, 3) and mask.shape == (6, 5)
    assert int(mask[0, 0]) == 1
    assert len(VOC2012(data_file=str(tmp_path), mode="valid")) == 1


@pytest.mark.fast
def test_imikolov_ngrams():
    from paddle_tpu.text.datasets import Imikolov

    ds = Imikolov(mode="synthetic", data_type="NGRAM", window_size=3,
                  min_word_freq=5)
    assert len(ds) > 100
    g = ds[0]
    assert g.shape == (3,) and g.dtype == np.int64
    assert ds.vocab_size > 10
    seq = Imikolov(mode="synthetic", data_type="SEQ", window_size=8,
                   min_word_freq=5)
    src, trg = seq[0]
    np.testing.assert_array_equal(src[1:], trg[:-1])  # shifted-by-one pair
