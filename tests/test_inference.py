"""Inference stack tests: jit.save/load (StableHLO export) + Predictor.

Mirrors the reference's inference tests (SURVEY.md §4 "Inference tests":
C++ predictors over small saved models) — save a small model, reload in a
fresh object, check numerical identity and the handle-based predictor API.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit import InputSpec

pytestmark = pytest.mark.fast  # whole-module smoke: cheap on 1 core


class SmallNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _np(t):
    return np.asarray(t._value)


def test_jit_save_load_roundtrip(tmp_path):
    net = SmallNet()
    net.eval()
    x = paddle.to_tensor(np.random.RandomState(0).randn(3, 16).astype("float32"))
    ref = _np(net(x))

    prefix = str(tmp_path / "small")
    paddle.jit.save(net, prefix, input_spec=[InputSpec([3, 16], "float32", "x")])
    loaded = paddle.jit.load(prefix)
    out = loaded(x)
    np.testing.assert_allclose(_np(out), ref, rtol=1e-5, atol=1e-5)
    assert loaded.input_names == ["x"]


def test_jit_save_batch_polymorphic(tmp_path):
    net = SmallNet()
    net.eval()
    prefix = str(tmp_path / "poly")
    paddle.jit.save(net, prefix, input_spec=[InputSpec([None, 16], "float32", "x")])
    loaded = paddle.jit.load(prefix)
    for bs in (1, 5, 9):
        x = paddle.to_tensor(np.ones((bs, 16), "float32"))
        out = loaded(x)
        assert tuple(_np(out).shape) == (bs, 4)
        np.testing.assert_allclose(_np(out), _np(net(x)), rtol=1e-5, atol=1e-5)


def test_predictor_handles(tmp_path):
    net = SmallNet()
    net.eval()
    prefix = str(tmp_path / "pred")
    paddle.jit.save(net, prefix, input_spec=[InputSpec([2, 16], "float32", "input")])

    from paddle_tpu import inference

    config = inference.Config(prefix)
    config.enable_memory_optim()
    predictor = inference.create_predictor(config)
    assert predictor.get_input_names() == ["input"]

    x = np.random.RandomState(1).randn(2, 16).astype("float32")
    h = predictor.get_input_handle("input")
    h.copy_from_cpu(x)
    predictor.run()
    names = predictor.get_output_names()
    assert len(names) == 1
    out = predictor.get_output_handle(names[0]).copy_to_cpu()
    ref = _np(net(paddle.to_tensor(x)))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_predictor_inputs_stay_device_resident(tmp_path):
    # run() re-device_puts an input only when copy_from_cpu bumped its
    # version; unchanged handles reuse the cached device array, and
    # output handles hold device arrays until copy_to_cpu is asked.
    import jax

    net = SmallNet()
    net.eval()
    prefix = str(tmp_path / "devres")
    paddle.jit.save(net, prefix, input_spec=[InputSpec([2, 16], "float32", "input")])
    from paddle_tpu import inference

    predictor = inference.create_predictor(inference.Config(prefix))
    h = predictor.get_input_handle("input")
    x = np.random.RandomState(2).randn(2, 16).astype("float32")
    h.copy_from_cpu(x)
    predictor.run()
    dev1 = predictor._dev_inputs["input"][1]
    assert isinstance(dev1, jax.Array)
    predictor.run()  # no copy_from_cpu between runs
    assert predictor._dev_inputs["input"][1] is dev1
    h.copy_from_cpu(x + 1.0)
    predictor.run()
    assert predictor._dev_inputs["input"][1] is not dev1
    out_h = predictor.get_output_handle(predictor.get_output_names()[0])
    assert isinstance(out_h._value, jax.Array)
    np.testing.assert_allclose(
        out_h.copy_to_cpu(), _np(net(paddle.to_tensor(x + 1.0))),
        rtol=1e-5, atol=1e-5)


def test_predictor_positional_run(tmp_path):
    net = SmallNet()
    net.eval()
    prefix = str(tmp_path / "pos")
    paddle.jit.save(net, prefix, input_spec=[InputSpec([None, 16], "float32")])
    from paddle_tpu import inference

    predictor = inference.create_predictor(inference.Config(prefix + ".pdmodel"))
    x = np.zeros((4, 16), "float32")
    outs = predictor.run([x])
    assert outs[0].shape == (4, 4)


def test_save_inference_model_wiring(tmp_path):
    net = SmallNet()
    prefix = str(tmp_path / "static_export")
    paddle.static.save_inference_model(
        prefix, [InputSpec([2, 16], "float32", "x")], None, model=net
    )
    layer, feed_names, _ = paddle.static.load_inference_model(prefix)
    assert feed_names == ["x"]
    x = paddle.to_tensor(np.ones((2, 16), "float32"))
    net.eval()
    np.testing.assert_allclose(_np(layer(x)), _np(net(x)), rtol=1e-5, atol=1e-5)


def test_translated_layer_state_dict(tmp_path):
    net = SmallNet()
    prefix = str(tmp_path / "sd")
    paddle.jit.save(net, prefix, input_spec=[InputSpec([1, 16], "float32")])
    loaded = paddle.jit.load(prefix)
    sd = loaded.state_dict()
    assert len(sd) == 4  # fc1/fc2 weight+bias as frozen buffers
