"""paddle.distributed.parallelize / to_distributed on the 8-device CPU mesh:
plan application places params with the right shardings, the parallelized
model trains with loss parity against the single-device run, and
to_distributed wires a dp mesh + sharded dataloader."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

pytestmark = pytest.mark.fast


def _np(t):
    return np.asarray(t._value if hasattr(t, "_value") else t)


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.up = nn.Linear(16, 32)
        self.act = nn.ReLU()
        self.down = nn.Linear(32, 16)

    def forward(self, x):
        return self.down(self.act(self.up(x)))


def _mesh2d():
    import jax

    from paddle_tpu.distributed import ProcessMesh

    n = len(jax.devices())
    return ProcessMesh(np.arange(n).reshape(n // 2, 2),
                       dim_names=["dp", "mp"])


def test_parallelize_places_params():
    from paddle_tpu.distributed import (ColWiseParallel, RowWiseParallel,
                                        parallelize)

    paddle.seed(0)
    m = MLP()
    mesh = _mesh2d()
    plan = {"up": ColWiseParallel(), "down": RowWiseParallel()}
    m, _ = parallelize(m, None, mesh,
                       {"mp_config": {"parallelize_plan": plan}})
    assert m.up.weight.dist_spec == __import__("jax").sharding.PartitionSpec(
        None, "mp")
    assert tuple(m.up.weight._value.sharding.spec) == (None, "mp")
    assert tuple(m.up.bias._value.sharding.spec) == ("mp",)
    assert tuple(m.down.weight._value.sharding.spec) == ("mp", None)
    assert m.down.bias._value.sharding.spec == ()  # replicated

    with pytest.raises(ValueError):
        parallelize(MLP(), None, mesh,
                    {"mp_config": {"parallelize_plan": {"nope": plan["up"]}}})
    with pytest.raises(NotImplementedError):
        parallelize(MLP(), None, mesh, {"pp_config": {"split_spec": "x"}})


def test_parallelize_loss_parity():
    """mp2-parallelized training must match the single-device trajectory."""
    from paddle_tpu.distributed import (ColWiseParallel, RowWiseParallel,
                                        parallelize)
    from paddle_tpu.jit import TrainStep

    rs = np.random.RandomState(0)
    xb = rs.randn(8, 16).astype("float32")
    yb = rs.randn(8, 16).astype("float32")

    def run(parallel):
        paddle.seed(42)
        m = MLP()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        if parallel:
            mesh = _mesh2d()
            m, opt = parallelize(
                m, opt, mesh,
                {"mp_config": {"parallelize_plan": {
                    "up": ColWiseParallel(), "down": RowWiseParallel()}}})
        step = TrainStep(
            m, lambda mm, x, y: paddle.mean((mm(x) - y) ** 2), opt)
        return [float(step(paddle.to_tensor(xb),
                           paddle.to_tensor(yb))._value) for _ in range(4)]

    ref = run(False)
    par = run(True)
    np.testing.assert_allclose(par, ref, rtol=2e-5, atol=1e-6)


def test_parallelize_sharding_level():
    from paddle_tpu.distributed import parallelize

    paddle.seed(0)
    m = MLP()
    import jax

    from paddle_tpu.distributed import ProcessMesh

    n = len(jax.devices())
    mesh = ProcessMesh(np.arange(n).reshape(n // 2, 2),
                       dim_names=["dp", "sharding"])
    m, _ = parallelize(m, None, mesh, {"dp_config": {"sharding_level": 2}})
    spec = tuple(m.up.weight._value.sharding.spec)
    assert "sharding" in spec, f"param not ZeRO-sharded: {spec}"


def test_to_distributed_dp_default():
    import jax

    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed import to_distributed

    prev = mesh_mod.get_global_mesh()
    try:
        paddle.seed(0)
        m = MLP()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        n = len(jax.devices())
        data = [(np.ones((n, 16), np.float32), np.ones((n, 16), np.float32))]
        m, opt, dl = to_distributed(m, opt, data)
        assert m.up.weight._value.sharding.spec == ()  # replicated
        (xb, _), = list(dl)
        assert xb._value.sharding.spec[0] == "dp"
    finally:
        mesh_mod.set_global_mesh(prev)  # don't leak into other tests


def test_parallelize_sequence_parallel_markers():
    """SequenceParallelBegin/End install sharding-constraint hooks and the
    constrained model still trains with loss parity to the plain run."""
    from paddle_tpu.distributed import (ColWiseParallel, RowWiseParallel,
                                        SequenceParallelBegin,
                                        SequenceParallelEnd, parallelize)
    from paddle_tpu.jit import TrainStep

    rs = np.random.RandomState(0)
    xb = rs.randn(4, 6, 16).astype("float32")  # [batch, seq, hidden]
    yb = rs.randn(4, 6, 16).astype("float32")

    def run(parallel):
        paddle.seed(7)
        m = MLP()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=m.parameters())
        if parallel:
            mesh = _mesh2d()
            m, opt = parallelize(
                m, opt, mesh,
                {"mp_config": {"parallelize_plan": {
                    "up": [ColWiseParallel(), SequenceParallelBegin()],
                    "down": [RowWiseParallel(), SequenceParallelEnd()]}}})
        step = TrainStep(
            m, lambda mm, a, b: paddle.mean((mm(a) - b) ** 2), opt)
        return [float(step(paddle.to_tensor(xb),
                           paddle.to_tensor(yb))._value) for _ in range(3)]

    np.testing.assert_allclose(run(True), run(False), rtol=2e-5, atol=1e-6)
