"""Tensor-parallel (mp-sharded) decode engine (docs/SERVING.md).

Gates the sharded-serving promises: a dp1 x mp2 engine — paged KV pools
split over kv heads under GSPMD, attention output replicated by an exact
all-gather — produces BIT-EQUAL token streams to the single-device
engine with prefix caching and speculation on, while compiling exactly
the same ``buckets_used + 2`` programs (sharding must not add recompile
churn), and an mp degree that does not divide the kv heads is rejected
loudly at construction.
"""
import jax
import numpy as np
import pytest

import paddle_tpu.inference as inference
from paddle_tpu.inference.engine import (DecodeEngine, EngineConfig,
                                         SamplingParams)
from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

VOCAB = 61


@pytest.fixture(scope="module")
def model():
    import paddle_tpu as paddle
    from paddle_tpu.distributed import mesh as _mesh
    from paddle_tpu.distributed.fleet.topology import (
        get_hybrid_communicate_group, set_hybrid_communicate_group)

    # shield the model build from any hybrid-parallel group / global mesh
    # a fleet test left behind (same idiom as test_decode_engine)
    prev = get_hybrid_communicate_group()
    prev_mesh = _mesh.get_global_mesh()
    set_hybrid_communicate_group(None)
    _mesh.set_global_mesh(None)
    try:
        paddle.seed(7)
        m = GPTForCausalLM(GPTConfig(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=128,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
        m.eval()
        yield m
        inference.disable_decode_engine(m)
    finally:
        set_hybrid_communicate_group(prev)
        _mesh.set_global_mesh(prev_mesh)


def _mp_mesh(mp):
    from paddle_tpu.distributed.mesh import build_mesh

    return build_mesh((1, mp), ("dp", "mp"), devices=jax.devices()[:mp])


def _workload():
    """Mixed greedy/sampled requests sharing a 32-token prefix (2 full
    pages) so the prefix cache AND both samplers are exercised."""
    rng = np.random.default_rng(0)
    prefix = rng.integers(1, VOCAB, size=32, dtype=np.int64)
    reqs = []
    for i, tail in enumerate((9, 17, 5)):
        prompt = np.concatenate(
            [prefix, rng.integers(1, VOCAB, size=tail, dtype=np.int64)])
        reqs.append((prompt, SamplingParams(
            max_new_tokens=10, do_sample=(i % 2 == 1), temperature=0.8,
            top_k=8, seed=100 + i)))
    return reqs


def _drain(eng, reqs):
    rids = [eng.submit(p, params) for p, params in reqs]
    eng.run()
    return [eng.result(r) for r in rids]


CFG = dict(num_slots=2, max_length=64, page_size=16, prefix_cache=True,
           speculate_k=2, spec_adaptive=False)


@pytest.mark.slow
def test_mp2_bit_equal_with_prefix_and_speculation(model):
    reqs = _workload()
    ref = DecodeEngine(model, EngineConfig(**CFG))
    want = _drain(ref, reqs)

    eng = DecodeEngine(model, EngineConfig(**CFG, mesh=_mp_mesh(2)))
    got = _drain(eng, reqs)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)

    # sharding must not change WHAT compiles: same program set, exactly
    # len(buckets used) + decode + verify on both engines
    assert eng.stats()["compiled"] == ref.stats()["compiled"]
    buckets_used = sum(1 for name in eng.stats()["compiled"]
                      if name.startswith("prefill_"))
    assert eng.compile_count == buckets_used + 2

    # the KV pool really is split over the mp axis
    from paddle_tpu.distributed.mesh import P
    assert eng._kc.sharding.spec == P(None, None, "mp")
    assert eng._mp_degree == 2

    # prefix sharing survived sharding (2 full pages of shared prefix,
    # second+third request each reuse them)
    assert eng.stats()["prefix_hit_tokens"] == ref.stats()["prefix_hit_tokens"]
    assert eng.stats()["prefix_hit_tokens"] >= 32


def test_mp_must_divide_kv_heads(model):
    # 4 kv heads cannot split 8 ways: loud ValueError at construction,
    # not a silent wrong-shard layout
    with pytest.raises(ValueError, match="divide"):
        DecodeEngine(model, EngineConfig(
            num_slots=2, max_length=64, mesh=_mp_mesh(8)))


@pytest.fixture(scope="module")
def model64():
    """Vocab-64 twin of ``model``: the quantized logit recombination needs
    vocab divisible by the mp degree (61 deliberately is not)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed import mesh as _mesh
    from paddle_tpu.distributed.fleet.topology import (
        get_hybrid_communicate_group, set_hybrid_communicate_group)

    prev = get_hybrid_communicate_group()
    prev_mesh = _mesh.get_global_mesh()
    set_hybrid_communicate_group(None)
    _mesh.set_global_mesh(None)
    try:
        paddle.seed(7)
        m = GPTForCausalLM(GPTConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=128,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
        m.eval()
        yield m
        inference.disable_decode_engine(m)
    finally:
        set_hybrid_communicate_group(prev)
        _mesh.set_global_mesh(prev_mesh)


def _workload64():
    rng = np.random.default_rng(0)
    prefix = rng.integers(1, 64, size=32, dtype=np.int64)
    reqs = []
    for i, tail in enumerate((9, 17, 5)):
        prompt = np.concatenate(
            [prefix, rng.integers(1, 64, size=tail, dtype=np.int64)])
        reqs.append((prompt, SamplingParams(
            max_new_tokens=10, do_sample=(i % 2 == 1), temperature=0.8,
            top_k=8, seed=100 + i)))
    return reqs


def test_logit_wire_config_resolution(model64, monkeypatch):
    # pinned "off" and "f32" both mean the exact-path program
    eng = DecodeEngine(model64, EngineConfig(**CFG, mesh=_mp_mesh(2),
                                             logit_wire="off"))
    assert eng._logit_wire == "f32"
    # explicit int8 sticks; without an mp axis the wire is forced exact
    eng2 = DecodeEngine(model64, EngineConfig(**CFG, mesh=_mp_mesh(2),
                                              logit_wire="int8"))
    assert eng2._logit_wire == "int8" and eng2._logit_verify
    single = DecodeEngine(model64, EngineConfig(**CFG, logit_wire="int8"))
    assert single._logit_wire == "f32"
    # None resolves from the ambient mp_comm config (env grammar)
    monkeypatch.setenv("PADDLE_TPU_MP_COMM", "int8,verify=off")
    amb = DecodeEngine(model64, EngineConfig(**CFG, mesh=_mp_mesh(2)))
    assert amb._logit_wire == "int8" and not amb._logit_verify
    with pytest.raises(ValueError, match="logit_wire"):
        DecodeEngine(model64, EngineConfig(**CFG, logit_wire="fp8"))


@pytest.mark.slow
def test_mp2_int8_logit_wire_bit_equal(model64, monkeypatch, tmp_path):
    """ISSUE 13: int8 absmax logit recombination + exact-argmax verify
    keeps the mp-sharded engine greedy BIT-EQUAL to the single-device
    engine (the PR 9 contract), and the wire gauge is recorded."""
    monkeypatch.setenv("PADDLE_TPU_TELEMETRY_DIR", str(tmp_path))
    from paddle_tpu import observability as _obs

    _obs.reset()
    reqs = _workload64()
    ref = DecodeEngine(model64, EngineConfig(**CFG))
    want = _drain(ref, reqs)

    eng = DecodeEngine(model64, EngineConfig(**CFG, mesh=_mp_mesh(2),
                                             logit_wire="int8"))
    got = _drain(eng, reqs)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)
    assert _obs.gauge("serving_logit_wire_bytes").value() > 0

    # mp_comm=off restores the exact program byte-for-byte
    off = DecodeEngine(model64, EngineConfig(**CFG, mesh=_mp_mesh(2),
                                             logit_wire="off"))
    got_off = _drain(off, reqs)
    for w, g in zip(want, got_off):
        np.testing.assert_array_equal(w, g)


def test_admission_backoff_replaces_hot_spin(model):
    """A pages-starved engine must back off (bounded sleep + histogram),
    not hot-spin: admission_waits advances while the waiting request
    cannot be admitted, and the request still completes once capacity
    frees up."""
    eng = DecodeEngine(model, EngineConfig(num_slots=2, max_length=64,
                                           page_size=16))
    # swallow every free page so admission CANNOT succeed
    held = eng.pool.alloc(eng.pool.available())
    assert held and eng.pool.available() == 0
    rid = eng.submit(np.arange(1, 9, dtype=np.int64),
                     SamplingParams(max_new_tokens=4))
    for _ in range(3):
        assert eng.step()  # waiting work exists -> engine stays busy
    assert eng.admission_waits >= 3
    assert 0.0 < eng.admission_wait_s <= 3 * 0.05  # bounded backoff
    for pg in held:
        eng.pool.decref(pg)
    eng.run()
    assert len(eng.result(rid)) == 12
    # backoff resets once admission succeeds
    assert eng._backoff_s == 0.0
