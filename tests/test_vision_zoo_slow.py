"""Full vision-zoo compile sweep — XLA-CPU conv compilation is tens of
seconds per architecture, so this file is `slow`-marked (run with
`pytest --runslow`). The fast representatives + all vision.ops numerics
live in test_vision_zoo_ops.py."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import models

pytestmark = pytest.mark.slow


def _np(t):
    return np.asarray(t._value)


def _fwd(model, hw=64):
    model.eval()
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, hw, hw).astype("float32"))
    return _np(model(x))


@pytest.mark.parametrize(
    "ctor,kwargs,hw",
    [
        (models.squeezenet1_0, dict(num_classes=10), 64),
        (models.squeezenet1_1, dict(num_classes=10), 64),
        (models.densenet121, dict(num_classes=10), 64),
        (models.googlenet, dict(num_classes=10), 64),
        (models.inception_v3, dict(num_classes=10), 96),
        (models.shufflenet_v2_x0_25, dict(num_classes=10), 64),
        (models.shufflenet_v2_swish, dict(num_classes=10), 64),
        (models.mobilenet_v3_small, dict(num_classes=10), 64),
        (models.mobilenet_v3_large, dict(num_classes=10), 64),
    ],
)
def test_model_forward_shapes(ctor, kwargs, hw):
    out = _fwd(ctor(**kwargs), hw)
    assert out.shape == (2, 10)
    assert np.isfinite(out).all()


def test_googlenet_train_mode_aux_heads():
    m = models.googlenet(num_classes=7)
    m.train()
    x = paddle.to_tensor(np.random.RandomState(0).randn(2, 3, 96, 96).astype("float32"))
    out, aux1, aux2 = m(x)
    assert _np(out).shape == _np(aux1).shape == _np(aux2).shape == (2, 7)


def test_densenet_params_train():
    m = models.densenet121(num_classes=4)
    opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
    x = paddle.to_tensor(np.random.RandomState(1).randn(4, 3, 32, 32).astype("float32"))
    y = paddle.to_tensor(np.array([0, 1, 2, 3]))
    loss_fn = paddle.nn.CrossEntropyLoss()
    losses = []
    for _ in range(3):
        loss = loss_fn(m(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(_np(loss)))
    assert losses[-1] < losses[0]
