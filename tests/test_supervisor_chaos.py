"""Kill -9 soak for the fleet supervisor's flip transaction: a scripted
colocation run (train phases at journal-directed widths, interleaved
with idempotent serve phases) is SIGKILLed at EVERY named flip fence —
``plan``, ``drain``, ``quiesce``, ``resize``, ``commit``, ``finalize`` —
and relaunched (chaos disarmed via PADDLE_RESTART_COUNT).

The relaunched supervisor's ``recover()`` must resolve the interrupted
flip (roll forward at/past ``commit``, roll back before it) such that:

* the training-loss trajectory is BIT-EQUAL to an unkilled reference
  run — widths are applied exactly-once, no phase trains at a
  half-flipped width;
* the served-request ledger holds exactly the reference's request ids,
  each EXACTLY once — nothing dropped, nothing duplicated;
* the journal is left with no pending flip and the same committed-flip
  count as the reference.

A second sweep targets the SECOND flip of the run (the opposite
direction) via PADDLE_CHAOS_FLIP_SKIP, so both to_training and
to_serving transactions take kills.

Marked slow+chaos (boots fresh interpreters):
    pytest tests/test_supervisor_chaos.py --runslow
"""
import json
import os
import signal
import subprocess
import sys
import textwrap

import pytest

pytestmark = [pytest.mark.slow, pytest.mark.chaos]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FENCES = ("plan", "drain", "quiesce", "resize", "commit", "finalize")

#: the scripted run: (target training width, cumulative train steps)
#: per phase — four flips total, alternating directions
HARNESS = textwrap.dedent("""
    import hashlib, json, os, sys
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, os.environ["PT_REPO"])
    from paddle_tpu.distributed.fleet.supervisor import (
        FleetSupervisor, FlipDecision, SupervisorConfig,
        _atomic_write_json, _read_json)

    state = sys.argv[1]
    train_path = os.path.join(state, "train_state.json")
    ledger_path = os.path.join(state, "ledger.jsonl")

    # recover() runs inside the constructor: an interrupted flip is
    # resolved before the script below ever looks at the roles doc
    sup = FleetSupervisor(
        os.path.join(state, "journal"),
        config=SupervisorConfig(hysteresis_s=0.0, cooldown_s=0.0,
                                breaker_max_flips=100),
        roles={"e0": "serving", "e1": "serving"}, training_width=0)

    def width():
        return int(sup.roles_doc.get("training_width", 0))

    def ensure_width(target):
        # idempotent desired-state convergence: a rolled-FORWARD
        # recovery already reached the target (no double flip); a
        # rolled-BACK one retries the flip exactly once
        for _ in range(4):
            w = width()
            if w == target:
                return
            d = "to_training" if target > w else "to_serving"
            sup.flip(FlipDecision(d, "e1", f"script->{target}"))
        raise SystemExit(f"ensure_width({target}) did not converge")

    def train(upto_steps):
        st = _read_json(train_path) or {"loss": 1.0, "hist": []}
        w = width()
        while len(st["hist"]) < upto_steps:
            step = len(st["hist"])
            # the recurrence DEPENDS on the width: trajectory equality
            # proves every phase trained at exactly the scripted width
            st["loss"] = 0.9 * st["loss"] + 1.0 / (w + 1) + 0.001 * step
            st["hist"].append(st["loss"])
            _atomic_write_json(train_path, st)

    def serve(phase):
        have = set()
        if os.path.exists(ledger_path):
            with open(ledger_path) as f:
                have = {json.loads(ln)["rid"] for ln in f if ln.strip()}
        with open(ledger_path, "a") as f:
            for j in range(4):
                rid = f"p{phase}r{j}"
                if rid in have:
                    continue   # exactly-once: replayed phases dedup
                tok = hashlib.md5(rid.encode()).hexdigest()[:8]
                f.write(json.dumps({"rid": rid, "tok": tok}) + "\\n")
                f.flush()

    PHASES = [(1, 3), (0, 6), (1, 9), (0, 12)]
    # durable phase cursor: a relaunch resumes at the interrupted
    # phase instead of replaying the width schedule from the top
    prog_path = os.path.join(state, "progress.json")
    start = int((_read_json(prog_path) or {}).get("next", 0))
    for i, (target_w, steps) in enumerate(PHASES):
        if i < start:
            continue
        ensure_width(target_w)
        train(steps)
        serve(i)
        _atomic_write_json(prog_path, {"next": i + 1})
    print(json.dumps({
        "hist": (_read_json(train_path) or {})["hist"],
        "flips": sup.roles_doc.get("flips_committed"),
        "pending": sup.journal.pending(),
    }))
""")


def _launch(state_dir, extra_env):
    env = {**os.environ, "PT_REPO": REPO}
    env.pop("PADDLE_CHAOS", None)
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, "-c", HARNESS, str(state_dir)],
        capture_output=True, text=True, env=env, timeout=180)


def _finish(state_dir):
    """The clean (relaunched / reference) run's final report."""
    proc = _launch(state_dir, {"PADDLE_RESTART_COUNT": "1"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _ledger_rids(state_dir):
    with open(os.path.join(state_dir, "ledger.jsonl")) as f:
        return [json.loads(ln)["rid"] for ln in f if ln.strip()]


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    d = tmp_path_factory.mktemp("ref")
    out = _finish(d)
    rids = _ledger_rids(d)
    assert out["flips"] == 4 and out["pending"] is None
    assert len(rids) == len(set(rids)) == 16
    return {"hist": out["hist"], "rids": sorted(rids)}


CASES = [(f, 0) for f in FENCES] + [("quiesce", 1), ("commit", 1)]


@pytest.mark.parametrize("fence,skip", CASES,
                         ids=[f"{f}-flip{n + 1}" for f, n in CASES])
def test_sigkill_at_fence_recovers_bit_equal(tmp_path, reference,
                                             fence, skip):
    chaos_env = {
        "PADDLE_CHAOS": "1",
        "PADDLE_CHAOS_FLIP_MODE": "kill",
        "PADDLE_CHAOS_FLIP_AT": fence,
        "PADDLE_CHAOS_FLIP_SKIP": str(skip),
        "PADDLE_RESTART_COUNT": "0",
    }
    killed = _launch(tmp_path, chaos_env)
    # the fence must actually have fired — a soak that never kills
    # proves nothing
    assert killed.returncode == -signal.SIGKILL, (
        fence, skip, killed.returncode, killed.stdout, killed.stderr)
    # mid-flip state on disk now; relaunch with chaos disarmed
    out = _finish(tmp_path)
    assert out["pending"] is None
    assert out["flips"] == 4
    # bit-equal trajectory: every phase trained at the scripted width,
    # flips applied exactly once (JSON floats round-trip exactly)
    assert out["hist"] == reference["hist"]
    # zero dropped, zero duplicated requests
    rids = _ledger_rids(tmp_path)
    assert sorted(rids) == reference["rids"]
    assert len(rids) == len(set(rids))


def test_latency_mode_delays_without_killing(tmp_path):
    out = _launch(tmp_path, {
        "PADDLE_CHAOS": "1",
        "PADDLE_CHAOS_FLIP_MODE": "latency",
        "PADDLE_CHAOS_FLIP_AT": "commit",
        "PADDLE_CHAOS_FLIP_LATENCY_MS": "30",
        "PADDLE_RESTART_COUNT": "0",
    })
    assert out.returncode == 0, out.stdout + out.stderr
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["flips"] == 4 and report["pending"] is None
