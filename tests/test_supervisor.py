"""Fleet supervisor: the flip state machine and its crash recovery
(docs/COLOCATION.md).

Tier-1 deterministic tests: the journal's atomic fence protocol, the
recovery rule (roll forward at/past ``commit``, roll back before it),
the hysteresis/cooldown/breaker gates around ``decide()``, planner-
backed flip pricing, and the store-side drain/evacuate integration with
a real router + engine workers (slow). The SIGKILL-at-every-fence soak
lives in test_supervisor_chaos.py.
"""
import json
import os

import numpy as np
import pytest
from conftest import free_port

from paddle_tpu.distributed.fleet.supervisor import (
    COMMIT_INDEX, FENCES, FleetSupervisor, FlipDecision, FlipExecutor,
    FlipJournal, StoreFleetExecutor, SupervisorConfig, read_health)

pytestmark = pytest.mark.fast


def _health(burn=0.0, backlog=0):
    return {
        "classes": {"interactive": {"objectives": {
            "burn_rate_latency": burn, "burn_rate_availability": 0.0}}},
        "queues": {"admission": {"interactive": backlog}},
    }


class RecordingExecutor(FlipExecutor):
    """Records the per-fence actions in call order; optionally raises at
    one of them to exercise the rollback path."""

    def __init__(self, fail_at=None, drain_clean=True):
        self.calls = []
        self.fail_at = fail_at
        self.drain_clean = drain_clean

    def _hit(self, name, *args):
        self.calls.append((name,) + args)
        if name == self.fail_at:
            raise RuntimeError(f"injected {name} failure")

    def drain(self, engine, deadline_s):
        self._hit("drain", engine)
        return self.drain_clean

    def quiesce(self, engine):
        self._hit("quiesce", engine)

    def resize(self, source_width, target_width):
        self._hit("resize", source_width, target_width)

    def activate(self, engine, role):
        self._hit("activate", engine, role)

    def rollback(self, doc):
        self._hit("rollback", doc.get("engine"))


def _supervisor(tmp_path, executor=None, **cfg):
    cfg.setdefault("hysteresis_s", 0.0)
    cfg.setdefault("cooldown_s", 0.0)
    return FleetSupervisor(
        str(tmp_path / "journal"), executor=executor or RecordingExecutor(),
        config=SupervisorConfig(**cfg),
        roles={"e0": "serving", "e1": "serving"}, training_width=0)


# -- journal ----------------------------------------------------------------

def test_journal_fence_round_trip(tmp_path):
    j = FlipJournal(str(tmp_path / "j"))
    assert j.pending() is None and j.load_roles() is None
    doc = {"id": 1, "direction": "to_training", "engine": "e1"}
    j.begin(doc)
    assert j.pending()["fence"] == "plan"
    for fence in FENCES[1:]:
        j.advance(doc, fence)
        assert j.pending()["fence"] == fence
        assert fence in j.pending()["fences"]
    with pytest.raises(ValueError):
        j.advance(doc, "teleport")
    j.close(doc, "committed")
    assert j.pending() is None
    (entry,) = j.history()
    assert entry["outcome"] == "committed" and entry["id"] == 1
    # re-closing (kill between history append and current unlink) dedups
    j.close(doc, "committed")
    assert len(j.history()) == 1


def test_journal_writes_are_atomic_files(tmp_path):
    j = FlipJournal(str(tmp_path / "j"))
    j.save_roles({"roles": {"e0": "serving"}})
    j.begin({"id": 2, "direction": "to_serving", "engine": "e0"})
    # no tmp siblings survive a completed write
    assert not [f for f in os.listdir(j.root) if ".tmp." in f]
    assert json.load(open(j.roles_path))["roles"] == {"e0": "serving"}


# -- crash recovery ---------------------------------------------------------

def _pending_doc(fence):
    src = {"roles": {"e0": "serving", "e1": "serving"},
           "training_width": 0, "breaker_open_until": 0.0,
           "flips_committed": 0}
    tgt = json.loads(json.dumps(src))
    tgt["roles"]["e1"] = "training"
    tgt["training_width"] = 1
    tgt["flips_committed"] = 1
    return {
        "id": 9, "direction": "to_training", "engine": "e1",
        "reason": "test", "price": {}, "source_role": "serving",
        "target_role": "training", "source_roles": dict(src["roles"]),
        "source_width": 0, "target_width": 1,
        "source_roles_doc": src, "target_roles_doc": tgt,
        "resized": fence in ("commit", "finalize"),
        "fence": fence, "fences": {fence: 0.0},
    }


@pytest.mark.parametrize("fence", FENCES)
def test_recover_resolves_every_fence(tmp_path, fence):
    root = str(tmp_path / "journal")
    j = FlipJournal(root)
    doc = _pending_doc(fence)
    j.save_roles(doc["source_roles_doc"])
    j.begin({"id": 0})          # create then overwrite with the fence
    import paddle_tpu.distributed.fleet.supervisor as sup_mod
    sup_mod._atomic_write_json(j.current_path, doc)
    ex = RecordingExecutor()
    sup = FleetSupervisor(root, executor=ex)
    roles = sup.roles_doc
    if FENCES.index(fence) >= COMMIT_INDEX:
        assert sup.last_outcome == "rolled_forward"
        assert roles["roles"]["e1"] == "training"
        assert roles["training_width"] == 1
        assert ("activate", "e1", "training") in ex.calls
        assert not any(c[0] == "rollback" for c in ex.calls)
        assert sup.journal.history()[-1]["outcome"] == "rolled_forward"
    else:
        assert sup.last_outcome == "rolled_back"
        assert roles["roles"]["e1"] == "serving"
        assert roles["training_width"] == 0
        assert ("rollback", "e1") in ex.calls
        assert not any(c[0] == "activate" for c in ex.calls)
        assert sup.journal.history()[-1]["outcome"] == "rolled_back"
    assert sup.journal.pending() is None


def test_recover_noop_without_pending(tmp_path):
    ex = RecordingExecutor()
    sup = _supervisor(tmp_path, executor=ex)
    assert sup.last_outcome is None and ex.calls == []


# -- the transaction --------------------------------------------------------

def test_flip_to_training_call_order(tmp_path):
    ex = RecordingExecutor()
    sup = _supervisor(tmp_path, executor=ex)
    out = sup.flip(FlipDecision("to_training", "e1", "test"), now=100.0)
    assert out == "committed"
    assert [c[0] for c in ex.calls] == \
        ["drain", "quiesce", "resize", "activate"]
    assert ("resize", 0, 1) in ex.calls
    assert ("activate", "e1", "training") in ex.calls
    doc = sup.roles_doc
    assert doc["roles"] == {"e0": "serving", "e1": "training"}
    assert doc["training_width"] == 1 and doc["flips_committed"] == 1
    entry = sup.journal.history()[-1]
    assert entry["outcome"] == "committed"
    assert set(entry["fences"]) == set(FENCES)


def test_flip_to_serving_skips_drain(tmp_path):
    ex = RecordingExecutor()
    sup = _supervisor(tmp_path, executor=ex)
    sup.journal.save_roles({"roles": {"e0": "serving", "e1": "training"},
                            "training_width": 1, "breaker_open_until": 0.0,
                            "flips_committed": 0})
    out = sup.flip(FlipDecision("to_serving", "e1", "test"), now=100.0)
    assert out == "committed"
    assert [c[0] for c in ex.calls] == ["quiesce", "resize", "activate"]
    assert ("resize", 1, 0) in ex.calls
    assert sup.roles_doc["roles"]["e1"] == "serving"
    assert sup.roles_doc["training_width"] == 0


@pytest.mark.parametrize("fail_at", ["drain", "quiesce", "resize"])
def test_executor_failure_rolls_back(tmp_path, fail_at):
    ex = RecordingExecutor(fail_at=fail_at)
    sup = _supervisor(tmp_path, executor=ex)
    out = sup.flip(FlipDecision("to_training", "e1", "test"), now=100.0)
    assert out == "rolled_back"
    assert ex.calls[-1][0] == "rollback"
    assert not any(c[0] == "activate" for c in ex.calls)
    doc = sup.roles_doc
    assert doc["roles"] == {"e0": "serving", "e1": "serving"}
    assert doc["training_width"] == 0 and doc["flips_committed"] == 0
    assert sup.journal.pending() is None
    assert sup.journal.history()[-1]["outcome"] == "rolled_back"


# -- decision gates ---------------------------------------------------------

def test_hysteresis_holds_then_fires(tmp_path):
    sup = _supervisor(tmp_path, hysteresis_s=2.0)
    sup.journal.save_roles({"roles": {"e0": "serving", "e1": "training"},
                            "training_width": 1, "breaker_open_until": 0.0,
                            "flips_committed": 0})
    hot = _health(burn=3.0)
    assert sup.decide(hot, now=10.0) is None          # just started
    assert sup.decide(hot, now=11.0) is None          # still held < 2s
    d = sup.decide(hot, now=12.0)                     # held 2s: fire
    assert d is not None and d.direction == "to_serving" and d.engine == "e1"
    # one cool sample resets the pressure clock
    assert sup.decide(_health(burn=0.0), now=13.0) is None
    assert sup.decide(hot, now=14.0) is None
    assert sup.decide(hot, now=16.0) is not None


def test_queue_backlog_is_pressure_too(tmp_path):
    sup = _supervisor(tmp_path, queue_high=8)
    sup.journal.save_roles({"roles": {"e0": "serving", "e1": "training"},
                            "training_width": 1, "breaker_open_until": 0.0,
                            "flips_committed": 0})
    d = sup.decide(_health(burn=0.0, backlog=9), now=10.0)
    assert d is not None and d.direction == "to_serving"
    assert "backlog=9" in d.reason


def test_cooldown_spaces_flips(tmp_path):
    sup = _supervisor(tmp_path, cooldown_s=5.0)
    sup.journal.save_roles({"roles": {"e0": "serving", "e1": "training"},
                            "training_width": 1, "breaker_open_until": 0.0,
                            "flips_committed": 0})
    assert sup.flip(sup.decide(_health(burn=3.0), now=10.0),
                    now=10.0) == "committed"
    sup.journal.save_roles({"roles": {"e0": "serving", "e1": "training"},
                            "training_width": 1, "breaker_open_until": 0.0,
                            "flips_committed": 1})
    assert sup.decide(_health(burn=3.0), now=12.0) is None   # cooling
    assert sup.decide(_health(burn=3.0), now=15.5) is not None


def test_min_serving_floor_blocks_to_training(tmp_path, monkeypatch):
    sup = _supervisor(tmp_path, min_serving=2)
    monkeypatch.setattr(sup, "price", lambda d: {"approve": True})
    assert sup.decide(_health(burn=0.0), now=10.0) is None
    sup.config.min_serving = 1
    d = sup.decide(_health(burn=0.0), now=10.0)
    assert d is not None and d.direction == "to_training"
    assert d.engine == "e1"    # highest-sorted serving engine flips


def test_pricing_veto_blocks_to_training(tmp_path, monkeypatch):
    sup = _supervisor(tmp_path)
    monkeypatch.setattr(
        sup, "price", lambda d: {"approve": False, "speedup": 1.001})
    assert sup.decide(_health(burn=0.0), now=10.0) is None


def test_breaker_opens_on_flip_storm(tmp_path):
    sup = _supervisor(tmp_path, breaker_window_s=60.0, breaker_max_flips=2,
                      breaker_open_s=30.0)
    for i in range(3):
        sup.journal.save_roles(
            {"roles": {"e0": "serving", "e1": "training"},
             "training_width": 1, "breaker_open_until": 0.0,
             "flips_committed": i})
        out = sup.flip(FlipDecision("to_serving", "e1", "storm"),
                       now=10.0 + i)
        assert out == "committed"
    assert sup.roles_doc["breaker_open_until"] > 0
    # while open the supervisor only observes, even under hard pressure
    sup.journal.save_roles({**sup.roles_doc,
                            "roles": {"e0": "serving", "e1": "training"},
                            "training_width": 1})
    assert sup.decide(_health(burn=9.0), now=100.0) is None


def test_signals_collapse_health_doc():
    sig = FleetSupervisor._signals(_health(burn=2.5, backlog=3))
    assert sig["max_burn"] == 2.5 and sig["admission_backlog"] == 3
    assert FleetSupervisor._signals({}) == \
        {"max_burn": 0.0, "admission_backlog": 0}


def test_read_health_tolerates_missing_and_torn(tmp_path):
    assert read_health(str(tmp_path / "nope.json")) == {}
    p = tmp_path / "torn.json"
    p.write_text('{"torn')
    assert read_health(str(p)) == {}


def test_tick_reads_health_path(tmp_path):
    hp = tmp_path / "fleet_health.json"
    hp.write_text(json.dumps(_health(burn=3.0)))
    sup = FleetSupervisor(
        str(tmp_path / "journal"), executor=RecordingExecutor(),
        config=SupervisorConfig(hysteresis_s=0.0, cooldown_s=0.0),
        health_path=str(hp),
        roles={"e0": "serving", "e1": "training"}, training_width=1)
    assert sup.tick(now=10.0) == "committed"
    assert sup.roles_doc["roles"]["e1"] == "serving"
    hp.write_text(json.dumps(_health(burn=0.7)))
    assert sup.tick(now=20.0) is None       # mid-band burn: hold
    hp.write_text(json.dumps(_health(burn=0.1)))
    assert sup.tick(now=30.0) == "committed"  # idle again: back to training
    assert sup.roles_doc["roles"]["e1"] == "training"


# -- pricing against the real planner ---------------------------------------

def test_price_runs_the_stage_planner(tmp_path):
    sup = _supervisor(tmp_path)
    grow = sup.price("to_training")
    assert grow["source_width"] == 0 and grow["target_width"] == 1
    assert grow["source"] is None                  # width 0: idle side
    assert grow["target"]["predicted_step_s"] > 0
    assert grow["approve"] is True                 # growth from idle
    sup.journal.save_roles({"roles": {"e0": "serving", "e1": "training",
                                      "e2": "training"},
                            "training_width": 2, "breaker_open_until": 0.0,
                            "flips_committed": 0})
    grow2 = sup.price("to_training")
    assert "speedup" in grow2 and grow2["speedup"] > 0
    assert grow2["approve"] == (
        grow2["speedup"] >= 1.0 + sup.config.min_speedup)
    shrink = sup.price("to_serving")
    assert shrink["target_width"] == 1 and shrink["approve"] is True


# -- store-side executor + router/worker drain (the real fleet) -------------

VOCAB = 61
ENG = dict(num_slots=2, max_length=64, page_size=16, prefix_cache=True)


@pytest.fixture(scope="module")
def model():
    import paddle_tpu as paddle
    import paddle_tpu.inference as inference
    from paddle_tpu.distributed import mesh as _mesh
    from paddle_tpu.distributed.fleet.topology import (
        get_hybrid_communicate_group, set_hybrid_communicate_group)
    from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

    prev = get_hybrid_communicate_group()
    prev_mesh = _mesh.get_global_mesh()
    set_hybrid_communicate_group(None)
    _mesh.set_global_mesh(None)
    try:
        paddle.seed(7)
        m = GPTForCausalLM(GPTConfig(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=128,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
        m.eval()
        yield m
        inference.disable_decode_engine(m)
    finally:
        set_hybrid_communicate_group(prev)
        _mesh.set_global_mesh(prev_mesh)


@pytest.fixture()
def store():
    from paddle_tpu.runtime import TCPStore

    s = TCPStore(host="127.0.0.1", port=free_port(), is_master=True,
                 timeout=20.0)
    yield s
    s.close()


def _reference(model, requests):
    from paddle_tpu.inference.engine import DecodeEngine, EngineConfig

    eng = DecodeEngine(model, EngineConfig(num_slots=4, max_length=64,
                                           page_size=16, prefix_cache=True))
    rids = [eng.submit(p, params) for p, params in requests]
    eng.run()
    return [eng.result(r) for r in rids]


def _drive(router, workers, rounds=800):
    for _ in range(rounds):
        router.pump()
        for w in workers:
            w.poll_once()
        if not router.pending():
            return
    raise AssertionError(f"undrained after {rounds} rounds: {router.stats()}")


@pytest.mark.slow
def test_drain_then_evacuate_loses_nothing(model, store):
    """The executor's drain path end to end: the drained engine finishes
    in-flight work and reports ``drained``; the router stops placing on
    it; a second (timed-out) drain evacuates through the failover
    resubmit path — and every result stays bit-equal to a one-engine
    reference."""
    from paddle_tpu.serving import EngineWorker, Router

    w0 = EngineWorker(model, store, **ENG)
    w1 = EngineWorker(model, store, **ENG)
    router = Router(store, queue_limit=32, seed=5)
    resized = []
    execu = StoreFleetExecutor(
        store, router=router,
        resize_fn=lambda s, t: resized.append((s, t)),
        pump=lambda: (router.pump(), w0.poll_once(), w1.poll_once()),
        poll_s=0.0)
    rng = np.random.default_rng(3)
    prompts = [rng.integers(1, VOCAB, size=n).astype(np.int64)
               for n in (20, 33, 17, 25, 21, 29)]
    rids = [router.submit(p, slo="standard", max_new_tokens=8,
                          do_sample=(i % 2 == 0), temperature=0.7,
                          top_k=8) for i, p in enumerate(prompts)]
    router.pump()          # both engines hold dispatched work now
    assert execu.drain(w1.name, deadline_s=30.0) is True
    occ = router._engines[w1.name]
    assert occ.draining
    # drained engine is out of the placement set: new work lands on w0
    more = [router.submit(p, slo="standard", max_new_tokens=8)
            for p in prompts[:2]]
    _drive(router, [w0, w1])
    for r in more:
        assert router._requests[r].engine == w0.name
    # a resumed engine lifts its drain state within one ctl-mirror period
    execu.activate(w1.name, "serving")
    import time as _time
    _time.sleep(0.3)
    w1.poll_once()
    assert not w1.draining
    want = _reference(model, [(p, router._requests[r].params)
                              for p, r in zip(prompts, rids)])
    for r, w in zip(rids, want):
        np.testing.assert_array_equal(router.result(r), w)
    assert router.stats()["done"] == len(rids) + len(more)


@pytest.mark.slow
def test_drain_timeout_evacuates_inflight(model, store):
    """A drain whose engine never finishes in time hands its in-flight
    requests to the rest of the fleet via ``Router.evacuate`` — nothing
    dropped, nothing duplicated, results bit-equal."""
    from paddle_tpu.serving import EngineWorker, Router

    w0 = EngineWorker(model, store, **ENG)
    w1 = EngineWorker(model, store, **ENG)
    router = Router(store, queue_limit=32, seed=5)
    # pump only w0 during the drain wait: w1 is wedged on purpose
    execu = StoreFleetExecutor(
        store, router=router,
        pump=lambda: (router.pump(), w0.poll_once()), poll_s=0.0)
    rng = np.random.default_rng(4)
    prompts = [rng.integers(1, VOCAB, size=n).astype(np.int64)
               for n in (20, 33, 17, 25)]
    rids = [router.submit(p, slo="standard", max_new_tokens=8,
                          do_sample=True, temperature=0.7, top_k=8,
                          seed=None) for p in prompts]
    router.pump()
    assert any(router._requests[r].engine == w1.name for r in rids)
    assert execu.drain(w1.name, deadline_s=0.3) is False
    # w1 never ran: its whole book was resubmitted, and w0 finishes all
    _drive(router, [w0])
    want = _reference(model, [(p, router._requests[r].params)
                              for p, r in zip(prompts, rids)])
    for r, w in zip(rids, want):
        np.testing.assert_array_equal(router.result(r), w)
    stats = router.stats()
    assert stats["done"] == len(rids)
    assert router.counters["failover_resubmits"] >= 1
