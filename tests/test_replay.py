"""Workload-replay harness invariants (docs/REPLAY.md).

The replay module's whole value is its determinism contract — same
spec, same seed, same stream, same ledger digest, on any host — plus
the fidelity of its stub tier to the real store-dataplane contracts.
These tests pin both, and the shard-mode partition property the
scaling bench (scripts/bench_replay.py) depends on.
"""
import itertools

import numpy as np
import pytest

from paddle_tpu.serving import Router
from paddle_tpu.serving.protocol import (k_count, k_done, k_engine, k_occ,
                                         unpack)
from paddle_tpu.serving.replay import (MemStore, ReplayLedger, StubWorker,
                                       VirtualClock, arrivals, make_spec,
                                       replay, run_leaf_shard,
                                       run_stub_replay, _Reservoir)


# -- arrival streams ----------------------------------------------------------

def _take(spec, n):
    return list(itertools.islice(arrivals(spec), n))


def test_arrivals_deterministic_and_time_ordered():
    spec = make_spec("mixed", seed=42, rate_rps=2000.0)
    a = _take(spec, 3000)
    b = _take(spec, 3000)
    assert len(a) == 3000
    for ea, eb in zip(a, b):
        assert ea["t"] == eb["t"]
        assert ea["tenant"] == eb["tenant"]
        assert ea["slo"] == eb["slo"]
        assert ea["max_new_tokens"] == eb["max_new_tokens"]
        np.testing.assert_array_equal(ea["prompt"], eb["prompt"])
    ts = [e["t"] for e in a]
    assert ts == sorted(ts), "merged stream must be time-ordered"
    assert ts[0] >= 0.0


def test_arrivals_seed_changes_stream():
    a = _take(make_spec("mixed", seed=1, rate_rps=2000.0), 500)
    b = _take(make_spec("mixed", seed=2, rate_rps=2000.0), 500)
    assert any(ea["t"] != eb["t"] for ea, eb in zip(a, b))


def test_arrivals_mix_properties():
    spec = make_spec("mixed", seed=7, rate_rps=4000.0, tenants=16,
                     tagged_share=0.75)
    evs = _take(spec, 8000)
    # tagged share lands near the configured fraction
    tagged = sum(1 for e in evs if e["tenant"] is not None)
    assert 0.65 < tagged / len(evs) < 0.85
    # Zipf head: the rank-0 tenant dominates the tagged slice
    from collections import Counter
    counts = Counter(e["tenant"] for e in evs if e["tenant"])
    assert counts.most_common(1)[0][0] == "t000"
    # every SLO class appears; agentic turns are interactive-only extras
    assert {e["slo"] for e in evs} == {"interactive", "standard", "batch"}
    # longdoc component produces the long-prefill outliers
    assert max(len(e["prompt"]) for e in evs) >= 192


def test_agentic_sessions_grow_shared_prefixes():
    spec = {"seed": 3, "rate_rps": 200.0,
            "mix": [{"kind": "agentic", "share": 1.0, "turns": 5,
                     "think_s": 0.2, "turn_tokens": 8}],
            "tenants": {"n": 4, "tagged_share": 1.0},
            "slo_mix": {"interactive": 1.0},
            "prompt_tokens": [8, 16], "max_new_tokens": [4, 8]}
    evs = _take(spec, 400)
    # multi-turn sessions: some event's prompt extends an earlier
    # event's prompt exactly (the prefix-affinity traffic shape)
    extended = 0
    by_len = sorted(evs, key=lambda e: len(e["prompt"]))
    for i, e in enumerate(by_len):
        p = e["prompt"]
        for other in by_len[i + 1:]:
            q = other["prompt"]
            if len(q) > len(p) and np.array_equal(q[:len(p)], p):
                extended += 1
                break
    assert extended >= len(evs) // 4


def test_abuse_component_respects_window():
    spec = make_spec("mixed", seed=9, rate_rps=1000.0, abuse_rps=2000.0)
    spec["abuse"]["start_s"] = 1.0
    spec["abuse"]["end_s"] = 2.0
    evs = _take(spec, 6000)
    abuse_t = [e["t"] for e in evs if e["tenant"] == "abuser"]
    assert abuse_t, "abuse window must produce traffic"
    assert min(abuse_t) >= 1.0
    assert max(abuse_t) <= 2.0 + 0.1


# -- MemStore + StubWorker fidelity -------------------------------------------

def test_memstore_tcpstore_surface():
    s = MemStore()
    assert s.add("k", 1) == 1
    assert s.add("k", 2) == 3
    s.set("x", b"v")
    assert s.get("x") == b"v"
    assert s.check("x") and s.check(["x", "k"])
    assert not s.check(["x", "missing"])
    s.wait(["x"])
    with pytest.raises(RuntimeError):
        s.wait(["missing"])
    assert s.delete_key("x") and not s.delete_key("x")


def test_stub_worker_registers_like_engine_worker():
    """The stub must speak the exact store registration + occupancy
    contract (serving/worker.py) the router discovers engines by."""
    store, clock = MemStore(), VirtualClock()
    w = StubWorker(store, "ns", clock=clock, name="s0", num_slots=8)
    assert int(store.add(k_count("ns"), 0)) == 1
    rec = unpack(store.get(k_engine("ns", 0)))
    for key in ("name", "index", "num_slots", "max_length", "page_size",
                "buckets", "pid", "addr", "role", "kv_wire"):
        assert key in rec, f"registration record missing {key!r}"
    assert rec["name"] == "s0" and rec["role"] == "unified"
    w.poll()
    occ = unpack(store.get(k_occ("ns", "s0")))
    for key in ("beat", "acked_seq", "done_count", "name", "role",
                "prefill_queue", "draining", "drained",
                "outstanding_tokens"):
        assert key in occ, f"occupancy beat missing {key!r}"
    b0 = occ["beat"]
    w.poll()
    assert unpack(store.get(k_occ("ns", "s0")))["beat"] == b0 + 1


def test_stub_worker_serves_at_token_rate_and_writes_done():
    store, clock = MemStore(), VirtualClock()
    leaf = Router(store, namespace="ns", dataplane="store", clock=clock)
    w = StubWorker(store, "ns", clock=clock, name="s0",
                   tokens_per_s=100.0)
    rid = leaf.submit(np.arange(40, dtype=np.int64), max_new_tokens=10)
    leaf.pump()
    w.poll()
    assert not store.check(k_done("ns", rid)), \
        "cost 50 must not finish with 0 accrued budget"
    clock.advance(0.3)   # 30 tokens accrued: still short
    w.poll()
    assert not store.check(k_done("ns", rid))
    clock.advance(0.25)  # 55 total: done, BEFORE the ack beat
    w.poll()
    assert store.check(k_done("ns", rid))
    leaf.pump()
    assert leaf.status(rid) == "done"
    toks = leaf.result(rid)
    assert len(toks) > 0


def test_stub_results_derive_from_sampling_seed():
    store, clock = MemStore(), VirtualClock()
    leaf = Router(store, namespace="ns", dataplane="store", clock=clock,
                  retain_results=True)
    w = StubWorker(store, "ns", clock=clock, tokens_per_s=1e9)
    r1 = leaf.submit(np.arange(8, dtype=np.int64), max_new_tokens=4,
                     seed=123)
    r2 = leaf.submit(np.arange(8, dtype=np.int64), max_new_tokens=4,
                     seed=123)
    r3 = leaf.submit(np.arange(8, dtype=np.int64), max_new_tokens=4,
                     seed=124)
    leaf.pump()
    clock.advance(1.0)
    w.poll()
    leaf.pump()
    np.testing.assert_array_equal(leaf.result(r1), leaf.result(r2))
    assert not np.array_equal(leaf.result(r1), leaf.result(r3))


# -- ledger -------------------------------------------------------------------

def test_reservoir_is_deterministic_and_bounded():
    r1, r2 = _Reservoir(cap=64), _Reservoir(cap=64)
    for i in range(10_000):
        v = float((i * 7919) % 1000)
        r1.add(v)
        r2.add(v)
    assert r1.vals == r2.vals
    assert len(r1.vals) <= 64
    assert 0.0 <= r1.quantile(0.5) <= 1000.0
    assert r1.quantile(0.0) <= r1.quantile(0.99)


def test_ledger_digest_covers_order_outcome_and_tokens():
    import dataclasses
    from paddle_tpu.serving.router import RouterRequest
    from paddle_tpu.inference.engine import SamplingParams

    def req(status, tokens=None, reason=None):
        r = RouterRequest(rid=0, prompt=np.empty(0, np.int64),
                          params=SamplingParams(), slo="standard",
                          submit_t=0.0, deadline_t=1.0, block_keys=[],
                          status=status, shed_reason=reason)
        r.tenant = "t"
        if tokens is not None:
            r.tokens = np.asarray(tokens, dtype=np.int64)
        return r

    a, b, c, d = (ReplayLedger() for _ in range(4))
    a.resolve(1, req("done", [1, 2]))
    a.resolve(2, req("shed", reason="quota"))
    b.resolve(1, req("done", [1, 2]))
    b.resolve(2, req("shed", reason="quota"))
    assert a.digest == b.digest
    c.resolve(2, req("shed", reason="quota"))   # order flipped
    c.resolve(1, req("done", [1, 2]))
    assert c.digest != a.digest
    d.resolve(1, req("done", [1, 3]))           # different tokens
    d.resolve(2, req("shed", reason="quota"))
    assert d.digest != a.digest
    assert a.rows[("t", "standard")]["shed_quota"] == 1


# -- end-to-end stub replay ---------------------------------------------------

def test_replay_resolves_everything_and_reaps_store():
    spec = make_spec("mixed", seed=21, rate_rps=3000.0)
    out = run_stub_replay(spec, 3000, n_leaves=2, engines_per_leaf=2,
                          tokens_per_s=200_000.0)
    assert out["resolved"] == out["requests"] == 3000
    total = 0
    for cls in out["classes"].values():
        total += sum(v for k, v in cls.items() if isinstance(v, int))
    assert total == 3000
    assert out["dispatch_rps"] > 0
    assert "admission_s" in out["classes"]["interactive"]


def test_replay_heap_and_scan_dispatch_agree():
    """The PR 19 hot-loop refactor must be a pure optimization: the
    lazy-invalidation heap places every request on the SAME engine the
    O(E) scan would (identical tie-break), so the run digests match."""
    spec = make_spec("mixed", seed=31, rate_rps=3000.0)
    kw = dict(n_leaves=1, engines_per_leaf=5, tokens_per_s=150_000.0)
    heap = run_stub_replay(spec, 2500, dispatch_mode="heap", **kw)
    scan = run_stub_replay(spec, 2500, dispatch_mode="scan", **kw)
    assert heap["digest"] == scan["digest"]
    assert heap["classes"] == scan["classes"]


def test_shard_partition_covers_stream_exactly():
    """2-leaf shard runs partition the global stream: every gid lands in
    exactly one shard, and each shard's work matches what the 1-leaf
    run dispatched for those gids (same seeds, same hash)."""
    spec = make_spec("mixed", seed=17, rate_rps=3000.0)
    kw = dict(engines_per_leaf=2, tokens_per_s=500_000.0)
    whole = run_leaf_shard(spec, 2000, ["leaf0"], "leaf0", **kw)
    a = run_leaf_shard(spec, 2000, ["leaf0", "leaf1"], "leaf0", **kw)
    b = run_leaf_shard(spec, 2000, ["leaf0", "leaf1"], "leaf1", **kw)
    assert whole["requests"] == 2000
    assert a["requests"] + b["requests"] == 2000
    assert 0 < a["requests"] < 2000, "both shards must get traffic"
    assert whole["digest"] != ""  # digest present
    # shard runs are themselves deterministic
    a2 = run_leaf_shard(spec, 2000, ["leaf0", "leaf1"], "leaf0", **kw)
    assert a2["digest"] == a["digest"]


def test_virtual_clock_controls_deadlines():
    """Virtual time drives deadline sheds: a queued request past its
    class deadline sheds when the clock says so, not wall time."""
    store, clock = MemStore(), VirtualClock()
    leaf = Router(store, namespace="ns", dataplane="store", clock=clock,
                  deadlines={"interactive": 1.0})
    # no workers at all: nothing can dispatch, deadline must fire
    rid = leaf.submit(np.arange(8, dtype=np.int64),
                      slo="interactive", max_new_tokens=4)
    leaf.pump()
    assert leaf.status(rid) == "queued"
    clock.advance(1.5)
    leaf.pump()
    assert leaf.status(rid) == "shed"
    assert leaf._requests[rid].shed_reason == "deadline"
