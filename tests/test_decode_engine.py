"""KV-cached decode engine + serving path (docs/SERVING.md).

Gates the four serving promises: engine greedy decode is BIT-EQUAL to
the naive full-forward loops, continuous batching keeps its invariants
(mid-flight join, EOS eviction, slot reuse without KV leakage), int8 KV
stays within tolerance of f32, and a mixed-length workload compiles at
most ``buckets_used + 1`` programs.
"""
import numpy as np
import pytest

import paddle_tpu.inference as inference
from paddle_tpu.inference.engine import (DecodeEngine, EngineConfig,
                                         SamplingParams, pow2_bucket)
from paddle_tpu.text import generation
from paddle_tpu.text.models.gpt import GPTConfig, GPTForCausalLM

VOCAB = 61


@pytest.fixture(scope="module")
def model():
    import paddle_tpu as paddle
    from paddle_tpu.distributed import mesh as _mesh
    from paddle_tpu.distributed.fleet.topology import (
        get_hybrid_communicate_group, set_hybrid_communicate_group)

    # serving is single-process here: shield the model build from any
    # hybrid-parallel group / pp-sliced global mesh a fleet test left
    # behind in this interpreter (mp-degree vocab splits, SpmdPipeline
    # decoder folding)
    prev = get_hybrid_communicate_group()
    prev_mesh = _mesh.get_global_mesh()
    set_hybrid_communicate_group(None)
    _mesh.set_global_mesh(None)
    try:
        paddle.seed(7)
        m = GPTForCausalLM(GPTConfig(
            vocab_size=VOCAB, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, max_position_embeddings=128,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0))
        m.eval()
        yield m
        inference.disable_decode_engine(m)
    finally:
        set_hybrid_communicate_group(prev)
        _mesh.set_global_mesh(prev_mesh)


@pytest.fixture(autouse=True)
def _detach_engine(model):
    yield
    inference.disable_decode_engine(model)


def _prompts(b, t, seed=0):
    return np.random.default_rng(seed).integers(
        1, VOCAB, (b, t), dtype=np.int64)


def test_pow2_bucket():
    assert [pow2_bucket(n) for n in (1, 16, 17, 33, 100)] == [
        16, 16, 32, 64, 128]
    assert pow2_bucket(100, hi=48) == 48
    assert EngineConfig(max_length=100).resolved_buckets() == [16, 32, 64, 100]


def test_engine_greedy_bit_equal_generate(model):
    ids = _prompts(3, 7)
    ref = generation.generate(model, ids, max_new_tokens=12,
                              use_engine=False)
    inference.enable_decode_engine(model, num_slots=4, max_length=64)
    out = generation.generate(model, ids, max_new_tokens=12)
    np.testing.assert_array_equal(ref, out)


@pytest.mark.slow
def test_engine_greedy_bit_equal_generate_padded(model):
    ids = _prompts(2, 9, seed=3)
    ref = generation.generate_padded(model, ids, max_length=24,
                                     use_engine=False)
    inference.enable_decode_engine(model, num_slots=2, max_length=64)
    out = generation.generate_padded(model, ids, max_length=24)
    np.testing.assert_array_equal(ref, out)


def test_generate_bucketing_matches_fixed_shape(model):
    # the legacy loop's pow2 right-pad buckets must not change tokens
    ids = _prompts(2, 5, seed=5)
    a = generation.generate(model, ids, max_new_tokens=11, use_engine=False)
    b = generation.generate_padded(model, ids, max_length=16,
                                   use_engine=False)
    np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_join_mid_flight_and_slot_reuse(model):
    # 3 requests on 2 slots: the third joins only after a slot frees,
    # and its tokens must equal a solo run (slot reuse leaks no KV).
    eng = inference.enable_decode_engine(model, num_slots=2, max_length=64)
    ids = _prompts(3, 6, seed=11)
    r0 = eng.submit(ids[0], SamplingParams(max_new_tokens=10))
    r1 = eng.submit(ids[1], SamplingParams(max_new_tokens=3))
    r2 = eng.submit(ids[2], SamplingParams(max_new_tokens=5))
    eng.step()  # admits r0/r1 only — both slots busy, r2 waits
    assert eng.stats()["running"] == 2 and eng.stats()["waiting"] == 1
    assert eng._requests[r2].status == "waiting"
    while eng._requests[r1].status != "done":
        eng.step()
    eng.step()  # r1's slot is free; r2 joins while r0 still decodes
    assert eng._requests[r2].status in ("running", "done")
    assert eng._requests[r0].status == "running"
    eng.run()
    got = {r: eng.result(r) for r in (r0, r1, r2)}
    assert [len(got[r]) for r in (r0, r1, r2)] == [16, 9, 11]

    solo = DecodeEngine(model, EngineConfig(num_slots=2, max_length=64))
    for i, r in enumerate((r0, r1, r2)):
        sid = solo.submit(ids[i], SamplingParams(
            max_new_tokens=[10, 3, 5][i]))
        solo.run()
        np.testing.assert_array_equal(solo.result(sid), got[r])


def test_eos_evicts_and_frees_slot(model):
    eng = inference.enable_decode_engine(model, num_slots=2, max_length=64)
    ids = _prompts(1, 6, seed=2)[0]
    rid = eng.submit(ids, SamplingParams(max_new_tokens=20))
    eng.run()
    free_run = eng.result(rid)
    eos = int(free_run[len(ids) + 2])  # third generated token
    rid2 = eng.submit(ids, SamplingParams(max_new_tokens=20,
                                          eos_token_id=eos))
    eng.run()
    out = eng.result(rid2)
    # stopped at (and including) the FIRST eos in the greedy stream,
    # short of max_new_tokens
    first = len(ids) + int(np.argmax(free_run[len(ids):] == eos))
    assert len(out) == first + 1 and out[-1] == eos
    assert len(out) < len(free_run)
    np.testing.assert_array_equal(out, free_run[:len(out)])
    assert eng.stats()["running"] == 0 and len(eng._free) == 2


@pytest.mark.slow
def test_int8_kv_close_to_f32(model):
    ids = _prompts(2, 8, seed=9)
    f32 = DecodeEngine(model, EngineConfig(num_slots=2, max_length=64))
    q = DecodeEngine(model, EngineConfig(num_slots=2, max_length=64,
                                         kv_dtype="int8"))
    a = np.asarray(f32.generate_batch(ids, max_new_tokens=12)._value)
    b = np.asarray(q.generate_batch(ids, max_new_tokens=12)._value)
    agree = (a == b).mean()
    assert agree >= 0.9, f"int8 KV diverged from f32: {agree:.0%} agreement"


def test_compile_count_gate(model):
    # mixed workload over 3 buckets compiles <= buckets_used + 1 programs
    eng = inference.enable_decode_engine(
        model, num_slots=4, max_length=128)
    assert eng.buckets == [16, 32, 64, 128]
    for t0 in (5, 20, 40, 10, 25):  # buckets 16, 32, 64, 16, 32
        eng.submit(_prompts(1, t0, seed=t0)[0],
                   SamplingParams(max_new_tokens=4))
    eng.run()
    assert eng.stats()["compile_count"] <= 3 + 1
    before = eng.stats()["compile_count"]
    eng.submit(_prompts(1, 12, seed=99)[0], SamplingParams(max_new_tokens=4))
    eng.run()  # same bucket (16) — nothing new compiles
    assert eng.stats()["compile_count"] == before


def test_sampling_is_scheduling_invariant(model):
    ids = _prompts(4, 6, seed=21)
    p = SamplingParams(max_new_tokens=8, do_sample=True, temperature=0.8,
                      top_k=12, top_p=0.95, seed=123)
    solo = DecodeEngine(model, EngineConfig(num_slots=1, max_length=64))
    rid = solo.submit(ids[0], p)
    solo.run()
    alone = solo.result(rid)

    # same request, different slot count, batched with other traffic
    busy = DecodeEngine(model, EngineConfig(num_slots=4, max_length=64))
    others = [busy.submit(ids[i], SamplingParams(max_new_tokens=5))
              for i in (1, 2, 3)]
    rid2 = busy.submit(ids[0], p)
    busy.run()
    np.testing.assert_array_equal(alone, busy.result(rid2))
    assert all(busy._requests[r].status == "done" for r in others)


def test_submit_validation(model):
    eng = DecodeEngine(model, EngineConfig(num_slots=1, max_length=32))
    with pytest.raises(ValueError):
        eng.submit(np.array([], np.int32))
    with pytest.raises(ValueError):
        eng.submit(_prompts(1, 40, seed=1)[0])  # exceeds largest bucket
    with pytest.raises(ValueError):
        eng.submit(_prompts(1, 8, seed=1)[0],
                   SamplingParams(max_new_tokens=30))  # overflows ring


def test_transformer_static_cache_matches_concat_grow():
    import jax.numpy as jnp

    from paddle_tpu.framework.core import Tensor
    from paddle_tpu.framework.op import raw
    from paddle_tpu.nn.layers.transformer import (TransformerDecoder,
                                                  TransformerDecoderLayer)

    import paddle_tpu as paddle

    paddle.seed(3)
    B, T, E, H = 2, 5, 16, 4
    dec = TransformerDecoder(
        TransformerDecoderLayer(E, H, 32, dropout=0.0), 2)
    dec.eval()
    rng = np.random.default_rng(0)
    x = Tensor(jnp.asarray(rng.standard_normal((B, T, E)), jnp.float32))
    mem = Tensor(jnp.asarray(rng.standard_normal((B, 3, E)), jnp.float32))
    legacy = dec.gen_cache(mem)
    static = dec.gen_cache(mem, max_length=8)
    assert raw(static[0][0].k).shape == (B, 8, H, E // H)
    for t in range(T):
        xt = Tensor(raw(x)[:, t:t + 1])
        ol, legacy = dec(xt, mem, cache=legacy)
        os_, static = dec(xt, mem, cache=static, cache_position=t)
        np.testing.assert_allclose(np.asarray(raw(ol)),
                                   np.asarray(raw(os_)),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.slow
@pytest.mark.chaos
def test_throughput_soak(model):
    """Sustained mixed traffic: 24 random-size requests through 4 slots.

    Everything must drain, token budgets must be exact, and the program
    count must stay at buckets_used + 1 no matter the arrival order."""
    rng = np.random.default_rng(0)
    eng = inference.enable_decode_engine(model, num_slots=4, max_length=128)
    want = {}
    for i in range(24):
        t0 = int(rng.integers(3, 60))
        n = int(rng.integers(1, 16))
        rid = eng.submit(_prompts(1, t0, seed=i)[0],
                         SamplingParams(max_new_tokens=n,
                                        do_sample=bool(i % 2), seed=i))
        want[rid] = t0 + n
        if i % 5 == 4:
            eng.step()  # interleave arrivals with decode progress
    eng.run()
    for rid, total in want.items():
        assert len(eng.result(rid)) == total
    used = {b for b in eng.stats()["compiled"] if b != "decode"}
    assert eng.stats()["compile_count"] <= len(used) + 1
