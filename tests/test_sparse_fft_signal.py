"""paddle.sparse / paddle.fft / paddle.signal tests vs numpy/scipy references
(SURVEY.md §4 op-vs-reference pattern)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import fft, signal, sparse

pytestmark = pytest.mark.fast  # whole-module smoke: cheap on 1 core


def _np(t):
    return np.asarray(t._value)


# ---------------------------------------------------------------------------
# sparse
# ---------------------------------------------------------------------------
def _rand_coo(rs, shape=(6, 5), nnz=8):
    dense = np.zeros(shape, "float32")
    rows = rs.randint(0, shape[0], nnz)
    cols = rs.randint(0, shape[1], nnz)
    vals = rs.randn(nnz).astype("float32")
    for r, c, v in zip(rows, cols, vals):
        dense[r, c] += v
    idx = np.stack([rows, cols])
    return idx, vals, dense


def test_sparse_coo_roundtrip():
    rs = np.random.RandomState(0)
    idx, vals, dense = _rand_coo(rs)
    st = sparse.sparse_coo_tensor(idx, vals, dense.shape)
    assert st.is_sparse_coo() and not st.is_sparse_csr()
    np.testing.assert_allclose(_np(st.to_dense()), dense, rtol=1e-6)
    co = st.coalesce()
    assert co.nnz() <= st.nnz()
    np.testing.assert_allclose(_np(co.to_dense()), dense, rtol=1e-6)


def test_sparse_csr_and_conversion():
    crows = np.array([0, 2, 3, 5], "int32")
    cols = np.array([0, 2, 1, 0, 2], "int32")
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0], "float32")
    st = sparse.sparse_csr_tensor(crows, cols, vals, [3, 3])
    dense = np.array([[1, 0, 2], [0, 3, 0], [4, 0, 5]], "float32")
    np.testing.assert_allclose(_np(st.to_dense()), dense)
    coo = st.to_sparse_coo()
    assert coo.is_sparse_coo()
    np.testing.assert_allclose(_np(coo.to_dense()), dense)
    back = coo.to_sparse_csr()
    np.testing.assert_allclose(_np(back.to_dense()), dense)


def test_sparse_matmul_and_elementwise():
    rs = np.random.RandomState(1)
    idx, vals, dense = _rand_coo(rs)
    st = sparse.sparse_coo_tensor(idx, vals, dense.shape)
    y = rs.randn(5, 3).astype("float32")
    np.testing.assert_allclose(_np(sparse.matmul(st, y)), dense @ y, rtol=1e-5, atol=1e-6)

    idx2, vals2, dense2 = _rand_coo(rs)
    st2 = sparse.sparse_coo_tensor(idx2, vals2, dense2.shape)
    np.testing.assert_allclose(
        _np(sparse.add(st, st2).to_dense()), dense + dense2, rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        _np(sparse.subtract(st, st2).to_dense()), dense - dense2, rtol=1e-5, atol=1e-6
    )
    np.testing.assert_allclose(
        _np(sparse.multiply(st, 2.0).to_dense()), dense * 2, rtol=1e-6
    )


def test_sparse_unary_and_softmax():
    rs = np.random.RandomState(2)
    idx, vals, dense = _rand_coo(rs)
    st = sparse.sparse_coo_tensor(idx, vals, dense.shape).coalesce()
    np.testing.assert_allclose(
        _np(sparse.relu(st).to_dense()), np.maximum(dense, 0), rtol=1e-6
    )
    sm = sparse.nn.Softmax()(st)
    out = _np(sm.to_dense())
    mask = _np(st.to_dense()) != 0
    # each nonzero row sums to 1 over stored positions
    row_sums = out.sum(-1)[mask.any(-1)]
    np.testing.assert_allclose(row_sums, 1.0, rtol=1e-5)


def test_sparse_softmax_3d():
    rs = np.random.RandomState(4)
    dense = np.zeros((2, 4, 5), "float32")
    b = rs.randint(0, 2, 10)
    r = rs.randint(0, 4, 10)
    c = rs.randint(0, 5, 10)
    v = rs.randn(10).astype("float32")
    for bi, ri, ci, vi in zip(b, r, c, v):
        dense[bi, ri, ci] += vi
    st = sparse.sparse_coo_tensor(np.stack([b, r, c]), v, dense.shape).coalesce()
    out = _np(sparse.nn.Softmax()(st).to_dense())
    mask = _np(st.to_dense()) != 0
    row_sums = out.sum(-1)[mask.any(-1)]
    np.testing.assert_allclose(row_sums, 1.0, rtol=1e-5)
    # stored positions match dense softmax restricted to the sparsity pattern
    for bi in range(2):
        for ri in range(4):
            m = mask[bi, ri]
            if not m.any():
                continue
            e = np.exp(dense[bi, ri][m] - dense[bi, ri][m].max())
            np.testing.assert_allclose(out[bi, ri][m], e / e.sum(), rtol=1e-5)


def test_masked_matmul():
    rs = np.random.RandomState(3)
    x = rs.randn(4, 6).astype("float32")
    y = rs.randn(6, 5).astype("float32")
    idx, vals, dense = _rand_coo(rs, shape=(4, 5), nnz=6)
    mask = sparse.sparse_coo_tensor(idx, vals, (4, 5)).coalesce()
    out = sparse.masked_matmul(x, y, mask)
    full = x @ y
    got = _np(out.to_dense())
    m = _np(mask.to_dense()) != 0
    np.testing.assert_allclose(got[m], full[m], rtol=1e-5, atol=1e-5)
    assert np.all(got[~m] == 0)


# ---------------------------------------------------------------------------
# fft
# ---------------------------------------------------------------------------
def test_fft_family_matches_numpy():
    rs = np.random.RandomState(0)
    x = rs.randn(4, 16).astype("float32")
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(_np(fft.fft(t)), np.fft.fft(x), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(_np(fft.rfft(t)), np.fft.rfft(x), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        _np(fft.ifft(fft.fft(t))), x.astype("complex64"), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        _np(fft.irfft(fft.rfft(t))), x, rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(_np(fft.fft2(t)), np.fft.fft2(x), rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(
        _np(fft.fft(t, norm="ortho")), np.fft.fft(x, norm="ortho"), rtol=1e-4, atol=1e-4
    )
    np.testing.assert_allclose(_np(fft.fftfreq(16, 0.5)), np.fft.fftfreq(16, 0.5), rtol=1e-6)
    np.testing.assert_allclose(_np(fft.fftshift(t)), np.fft.fftshift(x), rtol=1e-6)


def test_hfft2_ihfft2_match_scipy():
    import scipy.fft as sfft

    rs = np.random.RandomState(0)
    z = (rs.randn(6, 5) + 1j * rs.randn(6, 5)).astype("complex64")
    np.testing.assert_allclose(
        _np(fft.hfft2(paddle.to_tensor(z))), sfft.hfft2(z), rtol=1e-3, atol=1e-3
    )
    xr = rs.randn(6, 8).astype("float32")
    np.testing.assert_allclose(
        _np(fft.ihfft2(paddle.to_tensor(xr))), sfft.ihfft2(xr), rtol=1e-4, atol=1e-5
    )


def test_hfft2_ihfft2_norms_match_scipy():
    import scipy.fft as sfft

    rs = np.random.RandomState(1)
    z = (rs.randn(6, 5) + 1j * rs.randn(6, 5)).astype("complex64")
    xr = rs.randn(6, 8).astype("float32")
    for norm in ("backward", "ortho", "forward"):
        np.testing.assert_allclose(
            _np(fft.hfft2(paddle.to_tensor(z), norm=norm)),
            sfft.hfft2(z, norm=norm), rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(
            _np(fft.ihfft2(paddle.to_tensor(xr), norm=norm)),
            sfft.ihfft2(xr, norm=norm), rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# signal
# ---------------------------------------------------------------------------
def test_frame_overlap_add_roundtrip():
    x = np.arange(32, dtype="float32")
    f = signal.frame(paddle.to_tensor(x), 8, 8)  # non-overlapping
    assert _np(f).shape == (8, 4)
    y = signal.overlap_add(f, 8)
    np.testing.assert_allclose(_np(y), x)


def test_frame_overlap_add_axis0():
    rs = np.random.RandomState(2)
    x = rs.randn(32, 3).astype("float32")  # time-first, batch trailing
    f = signal.frame(paddle.to_tensor(x), 8, 4, axis=0)
    assert _np(f).shape == (7, 8, 3)
    # frame i along axis 0 == x[i*hop : i*hop+len]
    np.testing.assert_allclose(_np(f)[2], x[8:16])
    y = signal.overlap_add(f, 4, axis=0)
    ref = signal.overlap_add(
        paddle.to_tensor(np.moveaxis(_np(f), (0, 1), (-1, -2))), 4)
    np.testing.assert_allclose(_np(y), np.moveaxis(_np(ref), -1, 0), rtol=1e-6)


def test_stft_matches_manual_dft():
    rs = np.random.RandomState(0)
    x = rs.randn(2, 64).astype("float32")
    n_fft, hop = 16, 8
    out = _np(signal.stft(paddle.to_tensor(x), n_fft, hop_length=hop, center=False))
    assert out.shape == (2, n_fft // 2 + 1, (64 - n_fft) // hop + 1)
    # frame 0 of batch 0 == rfft of x[0, :16]
    np.testing.assert_allclose(out[0, :, 0], np.fft.rfft(x[0, :n_fft]), rtol=1e-4, atol=1e-4)


def test_stft_istft_roundtrip():
    rs = np.random.RandomState(1)
    x = rs.randn(3, 128).astype("float32")
    n_fft, hop = 32, 8
    win = np.hanning(n_fft).astype("float32")
    spec = signal.stft(paddle.to_tensor(x), n_fft, hop_length=hop, window=paddle.to_tensor(win))
    y = signal.istft(spec, n_fft, hop_length=hop, window=paddle.to_tensor(win), length=128)
    np.testing.assert_allclose(_np(y), x, rtol=1e-3, atol=1e-3)
