"""Multiprocess DataLoader with shared memory (SURVEY.md §2.2 "Data";
reference: python/paddle/io/ multiprocess workers + shm)."""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import io

pytestmark = pytest.mark.fast  # whole-module smoke: cheap on 1 core


class _HeavyDataset(io.Dataset):
    """Python-heavy per-sample transform: pure-Python loop, holds the GIL."""

    def __init__(self, n=64, work=4000):
        self.n = n
        self.work = work

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        acc = 0.0
        for k in range(self.work):  # GIL-bound on threads
            acc += (i * 31 + k) % 97
        x = np.full((64, 64), np.float32(acc % 1000) / 1000.0, np.float32)
        return x, np.int64(i % 10)


def _epoch_time(loader):
    t0 = time.perf_counter()
    n = 0
    for xb, yb in loader:
        n += int(xb.shape[0])
    return time.perf_counter() - t0, n


def test_multiprocess_correctness():
    ds = _HeavyDataset(n=16, work=10)
    ref = [ds[i] for i in range(16)]
    loader = io.DataLoader(ds, batch_size=4, num_workers=2, shuffle=False)
    seen = 0
    for bi, (xb, yb) in enumerate(loader):
        assert xb.shape == [4, 64, 64]
        for j in range(4):
            i = bi * 4 + j
            np.testing.assert_allclose(xb.numpy()[j], ref[i][0])
            assert int(yb.numpy()[j]) == int(ref[i][1])
            seen += 1
    assert seen == 16


@pytest.mark.skipif(
    (os.cpu_count() or 1) < 4,
    reason="worker processes cannot outrun the GIL without >=4 physical "
    "cores (CI sandbox exposes %d); the mp path's overhead-parity is "
    "asserted below instead" % (os.cpu_count() or 1),
)
def test_multiprocess_beats_threads_on_python_heavy_transform():
    """VERDICT #8 'done' criterion: >2x throughput over the thread path.

    Per-sample work must dwarf process/queue overhead: ~15ms of pure-Python
    looping each, ~1s per epoch single-threaded.
    """
    ds = _HeavyDataset(n=64, work=60_000)
    workers = 4

    mp_loader = io.DataLoader(ds, batch_size=8, num_workers=workers,
                              persistent_workers=True)
    # warm epoch: pays the one-time fork cost of the persistent pool
    _epoch_time(mp_loader)
    t_mp, n1 = _epoch_time(mp_loader)

    th_loader = io.DataLoader(
        ds, batch_size=8, num_workers=workers,
        collate_fn=io.default_collate_fn,  # custom collate → thread path
    )
    t_th, n2 = _epoch_time(th_loader)
    assert n1 == n2 == 64
    assert t_mp * 2.0 < t_th, (
        f"multiprocess epoch {t_mp:.3f}s not >2x faster than threads "
        f"{t_th:.3f}s on a GIL-bound transform"
    )


def test_multiprocess_overhead_parity():
    """Even without spare cores, the persistent-pool mp path must stay in
    the same ballpark as threads (no pathological per-batch overhead)."""
    ds = _HeavyDataset(n=32, work=20_000)
    mp_loader = io.DataLoader(ds, batch_size=8, num_workers=2,
                              persistent_workers=True)
    _epoch_time(mp_loader)  # pay the fork once
    t_mp, n1 = _epoch_time(mp_loader)
    t_th, n2 = _epoch_time(
        io.DataLoader(ds, batch_size=8, num_workers=2,
                      collate_fn=io.default_collate_fn)
    )
    assert n1 == n2 == 32
    assert t_mp < 2.5 * t_th + 0.25, (
        f"mp epoch {t_mp:.3f}s vs threads {t_th:.3f}s: per-batch overhead "
        "out of band"
    )


def test_worker_info_and_init_fn():
    inits = []

    class _Probe(io.Dataset):
        def __len__(self):
            return 8

        def __getitem__(self, i):
            info = io.get_worker_info()
            assert info is not None and 0 <= info.id < 2
            return np.full((4,), info.id, np.float32)

    loader = io.DataLoader(_Probe(), batch_size=2, num_workers=2)
    ids = set()
    for (b,) in zip(loader):
        ids.update(np.unique(b.numpy()).tolist())
    assert ids <= {0.0, 1.0}
    assert io.get_worker_info() is None  # parent process


def test_worker_error_propagates():
    class _Boom(io.Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            if i == 2:
                raise ValueError("boom at index 2")
            return np.zeros((4,), np.float32)

    loader = io.DataLoader(_Boom(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom at index 2"):
        list(loader)


def test_tensor_samples_raise_clear_error():
    """Tensor-returning datasets must fail loudly under worker processes
    (jax must not run in forked children), not silently return lists."""

    class _TensorDS(io.Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            return paddle.to_tensor(np.zeros((3,), np.float32))

    loader = io.DataLoader(_TensorDS(), batch_size=2, num_workers=2)
    with pytest.raises(RuntimeError, match="num_workers=0"):
        list(loader)


def test_thread_path_worker_error_propagates():
    """Thread-path worker exceptions raise instead of hanging the consumer."""

    class _Boom(io.Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            if i == 1:
                raise ValueError("thread boom")
            return np.zeros((4,), np.float32)

    loader = io.DataLoader(
        _Boom(), batch_size=2, num_workers=2,
        collate_fn=io.default_collate_fn,  # custom collate → thread path
    )
    with pytest.raises(RuntimeError, match="worker failed"):
        list(loader)


def test_no_shm_leak():
    import glob

    before = set(glob.glob("/dev/shm/*"))
    ds = _HeavyDataset(n=16, work=10)
    for _ in io.DataLoader(ds, batch_size=4, num_workers=2):
        pass
    time.sleep(0.2)
    leaked = set(glob.glob("/dev/shm/*")) - before
    assert not leaked, f"leaked shm segments: {leaked}"


def test_buffer_reader_lookahead_and_order():
    """use_buffer_reader pre-pulls prefetch_factor batches (the H2D for the
    next batch is issued before the current one is consumed) and preserves
    batch order/content exactly; use_buffer_reader=False matches too."""
    import numpy as np
    import paddle_tpu as paddle

    pulled = []

    class Tracked(paddle.io.Dataset):
        def __len__(self):
            return 12

        def __getitem__(self, i):
            pulled.append(i)
            return np.full((3,), i, np.float32)

    dl = paddle.io.DataLoader(Tracked(), batch_size=2, num_workers=0,
                              use_buffer_reader=True, prefetch_factor=2)
    it = iter(dl)
    first = next(it)
    # lookahead: with the first batch in hand, the loader has already
    # constructed at least one MORE batch (>= 4 samples pulled)
    assert len(pulled) >= 4, pulled
    rest = list(it)
    batches = [first] + rest
    assert len(batches) == 6
    for b, batch in enumerate(batches):
        arr = np.asarray(batch[0]._value if hasattr(batch[0], "_value")
                         else batch[0])
        np.testing.assert_allclose(arr[0], 2 * b)

    dl2 = paddle.io.DataLoader(Tracked(), batch_size=2, num_workers=0,
                               use_buffer_reader=False)
    flat = [np.asarray(b[0]._value if hasattr(b[0], "_value") else b[0])
            for b in dl2]
    np.testing.assert_allclose([a[0] for a in flat],
                               [0, 2, 4, 6, 8, 10])
