"""dy2static break/continue elimination: loops whose only conversion
blocker is a top-level break/continue (bare, or the sole body of a plain
``if``) now compile to lax.while_loop with a carried stop flag.

Reference: ``jit/dy2static/transformers/break_continue_transformer.py`` —
the reference rewrites break/continue into gating booleans; same contract
here."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle

pytestmark = pytest.mark.fast


def _assert_no_fallback(record):
    msgs = [str(w.message) for w in record if "EAGER" in str(w.message)]
    assert not msgs, f"dy2static fell back to eager: {msgs}"


def _run_static(fn, *argsets):
    sfn = paddle.jit.to_static(fn)
    outs = []
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        for args in argsets:
            outs.append(sfn(*args))
    _assert_no_fallback(rec)
    return outs, sfn


def test_while_with_conditional_break():
    def f(x):
        s = paddle.zeros([])
        while s < 100.0:
            s = s + x.sum()
            if s > 10.0:
                break
            s = s + 1.0
        return s

    x = paddle.to_tensor(np.full((3,), 2.0, "float32"))
    (got,), sfn = _run_static(f, (x,))
    np.testing.assert_allclose(got.numpy(), f(x).numpy(), rtol=1e-6)
    assert sfn.program_cache_size == 1


def test_while_true_break_pattern():
    """The classic ``while True: ... if c: break`` — the carried flag IS
    the loop condition."""

    def f(x):
        s = paddle.zeros([])
        n = paddle.zeros([])
        while True:
            s = s + x.mean()
            n = n + 1.0
            if s > 5.0:
                break
        return s, n

    x = paddle.to_tensor(np.full((4,), 1.5, "float32"))
    (got,), _ = _run_static(f, (x,))
    ref = f(x)
    np.testing.assert_allclose(got[0].numpy(), ref[0].numpy(), rtol=1e-6)
    np.testing.assert_allclose(got[1].numpy(), ref[1].numpy(), rtol=1e-6)


def test_for_range_with_continue():
    def f(x):
        s = paddle.zeros([])
        for i in range(6):
            if x.sum() + i < 3.0:
                continue
            s = s + i
        return s

    x = paddle.to_tensor(np.full((2,), 0.5, "float32"))
    (got,), _ = _run_static(f, (x,))
    np.testing.assert_allclose(got.numpy(), f(x).numpy(), rtol=1e-6)


def test_for_range_with_break():
    def f(x):
        s = paddle.zeros([])
        for i in range(10):
            s = s + x.mean()
            if s > 4.0:
                break
        return s, i

    x = paddle.to_tensor(np.full((2,), 1.0, "float32"))
    (got,), _ = _run_static(f, (x,))
    ref = f(x)
    np.testing.assert_allclose(got[0].numpy(), ref[0].numpy(), rtol=1e-6)
    # loop variable keeps the last-iterated value, Python semantics
    # (eager returns a python int; converted returns a scalar tensor)
    assert int(np.asarray(got[1].numpy())) == int(ref[1])


def test_break_after_continue_mixed():
    def f(x):
        s = paddle.zeros([])
        while s < 50.0:
            s = s + x.sum()
            if s < 2.0:
                continue
            s = s + 10.0
            if s > 20.0:
                break
        return s

    x = paddle.to_tensor(np.full((2,), 0.4, "float32"))
    (got,), _ = _run_static(f, (x,))
    np.testing.assert_allclose(got.numpy(), f(x).numpy(), rtol=1e-6)


def test_unsupported_break_form_still_falls_back_correctly():
    """A break buried deeper than the supported shapes (here: inside a
    NESTED if) rejects the rewrite; the loop keeps the ORIGINAL statements
    and, with a tensor condition forcing conversion, the callable degrades
    to the eager fallback WITH the warning — results stay correct."""

    def f(x):
        s = paddle.zeros([])
        for i in range(6):
            if x.sum() > 0:  # tensor condition: forces a conversion attempt
                if i > 2:  # nested if holding the break: unsupported shape
                    break
            s = s + 1.0
        return s

    x = paddle.to_tensor(np.ones((2,), "float32"))
    sfn = paddle.jit.to_static(f)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        out = sfn(x)
    assert any("EAGER" in str(w.message) for w in rec)
    np.testing.assert_allclose(out.numpy(), f(x).numpy(), rtol=1e-6)
